"""Trial schedulers (reference: python/ray/tune/schedulers/ —
async_hyperband.py ASHA, median_stopping_rule.py, pbt.py).

Contract: `on_result(trial, result, runner) -> decision`, where decision is
CONTINUE or STOP; PBT may additionally mutate other trials through the
runner (exploit/explore).
"""
from __future__ import annotations

import random

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial, result, runner):
        return CONTINUE


class AsyncHyperBandScheduler:
    """ASHA: successive-halving rungs; a trial only continues past a rung if
    it is in the top 1/reduction_factor of completed results at that rung
    (reference: schedulers/async_hyperband.py)."""

    def __init__(self, metric: str, mode: str = "max", grace_period: int = 1,
                 max_t: int = 100, reduction_factor: int = 3):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.max_t = max_t
        self.rf = reduction_factor
        # rung milestones: grace, grace*rf, grace*rf^2 ... < max_t
        self.rungs: list[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_values: dict[int, dict[str, float]] = \
            {r: {} for r in self.rungs}

    def on_result(self, trial, result, runner):
        t = result.get("training_iteration", 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        # record at the rung the trial just reached
        for rung in self.rungs:
            if t == rung:
                self.rung_values[rung][trial.trial_id] = float(value)
        # (re-)evaluate against the latest rung at or below t on EVERY
        # result: a trial that passed a rung while it had few peers is
        # re-judged as peers arrive (async halving without first-arrival
        # survivor bias).
        latest = None
        for rung in self.rungs:
            if rung <= t and trial.trial_id in self.rung_values[rung]:
                latest = rung
        if latest is None:
            return CONTINUE
        records = self.rung_values[latest]
        if len(records) < 2:
            return CONTINUE
        ordered = sorted(records.values(), reverse=(self.mode == "max"))
        keep = max(1, len(ordered) // self.rf)
        cutoff = ordered[keep - 1]
        mine = records[trial.trial_id]
        good = mine >= cutoff if self.mode == "max" else mine <= cutoff
        return CONTINUE if good else STOP


class MedianStoppingRule:
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.history: dict[str, list[float]] = {}

    def on_result(self, trial, result, runner):
        t = result.get("training_iteration", 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        self.history.setdefault(trial.trial_id, []).append(float(value))
        if t < self.grace or len(self.history) < self.min_samples:
            return CONTINUE
        import statistics

        averages = [statistics.fmean(v) for k, v in self.history.items()
                    if k != trial.trial_id and v]
        if len(averages) < self.min_samples - 1:
            return CONTINUE
        median = statistics.median(averages)
        mine = (max if self.mode == "max" else min)(
            self.history[trial.trial_id])
        bad = mine < median if self.mode == "max" else mine > median
        return STOP if bad else CONTINUE


class PopulationBasedTraining:
    """PBT (reference: schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials clone the checkpoint + config of a top-quantile
    trial, with hyperparameters perturbed (explore)."""

    def __init__(self, metric: str, mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 perturbation_factors=(0.8, 1.2), seed: int | None = None):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.factors = perturbation_factors
        self.rng = random.Random(seed)
        self.latest: dict[str, float] = {}
        self.last_perturb: dict[str, int] = {}

    def on_result(self, trial, result, runner):
        value = result.get(self.metric)
        t = result.get("training_iteration", 0)
        if value is None:
            return CONTINUE
        self.latest[trial.trial_id] = float(value)
        if t - self.last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self.last_perturb[trial.trial_id] = t
        ranked = sorted(self.latest.items(), key=lambda kv: kv[1],
                        reverse=(self.mode == "max"))
        n = len(ranked)
        if n < 2:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        top = [tid for tid, _ in ranked[:k]]
        bottom = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and trial.trial_id not in top:
            source_id = self.rng.choice(top)
            source = runner.get_trial(source_id)
            if source is not None and source.latest_checkpoint is not None:
                new_config = self._explore(dict(source.config))
                runner.exploit(trial, source, new_config)
        return CONTINUE

    def _explore(self, config: dict) -> dict:
        for key, mutation in self.mutations.items():
            if key not in config:
                continue
            if callable(mutation):
                config[key] = mutation()
            elif isinstance(mutation, list):
                config[key] = self.rng.choice(mutation)
            else:   # numeric perturbation of the current value
                config[key] = config[key] * self.rng.choice(self.factors)
        return config


class PB2(PopulationBasedTraining):
    """Population-based bandits (reference: tune/schedulers/pb2.py):
    PBT's exploit step, but explore picks the NEXT hyperparameters by
    maximizing a GP-UCB acquisition fit on (hyperparams, time) ->
    score-improvement observations, instead of random perturbation —
    sample-efficient on small populations. GP: RBF kernel + cholesky on
    the (tiny) observation set, UCB argmax over uniform candidate draws
    inside `hyperparam_bounds`.
    """

    def __init__(self, metric: str, mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_bounds: dict | None = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.5, n_candidates: int = 64,
                 seed: int | None = None):
        super().__init__(metric, mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds "
                             "{key: (low, high)}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        # observations: (normalized hparam vector + t, score delta)
        self._X: list[list[float]] = []
        self._y: list[float] = []
        self._last_score: dict[str, float] = {}

    def on_result(self, trial, result, runner):
        value = result.get(self.metric)
        if value is not None:
            prev = self._last_score.get(trial.trial_id)
            if prev is not None:
                delta = float(value) - prev
                if self.mode == "min":
                    delta = -delta
                self._X.append(self._featurize(
                    trial.config, result.get("training_iteration", 0)))
                self._y.append(delta)
            self._last_score[trial.trial_id] = float(value)
        return super().on_result(trial, result, runner)

    def _featurize(self, config: dict, t: float) -> list[float]:
        x = []
        for k, (lo, hi) in sorted(self.bounds.items()):
            v = float(config.get(k, lo))
            x.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        x.append(float(t) / (self.interval * 10.0))
        return x

    def _explore(self, config: dict) -> dict:
        import numpy as np

        out = dict(config)
        keys = sorted(self.bounds)
        cands = []
        for _ in range(self.n_candidates):
            cands.append({k: self.rng.uniform(*self.bounds[k])
                          for k in keys})
        if len(self._y) < 4:
            # not enough observations for the GP: uniform resample
            out.update(cands[0])
            return out
        X = np.asarray(self._X[-128:], dtype=np.float64)
        y = np.asarray(self._y[-128:], dtype=np.float64)
        y = (y - y.mean()) / (y.std() + 1e-8)
        t_now = max((x[-1] for x in self._X), default=0.0)
        C = np.asarray([self._featurize(c, 0)[:-1] + [t_now]
                        for c in cands])

        def rbf(a, b, ls=0.3):
            d = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-d / (2 * ls * ls))

        K = rbf(X, X) + 1e-4 * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
        Ks = rbf(C, X)
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 - (v * v).sum(0), 1e-9, None)
        best = int(np.argmax(mu + self.kappa * np.sqrt(var)))
        out.update(cands[best])
        return out


class HyperBandForBOHB(AsyncHyperBandScheduler):
    """BOHB's scheduler half (reference: tune/schedulers/hb_bohb.py):
    successive-halving rungs (inherited) that additionally FEED every
    rung-level observation to the paired BOHBSearcher, so the model
    samples from the highest rung with enough data. Pair with
    `search.BOHBSearcher` in TuneConfig."""

    def __init__(self, *args, searcher=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._bohb_searcher = searcher

    def attach_searcher(self, searcher):
        self._bohb_searcher = searcher

    def on_result(self, trial, result, runner):
        if self._bohb_searcher is not None and \
                result.get(self.metric) is not None:
            self._bohb_searcher.observe_rung(
                trial.config, result.get("training_iteration", 0),
                float(result[self.metric]))
        return super().on_result(trial, result, runner)
