from ray_tpu.data.dataset_pipeline import DatasetPipeline  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    ActorPoolStrategy,
    Dataset,
    GroupedDataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)
