from ray_tpu.data.dataset_pipeline import DatasetPipeline  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    ActorPoolStrategy,
    Dataset,
    GroupedDataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)
from ray_tpu.data.random_access import RandomAccessDataset  # noqa: F401
