"""DatasetPipeline — windowed, pipelined dataset execution.

Reference: python/ray/data/dataset_pipeline.py +
_internal/pipeline_executor.py. A pipeline is an ordered list of Dataset
windows; transforms apply per-window lazily, and consumption overlaps
stage execution: while window i's batches are being consumed, window i+1's
stage tasks are already submitted (its block refs are futures resolving in
the background). On TPU this composes with
`iter_batches(device_put=True)`'s batch lookahead: disk → host transform →
HBM all run concurrently.
"""
from __future__ import annotations

import itertools


class DatasetPipeline:
    def __init__(self, windows: list, loop: bool = False):
        self._windows = list(windows)
        self._loop = loop

    # ------------------------------------------------------------ transforms
    def _per_window(self, method: str, *args, **kwargs) -> "DatasetPipeline":
        return DatasetPipeline(
            [getattr(w, method)(*args, **kwargs) for w in self._windows],
            loop=self._loop)

    def map(self, fn):
        return self._per_window("map", fn)

    def map_batches(self, fn, **kw):
        return self._per_window("map_batches", fn, **kw)

    def filter(self, fn):
        return self._per_window("filter", fn)

    def flat_map(self, fn):
        return self._per_window("flat_map", fn)

    def random_shuffle_each_window(self, *, seed=None):
        return DatasetPipeline(
            [w.random_shuffle(seed=seed) for w in self._windows],
            loop=self._loop)

    def repeat(self, times: int | None = None) -> "DatasetPipeline":
        if times is None:
            return DatasetPipeline(self._windows, loop=True)
        return DatasetPipeline(self._windows * times, loop=False)

    # ----------------------------------------------------------- consumption
    def _window_iter(self):
        if self._loop:
            return itertools.cycle(self._windows)
        return iter(self._windows)

    def iter_datasets(self):
        """Yield materialized windows with one-window lookahead: the next
        window's stage tasks are submitted (async) before the current
        window is handed to the consumer."""
        it = self._window_iter()
        try:
            current = next(it).materialize()
        except StopIteration:
            return
        for upcoming in it:
            upcoming = upcoming.materialize()   # submits tasks, no blocking
            yield current
            current = upcoming
        yield current

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     device_put: bool = False, drop_last: bool = False):
        """One batch stream over ALL windows: the batch remainder is
        carried across window boundaries, so only the FINAL batch may be
        short (honoring ``drop_last``) — per-window batching used to emit
        a partial batch at every window edge. Streaming mode (default)
        runs one bounded-prefetch executor across windows, so window
        i+1's stage tasks execute while window i's batches are consumed;
        ``RAY_TPU_DATA_STREAMING=0`` keeps the legacy one-window
        lookahead with identical batch output."""
        from ray_tpu.data._internal.streaming import iterator as _si

        yield from _si.pipeline_iter_batches(
            self, batch_size=batch_size, batch_format=batch_format,
            device_put=device_put, drop_last=drop_last)

    def iter_rows(self):
        for ds in self.iter_datasets():
            yield from ds.iter_rows()

    def take(self, limit: int = 20) -> list:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def count(self) -> int:
        if self._loop:
            raise ValueError("count() on an infinite (repeat()) pipeline")
        return sum(ds.count() for ds in self._windows)

    def num_windows(self) -> int:
        return len(self._windows)

    def __repr__(self):
        return (f"DatasetPipeline(windows={len(self._windows)}, "
                f"loop={self._loop})")
