"""Streaming data-plane execution (reference: ray.data's streaming
executor, _internal/execution/streaming_executor.py — the Dataset layer
of the Ray paper, arXiv:1712.05889, with the "keep the chips busy"
discipline of arXiv:2011.03641).

`Dataset.iter_batches` / `DatasetPipeline` ride this by default; the
legacy materialize-then-iterate path is the bit-identical kill switch
``RAY_TPU_DATA_STREAMING=0`` (cataloged in `_private/knobs.py`).
"""
from ray_tpu.data._internal.streaming.executor import (  # noqa: F401
    StreamingExecutor,
    last_executor,
    prefetch_budget,
    streaming_enabled,
)
