"""Task-side re-blocking: repartition / zip / uneven split without
driver materialization.

The legacy implementations pulled every row onto the driver with
``take_all()`` and re-created blocks with ``from_items`` — O(dataset) on
the driver for ops whose output lives in the object store anyway. Here
the driver only sees per-block ROW COUNTS (a handful of ints); slicing
and concatenation run as remote tasks over block refs, and the output
blocks never leave the store (reference: ray.data's split_at_indices /
zip over block lists, _internal/split.py).

Row-range math mirrors ``from_items`` exactly (chunk = ceil(total/n),
contiguous ranges, possibly-empty tails), and ``_slice_concat`` re-
columnarizes row-list merges, so output content matches the legacy
driver path row for row.
"""
from __future__ import annotations

import builtins

import ray_tpu
from ray_tpu.data import block as B

_count_task = None
_slice_concat_task = None
_zip_task = None


def _exec_chain(stages, blk):
    for fn in stages:
        blk = fn(blk)
    return blk


def _count_block(stages, blk) -> int:
    return B.num_rows(_exec_chain(stages, blk))


def _get_count_task():
    global _count_task
    if _count_task is None:
        _count_task = ray_tpu.remote(_count_block)
    return _count_task


def _slice_concat(pieces, *blocks):
    """pieces: [(block_pos, lo, hi), ...] row ranges over the positional
    block args; returns one merged block. Row-list merges are re-
    columnarized for representation parity with the legacy
    ``columnarize(rows)`` path."""
    parts = [B.slice_block(blocks[p], lo, hi) for (p, lo, hi) in pieces]
    if not parts:
        return []
    merged = B.concat_blocks(parts) if len(parts) > 1 else parts[0]
    if not B.is_columnar(merged) and isinstance(merged, list):
        merged = B.columnarize(merged)
    return merged


def _get_slice_concat_task():
    global _slice_concat_task
    if _slice_concat_task is None:
        _slice_concat_task = ray_tpu.remote(_slice_concat)
    return _slice_concat_task


def _zip_slices(a_pieces, b_pieces, n_a, *blocks):
    """Zip row ranges of two datasets' blocks into one list block of
    (row_a, row_b) tuples — the exact row shape the legacy
    ``list(zip(take_all, take_all))`` path produced. The first ``n_a``
    positional blocks belong to the left dataset."""
    rows_a = [row
              for (p, lo, hi) in a_pieces
              for row in B.to_rows(B.slice_block(blocks[p], lo, hi))]
    rows_b = [row
              for (p, lo, hi) in b_pieces
              for row in B.to_rows(B.slice_block(blocks[n_a + p], lo, hi))]
    return B.columnarize(list(zip(rows_a, rows_b)))


def _get_zip_task():
    global _zip_task
    if _zip_task is None:
        _zip_task = ray_tpu.remote(_zip_slices)
    return _zip_task


def block_counts(refs) -> list[int]:
    """Row count per block via remote tasks (ints to the driver, never
    rows)."""
    task = _get_count_task()
    return ray_tpu.get([task.remote([], r) for r in refs])


def _ranges_for(start: int, stop: int, offsets: list[int]):
    """Map a global row range onto per-block (block_idx, lo, hi) pieces.
    ``offsets`` are the blocks' global start offsets plus a final total."""
    pieces = []
    for i in builtins.range(len(offsets) - 1):
        b_lo, b_hi = offsets[i], offsets[i + 1]
        lo, hi = max(start, b_lo), min(stop, b_hi)
        if lo < hi:
            pieces.append((i, lo - b_lo, hi - b_lo))
    return pieces


def _offsets(counts: list[int]) -> list[int]:
    out = [0]
    for c in counts:
        out.append(out[-1] + c)
    return out


def repartition_refs(refs, num_blocks: int) -> list:
    """Re-block ``refs`` into ``num_blocks`` output block refs with the
    ``from_items`` chunking (contiguous, chunk = ceil(total/n))."""
    counts = block_counts(refs)
    total = sum(counts)
    offsets = _offsets(counts)
    n = max(1, min(num_blocks, total or 1))
    chunk = (total + n - 1) // n if total else 0
    task = _get_slice_concat_task()
    out = []
    for j in builtins.range(n):
        start, stop = j * chunk, min((j + 1) * chunk, total)
        pieces = _ranges_for(start, stop, offsets)
        needed = sorted({p for (p, _, _) in pieces})
        remap = {p: k for k, p in enumerate(needed)}
        local = [(remap[p], lo, hi) for (p, lo, hi) in pieces]
        out.append(task.remote(local, *[refs[p] for p in needed]))
    return out


def zip_refs(a_refs, b_refs, num_blocks: int) -> list:
    """Pair rows of two materialized datasets (truncating to the
    shorter), producing ``num_blocks``-chunked list blocks of tuples —
    task-side, matching the legacy driver zip row for row."""
    a_counts, b_counts = block_counts(a_refs), block_counts(b_refs)
    total = min(sum(a_counts), sum(b_counts))
    a_off, b_off = _offsets(a_counts), _offsets(b_counts)
    n = max(1, min(num_blocks, total or 1))
    chunk = (total + n - 1) // n if total else 0
    task = _get_zip_task()
    out = []
    for j in builtins.range(n):
        start, stop = j * chunk, min((j + 1) * chunk, total)
        a_pieces = _ranges_for(start, stop, a_off)
        b_pieces = _ranges_for(start, stop, b_off)
        a_need = sorted({p for (p, _, _) in a_pieces})
        b_need = sorted({p for (p, _, _) in b_pieces})
        a_map = {p: k for k, p in enumerate(a_need)}
        b_map = {p: k for k, p in enumerate(b_need)}
        out.append(task.remote(
            [(a_map[p], lo, hi) for (p, lo, hi) in a_pieces],
            [(b_map[p], lo, hi) for (p, lo, hi) in b_pieces],
            len(a_need),
            *[a_refs[p] for p in a_need],
            *[b_refs[p] for p in b_need]))
    return out


def split_refs_uneven(refs, n: int) -> list[list]:
    """Uneven split: one single-block shard per split, with the legacy
    row chunking (chunk = ceil(total/n); trailing shards may be empty)."""
    counts = block_counts(refs)
    total = sum(counts)
    offsets = _offsets(counts)
    chunk = (total + n - 1) // n if total else 0
    task = _get_slice_concat_task()
    shards = []
    for j in builtins.range(n):
        start, stop = j * chunk, min((j + 1) * chunk, total)
        if total == 0 or start >= stop:
            shards.append([ray_tpu.put([])])
            continue
        pieces = _ranges_for(start, stop, offsets)
        needed = sorted({p for (p, _, _) in pieces})
        remap = {p: k for k, p in enumerate(needed)}
        local = [(remap[p], lo, hi) for (p, lo, hi) in pieces]
        shards.append([task.remote(local, *[refs[p] for p in needed])])
    return shards
