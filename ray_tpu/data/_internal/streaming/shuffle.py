"""Collective-exchange shuffle: the block-partition all-to-all riding
the host collective plane.

The default ``random_shuffle`` exchanges its n*n partitions as object-
store refs (n map tasks emit n partitions each, n reduce tasks each pull
one partition per block). With ``RAY_TPU_DATA_SHUFFLE_COLLECTIVE=1`` the
exchange instead runs on a gang of n actors joined into a host
collective group: each actor partitions its block and sends partition j
straight to actor j over the PR 4 pipelined one-way segment path (same-
node peers ride the ``put_ephemeral`` shm frames, cross-node peers the
segmented zero-copy socket frames) — no per-partition object-store
round trip, and the exchange shows up in the collective telemetry
plane like any other op.

Partition/permutation math is IDENTICAL to the task-based path (same
per-block seed derivation, same merge order, same final permutation),
so both paths produce the same rows for the same seed — which is also
the test oracle. Any failure falls back to the task-based shuffle.
"""
from __future__ import annotations

import builtins
import os
import pickle

import numpy as np

import ray_tpu
from ray_tpu.data import block as B


def shuffle_collective_enabled() -> bool:
    return os.environ.get("RAY_TPU_DATA_SHUFFLE_COLLECTIVE", "0") == "1"


class _ExchangeWorker:
    """Actor body: one rank of the shuffle exchange gang."""

    def setup(self, world: int, rank: int, group: str) -> int:
        from ray_tpu.util import collective as col

        col.init_collective_group(world, rank, "host", group)
        return rank

    def exchange(self, group: str, world: int, rank: int,
                 seed_base: int, stages, block):
        from ray_tpu.util import collective as col

        for fn in stages:
            block = fn(block)
        rows_n = B.num_rows(block)
        rng = np.random.default_rng(seed_base + rank)
        perm = rng.permutation(rows_n)
        parts = [B.take_indices(block, idx)
                 for idx in np.array_split(perm, world)]
        got = {rank: parts[rank]}
        # cyclic-shift schedule: at offset k every rank sends to rank+k
        # and receives from rank-k. Sends are one-way pushes (PR 4), so
        # the whole round is deadlock-free without pairwise ordering.
        for off in builtins.range(1, world):
            dst = (rank + off) % world
            src = (rank - off) % world
            blob = np.frombuffer(
                pickle.dumps(parts[dst],
                             protocol=pickle.HIGHEST_PROTOCOL),
                dtype=np.uint8)
            col.send(blob, dst, group)
            got[src] = pickle.loads(
                np.asarray(col.recv(src, group)).tobytes())
        # merge in BLOCK order (not arrival order) — the task-based
        # reduce concatenates partition i of block 0..n-1 in order, and
        # matching it keeps the two paths row-identical per seed
        merged = B.concat_blocks([got[b] for b in builtins.range(world)])
        rng2 = np.random.default_rng((seed_base ^ 0x5EED) + rank)
        return B.take_indices(merged,
                              rng2.permutation(B.num_rows(merged)))

    def teardown(self, group: str):
        from ray_tpu.util import collective as col

        try:
            col.destroy_collective_group(group)
        except Exception:
            pass
        return True


def shuffle_via_collective(ds, seed_base: int):
    """Run the all-to-all on a collective actor gang; returns the output
    block refs, or None when the path does not apply (world < 2)."""
    n = ds.num_blocks
    if n < 2:
        return None
    group = f"data_shuffle_{os.urandom(4).hex()}"
    worker_cls = ray_tpu.remote(_ExchangeWorker)
    actors = [worker_cls.remote() for _ in builtins.range(n)]
    try:
        ray_tpu.get([a.setup.remote(n, i, group)
                     for i, a in enumerate(actors)], timeout=120)
        out = [actors[i].exchange.remote(group, n, i, seed_base,
                                         ds._stages, ref)
               for i, ref in enumerate(ds._block_refs)]
        # block until every exchange result is SEALED somewhere before
        # the gang tears down (the data stays in the object store; the
        # driver never sees rows)
        _, not_ready = ray_tpu.wait(out, num_returns=n, timeout=300,
                                    fetch_local=False)
        if not_ready:
            raise TimeoutError(
                f"collective shuffle exchange stalled "
                f"({len(not_ready)}/{n} ranks pending)")
        ray_tpu.get([a.teardown.remote(group) for a in actors],
                    timeout=30)
        return out
    finally:
        for a in actors:
            try:
                ray_tpu.kill(a, no_restart=True)
            except Exception:
                pass
