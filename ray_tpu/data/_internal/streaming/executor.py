"""Pull-based streaming block executor with a bounded in-flight budget.

Reference: python/ray/data/_internal/execution/streaming_executor.py —
operators pull blocks on demand and a resource budget bounds how much of
the dataset is materialized at once. Here the unit is one block:

- **Bounded in-flight budget.** At most ``RAY_TPU_DATA_PREFETCH_BLOCKS``
  (default 4) blocks per consumer are alive between the consumer's read
  position and the furthest submitted map task — buffered blocks, fetches
  in flight, and submitted-but-unfetched tasks all count. Map-stage tasks
  are submitted lazily as the consumer advances (the legacy path submits
  one task per block up front), so a dataset much larger than host RAM
  streams at bounded memory.
- **Per-consumer backpressure.** Fetch workers park on the executor's
  condition when the consumer's buffer is full and wake when the consumer
  drains a slot — a slow train step stops the producers instead of
  growing an unbounded queue.
- **Shm-staged prefetch (zero-copy).** A prefetched block is held as a
  `PinnedBuffer` view into the node's shared-memory object store whenever
  the bytes are there (task results and `ray_tpu.put` blocks always are);
  borrower-inline bytes that arrive on the heap are re-staged into the
  store via the PR 4 ``put_ephemeral`` path. Either way the prefetch
  buffer holds store-accounted pins, not heap copies — deserialization
  happens once, at consume time, exactly like the legacy get path.
- **Locality-aware pull ordering.** Within the prefetch window, blocks
  that already have a local copy are pulled first (they complete
  instantly into the buffer) while remote blocks start their pulls in
  dataset order — delivery order to the consumer is always dataset
  order, so streaming output is bit-identical to the legacy path.
- **Fault tolerance.** Each block fetch runs under the unified
  `_private/retry.py` policy (method ``data_block_fetch``, registered
  retry-safe: it is a pure read); the seeded fault-injection plane is
  consulted at the same boundary so chaos schedules like
  ``drop:data_block_fetch:#2`` exercise the retry path deterministically.

Telemetry (all off under ``RAY_TPU_INTERNAL_TELEMETRY=0``):
``ray_tpu_data_blocks_total{consumer,source=local|remote}`` and the
``ray_tpu_data_prefetch_depth_blocks{consumer}`` gauge live here;
``ray_tpu_data_wait_seconds{consumer}`` is stamped by the batch iterator
(`iterator.py`).
"""
from __future__ import annotations

import os
import threading
import time

from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import telemetry as _tm

DEFAULT_PREFETCH_BLOCKS = 4

# Heap-held fetched bytes at least this big are re-staged into the shm
# store (put_ephemeral) so the prefetch buffer stays store-accounted.
STAGE_MIN_BYTES = 32 * 1024

_STAGE_PREFIX = b"dstrm"


def streaming_enabled() -> bool:
    """RAY_TPU_DATA_STREAMING=0 is the legacy-path kill switch."""
    return os.environ.get("RAY_TPU_DATA_STREAMING", "1") != "0"


def prefetch_budget() -> int:
    try:
        v = int(os.environ.get("RAY_TPU_DATA_PREFETCH_BLOCKS",
                               str(DEFAULT_PREFETCH_BLOCKS)))
    except ValueError:
        v = DEFAULT_PREFETCH_BLOCKS
    return max(1, v)


_last_executor: "StreamingExecutor | None" = None


def last_executor() -> "StreamingExecutor | None":
    """The most recently constructed executor in this process (tests and
    the data-wait summary introspect its stats). A strong reference is
    deliberate: a closed executor holds no buffers, and the weakref
    would die with the generator chain the moment iteration finishes."""
    return _last_executor


class DataFetchDropped(Exception):
    """A block fetch was dropped by the fault-injection plane (chaos
    schedules with method ``data_block_fetch``) — transient by contract,
    retried by the executor's RetryPolicy."""


def _mint_stage_id() -> bytes:
    return _STAGE_PREFIX + os.urandom(16 - len(_STAGE_PREFIX))


_NO_VALUE = object()


class _Slot:
    """One fetched block parked in the prefetch buffer: raw heap bytes,
    a pinned zero-copy view into the shm store (optionally an ephemeral
    staging object this executor minted and must delete), or — on the
    no-core-worker fallback (ray:// client mode) — an already-
    deserialized value."""

    __slots__ = ("data", "pin", "stage_id", "error", "value")

    def __init__(self, data=None, pin=None, stage_id=None, error=None,
                 value=_NO_VALUE):
        self.data = data
        self.pin = pin
        self.stage_id = stage_id
        self.error = error
        self.value = value

    def view(self):
        return self.pin.memoryview() if self.pin is not None else self.data

    def release(self, store=None):
        if self.pin is not None:
            try:
                self.pin.release()
            except Exception:
                pass
            self.pin = None
        if self.stage_id is not None and store is not None:
            try:
                store.delete_ephemeral(self.stage_id)
            except Exception:
                pass
            self.stage_id = None
        self.data = None


class StreamingExecutor:
    """Stream blocks, in order, from an iterable of block sources.

    ``items`` yields opaque sources (possibly an infinite generator — a
    looping DatasetPipeline); ``submit(source) -> ObjectRef`` turns one
    into a block ref, submitting its map-stage task on demand. Blocks
    are delivered to exactly one consumer via :meth:`iter_blocks`.
    """

    def __init__(self, items, submit=None, *, budget: int | None = None,
                 consumer: str = "default", fetch_threads: int = 2):
        global _last_executor
        self._items = iter(items)
        self._submit = submit if submit is not None else (lambda s: s)
        self._budget = budget if budget is not None else prefetch_budget()
        self._budget = max(1, int(self._budget))
        self.consumer = consumer
        self._cond = threading.Condition()
        # index spaces: [0, _next_claim) claimed from the iterator,
        # [0, _next_yield) delivered to the consumer. Live indices are
        # always within [_next_yield, _next_yield + budget).
        self._next_claim = 0
        self._next_yield = 0
        self._pending: dict[int, object] = {}   # idx -> block ref
        self._inflight: set[int] = set()
        self._buffer: dict[int, _Slot] = {}
        self._exhausted = False
        self._closed = False
        self._started = False
        # observability / test oracles
        self.peak_buffered_blocks = 0
        self.blocks_local = 0
        self.blocks_remote = 0
        self.fetch_order: list[int] = []
        n_threads = max(1, min(int(fetch_threads), self._budget))
        self._threads = [
            threading.Thread(target=self._fetch_loop, daemon=True,
                             name=f"data-stream-fetch-{i}")
            for i in range(n_threads)
        ]
        _last_executor = self

    # ------------------------------------------------------------ plumbing

    def _worker(self):
        from ray_tpu._private.worker_runtime import current_worker

        return current_worker()

    def _note_peak_locked(self):
        live = len(self._buffer) + len(self._inflight) + len(self._pending)
        if live > self.peak_buffered_blocks:
            self.peak_buffered_blocks = live

    def _refill(self):
        """Claim sources from the item iterator up to the budget window
        and submit their map tasks (submission is non-blocking). Called
        at start and every time the consumer frees a slot, so task
        submission never waits behind a blocked fetch."""
        while True:
            with self._cond:
                if (self._closed or self._exhausted
                        or self._next_claim
                        >= self._next_yield + self._budget):
                    return
                idx = self._next_claim
                try:
                    source = next(self._items)
                except StopIteration:
                    self._exhausted = True
                    self._cond.notify_all()
                    return
                self._next_claim += 1
            # submit OUTSIDE the lock: task submission touches the lease
            # pipeline and must not serialize the consumer/fetchers
            try:
                ref = self._submit(source)
                err = None
            except BaseException as e:  # noqa: BLE001 — delivered in order
                ref, err = None, e
            with self._cond:
                if err is not None:
                    self._buffer[idx] = _Slot(error=err)
                else:
                    self._pending[idx] = ref
                self._note_peak_locked()
                self._cond.notify_all()

    def _is_local(self, ref) -> bool:
        """Does this node already hold the bytes (no network pull)?"""
        try:
            w = self._worker()
            if w.memory_store.get_nowait(ref.id) is not None:
                return True
            if ref.id in w._ref_to_task:
                return False   # still producing: not fetchable yet
            return w.store.contains(ref.id)
        except Exception:
            return False

    def _pick(self) -> tuple[int, object] | None:
        """Choose the next pending index to fetch: same-node blocks
        first (they fill the buffer instantly), remote blocks in dataset
        order otherwise. Locality probes run outside the lock."""
        with self._cond:
            candidates = sorted(self._pending)
        if not candidates:
            return None
        choice = None
        for idx in candidates:
            with self._cond:
                ref = self._pending.get(idx)
            if ref is None:
                continue
            if self._is_local(ref):
                choice = idx
                break
            if choice is None:
                choice = idx   # lowest remote index as the fallback
        if choice is None:
            return None
        with self._cond:
            ref = self._pending.pop(choice, None)
            if ref is None:
                return None   # raced another fetcher
            self._inflight.add(choice)
            return choice, ref

    def _fetch_loop(self):
        while True:
            with self._cond:
                if self._closed:
                    return
                done = (self._exhausted and not self._pending
                        and not self._inflight and not self._buffer
                        and self._next_yield >= self._next_claim)
                if done:
                    self._cond.notify_all()
                    return
                has_work = bool(self._pending)
                if not has_work:
                    self._cond.wait(0.2)
                    continue
            picked = self._pick()
            if picked is None:
                continue
            idx, ref = picked
            try:
                slot, source = self._fetch_one(ref)
            except BaseException as e:  # noqa: BLE001 — surfaced in order
                slot, source = _Slot(error=e), None
            with self._cond:
                self._inflight.discard(idx)
                if self._closed:
                    slot.release(self._store_or_none())
                    return
                self._buffer[idx] = slot
                if source == "local":
                    self.blocks_local += 1
                elif source == "remote":
                    self.blocks_remote += 1
                self.fetch_order.append(idx)
                self._note_peak_locked()
                depth = len(self._buffer)
                self._cond.notify_all()
            if source is not None:
                _tm.counter_inc("ray_tpu_data_blocks_total",
                                tags={"consumer": self.consumer,
                                      "source": source})
                _tm.gauge_set("ray_tpu_data_prefetch_depth_blocks", depth,
                              tags={"consumer": self.consumer})

    def _store_or_none(self):
        try:
            return self._worker().store
        except Exception:
            return None

    # ------------------------------------------------------------ fetching

    def _fetch_one(self, ref) -> tuple[_Slot, str]:
        """Materialize one block's serialized bytes locally, under the
        unified retry policy. Returns (slot, "local"|"remote")."""
        from ray_tpu._private.retry import RetryPolicy

        policy = RetryPolicy.from_config()
        return policy.run(
            lambda timeout: self._fetch_once(ref, timeout),
            method="data_block_fetch",
            retry_on=(DataFetchDropped, TimeoutError, ConnectionError,
                      OSError))

    def _fetch_once(self, ref, timeout) -> tuple[_Slot, str]:
        if _fi.ACTIVE is not None:
            plan = _fi.ACTIVE.on_send("data_block_fetch")
            if plan is not None:
                if plan.delay_s:
                    time.sleep(plan.delay_s)
                if plan.drop or plan.disconnect:
                    raise DataFetchDropped(
                        f"injected drop fetching block {ref.hex()}")
        w = self._worker()
        if w is None:
            # no core worker in this process (ray:// client mode): the
            # proxied get is the only fetch path — no staging, no pins
            import ray_tpu

            return _Slot(value=ray_tpu.get(ref, timeout=timeout)), "remote"
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            data = w.memory_store.get_nowait(ref.id)
            if data is not None:
                return self._stage(w, data), "local"
            if ref.id not in w._ref_to_task:
                buf = w.store.get(ref.id)
                if buf is not None:
                    if hasattr(buf, "view"):
                        # spill-backed host buffer: its memoryview keeps
                        # the backing alive, nothing to pin
                        return _Slot(data=buf.view()), "local"
                    return _Slot(pin=buf), "local"
                # not on this node: one bounded remote resolution round
                remaining = (None if deadline is None
                             else max(0.1, deadline - time.monotonic()))
                data = w._fetch_bytes(ref, remaining)
                # the pull caches big objects into local shm — prefer a
                # pinned zero-copy view over the heap copy it returned
                buf = w.store.get(ref.id)
                if buf is not None and not hasattr(buf, "view"):
                    return _Slot(pin=buf), "remote"
                return self._stage(w, data), "remote"
            # our own producing task is still running: wait on the owner
            # memory-store future like _fetch_bytes does
            entry = w.memory_store.entry(ref.id)
            entry.event.wait(0.05)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"streaming fetch timed out for block {ref.hex()}")

    def _stage(self, w, data) -> _Slot:
        """Heap bytes → shm-staged pin via put_ephemeral when big enough
        (bounded heap while buffered; zero-copy view back out). Store
        pressure falls back to holding the heap bytes."""
        try:
            if len(data) >= STAGE_MIN_BYTES:
                from ray_tpu._private import memory_anatomy as _ma

                stage_id = _mint_stage_id()
                with _ma.tagged("data_staging", owner=self.consumer):
                    w.store.put_ephemeral(stage_id, [data])
                pin = w.store.get(stage_id)
                if pin is not None and not hasattr(pin, "view"):
                    return _Slot(pin=pin, stage_id=stage_id)
                w.store.delete_ephemeral(stage_id)
        except Exception:
            pass
        return _Slot(data=data)

    # ----------------------------------------------------------- consuming

    def start(self):
        if self._started:
            return self
        self._started = True
        self._refill()
        for t in self._threads:
            t.start()
        return self

    def iter_blocks(self):
        """Yield deserialized blocks in dataset order. Closing the
        generator (or exhausting it) releases every buffered pin."""
        from ray_tpu._private import serialization as ser

        self.start()
        try:
            while True:
                with self._cond:
                    while True:
                        slot = self._buffer.pop(self._next_yield, None)
                        if slot is not None:
                            self._next_yield += 1
                            self._cond.notify_all()
                            break
                        if (self._exhausted and not self._pending
                                and not self._inflight
                                and self._next_yield >= self._next_claim):
                            return
                        if self._closed:
                            return
                        self._cond.wait(0.5)
                _tm.gauge_set("ray_tpu_data_prefetch_depth_blocks",
                              len(self._buffer),
                              tags={"consumer": self.consumer})
                # refill NOW (not after the yield): the freed budget slot
                # starts its fetch while the caller is still computing on
                # the previous batch
                self._refill()
                if slot.error is not None:
                    err = slot.error
                    slot.release(self._store_or_none())
                    raise err
                if slot.value is not _NO_VALUE:
                    yield slot.value
                    continue
                try:
                    # one copy out of the pinned store view, exactly like
                    # the legacy get path (deserialize may keep zero-copy
                    # numpy views of the input, so the input must outlive
                    # the block — heap bytes do, a released pin may not)
                    view = slot.view()
                    data = bytes(view) if slot.pin is not None else view
                finally:
                    slot.release(self._store_or_none())
                value, meta = ser.deserialize(data, self._worker(),
                                              with_meta=True)
                if meta.get("raised") and isinstance(value, BaseException):
                    raise value
                yield value
        finally:
            self.close()

    def close(self):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            slots = list(self._buffer.values())
            self._buffer.clear()
            self._pending.clear()
            self._cond.notify_all()
        store = self._store_or_none()
        for slot in slots:
            slot.release(store)

    def stats(self) -> dict:
        with self._cond:
            return {
                "consumer": self.consumer,
                "budget": self._budget,
                "peak_buffered_blocks": self.peak_buffered_blocks,
                "blocks_local": self.blocks_local,
                "blocks_remote": self.blocks_remote,
                "consumed": self._next_yield,
                "buffered": len(self._buffer),
            }

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
