"""Batch assembly over a block stream + the device-put double buffer.

One batching loop serves every path — legacy (``RAY_TPU_DATA_STREAMING=0``)
and streaming, Dataset and DatasetPipeline — so streaming output is
bit-identical to the legacy path by construction, and a pipeline carries
its batch remainder across window boundaries (only the final batch may be
short, honoring ``drop_last``).

With ``device_put=True`` the streaming path double-buffers: a producer
thread assembles batch k+1 (block fetch is already overlapped by the
executor) and dispatches its ``jax.device_put`` while the caller consumes
batch k, so the host→HBM transfer rides under the train step.

Every yielded batch stamps ``ray_tpu_data_wait_seconds{consumer}`` — the
wall time the consumer was blocked waiting for that batch, the "input
gates the step" signal the ROADMAP's <5% data-wait acceptance is measured
by. Off under ``RAY_TPU_INTERNAL_TELEMETRY=0``.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

from ray_tpu._private import telemetry as _tm
from ray_tpu.data import block as B
# jax-free module (parallel/__init__ is empty): the step-anatomy stamps
# below cost one tuple read per batch when no train step is active
from ray_tpu.parallel import step_anatomy as _sa
from ray_tpu.data._internal.streaming.executor import (
    StreamingExecutor,
    streaming_enabled,
)


def iter_batch_blocks(blocks, batch_size: int, drop_last: bool):
    """Slice a block stream into batch-sized blocks: numpy views + one
    concat per batch, zero per-row Python for columnar blocks (the exact
    assembly the legacy ``iter_batches`` loop used — kept verbatim so
    both paths produce identical bytes)."""
    pending: list = []       # partial blocks carried across block refs
    pending_n = 0
    for blk in blocks:
        pending.append(blk)
        pending_n += B.num_rows(blk)
        while pending_n >= batch_size:
            take, taken = [], 0
            while taken < batch_size:
                head = pending[0]
                hn = B.num_rows(head)
                need = batch_size - taken
                if hn <= need:
                    take.append(head)
                    taken += hn
                    pending.pop(0)
                else:
                    take.append(B.slice_block(head, 0, need))
                    pending[0] = B.slice_block(head, need, hn)
                    taken += need
            pending_n -= batch_size
            yield (B.concat_blocks(take) if len(take) > 1 else take[0])
    if pending_n and not drop_last:
        yield B.concat_blocks(pending)


def make_to_batch(batch_format: str, device_put: bool):
    def to_batch(blk):
        if batch_format == "numpy":
            batch = B.to_numpy_batch(blk)
        else:
            batch = B.to_rows(blk)
        if device_put:
            import jax

            batch = jax.device_put(batch)
        return batch

    return to_batch


def stamp_wait(gen, consumer: str):
    """Wrap a batch generator, observing the consumer-blocked time per
    batch (production time of each __next__). When a train step is
    active, the same interval goes to the step-anatomy ring as an
    EXPOSED ``data_wait`` activity — the input-gated share of that
    step, joined by step_id."""
    while True:
        t0 = time.perf_counter()
        m0 = time.monotonic()
        try:
            batch = next(gen)
        except StopIteration:
            return
        wait = time.perf_counter() - t0
        _tm.observe("ray_tpu_data_wait_seconds", wait,
                    tags={"consumer": consumer})
        _sa.record_activity("data_wait", m0, m0 + wait, blocking=True,
                            consumer=consumer)
        yield batch


def _double_buffered(batch_blocks, to_batch):
    """Producer thread converts (slice + device_put dispatch) batch k+1
    while the caller consumes batch k. Queue depth 2 = one batch in the
    caller's hands, one converted and waiting, one being converted."""
    q: _queue.Queue = _queue.Queue(maxsize=2)
    stop = threading.Event()

    def produce():
        try:
            for bb in batch_blocks:
                m0 = time.monotonic()
                item = ("ok", to_batch(bb))
                # background by construction: this thread's conversion
                # + device_put dispatch is the ingest work that HIDES
                # under the caller's train step — step anatomy reports
                # it as data_hidden (overlap proof for the data plane)
                _sa.record_activity("data_produce", m0, time.monotonic(),
                                    blocking=False)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        break
                    except _queue.Full:
                        continue
                if stop.is_set():
                    return
            while not stop.is_set():
                try:
                    q.put(("end", None), timeout=0.2)
                    return
                except _queue.Full:
                    continue
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            while not stop.is_set():
                try:
                    q.put(("err", e), timeout=0.2)
                    return
                except _queue.Full:
                    continue

    t = threading.Thread(target=produce, daemon=True,
                         name="data-stream-device-put")
    t.start()
    try:
        while True:
            try:
                kind, payload = q.get(timeout=1.0)
            except _queue.Empty:
                if not t.is_alive():
                    return   # producer died without a sentinel
                continue
            if kind == "end":
                return
            if kind == "err":
                raise payload
            yield payload
    finally:
        # The producer OWNS batch_blocks (closing a generator that is
        # executing in another thread raises); stop just flips the flag —
        # the producer exits at its next put, and the caller's executor
        # close unblocks a producer parked inside a block wait.
        stop.set()


def _one_batch_lookahead(batch_blocks, to_batch):
    """The legacy device-feed overlap: convert (and dispatch the device
    transfer of) batch k+1 before yielding batch k. Order and content
    are unchanged — only the conversion timing moves."""
    prev = None
    for bb in batch_blocks:
        batch = to_batch(bb)
        if prev is not None:
            yield prev
        prev = batch
    if prev is not None:
        yield prev


def stream_items(ds):
    """(stages, ref) sources for one Dataset, drawn lazily so the
    executor submits map-stage tasks on demand. ActorPoolStrategy
    datasets keep their eager pool materialization (the pool is sized
    from the block count up front) and stream the resulting refs."""
    from ray_tpu.data.dataset import _ActorPoolStrategy

    compute = getattr(ds, "_compute", None)
    if ds._stages and isinstance(compute, _ActorPoolStrategy):
        for ref in ds._materialized_refs():
            yield (None, ref)
        return
    stages = ds._stages
    for ref in ds._block_refs:
        yield (stages, ref)


def _make_submit():
    from ray_tpu.data.dataset import _get_chain_task

    def submit(item):
        stages, ref = item
        if stages:
            return _get_chain_task().remote(stages, ref)
        return ref

    return submit


def dataset_iter_batches(ds, *, batch_size: int, batch_format: str,
                         device_put: bool, drop_last: bool):
    """The streaming implementation behind ``Dataset.iter_batches``."""
    consumer = getattr(ds, "_consumer", None) or "default"
    to_batch = make_to_batch(batch_format, device_put)
    ex = StreamingExecutor(stream_items(ds), _make_submit(),
                           consumer=consumer)
    batch_blocks = iter_batch_blocks(ex.iter_blocks(), batch_size,
                                     drop_last)
    if device_put:
        gen = _double_buffered(batch_blocks, to_batch)
    else:
        gen = (to_batch(bb) for bb in batch_blocks)
    try:
        yield from stamp_wait(gen, consumer)
    finally:
        ex.close()


def pipeline_iter_batches(pipe, *, batch_size: int, batch_format: str,
                          device_put: bool, drop_last: bool):
    """``DatasetPipeline.iter_batches``: one batch stream over ALL
    windows, carrying the remainder across window boundaries. Streaming
    mode runs one executor over the concatenated window sources (window
    i+1's tasks submit while window i is consumed, bounded by the same
    budget); the kill-switch path fetches window blocks with the legacy
    one-window lookahead — both feed the same batcher, so their batches
    are identical."""
    consumer = getattr(pipe, "_consumer", None) or "default"
    to_batch = make_to_batch(batch_format, device_put)
    ex = None
    if streaming_enabled():
        def items():
            for w in pipe._window_iter():
                yield from stream_items(w)

        ex = StreamingExecutor(items(), _make_submit(), consumer=consumer)
        blocks = ex.iter_blocks()
    else:
        def legacy_blocks():
            import ray_tpu

            for ds in pipe.iter_datasets():
                for ref in ds._materialized_refs():
                    yield ray_tpu.get(ref)

        blocks = legacy_blocks()
    batch_blocks = iter_batch_blocks(blocks, batch_size, drop_last)
    if device_put and ex is not None:
        gen = _double_buffered(batch_blocks, to_batch)
    elif device_put:
        # kill-switch path keeps the legacy one-batch device lookahead
        gen = _one_batch_lookahead(batch_blocks, to_batch)
    else:
        gen = (to_batch(bb) for bb in batch_blocks)
    try:
        yield from stamp_wait(gen, consumer)
    finally:
        if ex is not None:
            ex.close()
