"""Block representations + vectorized block ops.

Reference: python/ray/data/block.py (Block = Arrow table / pandas / list).
TPU-native choice: the columnar format is dict[str, np.ndarray] (or a bare
np.ndarray for untyped datasets) — numpy is what feeds jax.device_put with
zero conversion, so batches slice out of blocks without touching Python
rows. List-of-rows remains the fallback for ragged/object data.

Block kinds:
    np.ndarray                 — columnless typed data
    dict[str, np.ndarray]      — columnar ("table") data
    list                       — rows (dicts or scalars), the slow path
"""
from __future__ import annotations

import numpy as np


def is_columnar(block) -> bool:
    return isinstance(block, np.ndarray) or (
        isinstance(block, dict)
        and all(isinstance(v, np.ndarray) for v in block.values()))


def columnarize(rows: list):
    """Rows → columnar block when the rows are uniform; otherwise return
    the row list unchanged."""
    if not rows:
        return rows
    first = rows[0]
    try:
        if isinstance(first, dict):
            keys = list(first)
            if all(isinstance(r, dict) and list(r) == keys for r in rows):
                cols = {k: np.asarray([r[k] for r in rows]) for k in keys}
                if all(v.dtype != object for v in cols.values()):
                    return cols
            return rows
        # Only scalar-like rows become an array: tuples/lists must survive
        # round trips as tuples/lists (np.asarray would turn ("x", 1) rows
        # into a 2-D unicode array).
        if not isinstance(first, (int, float, complex, str, bytes,
                                  np.generic, np.ndarray)):
            return rows
        arr = np.asarray(rows)
        if arr.dtype == object:
            return rows
        return arr
    except Exception:
        return rows


def num_rows(block) -> int:
    if isinstance(block, np.ndarray):
        return len(block)
    if isinstance(block, dict):
        return len(next(iter(block.values()))) if block else 0
    if hasattr(block, "to_dict") and hasattr(block, "columns"):
        return len(block)
    return len(block)


def slice_block(block, start: int, stop: int):
    if isinstance(block, np.ndarray):
        return block[start:stop]
    if isinstance(block, dict):
        return {k: v[start:stop] for k, v in block.items()}
    if hasattr(block, "iloc"):
        return block.iloc[start:stop]
    return block[start:stop]


def concat_blocks(blocks: list):
    blocks = [b for b in blocks if num_rows(b) > 0]
    if not blocks:
        return []
    first = blocks[0]
    if all(isinstance(b, np.ndarray) for b in blocks):
        return np.concatenate(blocks)
    if all(isinstance(b, dict) and is_columnar(b) for b in blocks):
        keys = list(first)
        if all(list(b) == keys for b in blocks):
            return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    # fallback: rows
    out = []
    for b in blocks:
        out.extend(to_rows(b))
    return out


def to_rows(block) -> list:
    if isinstance(block, np.ndarray):
        return list(block)
    if isinstance(block, dict) and is_columnar(block):
        keys = list(block)
        n = num_rows(block)
        return [{k: block[k][i] for k in keys} for i in range(n)]
    if hasattr(block, "to_dict") and hasattr(block, "columns"):
        return block.to_dict("records")
    return list(block)


def to_numpy_batch(block):
    """Columnar/array block → the numpy batch handed to jax.device_put.
    No per-row Python for columnar blocks."""
    if isinstance(block, np.ndarray):
        return block
    if isinstance(block, dict) and is_columnar(block):
        return block
    rows = to_rows(block)
    if rows and isinstance(rows[0], dict):
        return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
    return np.asarray(rows)


def take_indices(block, idx: np.ndarray):
    """Vectorized row selection (shuffle/partition fast path)."""
    if isinstance(block, np.ndarray):
        return block[idx]
    if isinstance(block, dict) and is_columnar(block):
        return {k: v[idx] for k, v in block.items()}
    rows = to_rows(block)
    return [rows[i] for i in idx]
