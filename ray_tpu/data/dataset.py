"""Distributed Dataset — columnar blocks as object-store refs, lazy plan.

Reference: python/ray/data/dataset.py:138 (Dataset), data/block.py (Block),
_internal/plan.py:46 (ExecutionPlan + Stage), _internal/compute.py:58,173
(TaskPoolStrategy / ActorPoolStrategy), _internal/push_based_shuffle.py,
_internal/sort.py.

Design: a Dataset is a list of block refs plus a chain of not-yet-executed
stages. Blocks are columnar (np.ndarray or dict[str, np.ndarray] — see
data/block.py) with list-of-rows as the ragged-data fallback; map-like
stages fuse and execute one task per block, shuffle partitions blocks with
vectorized numpy index math (no per-row Python on the hot path). TPU-native
additions: `iter_batches(..., device_put=True)` slices batches straight out
of columnar blocks and prefetches the next batch to the chip while the
current one is consumed — the host→HBM feed pipeline that replaces the
reference's `to_torch` pin-memory path. `window()` gives the pipelined
execution of the reference's DatasetPipeline (data/dataset_pipeline.py).
"""
from __future__ import annotations

import builtins
import os
import random as _random

import numpy as np

import ray_tpu
from ray_tpu.data import block as B


def _exec_chain(stages, block):
    for fn in stages:
        block = fn(block)
    return block


_chain_task = None


def _get_chain_task():
    global _chain_task
    if _chain_task is None:
        _chain_task = ray_tpu.remote(_exec_chain)
    return _chain_task


def _write_block(stages, block, write_one, out_path):
    # Runs on the WORKER: create the directory there too — driver and
    # worker need a shared filesystem for distributed writes (same
    # assumption as the reference's local-filesystem datasource; use a
    # network mount for multi-host clusters).
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    write_one(_exec_chain(stages, block), out_path)
    return out_path


_write_task = None


def _get_write_task():
    global _write_task
    if _write_task is None:
        _write_task = ray_tpu.remote(_write_block)
    return _write_task


def _column_values(block, on) -> np.ndarray:
    """Extract the numeric column/values from a block, validating that
    `on` matches the block shape (silently ignoring a bogus column name
    would produce plausible-looking nonsense)."""
    if isinstance(block, dict):
        if on is None:
            raise ValueError(
                f"dataset has named columns {sorted(block)}; pass on=...")
        return np.asarray(block[on], dtype=np.float64)
    if isinstance(block, np.ndarray):
        if on is not None:
            raise ValueError(
                f"on={on!r} given but the dataset has plain values, "
                f"not named columns")
        return block.astype(np.float64, copy=False)
    rows = _rows(block)
    if rows and isinstance(rows[0], dict):
        if on is None:
            raise ValueError(
                f"dataset has named columns {sorted(rows[0])}; pass on=...")
        return np.asarray([row[on] for row in rows], dtype=np.float64)
    if on is not None:
        raise ValueError(
            f"on={on!r} given but the dataset has plain values, "
            f"not named columns")
    return np.asarray(rows, dtype=np.float64)


def _agg_block(stages, block, on):
    """(count, sum, min, max, mean, M2) for one block's column/values —
    M2 = sum((x-mean)^2), so variance merges with Chan's algorithm
    instead of the cancellation-prone sum-of-squares; None for an empty
    block."""
    vals = _column_values(_exec_chain(stages, block), on)
    if vals.size == 0:
        return None
    mean = float(vals.mean())
    return (int(vals.size), float(vals.sum()), float(vals.min()),
            float(vals.max()), mean, float(np.square(vals - mean).sum()))


_agg_task = None


def _get_agg_task():
    global _agg_task
    if _agg_task is None:
        _agg_task = ray_tpu.remote(_agg_block)
    return _agg_task


class _ActorPoolStrategy:
    """(reference: compute.py:173 ActorPoolStrategy) map stages run on a
    pool of long-lived actors — amortizes heavyweight per-process state
    (e.g. a compiled jax program or loaded model) across blocks.

    With min_size < max_size the pool is sized to the workload when the
    dataset materializes: min(max_size, max(min_size, n_blocks)) actors —
    a small job doesn't pay for max_size actor startups, a large one is
    capped (the work-bound sizing of the reference's autoscaling pool;
    mid-execution scale-up is not implemented)."""

    def __init__(self, size: int | None = None, *, min_size: int = 2,
                 max_size: int | None = None):
        if size is not None:
            min_size = max_size = size
        if max_size is not None and max_size < min_size:
            raise ValueError(
                f"max_size={max_size} < min_size={min_size}")
        self.min_size = max(1, min_size)
        self.max_size = max_size or self.min_size

    @property
    def size(self):
        return self.max_size


def ActorPoolStrategy(size: int | None = None, *, min_size: int = 2,
                      max_size: int | None = None):
    return _ActorPoolStrategy(size, min_size=min_size, max_size=max_size)


class _BlockWorker:
    """Actor body for ActorPoolStrategy."""

    def apply(self, stages, block):
        return _exec_chain(stages, block)


class Dataset:
    def __init__(self, block_refs: list, stages: list | None = None,
                 compute=None):
        self._block_refs = list(block_refs)
        self._stages = list(stages or [])
        self._compute = compute   # default strategy for materialize()
        # Objects that must outlive this dataset's in-flight tasks but are
        # referenced only inside pickled closures (invisible to the
        # owner-based ref counter) — e.g. BatchPredictor's checkpoint ref.
        # Every Dataset derived from this one (via _derive) carries them
        # (advisor finding).
        self._keep_alive: tuple = ()

    def _pin(self, obj) -> "Dataset":
        self._keep_alive = self._keep_alive + (obj,)
        return self

    def _derive(self, block_refs, stages=None, compute=None,
                extra_pins=()) -> "Dataset":
        """Construct a Dataset downstream of this one, carrying the pins:
        the new blocks may be futures of tasks whose closures still need
        the pinned objects."""
        out = Dataset(block_refs, stages, compute=compute)
        out._keep_alive = self._keep_alive + tuple(extra_pins)
        return out

    # ------------------------------------------------------------ plan

    def _with_stage(self, fn, compute=None) -> "Dataset":
        return self._derive(self._block_refs, self._stages + [fn],
                            compute=compute or self._compute)

    def materialize(self, compute=None) -> "Dataset":
        """Execute pending stages: one task per block (TaskPoolStrategy) or
        a round-robin actor pool (ActorPoolStrategy)."""
        if not self._stages:
            return self
        stages = self._stages
        compute = compute if compute is not None else self._compute
        if isinstance(compute, _ActorPoolStrategy):
            worker_cls = ray_tpu.remote(_BlockWorker)
            n_blocks = len(self._block_refs)
            # work-bound sizing within [min_size, max_size]
            n_actors = min(compute.max_size,
                           max(compute.min_size, n_blocks))
            pool = [worker_cls.remote()
                    for _ in builtins.range(n_actors)]
            refs = [
                pool[i % len(pool)].apply.remote(stages, ref)
                for i, ref in enumerate(self._block_refs)
            ]
        else:
            task = _get_chain_task()
            refs = [task.remote(stages, ref) for ref in self._block_refs]
        return self._derive(refs)

    def _materialized_refs(self, compute=None):
        return self.materialize(compute)._block_refs

    def blocks(self) -> list:
        return [ray_tpu.get(r) for r in self._materialized_refs()]

    @property
    def num_blocks(self) -> int:
        return len(self._block_refs)

    # ------------------------------------------------------- transforms

    def map(self, fn) -> "Dataset":
        return self._with_stage(
            lambda block: B.columnarize([fn(row) for row in _rows(block)]))

    def flat_map(self, fn) -> "Dataset":
        return self._with_stage(
            lambda block: B.columnarize(
                [out for row in _rows(block) for out in fn(row)]))

    def filter(self, fn) -> "Dataset":
        return self._with_stage(
            lambda block: B.columnarize(
                [row for row in _rows(block) if fn(row)]))

    def map_batches(self, fn, *, batch_format: str = "auto",
                    compute=None) -> "Dataset":
        """fn: block -> block (numpy array in → numpy array out when the
        block is an array; list otherwise). `compute=ActorPoolStrategy(...)`
        runs this (and later) stages on a long-lived actor pool when the
        dataset materializes (reference: dataset.py:322 map_batches)."""
        return self._with_stage(fn, compute=compute)

    def repartition(self, num_blocks: int) -> "Dataset":
        """Re-block via remote tasks over block refs — the driver only
        sees per-block row counts, never rows (the old implementation
        pulled the whole dataset through ``take_all()``)."""
        from ray_tpu.data._internal.streaming import reblock

        refs = self._materialized_refs()
        return self._derive(reblock.repartition_refs(refs, num_blocks))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        """Push-based two-stage shuffle (reference:
        _internal/push_based_shuffle.py): map tasks split each block into
        N random partitions; reduce tasks concatenate partition i of every
        block. All intermediate partitions live in the object store.
        Columnar blocks partition with one numpy permutation + array
        indexing per block — no per-row Python.

        With ``RAY_TPU_DATA_SHUFFLE_COLLECTIVE=1`` the partition
        exchange instead rides the pipelined host-collective plane (an
        actor gang doing the all-to-all over one-way segment frames);
        identical rows per seed, falls back here on any failure."""
        if not self._block_refs:
            return self   # zero-block dataset: nothing to permute
        n = max(1, self.num_blocks)
        seed_base = seed if seed is not None else _random.randrange(2**31)

        from ray_tpu.data._internal.streaming import shuffle as _shuf

        if _shuf.shuffle_collective_enabled() and n >= 2:
            try:
                refs = _shuf.shuffle_via_collective(self, seed_base)
                if refs is not None:
                    return self._derive(refs)
            except Exception:
                pass   # gang/exchange failure: task-based path below

        @ray_tpu.remote(num_returns=n)
        def shuffle_map(stages, block, block_idx):
            block = _exec_chain(stages, block)
            rows_n = B.num_rows(block)
            rng = np.random.default_rng(seed_base + block_idx)
            perm = rng.permutation(rows_n)
            parts = [B.take_indices(block, idx)
                     for idx in np.array_split(perm, n)]
            return tuple(parts) if n > 1 else parts[0]

        @ray_tpu.remote
        def shuffle_reduce(reduce_idx, *parts):
            merged = B.concat_blocks(list(parts))
            rng = np.random.default_rng((seed_base ^ 0x5EED) + reduce_idx)
            return B.take_indices(merged, rng.permutation(B.num_rows(merged)))

        stages = self._stages
        part_refs = [shuffle_map.remote(stages, ref, i)
                     for i, ref in enumerate(self._block_refs)]
        if n == 1:
            part_refs = [[r] for r in part_refs]
        reduced = [
            shuffle_reduce.remote(
                i, *[part_refs[b][i] for b in builtins.range(n)])
            for i in builtins.range(n)
        ]
        return self._derive(reduced)

    def sort(self, key=None, descending: bool = False) -> "Dataset":
        """Sample-partition-sort (reference: _internal/sort.py): sample
        boundaries, range-partition blocks, sort each range."""
        keyfn = key if callable(key) else (
            (lambda row: row[key]) if key is not None else (lambda row: row))
        n = max(1, self.num_blocks)
        refs = self._materialized_refs()
        if n == 1:
            block = ray_tpu.get(refs[0])
            rows = sorted(_rows(block), key=keyfn, reverse=descending)
            return from_items(rows, parallelism=1)
        # boundary sampling on the driver (small sample per block)
        samples = []
        for ref in refs:
            rows = _rows(ray_tpu.get(ref))
            step = max(1, len(rows) // 8)
            samples.extend(keyfn(r) for r in rows[::step])
        samples.sort()
        bounds = [samples[int(len(samples) * (i + 1) / n)]
                  for i in builtins.range(n - 1)] if samples else []

        @ray_tpu.remote(num_returns=n)
        def range_partition(block):
            import bisect

            parts = [[] for _ in builtins.range(n)]
            for row in _rows(block):
                parts[bisect.bisect_left(bounds, keyfn(row))].append(row)
            return tuple(parts)

        @ray_tpu.remote
        def sort_merge(*parts):
            rows = [row for part in parts for row in part]
            return sorted(rows, key=keyfn, reverse=descending)

        part_refs = [range_partition.remote(ref) for ref in refs]
        ordered = [
            sort_merge.remote(*[part_refs[b][i] for b in builtins.range(n)])
            for i in builtins.range(n)
        ]
        if descending:
            ordered = ordered[::-1]
        return self._derive(ordered)

    def union(self, other: "Dataset") -> "Dataset":
        return self._derive(self._materialized_refs()
                            + other._materialized_refs(),
                            extra_pins=other._keep_alive)

    def zip(self, other: "Dataset") -> "Dataset":
        """Pair rows of two datasets (truncating to the shorter) via
        remote zip tasks over both sides' block refs — rows never land
        on the driver."""
        from ray_tpu.data._internal.streaming import reblock

        refs = reblock.zip_refs(self._materialized_refs(),
                                other._materialized_refs(),
                                self.num_blocks)
        return self._derive(refs, extra_pins=other._keep_alive)

    def split(self, n: int, *, equal: bool = True) -> list["Dataset"]:
        """Shard for per-worker consumption (reference: dataset.py split;
        used by Train's dataset_spec). The uneven case re-blocks with
        remote slice/concat tasks instead of driver ``take_all()``."""
        from ray_tpu.data._internal.streaming import reblock

        refs = self._materialized_refs()
        if len(refs) >= n and len(refs) % n == 0:
            per = len(refs) // n
            return [self._derive(refs[i * per:(i + 1) * per])
                    for i in builtins.range(n)]
        return [self._derive(shard)
                for shard in reblock.split_refs_uneven(refs, n)]

    def groupby(self, key) -> "GroupedDataset":
        return GroupedDataset(self, key)

    def window(self, *, blocks_per_window: int = 2) -> "DatasetPipeline":
        """Windowed pipelined execution (reference:
        data/dataset_pipeline.py): stages of window i+1 execute while
        window i is consumed."""
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        windows = []
        refs = self._block_refs
        for i in builtins.range(0, len(refs), blocks_per_window):
            windows.append(self._derive(refs[i:i + blocks_per_window],
                                        self._stages))
        return DatasetPipeline(windows)

    def repeat(self, times: int | None = None) -> "DatasetPipeline":
        """Epoch loop as a pipeline (reference: dataset.py repeat)."""
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        if times is None:
            return DatasetPipeline([self], loop=True)
        return DatasetPipeline([self] * times, loop=False)

    # ------------------------------------------------------ consumption

    def take(self, limit: int = 20) -> list:
        out = []
        for ref in self._materialized_refs():
            out.extend(_rows(ray_tpu.get(ref)))
            if len(out) >= limit:
                return out[:limit]
        return out

    def take_all(self) -> list:
        out = []
        for block in self.blocks():
            out.extend(_rows(block))
        return out

    def count(self) -> int:
        counter = ray_tpu.remote(lambda stages, b: len(_rows(
            _exec_chain(stages, b))))
        return sum(ray_tpu.get([counter.remote(self._stages, r)
                                for r in self._block_refs]))

    def show(self, limit: int = 20):
        for row in self.take(limit):
            print(row)

    def schema(self):
        first = self.take(1)
        if not first:
            return None
        row = first[0]
        if isinstance(row, dict):
            return {k: type(v).__name__ for k, v in row.items()}
        return type(row).__name__

    def iter_rows(self):
        for ref in self._materialized_refs():
            yield from _rows(ray_tpu.get(ref))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     device_put: bool = False, drop_last: bool = False):
        """Batched streaming iteration (data/_internal/streaming/): map
        tasks run on demand under a bounded prefetch budget
        (``RAY_TPU_DATA_PREFETCH_BLOCKS``), blocks stage zero-copy in the
        shm store with per-consumer backpressure, and with device_put a
        double-buffer thread overlaps fetch + slice + ``jax.device_put``
        of batch k+1 with the caller consuming batch k (the TPU host→HBM
        feed pipeline). ``RAY_TPU_DATA_STREAMING=0`` restores the legacy
        materialize-then-iterate path bit-for-bit. Per-batch consumer
        wait lands in ``ray_tpu_data_wait_seconds{consumer}``."""
        from ray_tpu.data._internal.streaming import (
            executor as _sx,
            iterator as _si,
        )

        if _sx.streaming_enabled():
            yield from _si.dataset_iter_batches(
                self, batch_size=batch_size, batch_format=batch_format,
                device_put=device_put, drop_last=drop_last)
            return
        yield from _si.stamp_wait(
            self._iter_batches_legacy(batch_size=batch_size,
                                      batch_format=batch_format,
                                      device_put=device_put,
                                      drop_last=drop_last),
            getattr(self, "_consumer", None) or "default")

    def _iter_batches_legacy(self, *, batch_size, batch_format,
                             device_put, drop_last):
        """The pre-streaming path (``RAY_TPU_DATA_STREAMING=0``): one
        blocking get per block with one-batch lookahead; with device_put
        the next batch is already on its way to the device while the
        caller consumes the current one."""
        def to_batch(blk):
            if batch_format == "numpy":
                batch = B.to_numpy_batch(blk)
            else:
                batch = B.to_rows(blk)
            if device_put:
                import jax

                batch = jax.device_put(batch)
            return batch

        # Batches slice straight out of blocks (columnar: numpy views +
        # one concat per batch — zero per-row Python).
        pending: list = []       # partial blocks carried across block refs
        pending_n = 0
        prev = None
        for ref in self._materialized_refs():
            blk = ray_tpu.get(ref)
            pending.append(blk)
            pending_n += B.num_rows(blk)
            while pending_n >= batch_size:
                take, taken = [], 0
                while taken < batch_size:
                    head = pending[0]
                    hn = B.num_rows(head)
                    need = batch_size - taken
                    if hn <= need:
                        take.append(head)
                        taken += hn
                        pending.pop(0)
                    else:
                        take.append(B.slice_block(head, 0, need))
                        pending[0] = B.slice_block(head, need, hn)
                        taken += need
                pending_n -= batch_size
                batch = to_batch(B.concat_blocks(take)
                                 if len(take) > 1 else take[0])
                if prev is not None:
                    yield prev
                prev = batch    # lookahead: device transfer overlaps consume
        if prev is not None:
            yield prev
        if pending_n and not drop_last:
            yield to_batch(B.concat_blocks(pending))

    def to_numpy(self) -> np.ndarray:
        return _rows_to_numpy(self.take_all())

    def to_pandas(self):
        import pandas as pd

        rows = self.take_all()
        if rows and isinstance(rows[0], dict):
            return pd.DataFrame(rows)
        return pd.DataFrame({"value": rows})

    def to_arrow(self):
        """Materialize as a single pyarrow Table (reference:
        dataset.py to_arrow_refs)."""
        import pyarrow as pa

        rows = self.take_all()
        if rows and isinstance(rows[0], dict):
            # from_pylist unions keys across rows (missing values → null),
            # matching to_pandas()'s NaN-fill behavior
            return pa.Table.from_pylist(rows)
        return pa.table({"value": rows})

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False, device=None,
                           dtypes=None):
        """Batched iteration yielding torch tensors (reference:
        dataset.py iter_torch_batches / to_torch at :2770 — the pin-memory
        GPU feed; on this framework the TPU path is
        ``iter_batches(device_put=True)``, torch output serves CPU-side
        models and interop)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            def convert(a):
                t = torch.as_tensor(np.ascontiguousarray(a))
                if dtypes is not None:
                    t = t.to(dtypes)
                if device is not None:
                    t = t.to(device)
                return t

            if isinstance(batch, dict):
                yield {k: convert(v) for k, v in batch.items()}
            else:
                yield convert(batch)

    def to_torch(self, *, label_column: str | None = None,
                 batch_size: int = 256, drop_last: bool = False):
        """Iterable of (features, label) torch pairs when label_column is
        given, else an iterable of feature tensors/dicts (reference:
        dataset.py to_torch)."""
        for batch in self.iter_torch_batches(batch_size=batch_size,
                                             drop_last=drop_last):
            if label_column is None:
                yield batch
            else:
                if not isinstance(batch, dict):
                    raise ValueError(
                        "label_column requires dict (columnar) rows; this "
                        "dataset yields plain arrays")
                label = batch.pop(label_column)
                yield batch, label

    def to_random_access_dataset(self, key: str, *,
                                 num_workers: int = 2):
        """Distributed key→row point-lookup index over this dataset
        (reference: random_access_dataset.py:23): sorted by `key`,
        partitioned across serving actors, O(log n) gets."""
        from ray_tpu.data.random_access import RandomAccessDataset

        return RandomAccessDataset(self, key, num_workers=num_workers)

    def to_tf(self, *, feature_columns=None, label_columns=None,
              batch_size: int = 256, drop_last: bool = False):
        """tf.data.Dataset over this dataset's batches (reference:
        dataset.py:2959 to_tf). Columnar batches become (features,
        labels) tensor tuples when label_columns is given, else feature
        dicts; shapes/dtypes are inferred from the first batch so
        tf.data gets a full output_signature (None leading dim)."""
        import tensorflow as tf

        # Infer the signature from the first batch WITHOUT recomputing
        # it: the partially-consumed iterator continues on the first
        # epoch, later epochs iterate fresh.
        it0 = self.iter_batches(batch_size=batch_size,
                                batch_format="numpy",
                                drop_last=drop_last)
        first_batch = next(iter(it0), None)
        if first_batch is None:
            raise ValueError("to_tf on an empty dataset")
        first = self._tf_split(first_batch, feature_columns,
                               label_columns)
        leftover = [it0]

        def gen():
            if leftover:
                rest = leftover.pop()
                yield first
                for batch in rest:
                    yield self._tf_split(batch, feature_columns,
                                         label_columns)
                return
            for batch in self.iter_batches(batch_size=batch_size,
                                           batch_format="numpy",
                                           drop_last=drop_last):
                yield self._tf_split(batch, feature_columns,
                                     label_columns)

        def sig_of(x):
            if isinstance(x, dict):
                return {k: sig_of(v) for k, v in x.items()}
            return tf.TensorSpec(shape=(None,) + x.shape[1:],
                                 dtype=tf.as_dtype(x.dtype))

        signature = (sig_of(first) if not isinstance(first, tuple)
                     else tuple(sig_of(p) for p in first))
        return tf.data.Dataset.from_generator(
            gen, output_signature=signature)

    @staticmethod
    def _tf_split(batch, feature_columns, label_columns):
        if not isinstance(batch, dict):
            return batch
        if label_columns is None:
            if feature_columns is not None:
                return {k: batch[k] for k in feature_columns}
            return batch
        labels = ({k: batch[k] for k in label_columns}
                  if not isinstance(label_columns, str)
                  else batch[label_columns])
        feats = (feature_columns if feature_columns is not None
                 else [k for k in batch
                       if (k != label_columns
                           if isinstance(label_columns, str)
                           else k not in label_columns)])
        features = {k: batch[k] for k in feats}
        if len(features) == 1:
            features = next(iter(features.values()))
        return features, labels

    def _write_blocks(self, path: str, ext: str, write_one):
        """One output file per block, written by remote tasks (reference:
        data/datasource/file_based_datasource.py write path). One cached
        remote task takes write_one as an argument — the _get_chain_task
        pattern — so repeated write calls reuse a submitter instead of
        registering a fresh closure per call."""
        import os as _os

        _os.makedirs(path, exist_ok=True)
        task = _get_write_task()
        return ray_tpu.get([
            task.remote(self._stages, ref, write_one,
                        _os.path.join(path, f"part-{i:05d}.{ext}"))
            for i, ref in enumerate(self._block_refs)])

    def write_parquet(self, path: str) -> list:
        # pyarrow directly — NOT pandas: constructing a DataFrame (whose
        # Index uses pyarrow-backed strings in this pandas build) on the
        # worker's RPC dispatch threads segfaults intermittently inside
        # pandas/pyarrow; pa.table from numpy columns avoids that path
        def write_one(block, out_path):
            import pyarrow.parquet as pq

            pq.write_table(_block_to_arrow_table(block), out_path)

        return self._write_blocks(path, "parquet", write_one)

    def write_csv(self, path: str) -> list:
        def write_one(block, out_path):
            import pyarrow.csv as pacsv

            pacsv.write_csv(_block_to_arrow_table(block), out_path)

        return self._write_blocks(path, "csv", write_one)

    def write_json(self, path: str) -> list:
        def write_one(block, out_path):
            import json as _json

            def plain(v):
                if isinstance(v, np.ndarray):
                    return v.tolist()
                if isinstance(v, np.generic):
                    return v.item()
                return v

            with open(out_path, "w") as f:
                for row in _rows(block):
                    if isinstance(row, dict):
                        row = {k: plain(v) for k, v in row.items()}
                    else:
                        row = plain(row)
                    f.write(_json.dumps(row) + "\n")

        return self._write_blocks(path, "json", write_one)

    def write_numpy(self, path: str, *, column: str | None = None) -> list:
        """One .npy file per block (reference:
        data/datasource/numpy_datasource.py write path). Columnar blocks
        need `column=` naming which array to save; plain-array blocks
        save directly."""
        def write_one(block, out_path):
            if isinstance(block, dict):
                if column is None:
                    raise ValueError(
                        f"dataset has named columns {sorted(block)}; "
                        f"pass column=...")
                np.save(out_path, np.asarray(block[column]))
            else:
                np.save(out_path, np.asarray(block))

        return self._write_blocks(path, "npy", write_one)

    def _numeric_partials(self, on=None):
        """Per-block (count, sum, min, max, mean, M2) partials via remote
        tasks; merged driver-side with Chan's parallel-variance algorithm
        (reference: dataset.py sum/mean/std over AggregateFn partials)."""
        task = _get_agg_task()
        parts = ray_tpu.get([task.remote(self._stages, ref, on)
                             for ref in self._block_refs])
        parts = [p for p in parts if p is not None]
        if not parts:
            raise ValueError("aggregation over an empty dataset")
        count, total, mn, mx, mean, m2 = parts[0]
        for n_b, tot_b, mn_b, mx_b, mean_b, m2_b in parts[1:]:
            delta = mean_b - mean
            merged = count + n_b
            mean = mean + delta * n_b / merged
            m2 = m2 + m2_b + delta * delta * count * n_b / merged
            count, total = merged, total + tot_b
            mn, mx = min(mn, mn_b), max(mx, mx_b)
        return count, total, mn, mx, mean, m2

    def sum(self, on=None) -> float:  # noqa: A003
        return self._numeric_partials(on)[1]

    def mean(self, on=None) -> float:
        count, total, *_ = self._numeric_partials(on)
        return total / count

    def min(self, on=None) -> float:  # noqa: A003
        return self._numeric_partials(on)[2]

    def max(self, on=None) -> float:  # noqa: A003
        return self._numeric_partials(on)[3]

    def std(self, on=None, ddof: int = 1) -> float:
        count, _, _, _, _, m2 = self._numeric_partials(on)
        if count <= ddof:
            return 0.0
        return float(np.sqrt(m2 / (count - ddof)))

    def stats(self) -> dict:
        sizes = ray_tpu.get([
            _get_chain_task().remote(
                self._stages + [lambda b: len(_rows(b))], r)
            for r in self._block_refs])
        return {"num_blocks": len(sizes), "block_sizes": sizes,
                "num_rows": sum(sizes)}

    def __repr__(self):
        return (f"Dataset(num_blocks={self.num_blocks}, "
                f"pending_stages={len(self._stages)})")


class GroupedDataset:
    """(reference: data/grouped_dataset.py) distributed hash-partition by
    key, then per-group aggregation inside reduce tasks — group data never
    lands on the driver."""

    def __init__(self, ds: Dataset, key):
        self.ds = ds
        self.keyfn = key if callable(key) else (lambda row: row[key])

    def _reduce(self, per_groups_fn) -> Dataset:
        """Two-stage: map tasks hash-partition each block's rows; reduce
        task i groups partition i of every block and applies
        per_groups_fn(groups_dict) -> rows."""
        ds = self.ds
        keyfn = self.keyfn
        n = max(1, ds.num_blocks)

        @ray_tpu.remote(num_returns=n)
        def part_map(stages, blk):
            import zlib

            rows = _rows(_exec_chain(stages, blk))
            parts = [[] for _ in builtins.range(n)]
            for row in rows:
                # stable hash: builtin hash() is salted per process, and the
                # map tasks run in different workers
                h = zlib.crc32(str(keyfn(row)).encode())
                parts[h % n].append(row)
            return tuple(parts) if n > 1 else parts[0]

        @ray_tpu.remote
        def part_reduce(*parts):
            groups: dict = {}
            for part in parts:
                for row in part:
                    groups.setdefault(keyfn(row), []).append(row)
            return B.columnarize(per_groups_fn(groups))

        part_refs = [part_map.remote(ds._stages, ref)
                     for ref in ds._block_refs]
        if n == 1:
            part_refs = [[r] for r in part_refs]
        reduced = [
            part_reduce.remote(*[part_refs[b][i]
                                 for b in builtins.range(n)])
            for i in builtins.range(n)
        ]
        return ds._derive(reduced)

    def count(self) -> Dataset:
        return self._reduce(lambda groups: [
            {"key": k, "count": len(v)} for k, v in groups.items()])

    def aggregate(self, agg_fn) -> Dataset:
        return self._reduce(lambda groups: [
            {"key": k, "value": agg_fn(v)} for k, v in groups.items()])

    def map_groups(self, fn) -> Dataset:
        return self._reduce(lambda groups: [
            out for _, v in groups.items() for out in fn(v)])

    def _column_agg(self, on, combine, out_name: str) -> Dataset:
        """Per-group column aggregation (reference: grouped_dataset.py
        sum/mean/min/max)."""
        def agg(groups):
            out = []
            for k, rows in groups.items():
                if rows and not isinstance(rows[0], dict):
                    raise ValueError(
                        f"on={on!r} given but grouped rows are plain "
                        f"values, not named columns")
                vals = [row[on] for row in rows]
                out.append({"key": k, out_name: combine(vals)})
            return out

        return self._reduce(agg)

    def sum(self, on) -> Dataset:  # noqa: A003
        return self._column_agg(on, lambda v: float(np.sum(v)), f"sum({on})")

    def mean(self, on) -> Dataset:
        return self._column_agg(on, lambda v: float(np.mean(v)),
                                f"mean({on})")

    def min(self, on) -> Dataset:  # noqa: A003
        return self._column_agg(on, lambda v: float(np.min(v)), f"min({on})")

    def max(self, on) -> Dataset:  # noqa: A003
        return self._column_agg(on, lambda v: float(np.max(v)), f"max({on})")


# -------------------------------------------------------------- block utils

def _block_to_arrow_table(block):
    import pyarrow as pa

    def col(a):
        arr = np.asarray(a)
        if arr.ndim > 1:
            return pa.array(arr.tolist())   # nested lists per row
        return pa.array(arr)

    if isinstance(block, dict):
        return pa.table({k: col(v) for k, v in block.items()})
    if isinstance(block, np.ndarray):
        return pa.table({"value": col(block)})
    rows = _rows(block)
    if rows and isinstance(rows[0], dict):
        return pa.Table.from_pylist(rows)
    return pa.table({"value": pa.array(rows)})


def _rows(block) -> list:
    return B.to_rows(block)


def _rows_to_numpy(rows):
    if rows and isinstance(rows[0], dict):
        return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
    return np.asarray(rows)


# -------------------------------------------------------------- constructors

def from_items(items: list, *, parallelism: int = 8) -> Dataset:
    items = list(items)
    n = max(1, min(parallelism, len(items) or 1))
    chunk = (len(items) + n - 1) // n
    refs = [ray_tpu.put(B.columnarize(items[i * chunk:(i + 1) * chunk]))
            for i in builtins.range(n)]
    return Dataset(refs)


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return from_items(list(builtins.range(n)), parallelism=parallelism)


def from_numpy(arr: np.ndarray, *, parallelism: int = 8) -> Dataset:
    chunks = np.array_split(arr, max(1, parallelism))
    return Dataset([ray_tpu.put(c) for c in chunks if len(c)])


def from_pandas(df, *, parallelism: int = 4) -> Dataset:
    n = max(1, parallelism)
    size = (len(df) + n - 1) // n
    refs = [ray_tpu.put(df.iloc[i * size:(i + 1) * size])
            for i in builtins.range(n) if i * size < len(df)]
    return Dataset(refs)


def read_csv(paths, *, parallelism: int = 4,
             chunk_rows: int = 200_000) -> Dataset:
    """Distributed read: one task per file, one block per `chunk_rows`
    rows. The block count per file is unknown until the file is read, so
    each task streams blocks out through ``num_returns="dynamic"``
    (reference: data/read_api.py read tasks produce a dynamic block
    count per file via ObjectRefGenerator, _raylet.pyx:168)."""
    if isinstance(paths, str):
        paths = [paths]

    @ray_tpu.remote(num_returns="dynamic")
    def _read_csv_file(path, rows):
        import pandas as pd

        for chunk in pd.read_csv(path, chunksize=rows):
            yield chunk

    gens = [_read_csv_file.remote(p, chunk_rows) for p in paths]
    refs = []
    for g in gens:
        refs.extend(ray_tpu.get(g))
    return Dataset(refs)


def read_json(paths) -> Dataset:
    import json

    if isinstance(paths, str):
        paths = [paths]
    rows = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    return from_items(rows)


def read_parquet(paths, *, parallelism: int = 4) -> Dataset:
    """Distributed read: one task per file, one block per row group —
    the block count only exists after the footer is open, which is
    exactly the ``num_returns="dynamic"`` shape (reference:
    data/read_api.py + _raylet.pyx:168)."""
    if isinstance(paths, str):
        paths = [paths]

    @ray_tpu.remote(num_returns="dynamic")
    def _read_parquet_file(path):
        import pyarrow.parquet as pq

        f = pq.ParquetFile(path)
        for rg in builtins.range(f.num_row_groups):
            t = f.read_row_group(rg)
            yield {name: t.column(name).to_numpy(zero_copy_only=False)
                   for name in t.column_names}

    gens = [_read_parquet_file.remote(p) for p in paths]
    refs = []
    for g in gens:
        refs.extend(ray_tpu.get(g))
    return Dataset(refs)


def _chunk_list(items: list, parallelism: int) -> list[list]:
    """Split items into at most `parallelism` contiguous non-empty
    chunks (the shared fan-out shape of the file readers)."""
    n = max(1, min(parallelism, len(items) or 1))
    chunk = (len(items) + n - 1) // n
    return [items[i * chunk:(i + 1) * chunk]
            for i in builtins.range(n) if items[i * chunk:(i + 1) * chunk]]


def read_numpy(paths, *, parallelism: int = 4) -> Dataset:
    """.npy files loaded by remote tasks, one block per file but at
    most `parallelism` tasks (reference:
    data/datasource/numpy_datasource.py)."""
    if isinstance(paths, str):
        paths = [paths]

    @ray_tpu.remote(num_returns="dynamic")
    def _load(batch):
        for p in batch:
            yield np.load(p)

    refs = []
    for gen in [_load.remote(b) for b in _chunk_list(paths, parallelism)]:
        refs.extend(ray_tpu.get(gen))
    return Dataset(refs)


def read_binary_files(paths, *, include_paths: bool = False,
                      parallelism: int = 4) -> Dataset:
    """Raw file bytes, one row per file (reference:
    data/datasource/binary_datasource.py). Rows are {"bytes": ...} (+
    {"path": ...} with include_paths) so downstream map stages see the
    same dict-row shape as other sources."""
    if isinstance(paths, str):
        paths = [paths]

    @ray_tpu.remote
    def _load(batch, with_paths):
        rows = []
        for p in batch:
            with open(p, "rb") as f:
                row = {"bytes": f.read()}
            if with_paths:
                row["path"] = p
            rows.append(row)
        return rows

    refs = [_load.remote(batch, include_paths)
            for batch in _chunk_list(paths, parallelism)]
    return Dataset(refs)


def read_images(paths, *, size: tuple | None = None,
                mode: str | None = None,
                include_paths: bool = False,
                parallelism: int = 4) -> Dataset:
    """Images → numpy arrays, decoded by remote tasks (reference:
    data/datasource/image_datasource.py — PIL decode, optional resize/
    mode convert). Rows are {"image": HxWxC uint8} (+ path)."""
    if isinstance(paths, str):
        paths = [paths]

    @ray_tpu.remote
    def _load(batch, sz, md, with_paths):
        from PIL import Image

        rows = []
        for p in batch:
            img = Image.open(p)
            if md is not None:
                img = img.convert(md)
            if sz is not None:
                img = img.resize(sz)
            row = {"image": np.asarray(img)}
            if with_paths:
                row["path"] = p
            rows.append(row)
        return rows

    refs = [_load.remote(batch, size, mode, include_paths)
            for batch in _chunk_list(paths, parallelism)]
    return Dataset(refs)


def read_text(paths) -> Dataset:
    if isinstance(paths, str):
        paths = [paths]
    rows = []
    for p in paths:
        with open(p) as f:
            rows.extend(line.rstrip("\n") for line in f)
    return from_items(rows)


def from_arrow(tables, *, parallelism: int = 4) -> Dataset:
    """pyarrow Table(s) → Dataset with one block per table (reference:
    data/read_api.py from_arrow). Columns land as numpy arrays — the
    columnar block format — so downstream batches slice without a row
    loop."""
    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    refs = []
    per_table = max(1, parallelism // max(1, len(tables)))
    for t in tables:
        n = len(t)
        if n == 0:
            continue
        k = min(per_table, n)
        size = (n + k - 1) // k
        for start in builtins.range(0, n, size):
            piece = t.slice(start, size)
            cols = {name: piece.column(name).to_numpy(
                        zero_copy_only=False)
                    for name in piece.column_names}
            refs.append(ray_tpu.put(cols))
    return Dataset(refs)


