"""RandomAccessDataset — distributed key→row point lookups.

Reference: python/ray/data/random_access_dataset.py:23 — sort the
dataset by a key column, partition the sorted blocks across N serving
actors, and resolve get(key) by binary-searching the block-boundary
index to the owning actor, which binary-searches inside its block.
O(log n) per lookup, horizontally scaled by num_workers.
"""
from __future__ import annotations

import bisect

import numpy as np

import ray_tpu


class _AccessShard:
    """Serving actor holding a contiguous run of sorted blocks. Receives
    block REFS and fetches them itself — the driver never materializes
    the dataset (reference: the serving actors own their blocks)."""

    def __init__(self, block_refs: list, key: str):
        self.key = key
        from ray_tpu.data import block as B

        blocks = [ray_tpu.get(r) for r in block_refs]
        merged = B.concat_blocks(blocks) if len(blocks) > 1 else blocks[0]
        self.cols = B.to_numpy_batch(merged)
        self.keys = np.asarray(self.cols[key])

    def first_key(self):
        if len(self.keys) == 0:
            return None   # all-empty sort ranges: driver drops the shard
        return self.keys[0].item() if hasattr(self.keys[0], "item") \
            else self.keys[0]

    def multiget(self, keys: list) -> list:
        out = []
        for k in keys:
            i = int(np.searchsorted(self.keys, k))
            if i < len(self.keys) and self.keys[i] == k:
                out.append({c: v[i].item() if hasattr(v[i], "item")
                            else v[i]
                            for c, v in self.cols.items()})
            else:
                out.append(None)
        return out

    def get(self, key):
        return self.multiget([key])[0]

    def stats(self) -> dict:
        return {"rows": int(len(self.keys))}


class RandomAccessDataset:
    """Created via ``Dataset.to_random_access_dataset(key,
    num_workers=N)``."""

    def __init__(self, dataset, key: str, num_workers: int = 2):
        self.key = key
        sorted_ds = dataset.sort(key=key)
        refs = list(sorted_ds._materialized_refs())
        if not refs:
            raise ValueError("cannot index an empty dataset")
        n = max(1, min(num_workers, len(refs)))
        per = (len(refs) + n - 1) // n
        shard_cls = ray_tpu.remote(_AccessShard)
        # refs travel; each shard pulls its own blocks from the store —
        # the driver holds O(num_workers) metadata, not the dataset
        self._shards = [
            shard_cls.options(num_cpus=0).remote(refs[i:i + per], key)
            for i in range(0, len(refs), per)
        ]
        bounds = ray_tpu.get(
            [s.first_key.remote() for s in self._shards], timeout=600)
        live = [(b, s) for b, s in zip(bounds, self._shards)
                if b is not None]
        if not live:
            raise ValueError("cannot index an empty dataset")
        self._lower_bounds = [b for b, _s in live]
        self._shards = [s for _b, s in live]

    def _shard_for(self, key) -> int:
        i = bisect.bisect_right(self._lower_bounds, key) - 1
        return max(0, i)

    def get_async(self, key):
        """ObjectRef resolving to the row dict, or None if absent."""
        shard = self._shards[self._shard_for(key)]
        return shard.get.remote(key)

    def get(self, key):
        return ray_tpu.get(self.get_async(key))

    def multiget(self, keys: list) -> list:
        """Batched lookups, one RPC per shard touched (reference:
        random_access_dataset.py:142)."""
        by_shard: dict[int, list] = {}
        order: list[tuple[int, int]] = []   # (shard, idx-in-shard-batch)
        for k in keys:
            s = self._shard_for(k)
            batch = by_shard.setdefault(s, [])
            order.append((s, len(batch)))
            batch.append(k)
        # submit every shard RPC first, gather ONCE: latency is the
        # slowest shard, not the sum of shard round trips
        refs = {s: self._shards[s].multiget.remote(batch)
                for s, batch in by_shard.items()}
        shard_ids = list(refs)
        values = ray_tpu.get([refs[s] for s in shard_ids], timeout=300)
        results = dict(zip(shard_ids, values))
        return [results[s][i] for s, i in order]

    def stats(self) -> list[dict]:
        return ray_tpu.get([s.stats.remote() for s in self._shards],
                           timeout=300)

