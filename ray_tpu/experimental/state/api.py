"""State API — programmatic cluster observability.

Reference: python/ray/experimental/state/api.py (list_actors/list_tasks/
list_objects/list_nodes/..., StateApiClient) with the aggregation the
reference does in dashboard/state_aggregator.py done client-side here: the
GCS serves cluster tables, raylets serve per-node lease/worker state.

Works connected (inside a driver: uses the current worker's GCS) or
standalone (address="host:port", e.g. from the CLI).
"""
from __future__ import annotations

import contextlib


@contextlib.contextmanager
def _gcs(address: str | None):
    """Yield a call(method, **kw) callable for the GCS."""
    if address is None:
        from ray_tpu._private.worker_runtime import current_worker

        w = current_worker()
        if w is not None:
            yield w.gcs.call
            return
        from ray_tpu.scripts.node import CLUSTER_FILE
        import json
        import os

        if not os.path.exists(CLUSTER_FILE):
            raise RuntimeError("not connected and no local cluster file; "
                               "pass address='host:port'")
        with open(CLUSTER_FILE) as f:
            address = json.load(f)["gcs_address"]
    from ray_tpu._private.protocol import RpcClient

    host, port = address.rsplit(":", 1)
    client = RpcClient((host, int(port)), timeout=10.0)
    try:
        yield client.call
    finally:
        client.close()


def _each_raylet(call, method: str) -> list:
    from ray_tpu._private.protocol import RpcClient

    out = []
    for n in call("get_nodes"):
        if not n["Alive"]:
            continue
        try:
            c = RpcClient((n["NodeManagerAddress"], n["NodeManagerPort"]),
                          timeout=5.0)
            try:
                out.extend(c.call(method))
            finally:
                c.close()
        except Exception:
            continue
    return out


def list_nodes(*, address: str | None = None) -> list[dict]:
    with _gcs(address) as call:
        return call("get_nodes")


def list_actors(*, address: str | None = None) -> list[dict]:
    with _gcs(address) as call:
        return call("list_actors")


def list_placement_groups(*, address: str | None = None) -> list[dict]:
    with _gcs(address) as call:
        return call("list_placement_groups")


def list_objects(*, address: str | None = None) -> list[dict]:
    """Union of per-node store inventories, merged by object id. Locations
    live with owning workers (owner-based directory), so the cluster-wide
    view is assembled from the raylets' stores rather than a GCS table."""
    with _gcs(address) as call:
        rows = _each_raylet(call, "list_store_objects")
    merged: dict[str, dict] = {}
    for r in rows:
        cur = merged.get(r["ObjectID"])
        if cur is None:
            merged[r["ObjectID"]] = dict(r)
        else:
            cur["Locations"] = sorted(set(cur["Locations"])
                                      | set(r["Locations"]))
            cur["Size"] = max(cur["Size"], r["Size"])
    return list(merged.values())


def list_tasks(*, address: str | None = None) -> list[dict]:
    """Raylet-level view: one row per active lease (running task slot).
    The reference's task events flow through its dashboard agent; here the
    lease table is the source of truth for what is running where."""
    with _gcs(address) as call:
        return _each_raylet(call, "list_leases")


def list_workers(*, address: str | None = None) -> list[dict]:
    with _gcs(address) as call:
        return _each_raylet(call, "list_workers")


def cluster_status(*, address: str | None = None) -> str:
    """`ray status` analog (reference: scripts.py:1872): node table +
    resource usage summary."""
    from ray_tpu._private.protocol import RpcClient

    with _gcs(address) as call:
        nodes = call("get_nodes")
        lines = ["======== Cluster status ========"]
        alive = [n for n in nodes if n["Alive"]]
        dead = [n for n in nodes if not n["Alive"]]
        lines.append(f"Nodes: {len(alive)} alive, {len(dead)} dead")
        total: dict = {}
        avail: dict = {}
        for n in alive:
            for k, v in n["Resources"].items():
                total[k] = total.get(k, 0) + v
            try:
                c = RpcClient((n["NodeManagerAddress"],
                               n["NodeManagerPort"]), timeout=5.0)
                try:
                    info = c.call("node_info")
                finally:
                    c.close()
                for k, v in info["resources_available"].items():
                    avail[k] = avail.get(k, 0) + v
            except Exception:
                continue
        lines.append("Resources (used/total):")
        for k in sorted(total):
            used = total[k] - avail.get(k, total[k])
            if k == "memory":
                lines.append(f"  {used / 2**30:.1f}/"
                             f"{total[k] / 2**30:.1f} GiB memory")
            else:
                lines.append(f"  {used:g}/{total[k]:g} {k}")
        for n in alive:
            tpu = n.get("tpu")
            suffix = (f" slice={tpu['slice_id']} worker={tpu['worker_id']}"
                      if tpu else "")
            lines.append(f"  node {n['NodeID'][:12]} "
                         f"{n['NodeManagerAddress']}:{n['NodeManagerPort']}"
                         f"{suffix}")
        return "\n".join(lines)


def memory_summary(*, address: str | None = None) -> str:
    """`ray memory` analog (reference: scripts.py:1822)."""
    objs = list_objects(address=address)
    lines = ["======== Object store ========",
             f"Objects tracked: {len(objs)}"]
    total = sum(o["Size"] for o in objs)
    lost = [o for o in objs if o["Lost"]]
    lines.append(f"Total bytes: {total}")
    if lost:
        lines.append(f"Lost objects: {len(lost)}")
    for o in sorted(objs, key=lambda o: -o["Size"])[:20]:
        lines.append(f"  {o['ObjectID'][:16]}  {o['Size']:>12}  "
                     f"on {len(o['Locations'])} node(s)")
    return "\n".join(lines)


def metrics_summary(*, address: str | None = None,
                    prometheus: bool = False):
    """Aggregate user metrics (ray_tpu.util.metrics Counter/Gauge/
    Histogram) across every worker process. prometheus=True renders the
    text exposition format (reference: the dashboard agent's Prometheus
    endpoint, reporter_agent.py:296)."""
    from ray_tpu.util.metrics import prometheus_text, registry_snapshot

    with _gcs(address) as call:
        snaps = registry_snapshot()           # this process too
        snaps.extend(_each_raylet(call, "metrics_snapshot"))
    if prometheus:
        return prometheus_text(snaps)
    return snaps


def summarize_tasks(*, address: str | None = None) -> dict:
    rows = list_tasks(address=address)
    return {"total_running": len(rows),
            "by_node": {r["node_id"]: sum(1 for x in rows
                                          if x["node_id"] == r["node_id"])
                        for r in rows}}
