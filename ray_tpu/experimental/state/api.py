"""State API — programmatic cluster observability.

Reference: python/ray/experimental/state/api.py (list_actors/list_tasks/
list_objects/list_nodes/..., StateApiClient) with the aggregation the
reference does in dashboard/state_aggregator.py done client-side here: the
GCS serves cluster tables, raylets serve per-node lease/worker state.

Works connected (inside a driver: uses the current worker's GCS) or
standalone (address="host:port", e.g. from the CLI).
"""
from __future__ import annotations

import contextlib


@contextlib.contextmanager
def _gcs(address: str | None):
    """Yield a call(method, **kw) callable for the GCS."""
    if address is None:
        from ray_tpu._private.worker_runtime import current_worker

        w = current_worker()
        if w is not None:
            yield w.gcs.call
            return
        from ray_tpu.scripts.node import CLUSTER_FILE
        import json
        import os

        if not os.path.exists(CLUSTER_FILE):
            raise RuntimeError("not connected and no local cluster file; "
                               "pass address='host:port'")
        with open(CLUSTER_FILE) as f:
            address = json.load(f)["gcs_address"]
    from ray_tpu._private.protocol import RpcClient

    host, port = address.rsplit(":", 1)
    client = RpcClient((host, int(port)), timeout=10.0)
    try:
        yield client.call
    finally:
        client.close()


def _each_raylet(call, method: str) -> list:
    from ray_tpu._private.protocol import RpcClient

    out = []
    for n in call("get_nodes"):
        if not n["Alive"]:
            continue
        try:
            c = RpcClient((n["NodeManagerAddress"], n["NodeManagerPort"]),
                          timeout=5.0)
            try:
                out.extend(c.call(method))
            finally:
                c.close()
        except Exception:
            continue
    return out


_FILTER_OPS = {
    "=": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a is not None and a < b,
    "<=": lambda a, b: a is not None and a <= b,
    ">": lambda a, b: a is not None and a > b,
    ">=": lambda a, b: a is not None and a >= b,
    "contains": lambda a, b: b in (a or ""),
}


def _apply_filters(rows: list[dict], filters, limit) -> list[dict]:
    """Predicate filtering + truncation, the reference's state-API
    filter form (python/ray/experimental/state/api.py — filters are
    (key, op, value) tuples ANDed together; `=` compares after str()
    coercion so CLI-sourced values match ints/bools)."""
    for f in filters or ():
        try:
            key, op, value = f
        except (TypeError, ValueError):
            raise ValueError(
                f"filter must be (key, op, value), got {f!r}") from None
        if op not in _FILTER_OPS:
            raise ValueError(f"unknown filter op {op!r} "
                             f"(one of {sorted(_FILTER_OPS)})")
        pred = _FILTER_OPS[op]
        if op in ("=", "!="):
            rows = [r for r in rows
                    if pred(str(r.get(key)), str(value))]
        else:
            rows = [r for r in rows if pred(r.get(key), value)]
    if limit is not None:
        rows = rows[:limit]
    return rows


def list_nodes(*, address: str | None = None, filters=None,
               limit=None) -> list[dict]:
    with _gcs(address) as call:
        return _apply_filters(call("get_nodes"), filters, limit)


def list_actors(*, address: str | None = None, filters=None,
                limit=None) -> list[dict]:
    with _gcs(address) as call:
        return _apply_filters(call("list_actors"), filters, limit)


def list_placement_groups(*, address: str | None = None, filters=None,
                          limit=None) -> list[dict]:
    with _gcs(address) as call:
        return _apply_filters(call("list_placement_groups"), filters,
                              limit)


def list_objects(*, address: str | None = None, filters=None,
                 limit=None) -> list[dict]:
    """Union of per-node store inventories, merged by object id. Locations
    live with owning workers (owner-based directory), so the cluster-wide
    view is assembled from the raylets' stores rather than a GCS table."""
    with _gcs(address) as call:
        rows = _each_raylet(call, "list_store_objects")
    merged: dict[str, dict] = {}
    for r in rows:
        cur = merged.get(r["ObjectID"])
        if cur is None:
            merged[r["ObjectID"]] = dict(r)
        else:
            cur["Locations"] = sorted(set(cur["Locations"])
                                      | set(r["Locations"]))
            cur["Size"] = max(cur["Size"], r["Size"])
    return _apply_filters(list(merged.values()), filters, limit)


def list_tasks(*, address: str | None = None, filters=None,
               limit=None, detail: bool = False) -> list[dict]:
    """Raylet-level view: one row per active lease (running task slot).
    The reference's task events flow through its dashboard agent; here the
    lease table is the source of truth for what is running where.
    detail=True additionally asks each leased worker what it is running
    (task id/desc/start time — the reference's `ray get tasks <id>`
    tier)."""
    with _gcs(address) as call:
        rows = _each_raylet(call, "list_leases")
    if detail:
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu._private.protocol import RpcClient

        def probe(r):
            addr = r.get("worker_addr")
            if not addr:
                return
            try:
                c = RpcClient(tuple(addr), timeout=2.0)
                try:
                    r.update(c.call("task_state"))
                finally:
                    c.close()
            except Exception:
                pass

        # concurrent probes: dead workers each cost up to the 2s
        # timeout, which must not stack serially across the cluster
        with ThreadPoolExecutor(max_workers=16) as pool:
            list(pool.map(probe, rows))
    return _apply_filters(rows, filters, limit)


def list_workers(*, address: str | None = None, filters=None,
                 limit=None) -> list[dict]:
    with _gcs(address) as call:
        return _apply_filters(_each_raylet(call, "list_workers"),
                              filters, limit)


# ---- per-entity detail lookups (reference: state api get_* tier) ----------

def get_actor(actor_id: str, *, address: str | None = None) -> dict | None:
    """One actor's full record by hex id."""
    for row in list_actors(address=address):
        if row["ActorID"] == actor_id:
            return row
    return None


def get_node(node_id: str, *, address: str | None = None) -> dict | None:
    for row in list_nodes(address=address):
        if row["NodeID"] == node_id:
            return row
    return None


def get_placement_group(pg_id: str, *,
                        address: str | None = None) -> dict | None:
    for row in list_placement_groups(address=address):
        if row["PlacementGroupID"] == pg_id:
            return row
    return None


def get_task(task_id: str, *, address: str | None = None) -> dict | None:
    """Detail for one RUNNING task by hex id (lease + worker probe)."""
    for row in list_tasks(address=address, detail=True):
        if row.get("task_id") == task_id:
            return row
    return None


def get_objects(object_id: str, *,
                address: str | None = None) -> list[dict]:
    """Every store's view of one object (locations/size/lost)."""
    return [r for r in list_objects(address=address)
            if r["ObjectID"] == object_id]


# ---- summaries (reference: `ray summary` / state_aggregator rollups) ------

def summarize_actors(*, address: str | None = None) -> dict:
    """Counts grouped class -> state (reference: `ray summary actors`)."""
    out: dict[str, dict[str, int]] = {}
    for a in list_actors(address=address):
        by_state = out.setdefault(a.get("ClassName") or "?", {})
        by_state[a["State"]] = by_state.get(a["State"], 0) + 1
    return out


def summarize_tasks(*, address: str | None = None) -> dict:
    """Running work grouped by description (leases + worker probes) plus
    queued demand by shape (reference: `ray summary tasks` groups by
    func_or_class_name and state), plus the per-task queue/scheduling/
    execution latency breakdown derived from the runtime event log:

    - ``queue_s``      SUBMITTED → last LEASE_GRANTED (waiting in the
                       scheduling queue for a leased worker; retries of
                       a failed dispatch accrue here),
    - ``scheduling_s`` LEASE_GRANTED → RUNNING (push + dependency
                       resolution on the executor),
    - ``execution_s``  RUNNING → FINISHED/FAILED (the task body).

    ``tasks`` holds one row per task seen in the event window (bounded
    per-process rings — a long-running cluster only covers recent
    tasks); ``latency`` aggregates count/mean/max per task description.
    """
    running: dict[str, int] = {}
    for t in list_tasks(address=address, detail=True):
        key = t.get("task_desc") or (
            "actor_task" if t.get("is_actor") else "task")
        running[key] = running.get(key, 0) + 1
    queued: dict[str, int] = {}
    with _gcs(address) as call:
        for n in call("get_cluster_load")["nodes"]:
            for shape in n.get("PendingDemand", ()):
                key = ",".join(f"{k}:{v:g}"
                               for k, v in sorted(shape.items()))
                queued[key] = queued.get(key, 0) + 1
    tasks = _task_latency_rows(
        list_cluster_events(address=address,
                            filters=[("kind", "=", "task_state")]))
    latency: dict[str, dict] = {}
    for row in tasks:
        agg = latency.setdefault(row["desc"] or "task", {
            "count": 0, "finished": 0, "failed": 0,
            "queue_s": _PhaseAgg(), "scheduling_s": _PhaseAgg(),
            "execution_s": _PhaseAgg()})
        agg["count"] += 1
        if row["state"] == "FINISHED":
            agg["finished"] += 1
        elif row["state"] == "FAILED":
            agg["failed"] += 1
        for phase in ("queue_s", "scheduling_s", "execution_s"):
            if row.get(phase) is not None:
                agg[phase].add(row[phase])
    for agg in latency.values():
        for phase in ("queue_s", "scheduling_s", "execution_s"):
            agg[phase] = agg[phase].summary()
    return {"running": running, "queued_by_shape": queued,
            "tasks": tasks, "latency": latency}


class _PhaseAgg:
    __slots__ = ("n", "total", "max")

    def __init__(self):
        self.n = 0
        self.total = 0.0
        self.max = 0.0

    def add(self, v: float):
        self.n += 1
        self.total += v
        self.max = max(self.max, v)

    def summary(self) -> dict:
        return {"count": self.n,
                "mean": (self.total / self.n) if self.n else 0.0,
                "max": self.max}


def _task_latency_rows(task_events: list[dict]) -> list[dict]:
    """Fold task_state events into one row per task id. For retried
    tasks the breakdown describes the attempt that reached RUNNING last
    (latest LEASE_GRANTED/RUNNING/terminal timestamps), with `attempts`
    counting dispatches; clock skew across hosts is clamped to >= 0."""
    per_task: dict[str, dict] = {}
    for e in task_events:
        tid = e.get("task_id")
        if tid is None:
            continue
        t = per_task.setdefault(tid, {
            "task_id": tid, "desc": None, "state": None, "attempts": 0,
            "_submitted": None, "_granted": None, "_running": None,
            "_end": None})
        state = e.get("state")
        ts = e.get("ts", 0.0)
        if e.get("desc"):
            t["desc"] = e["desc"]
        if state == "SUBMITTED":
            if t["_submitted"] is None or ts < t["_submitted"]:
                t["_submitted"] = ts
        elif state == "LEASE_GRANTED":
            t["attempts"] += 1
            if t["_granted"] is None or ts > t["_granted"]:
                t["_granted"] = ts
        elif state == "RUNNING":
            if t["_running"] is None or ts > t["_running"]:
                t["_running"] = ts
        elif state in ("FINISHED", "FAILED"):
            if t["_end"] is None or ts > t["_end"]:
                t["_end"] = ts
                t["state"] = state
        if state in ("SUBMITTED", "RESUBMITTED", "LEASE_GRANTED",
                     "RUNNING") and t["state"] not in ("FINISHED",
                                                       "FAILED"):
            t["state"] = state
    rows = []
    for t in per_task.values():
        sub, granted = t.pop("_submitted"), t.pop("_granted")
        run, end = t.pop("_running"), t.pop("_end")
        t["queue_s"] = (max(0.0, granted - sub)
                        if sub is not None and granted is not None
                        else None)
        t["scheduling_s"] = (max(0.0, run - granted)
                             if granted is not None and run is not None
                             else None)
        t["execution_s"] = (max(0.0, end - run)
                            if run is not None and end is not None
                            else None)
        t["submitted_at"] = sub
        rows.append(t)
    rows.sort(key=lambda r: r.get("submitted_at") or 0.0)
    return rows


def list_cluster_events(*, address: str | None = None, filters=None,
                        limit=None) -> list[dict]:
    """The cluster's structured runtime event stream (_private/events.py):
    task state transitions, actor lifecycle, node up/down, retry-budget
    exhaustion, injected faults. Unions this process's ring with the GCS
    process's and every raylet's (which fans out over its workers),
    dedups by (node, pid, seq) — in-process test clusters reach the same
    ring through several paths — and returns events time-ordered."""
    from ray_tpu._private import events as _events

    rows = _events.snapshot()
    with _gcs(address) as call:
        try:
            rows.extend(call("events_snapshot"))
        except Exception:
            pass   # pre-telemetry GCS build: its ring just isn't visible
        rows.extend(_each_raylet(call, "events_snapshot"))
    seen: set[tuple] = set()
    deduped = []
    for r in rows:
        key = (r.get("node"), r.get("pid"), r.get("seq"))
        if key in seen:
            continue
        seen.add(key)
        deduped.append(r)
    deduped.sort(key=lambda r: (r.get("ts", 0.0), r.get("node") or "",
                                r.get("pid") or 0, r.get("seq") or 0))
    rows = _apply_filters(deduped, filters, None)
    if limit is not None:
        # a time-ordered log truncates from the HEAD: keep the recent
        # tail (an operator debugging an incident wants the last N
        # events, not the cluster's first N). limit=0 means zero rows,
        # matching _apply_filters' semantics — rows[-0:] would be all.
        rows = rows[-limit:] if limit else []
    return rows


def summarize_objects(*, address: str | None = None) -> dict:
    """Object-store rollup: counts/bytes total and per node (reference:
    `ray summary objects`)."""
    objs = list_objects(address=address)
    per_node: dict[str, dict] = {}
    for o in objs:
        for node in o["Locations"]:
            agg = per_node.setdefault(node, {"count": 0, "bytes": 0})
            agg["count"] += 1
            agg["bytes"] += o["Size"]
    return {"total_objects": len(objs),
            "total_bytes": sum(o["Size"] for o in objs),
            "lost_objects": sum(1 for o in objs if o.get("Lost")),
            "per_node": per_node}


def summarize_control_plane(*, address: str | None = None) -> dict:
    """Control-plane scale & health rollup (cluster soak, round 12):
    the GCS's table sizes, death-feed fanout/coalescing counters,
    registration-admission throttling, and pubsub subscriber/resync
    state — the numbers `benchmarks/soak_bench.py` soaks and
    `ray-tpu control` prints."""
    with _gcs(address) as call:
        state = call("debug_state")
    return {
        "nodes": {"total": state.get("nodes", 0),
                  "alive": state.get("alive_nodes", 0)},
        "actors": {"total": state.get("actors", 0),
                   "alive": state.get("alive_actors", 0)},
        "placement_groups": state.get("placement_groups", 0),
        "objects_tracked": state.get("objects_tracked", 0),
        "death_feed": {
            "batches": state.get("death_batches", 0),
            "deaths_coalesced": state.get("deaths_coalesced", 0),
            "max_batch": state.get("max_death_batch", 0),
            "last_fanout_s": state.get("last_fanout_s", 0.0),
        },
        "registration": {
            "throttled": state.get("register_throttled", 0),
        },
        "pubsub": {
            "subscribers": state.get("pubsub_subscribers", 0),
            "resyncs_served": state.get("pubsub_resyncs_served", 0),
        },
    }


def summarize_topology(*, address: str | None = None) -> dict:
    """ICI-topology rollup: every TPU slice the raylets report (hosts
    with worker index / coords / chips, aliveness) plus which placement
    groups — and which pipeline STAGES of them — currently occupy each
    slice. The operator face of the SPREAD_ACROSS_SLICES scheduler:
    ``ray-tpu topology`` / dashboard ``/api/topology``."""
    with _gcs(address) as call:
        nodes = call("get_nodes")
        pgs = call("list_placement_groups")
    slice_of_node: dict[str, str] = {}
    slices: dict[str, dict] = {}
    for n in nodes:
        tpu = n.get("tpu") or {}
        if not tpu:
            continue
        sid = str(tpu.get("slice_id", "slice-0"))
        slice_of_node[n["NodeID"]] = sid
        entry = slices.setdefault(sid, {
            "hosts": [], "chips": 0, "alive_hosts": 0,
            "accelerator_type": tpu.get("accelerator_type"),
            "topology": tpu.get("topology")})
        host = {"node_id": n["NodeID"],
                "worker_id": int(tpu.get("worker_id", 0)),
                "hostname": n.get("hostname"),
                "alive": bool(n.get("Alive")),
                "chips": int(tpu.get("chips", 0) or 0)}
        if tpu.get("coords"):
            host["coords"] = tpu["coords"]
        entry["hosts"].append(host)
        entry["chips"] += host["chips"]
        entry["alive_hosts"] += 1 if host["alive"] else 0
    for entry in slices.values():
        entry["hosts"].sort(key=lambda h: h["worker_id"])
    occupants: list[dict] = []
    for pg in pgs:
        if pg.get("State") != "CREATED":
            continue
        labels = pg.get("Stages")
        bundle_nodes = pg.get("BundleNodes") or []
        if labels is None:
            labels = list(range(len(bundle_nodes)))
        stage_slices: dict[str, list] = {}
        touched = False
        for lab, nid in zip(labels, bundle_nodes):
            sid = slice_of_node.get(nid)
            if sid is None:
                continue
            touched = True
            bucket = stage_slices.setdefault(str(lab), [])
            if sid not in bucket:
                bucket.append(sid)
        if not touched:
            continue
        row = {"placement_group_id": pg["PlacementGroupID"],
               "name": pg.get("Name", ""), "job": pg.get("Job", ""),
               "strategy": pg.get("Strategy"),
               "stages": stage_slices}
        occupants.append(row)
        for sids in stage_slices.values():
            for sid in sids:
                occ = slices[sid].setdefault("occupants", [])
                if row["placement_group_id"] not in occ:
                    occ.append(row["placement_group_id"])
    return {"num_slices": len(slices),
            "slices": dict(sorted(slices.items())),
            "placement_groups": occupants}


def summarize_jobs(*, address: str | None = None) -> dict:
    """Multi-tenant rollup (the GCS job table + live usage): one row
    per job — priority, quota, cluster-wide usage (CREATED PG bundles +
    gossiped lease usage), dominant resource share, created/pending PG
    counts, preemption and quota-rejection counters — plus the
    cluster totals the soak asserts against:

    - ``quota_violations``: jobs whose live usage exceeds their quota
      (MUST be empty — quota enforcement is admission-time, so a
      violation means the scheduler placed past a cap);
    - ``preemptions`` / ``quota_rejections``: cluster totals;
    - ``serve_apps``: job → Serve app names for jobs that are Serve
      tenants (best-effort controller query) — the jobs-side half of
      the ``summarize_serve()`` cross-link, so an operator reading a
      preemption counter can see which app's autoscaler drove it.
    """
    with _gcs(address) as call:
        rows = call("list_jobs")
    serve_apps: dict[str, list] = {}
    try:
        import ray_tpu
        from ray_tpu.serve._private.constants import (
            CONTROLLER_NAME,
            SERVE_NAMESPACE,
        )

        if ray_tpu.is_initialized():
            controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                           namespace=SERVE_NAMESPACE)
            apps = ray_tpu.get(controller.get_app_status.remote(),
                               timeout=10)
            for app_name, app in apps.items():
                if app.get("job"):
                    serve_apps.setdefault(app["job"], []).append(app_name)
    except Exception:
        pass
    return {
        "jobs": rows,
        "quota_violations": sorted(r["Job"] for r in rows
                                   if r.get("OverQuota")),
        "preemptions": sum(r.get("Preemptions", 0) for r in rows),
        "quota_rejections": sum(r.get("QuotaRejections", 0)
                                for r in rows),
        "serve_apps": serve_apps,
    }


def cluster_status(*, address: str | None = None) -> str:
    """`ray status` analog (reference: scripts.py:1872): node table +
    resource usage summary."""
    from ray_tpu._private.protocol import RpcClient

    with _gcs(address) as call:
        nodes = call("get_nodes")
        lines = ["======== Cluster status ========"]
        alive = [n for n in nodes if n["Alive"]]
        dead = [n for n in nodes if not n["Alive"]]
        lines.append(f"Nodes: {len(alive)} alive, {len(dead)} dead")
        total: dict = {}
        avail: dict = {}
        for n in alive:
            for k, v in n["Resources"].items():
                total[k] = total.get(k, 0) + v
            try:
                c = RpcClient((n["NodeManagerAddress"],
                               n["NodeManagerPort"]), timeout=5.0)
                try:
                    info = c.call("node_info")
                finally:
                    c.close()
                for k, v in info["resources_available"].items():
                    avail[k] = avail.get(k, 0) + v
            except Exception:
                continue
        lines.append("Resources (used/total):")
        for k in sorted(total):
            used = total[k] - avail.get(k, total[k])
            if k == "memory":
                lines.append(f"  {used / 2**30:.1f}/"
                             f"{total[k] / 2**30:.1f} GiB memory")
            else:
                lines.append(f"  {used:g}/{total[k]:g} {k}")
        for n in alive:
            tpu = n.get("tpu")
            suffix = (f" slice={tpu['slice_id']} worker={tpu['worker_id']}"
                      if tpu else "")
            lines.append(f"  node {n['NodeID'][:12]} "
                         f"{n['NodeManagerAddress']}:{n['NodeManagerPort']}"
                         f"{suffix}")
        return "\n".join(lines)


def memory_summary(*, address: str | None = None) -> str:
    """`ray memory` analog (reference: scripts.py:1822)."""
    objs = list_objects(address=address)
    lines = ["======== Object store ========",
             f"Objects tracked: {len(objs)}"]
    total = sum(o["Size"] for o in objs)
    lost = [o for o in objs if o["Lost"]]
    lines.append(f"Total bytes: {total}")
    if lost:
        lines.append(f"Lost objects: {len(lost)}")
    for o in sorted(objs, key=lambda o: -o["Size"])[:20]:
        lines.append(f"  {o['ObjectID'][:16]}  {o['Size']:>12}  "
                     f"on {len(o['Locations'])} node(s)")
    return "\n".join(lines)


def summarize_memory(*, address: str | None = None,
                     top_k: int = 10) -> dict:
    """Memory-anatomy rollup (PR 18): every process's provenance ledger
    (_private/memory_anatomy.py) fanned out like the other telemetry
    RPCs — this process, the GCS, and each raylet's workers — deduped by
    (node, pid) and folded into:

    - ``categories``     cluster-wide live bytes/objects per provenance
                         category (task_arg/task_return/
                         collective_segment/serve_weights/data_staging/
                         checkpoint/other);
    - ``orphans``        leak-sweep rows (deduped by oid — raylet and
                         worker clients sweep the SAME node store) with
                         full creator provenance + reason;
    - ``dropped_frees``  one-way deletes that never landed, per pipeline
                         stage (owner_push/gcs_fanout/raylet_delete);
    - ``train_state``    per-rank params/grads/opt_state/bucket_inflight
                         bytes (exact, from the deterministic flatten);
    - ``top_owners``     the largest live objects cluster-wide;
    - ``per_process``    the raw per-ledger snapshots (ring omitted).
    """
    from ray_tpu._private import memory_anatomy as _ma

    snaps = [_ma.local_snapshot(top_k=top_k)]
    try:
        from ray_tpu._private.worker_runtime import current_worker

        w = current_worker()
        if w is not None:
            snaps[0].setdefault("node", w.node_id)
    except Exception:
        pass
    with _gcs(address) as call:
        try:
            snaps.extend(call("memory_snapshot"))
        except Exception:
            pass   # pre-memory-anatomy GCS build
        snaps.extend(_each_raylet(call, "memory_snapshot"))
    seen: set[tuple] = set()
    procs = []
    for s in snaps:
        key = (s.get("node"), s.get("pid"))
        if key in seen:
            continue
        seen.add(key)
        procs.append(s)

    categories: dict[str, dict] = {}
    dropped: dict[str, int] = {}
    train_state: dict[str, int] = {}
    orphan_by_oid: dict[str, dict] = {}
    owners: list[dict] = []
    for s in procs:
        for cat, v in (s.get("categories") or {}).items():
            agg = categories.setdefault(cat, {"bytes": 0, "objects": 0})
            agg["bytes"] += int(v.get("bytes", 0))
            agg["objects"] += int(v.get("objects", 0))
        for stage, n in (s.get("dropped_frees") or {}).items():
            dropped[stage] = dropped.get(stage, 0) + int(n)
        # per-rank state: each rank process reports its own rows — a
        # later report for the same (kind, rank) supersedes, not adds
        train_state.update(s.get("train_state") or {})
        for row in s.get("orphans") or ():
            orphan_by_oid.setdefault(row.get("oid"), row)
        for row in s.get("top_owners") or ():
            owners.append(dict(row, node=s.get("node")))
    owners.sort(key=lambda r: -(r.get("nbytes") or 0))
    orphans = sorted(orphan_by_oid.values(),
                     key=lambda r: -(r.get("nbytes") or 0))
    return {
        "categories": dict(sorted(categories.items())),
        "live_bytes": sum(c["bytes"] for c in categories.values()),
        "live_objects": sum(c["objects"] for c in categories.values()),
        "orphans": orphans,
        "orphan_bytes": sum(int(r.get("nbytes") or 0) for r in orphans),
        "dropped_frees": dropped,
        "train_state": dict(sorted(train_state.items())),
        "top_owners": owners[:top_k],
        "per_process": [{k: v for k, v in s.items() if k != "ring"}
                        for s in procs],
    }


def _fold_sums(snaps: dict, name: str) -> dict:
    """{sorted-tag-items: value} for one metric family out of a
    ``metrics_summary`` snapshot dict (Counter/Gauge values, Histogram
    observation sums) — the shared fold under every summarize_*."""
    fam = snaps.get(name)
    if not fam:
        return {}
    return {tuple(sorted(v["tags"].items())): v["value"]
            for v in fam.get("values", [])}


def _fold_counts(snaps: dict, name: str) -> dict:
    """{sorted-tag-items: total observation count} for one Histogram
    family out of a ``metrics_summary`` snapshot dict."""
    fam = snaps.get(name)
    if not fam:
        return {}
    return {tuple(sorted(row["tags"].items())): sum(row["counts"])
            for row in fam.get("counts", [])}


def summarize_collectives(*, address: str | None = None) -> dict:
    """Data-plane rollup (reference tier: `ray summary` — but over the
    collective/compile/device telemetry this framework's PR 3 adds).
    Reuses the PR 2 snapshot/aggregation RPCs — everything here is a
    fold over ``metrics_summary()`` plus the cluster event stream, so
    it works connected or standalone exactly like the other summaries:

    - ``ops``        one row per (group, backend, op): call count,
                     total/mean latency, payload bytes moved;
    - ``stragglers`` the COLLECTIVE_STRAGGLER events (group, op, seq,
                     late ranks with their lags);
    - ``compile``    per-fn pjit compile time + cache hit/miss counts
                     (parallel/compile_watch.py);
    - ``devices``    per-device HBM gauges (tpu_probe device poller).
    """
    snaps = {m["name"]: m for m in metrics_summary(address=address)}

    def _sums(name):
        return _fold_sums(snaps, name)

    def _counts(name):
        return _fold_counts(snaps, name)

    ops: dict[tuple, dict] = {}
    lat_sums = _sums("ray_tpu_collective_latency_seconds")
    for key, count in _counts("ray_tpu_collective_latency_seconds").items():
        tags = dict(key)
        total = lat_sums.get(key, 0.0)
        ops[key] = {"group": tags.get("group"),
                    "backend": tags.get("backend"), "op": tags.get("op"),
                    "count": int(count), "total_s": total,
                    "mean_s": (total / count) if count else 0.0,
                    "bytes": 0.0}
    for key, value in _sums("ray_tpu_collective_bytes_total").items():
        tags = dict(key)
        row = ops.setdefault(key, {
            "group": tags.get("group"), "backend": tags.get("backend"),
            "op": tags.get("op"), "count": 0, "total_s": 0.0,
            "mean_s": 0.0, "bytes": 0.0})
        row["bytes"] = value

    compile_fns: dict[str, dict] = {}
    comp_sums = _sums("ray_tpu_pjit_compile_seconds")
    for key, count in _counts("ray_tpu_pjit_compile_seconds").items():
        fn = dict(key).get("fn") or "?"
        total = comp_sums.get(key, 0.0)
        compile_fns[fn] = {"compiles": int(count), "total_s": total,
                           "mean_s": (total / count) if count else 0.0,
                           "cache_hits": 0, "cache_misses": 0}
    for key, value in _sums("ray_tpu_pjit_cache_total").items():
        tags = dict(key)
        fn = tags.get("fn") or "?"
        row = compile_fns.setdefault(fn, {
            "compiles": 0, "total_s": 0.0, "mean_s": 0.0,
            "cache_hits": 0, "cache_misses": 0})
        if tags.get("result") == "hit":
            row["cache_hits"] = int(value)
        elif tags.get("result") == "miss":
            row["cache_misses"] = int(value)

    devices: dict[tuple, dict] = {}
    for key, value in _sums("ray_tpu_device_hbm_bytes").items():
        tags = dict(key)
        # keyed by (node, device): local device ids restart at 0 on
        # every host, so the hostname disambiguates multi-host clusters
        dev = devices.setdefault(
            (tags.get("node"), tags.get("device"), tags.get("platform")),
            {"node": tags.get("node"), "device": tags.get("device"),
             "platform": tags.get("platform")})
        if tags.get("stat") == "in_use":
            dev["hbm_bytes_in_use"] = value
        elif tags.get("stat") == "limit":
            dev["hbm_bytes_limit"] = value

    stragglers = list_cluster_events(
        address=address, filters=[("kind", "=", "COLLECTIVE_STRAGGLER")])
    return {
        "ops": sorted(ops.values(),
                      key=lambda r: (r["group"] or "", r["op"] or "")),
        "stragglers": stragglers,
        "compile": compile_fns,
        "devices": [devices[k] for k in sorted(devices,
                                               key=lambda k: str(k))],
    }


def summarize_data(*, address: str | None = None) -> dict:
    """Streaming-data-plane rollup (folded from the metric catalog like
    ``summarize_collectives``): one row per dataset consumer with its
    batch count, total/mean data-wait, the live prefetch-buffer depth,
    and block counts by origin (local vs remote pulls). The headline
    ingest-health signal is ``mean_wait_s`` against the consumer's step
    time — the ROADMAP's "data wait per step < 5%" acceptance."""
    snaps = {m["name"]: m for m in metrics_summary(address=address)}

    def _sums(name):
        return _fold_sums(snaps, name)

    def _counts(name):
        return _fold_counts(snaps, name)

    consumers: dict[str, dict] = {}

    def _row(consumer):
        return consumers.setdefault(consumer, {
            "consumer": consumer, "batches": 0, "wait_total_s": 0.0,
            "mean_wait_s": 0.0, "prefetch_depth": 0.0,
            "blocks_local": 0, "blocks_remote": 0})

    wait_sums = _sums("ray_tpu_data_wait_seconds")
    for key, count in _counts("ray_tpu_data_wait_seconds").items():
        row = _row(dict(key).get("consumer") or "?")
        total = wait_sums.get(key, 0.0)
        row["batches"] = int(count)
        row["wait_total_s"] = total
        row["mean_wait_s"] = (total / count) if count else 0.0
    for key, value in _sums("ray_tpu_data_prefetch_depth_blocks").items():
        _row(dict(key).get("consumer") or "?")["prefetch_depth"] = value
    for key, value in _sums("ray_tpu_data_blocks_total").items():
        tags = dict(key)
        row = _row(tags.get("consumer") or "?")
        if tags.get("source") == "local":
            row["blocks_local"] = int(value)
        elif tags.get("source") == "remote":
            row["blocks_remote"] = int(value)

    return {"consumers": sorted(consumers.values(),
                                key=lambda r: r["consumer"])}


def summarize_steps(*, address: str | None = None,
                    last: int | None = None) -> dict:
    """Step-anatomy rollup: per-step, per-rank wall-clock attribution
    fused ACROSS the cluster by ``step_id`` (never by wall-clock
    windows — parallel/step_anatomy.py). Collects every process's step
    + activity records (driver-local plus a raylet→worker fan-out,
    like the other telemetry RPCs) and returns::

        {"steps": [{"step_id", "ranks": {rank: {wall_s, compute_s,
                     comm_exposed_s, comm_hidden_s, data_wait_s,
                     data_hidden_s, compile_s, other_s,
                     overlap_fraction}},
                    "critical_path": {"rank", "phase", "wall_s"},
                    "overlap_fraction", "complete"}],
         "ranks": per-rank rollups, "regressions": STEP_REGRESSION
         events, "incomplete": ring-eviction flag, "dropped": counts}

    ``last`` keeps only the most recent N steps (post-fusion).
    ``overlap_fraction`` is hidden / (hidden + exposed) auxiliary time —
    the 2011.03641 metric that says whether pipelining paid off;
    ``critical_path`` names the rank and phase that bounded each step.
    """
    from ray_tpu.parallel import step_anatomy

    exports = [step_anatomy.local_records()]
    with _gcs(address) as call:
        exports.extend(_each_raylet(call, "step_records"))
    fused = step_anatomy.fuse(exports)
    if last is not None:
        fused["steps"] = fused["steps"][-last:] if last else []
    try:
        fused["regressions"] = list_cluster_events(
            address=address, filters=[("kind", "=", "STEP_REGRESSION")])
    except Exception:
        fused["regressions"] = []
    return fused


def summarize_serve(*, address: str | None = None) -> dict:
    """Serving-plane rollup (reference tier: `serve status` + the serve
    dashboard page — but folded from this framework's metric catalog and
    event stream, like ``summarize_collectives``):

    - ``applications``  controller-reported app/deployment/replica FSM
                        status (empty when Serve isn't running);
    - ``requests``      per-deployment completed/error counts, latency
                        totals, sheds, failovers, live queue depth;
    - ``batching``      per-batch-fn executed batch count, mean batch
                        size, mean padded slots (shape-bucket waste);
    - ``events``        replica lifecycle + scaling + shed + tenancy
                        events (REPLICA_STARTED/DIED/DRAINED,
                        SERVE_SCALED, REQUEST_SHED, SERVE_APP_REGISTERED,
                        SERVE_CAPACITY_PLACED, SERVE_REPLICA_WARNED).

    Tenant apps (deployed with ``serve.run(..., job=...)``) carry a
    ``tenancy`` block joined from the GCS job table (the same rows
    ``summarize_jobs()`` reports) for the
    app's job: priority, quota, live usage, dominant share, and the
    preemption / quota-rejection counters — the Serve-side view of the
    same plane the training jobs contend in.
    """
    applications: dict = {}
    try:
        import ray_tpu
        from ray_tpu.serve._private.constants import (
            CONTROLLER_NAME,
            SERVE_NAMESPACE,
        )

        if ray_tpu.is_initialized():
            controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                           namespace=SERVE_NAMESPACE)
            applications = ray_tpu.get(
                controller.get_app_status.remote(), timeout=10)
    except Exception:
        applications = {}
    if any(app.get("job") for app in applications.values()):
        # Straight to the GCS job table: summarize_jobs() would repeat
        # the controller get_app_status RPC made above (its serve_apps
        # cross-link) — doubling controller round-trips per call.
        try:
            with _gcs(address) as call:
                job_rows = {r["Job"]: r for r in call("list_jobs")}
        except Exception:
            job_rows = {}
        for app in applications.values():
            job = app.get("job")
            if job and job in job_rows:
                r = job_rows[job]
                app["tenancy"] = {
                    "priority": r.get("Priority"),
                    "quota": r.get("Quota"),
                    "usage": r.get("Usage"),
                    "dominant_share": r.get("DominantShare"),
                    "preemptions": r.get("Preemptions"),
                    "quota_rejections": r.get("QuotaRejections"),
                    "over_quota": r.get("OverQuota"),
                }

    snaps = {m["name"]: m for m in metrics_summary(address=address)}

    def _sums(name):
        return _fold_sums(snaps, name)

    def _counts(name):
        return _fold_counts(snaps, name)

    requests: dict[str, dict] = {}

    def _dep_row(dep):
        return requests.setdefault(dep, {
            "ok": 0, "error": 0, "latency_total_s": 0.0, "mean_latency_s":
            0.0, "shed": 0, "failovers": 0, "queue_depth": 0.0})

    for key, value in _sums("ray_tpu_serve_requests_total").items():
        tags = dict(key)
        row = _dep_row(tags.get("deployment") or "?")
        if tags.get("result") in ("ok", "error"):
            row[tags["result"]] = int(value)
    lat_sums = _sums("ray_tpu_serve_request_latency_seconds")
    for key, count in _counts("ray_tpu_serve_request_latency_seconds"
                              ).items():
        row = _dep_row(dict(key).get("deployment") or "?")
        total = lat_sums.get(key, 0.0)
        row["latency_total_s"] = total
        row["mean_latency_s"] = (total / count) if count else 0.0
    for key, value in _sums("ray_tpu_serve_shed_total").items():
        _dep_row(dict(key).get("deployment") or "?")["shed"] = int(value)
    for key, value in _sums("ray_tpu_serve_failovers_total").items():
        _dep_row(dict(key).get("deployment") or "?")["failovers"] = \
            int(value)
    for key, value in _sums("ray_tpu_serve_queue_depth_tasks").items():
        # one series per (deployment, role): sum roles for total demand
        _dep_row(dict(key).get("deployment") or "?")["queue_depth"] += value

    batching: dict[str, dict] = {}
    size_sums = _sums("ray_tpu_serve_batch_size_tasks")
    for key, count in _counts("ray_tpu_serve_batch_size_tasks").items():
        fn = dict(key).get("fn") or "?"
        total = size_sums.get(key, 0.0)
        batching[fn] = {"batches": int(count),
                        "mean_batch_size": (total / count) if count else 0.0,
                        "mean_pad_waste": 0.0}
    pad_sums = _sums("ray_tpu_serve_batch_pad_waste_tasks")
    for key, count in _counts("ray_tpu_serve_batch_pad_waste_tasks").items():
        fn = dict(key).get("fn") or "?"
        row = batching.setdefault(fn, {"batches": int(count),
                                       "mean_batch_size": 0.0,
                                       "mean_pad_waste": 0.0})
        total = pad_sums.get(key, 0.0)
        row["mean_pad_waste"] = (total / count) if count else 0.0

    serve_kinds = {"REPLICA_STARTED", "REPLICA_DIED", "REPLICA_DRAINED",
                   "SERVE_SCALED", "REQUEST_SHED", "SERVE_APP_REGISTERED",
                   "SERVE_CAPACITY_PLACED", "SERVE_REPLICA_WARNED"}
    events = [e for e in list_cluster_events(address=address)
              if e.get("kind") in serve_kinds]
    return {"applications": applications, "requests": requests,
            "batching": batching, "events": events}


def metrics_summary(*, address: str | None = None,
                    prometheus: bool = False):
    """Aggregate metrics (user Counter/Gauge/Histogram plus the runtime's
    internal catalog, _private/telemetry.py) across every process: this
    one, the GCS, and each raylet's workers. Snapshots are merged into
    one family per metric name (counters/histograms sum per tag set,
    gauges keep the last collected value; processes reachable via two
    collection paths are deduped by (node, pid)). prometheus=True
    renders the text exposition format (reference: the dashboard agent's
    Prometheus endpoint, reporter_agent.py:296)."""
    from ray_tpu.util.metrics import (
        aggregate_snapshots,
        prometheus_text,
        registry_snapshot,
    )

    with _gcs(address) as call:
        snaps = registry_snapshot()           # this process too
        try:
            snaps.extend(call("metrics_snapshot"))   # the GCS process
        except Exception:
            pass
        snaps.extend(_each_raylet(call, "metrics_snapshot"))
    snaps = aggregate_snapshots(snaps)
    if prometheus:
        return prometheus_text(snaps)
    return snaps


