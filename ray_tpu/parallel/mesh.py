"""Device-mesh construction for every parallelism axis the framework knows.

This replaces the reference's NCCL/Gloo communicator world (its
python/ray/util/collective/collective_group/) with the TPU-native
equivalent: a named `jax.sharding.Mesh` whose axes are the parallelism
strategies themselves. All collectives then compile to ICI/DCN collectives
inside XLA programs instead of being library calls.

Axis vocabulary (sizes multiply to the device count):

  dp — data parallel: gradients psum'd over it; typically the outermost
       (slowest-varying) axis so it lands on DCN between slices.
  pp — pipeline parallel: stages; activations move via ppermute.
  ep — expert parallel: MoE experts sharded; tokens move via all_to_all.
  sp — sequence/context parallel: the sequence dimension of activations is
       sharded; ring attention rotates KV blocks around this axis.
  tp — tensor parallel: attention heads / MLP hidden sharded; innermost
       (fastest-varying) so its collectives ride nearest-neighbor ICI.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ray_tpu.parallel.compile_watch import timed_mesh_build

# Canonical axis order, slowest- to fastest-varying. Matches
# GlobalConfig.mesh_ici_axis_order.
AXIS_ORDER = ("dp", "pp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """How many ways to shard along each parallelism axis.

    Any axis left at -1 absorbs the remaining devices (at most one -1).
    """

    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolved(self, n_devices: int) -> "MeshConfig":
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError("At most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"Mesh {sizes} needs {fixed} devices but {n_devices} present"
            )
        return MeshConfig(**sizes)

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}


@timed_mesh_build("mesh")
def create_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axes: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all).

    On real TPU slices we delegate the physical layout to
    `mesh_utils.create_device_mesh`, which maps the logical axes onto the
    ICI torus so that the fastest-varying axes are nearest-neighbor; on CPU
    (tests) a plain reshape is used.
    """
    if config is None:
        config = MeshConfig(**(axes or {"dp": -1}))
    devices = list(devices if devices is not None else jax.devices())
    config = config.resolved(len(devices))
    sizes = config.axis_sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    if devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(
                shape, devices=np.asarray(devices, dtype=object)
            )
            return Mesh(dev_array, AXIS_ORDER)
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "mesh_utils.create_device_mesh failed (%s: %s); falling back "
                "to a naive device layout — collectives may cross non-neighbor "
                "ICI links", type(e).__name__, e,
            )
    dev_array = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    device = device or jax.devices()[0]
    return create_mesh(MeshConfig(), devices=[device])


def balanced_factorization(n: int, axes: Sequence[str]) -> Dict[str, int]:
    """Split n devices over `axes` as evenly as possible (used by the
    multi-chip dry run to make every requested axis non-degenerate when the
    device count allows)."""
    sizes = {a: 1 for a in axes}
    remaining = n
    # Greedily assign factors of 2 (TPU slice sizes are powers of two),
    # round-robin over the requested axes.
    i = 0
    axes = list(axes)
    while remaining % 2 == 0 and remaining > 1:
        sizes[axes[i % len(axes)]] *= 2
        remaining //= 2
        i += 1
    if remaining > 1:  # non-power-of-two leftover goes to the first axis
        sizes[axes[0]] *= remaining
    return sizes


def mesh_shape_summary(mesh: Mesh) -> str:
    return "x".join(f"{k}={v}" for k, v in mesh.shape.items())


def validate_mesh_for_model(mesh: Mesh, *, n_heads: int, n_layers: int) -> List[str]:
    """Sanity checks mirroring the reference's option validation layer
    (python/ray/_private/ray_option_utils.py): returns human-readable
    problems instead of letting XLA fail deep inside compilation."""
    problems = []
    shape = dict(mesh.shape)
    if n_heads % (shape.get("tp", 1)) != 0:
        problems.append(f"n_heads={n_heads} not divisible by tp={shape.get('tp')}")
    if n_layers % (shape.get("pp", 1)) != 0:
        problems.append(f"n_layers={n_layers} not divisible by pp={shape.get('pp')}")
    return problems


def group_devices_by_slice(devices: Sequence[jax.Device]) -> Dict[int, list]:
    """Group devices by their TPU slice (`slice_index`; single-slice and
    CPU devices all land in slice 0)."""
    groups: Dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
    return groups


@timed_mesh_build("hybrid_mesh")
def create_hybrid_mesh(
    config: MeshConfig | None = None,
    *,
    dcn_dp: int = -1,
    devices: Optional[Sequence[jax.Device]] = None,
    axes: Optional[Dict[str, int]] = None,
    slice_assignments: Optional[Sequence[int]] = None,
) -> Mesh:
    """Multi-slice mesh: `dp` spans slices over DCN, every other axis stays
    inside a slice on ICI (the megascale layout; public recipe:
    jax mesh_utils.create_hybrid_device_mesh).

    `config`/`axes` describe the WITHIN-slice sharding; `dcn_dp` is the
    between-slice data-parallel degree (-1 = one dp shard per slice). The
    returned mesh's dp axis size is ``dcn_dp * config.dp``; gradient psums
    over dp then hierarchically reduce inside each slice first (ICI) and
    cross slices (DCN) once — XLA does that decomposition when the axis is
    laid out slice-major, which this function guarantees.

    `slice_assignments` forces a slice id per device — the CPU-mesh test
    hook (virtual CPU devices all report slice 0).
    """
    devices = list(devices if devices is not None else jax.devices())
    if slice_assignments is not None:
        if len(slice_assignments) != len(devices):
            raise ValueError(
                f"slice_assignments has {len(slice_assignments)} entries "
                f"for {len(devices)} devices")
        groups: Dict[int, list] = {}
        for d, s in zip(devices, slice_assignments):
            groups.setdefault(s, []).append(d)
    else:
        groups = group_devices_by_slice(devices)
    n_slices = len(groups)
    if dcn_dp == -1:
        dcn_dp = n_slices
    if dcn_dp != n_slices:
        raise ValueError(
            f"dcn_dp={dcn_dp} but {n_slices} slices present (one dp shard "
            f"per slice is the supported DCN layout)")
    sizes = sorted(len(g) for g in groups.values())
    if sizes[0] != sizes[-1]:
        raise ValueError(f"uneven slices: {sizes}")
    per_slice = sizes[0]

    if config is None:
        # default: all within-slice devices on tp (dp is the DCN axis here)
        config = MeshConfig(**(axes or {"tp": -1}))
    config = config.resolved(per_slice)

    if devices[0].platform == "tpu" and slice_assignments is None:
        try:
            from jax.experimental import mesh_utils

            inner = tuple(config.axis_sizes()[a] for a in AXIS_ORDER)
            dcn = tuple(dcn_dp if a == "dp" else 1 for a in AXIS_ORDER)
            dev_array = mesh_utils.create_hybrid_device_mesh(
                inner, dcn, devices=devices)
            return Mesh(dev_array, AXIS_ORDER)
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "create_hybrid_device_mesh failed (%s: %s); using "
                "slice-major fallback layout", type(e).__name__, e)
    # Fallback (CPU tests / degraded TPU path): slice-major ordering makes
    # dp the slowest-varying axis, so dp index = slice for the DCN part.
    ordered: list = []
    for s in sorted(groups):
        ordered.extend(groups[s])
    sizes_d = config.axis_sizes()
    shape = tuple((dcn_dp * sizes_d[a]) if a == "dp" else sizes_d[a]
                  for a in AXIS_ORDER)
    dev_array = np.asarray(ordered, dtype=object).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)
