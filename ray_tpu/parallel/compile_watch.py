"""Compile-path observability for the pjit data plane.

A slow training step is either a slow step or a RECOMPILING step, and
without instrumentation the two are indistinguishable from the driver.
This module wraps the jitted callables ``make_train_step`` /
``eval_step`` / ``make_train_state`` hand out so every call is
classified against a per-signature compile cache:

- cache hit: one counter inc (``ray_tpu_pjit_cache_total{result=hit}``),
  then straight into the jitted function;
- cache miss: ``COMPILE_BEGIN``/``COMPILE_END`` cluster events, a span
  in BOTH the chrome-trace timeline (_private/profiling.py, µs) and
  util/tracing (ns — joins the surrounding task's trace), and the
  wall time into ``ray_tpu_pjit_compile_seconds``.

Classification is O(1) on the hit path: jitted callables expose
``_cache_size()`` (~0.1µs), so a call that grew the cache IS a
trace+compile — no signature re-derivation duplicating jit's own C++
dispatch on every training step. Callables without ``_cache_size``
fall back to a per-signature key at jit's abstraction level ((shape,
dtype) per array leaf + pytree structure). The measured duration is
trace + compile + first execution (the recompile-attribution signal
operators need), not a pure XLA compile timer; on the cache-size path
the COMPILE_BEGIN event is materialized after the fact (the miss is
only knowable once the call returns) and carries ``started_at``.

Mesh construction gets the same treatment through ``mesh_build_timer``
(``ray_tpu_mesh_build_seconds{kind}``): on a multi-slice pod,
``mesh_utils.create_device_mesh`` does real topology work worth seeing.

Everything is behind the ``RAY_TPU_INTERNAL_TELEMETRY=0`` kill switch;
disabled, a wrapped call costs one attribute read and one bool check.
"""
from __future__ import annotations

import contextlib
import functools
import threading
import time

from ray_tpu._private import events as _events
from ray_tpu._private import profiling as _prof
from ray_tpu._private import telemetry as _tm


def _abstract_key(args, kwargs):
    """Hashable per-call signature at jit's abstraction level: pytree
    structure + (shape, dtype) per array leaf, value for hashable
    scalar leaves (static-ish), type name otherwise. The PyTreeDef goes
    into the key AS-IS (it is hashable): rendering it to a string would
    cost a multi-KB format of the whole param tree on the cache-HIT
    path of every training step."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        elif isinstance(leaf, (int, float, bool, complex, str,
                               bytes, type(None))):
            sig.append((type(leaf).__name__, leaf))
        else:
            sig.append(type(leaf).__name__)
    return (treedef, tuple(sig))


class CompiledFunction:
    """Wraps a jitted callable with compile-cache observability.
    Transparent otherwise: unknown attributes (``lower``,
    ``clear_cache``, ...) delegate to the wrapped function."""

    def __init__(self, fn, name: str):
        self._fn = fn
        self._name = name
        self._seen: set = set()
        self._seen_lock = threading.Lock()
        functools.update_wrapper(self, fn, updated=())

    def __getattr__(self, item):
        if item == "_fn":
            # only reachable mid-unpickle (before __setstate__ ran);
            # without this guard delegation recurses to a stack overflow
            raise AttributeError(item)
        return getattr(self._fn, item)

    # The bare jax.jit return value cloudpickles across task boundaries;
    # the wrapper must too (the lock is unpicklable, and the _seen cache
    # is deliberately dropped: the receiving process's jit cache is
    # empty, so its first call IS a compile — a fresh cache keeps the
    # hit/miss classification truthful there).
    def __getstate__(self):
        return {"fn": self._fn, "name": self._name}

    def __setstate__(self, state):
        self.__init__(state["fn"], state["name"])

    def __call__(self, *args, **kwargs):
        if not _tm.ENABLED:
            return self._fn(*args, **kwargs)
        cache_size = getattr(self._fn, "_cache_size", None)
        if cache_size is None:
            return self._call_classified_by_signature(args, kwargs)
        # O(1) hot path: jit's own cache is the source of truth (it
        # also respects static args / weak types the signature key
        # can't see). A failed compile never grows the cache, so the
        # retry naturally counts as a miss again.
        before = cache_size()
        start = time.time()
        t0 = time.perf_counter()
        tags = {"fn": self._name}
        try:
            out = self._fn(*args, **kwargs)
        except BaseException:
            # NOT gated on the cache delta: some jax versions grow the
            # pjit cache even when tracing raises, so the delta can't
            # distinguish failure modes — the _seen set can (below)
            self._record_failed_call(args, kwargs, start,
                                     time.perf_counter() - t0, tags)
            raise
        if cache_size() == before:
            _tm.counter_inc("ray_tpu_pjit_cache_total",
                            tags={**tags, "result": "hit"})
            return out
        # a compile happened: remember the signature (cheap relative to
        # the compile it just paid for) so a LATER failing call of the
        # same signature classifies as a runtime error, not a compile
        # failure
        with self._seen_lock:
            self._seen.add(_abstract_key(args, kwargs))
        self._record_miss(start, time.perf_counter() - t0, tags)
        return out

    def _record_failed_call(self, args, kwargs, start, dur, tags):
        """Error-path classification (cost is irrelevant here): the
        cache did not grow, so either the compile itself failed (XLA
        error, OOM during lowering — signature never seen to succeed)
        or an already-compiled program failed at runtime (signature in
        _seen; not a compile event at all). Without this, a
        crash-looping worker shows ZERO compile activity on the common
        _cache_size path while the fallback path reports COMPILE_END
        ok=False."""
        try:
            key = _abstract_key(args, kwargs)
        except Exception:
            return
        with self._seen_lock:
            if key in self._seen:
                return   # runtime failure of a compiled program
        _tm.counter_inc("ray_tpu_pjit_cache_total",
                        tags={**tags, "result": "miss"})
        _events.record("COMPILE_BEGIN", fn=self._name, started_at=start)
        _events.record("COMPILE_END", fn=self._name, ok=False,
                       duration_s=dur)

    def _record_miss(self, start: float, dur: float, tags: dict):
        """Metrics + BEGIN/END events + both span planes for one
        compile, materialized after the fact (the cache-size delta is
        only knowable once the call returned). A compile inside an
        active train step additionally lands in the step-anatomy ring
        (and stamps the events) — a recompiling step must show up as a
        compile-bounded step, not unexplained "compute"."""
        from ray_tpu.parallel import step_anatomy as _sa
        from ray_tpu.util import tracing

        step_id = _sa.current_step_id()
        _tm.counter_inc("ray_tpu_pjit_cache_total",
                        tags={**tags, "result": "miss"})
        _tm.observe("ray_tpu_pjit_compile_seconds", dur, tags=tags)
        _events.record("COMPILE_BEGIN", fn=self._name, started_at=start,
                       step=step_id)
        _events.record("COMPILE_END", fn=self._name, ok=True,
                       duration_s=dur, step=step_id)
        if step_id is not None:
            m1 = time.monotonic()
            _sa.record_activity("compile", m1 - dur, m1, blocking=True,
                                fn=self._name)
        start_ns = int(start * 1e9)
        end_ns = start_ns + int(dur * 1e9)
        _prof.record_completed_span("compile", f"compile::{self._name}",
                                    start, dur, {"fn": self._name,
                                                 "step": step_id})
        tracing.record_completed_span(f"compile {self._name}", "INTERNAL",
                                      start_ns, end_ns,
                                      attributes={"fn": self._name,
                                                  "step": step_id})

    def _call_classified_by_signature(self, args, kwargs):
        """Fallback for callables without ``_cache_size``: classify by
        a per-signature key. The signature is taken BEFORE the call —
        donated buffers are unreadable after."""
        key = _abstract_key(args, kwargs)
        with self._seen_lock:
            hit = key in self._seen
            if not hit:
                self._seen.add(key)
        tags = {"fn": self._name}
        if hit:
            _tm.counter_inc("ray_tpu_pjit_cache_total",
                            tags={**tags, "result": "hit"})
            return self._fn(*args, **kwargs)
        from ray_tpu.parallel import step_anatomy as _sa
        from ray_tpu.util import tracing

        _tm.counter_inc("ray_tpu_pjit_cache_total",
                        tags={**tags, "result": "miss"})
        _events.record("COMPILE_BEGIN", fn=self._name)
        t0 = time.perf_counter()
        m0 = time.monotonic()
        try:
            with _prof.record_span("compile", f"compile::{self._name}"):
                with tracing.span(f"compile {self._name}", "INTERNAL",
                                  attributes={"fn": self._name}):
                    out = self._fn(*args, **kwargs)
        except BaseException:
            # a failed compile must not be remembered as compiled —
            # the retry should count (and be timed) as a miss again
            with self._seen_lock:
                self._seen.discard(key)
            _events.record("COMPILE_END", fn=self._name, ok=False,
                           duration_s=time.perf_counter() - t0)
            raise
        dur = time.perf_counter() - t0
        _sa.record_activity("compile", m0, time.monotonic(),
                            blocking=True, fn=self._name)
        _tm.observe("ray_tpu_pjit_compile_seconds", dur, tags=tags)
        _events.record("COMPILE_END", fn=self._name, ok=True,
                       duration_s=dur)
        return out


@contextlib.contextmanager
def mesh_build_timer(kind: str):
    """Time one device-mesh construction into
    ``ray_tpu_mesh_build_seconds{kind}`` + both span planes."""
    if not _tm.ENABLED:
        yield
        return
    from ray_tpu.util import tracing

    t0 = time.perf_counter()
    with _prof.record_span("mesh", f"mesh_build::{kind}"):
        with tracing.span(f"mesh_build {kind}", "INTERNAL",
                          attributes={"kind": kind}):
            yield
    _tm.observe("ray_tpu_mesh_build_seconds",
                time.perf_counter() - t0, tags={"kind": kind})


def timed_mesh_build(kind: str):
    """Decorator form of ``mesh_build_timer`` for the mesh factories."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with mesh_build_timer(kind):
                return fn(*args, **kwargs)
        return wrapper
    return deco
