"""Logical-axis sharding rules: model code names dimensions, this module
maps them to mesh axes.

This is the TPU-native replacement for the reference's per-framework
process-group plumbing (train/torch/config.py): instead of wiring NCCL
process groups, models annotate arrays with logical axis names and XLA
inserts the collectives implied by the mapping.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AxisVal = Union[None, str, Tuple[str, ...]]

# Default logical→mesh rules for transformer LMs. "seq" rides the sp axis
# (sequence/context parallelism); "heads"/"mlp"/"vocab" ride tp; "experts"
# ride ep; "layers" ride pp when pipelining is on; "batch" rides dp.
DEFAULT_RULES: Dict[str, AxisVal] = {
    "batch": "dp",
    "seq": "sp",
    "embed": None,
    "heads": "tp",
    "kv": None,
    "head_dim": None,
    "mlp": "tp",
    "experts": "ep",
    "expert_mlp": "tp",
    "vocab": "tp",
    "stage": "pp",
    "layers": None,
}


def spec(*logical_axes: Optional[str], rules: Optional[Dict[str, AxisVal]] = None) -> P:
    """PartitionSpec from logical axis names, e.g. spec("batch","seq","embed")."""
    rules = rules or DEFAULT_RULES
    out = []
    for ax in logical_axes:
        if ax is None:
            out.append(None)
        else:
            if ax not in rules:
                raise KeyError(f"No sharding rule for logical axis {ax!r}")
            out.append(rules[ax])
    return P(*out)


def named_sharding(
    mesh: Mesh, *logical_axes: Optional[str], rules: Optional[Dict[str, AxisVal]] = None
) -> NamedSharding:
    return NamedSharding(mesh, spec(*logical_axes, rules=rules))


def tree_shard(tree, mesh: Mesh, spec_tree):
    """Device-put a pytree with a matching pytree of PartitionSpecs."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, spec_tree
    )


def constrain(x, mesh: Mesh, *logical_axes: Optional[str], rules=None):
    """In-jit sharding constraint by logical names."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec(*logical_axes, rules=rules))
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def axis_size(mesh: Mesh, axis: Optional[str]) -> int:
    if axis is None:
        return 1
    return dict(mesh.shape).get(axis, 1)
