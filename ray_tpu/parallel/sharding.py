"""Logical-axis sharding rules: model code names dimensions, this module
maps them to mesh axes.

This is the TPU-native replacement for the reference's per-framework
process-group plumbing (train/torch/config.py): instead of wiring NCCL
process groups, models annotate arrays with logical axis names and XLA
inserts the collectives implied by the mapping.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AxisVal = Union[None, str, Tuple[str, ...]]

# Default logical→mesh rules for transformer LMs. "seq" rides the sp axis
# (sequence/context parallelism); "heads"/"mlp"/"vocab" ride tp; "experts"
# ride ep; "layers" ride pp when pipelining is on; "batch" rides dp.
DEFAULT_RULES: Dict[str, AxisVal] = {
    "batch": "dp",
    "seq": "sp",
    "embed": None,
    "heads": "tp",
    "kv": None,
    "head_dim": None,
    "mlp": "tp",
    "experts": "ep",
    "expert_mlp": "tp",
    "vocab": "tp",
    "stage": "pp",
    "layers": None,
}


def spec(*logical_axes: Optional[str], rules: Optional[Dict[str, AxisVal]] = None) -> P:
    """PartitionSpec from logical axis names, e.g. spec("batch","seq","embed")."""
    rules = rules or DEFAULT_RULES
    out = []
    for ax in logical_axes:
        if ax is None:
            out.append(None)
        else:
            if ax not in rules:
                raise KeyError(f"No sharding rule for logical axis {ax!r}")
            out.append(rules[ax])
    return P(*out)


def named_sharding(
    mesh: Mesh, *logical_axes: Optional[str], rules: Optional[Dict[str, AxisVal]] = None
) -> NamedSharding:
    return NamedSharding(mesh, spec(*logical_axes, rules=rules))


def tree_shard(tree, mesh: Mesh, spec_tree):
    """Device-put a pytree with a matching pytree of PartitionSpecs."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, spec_tree
    )


def constrain(x, mesh: Mesh, *logical_axes: Optional[str], rules=None):
    """In-jit sharding constraint by logical names."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec(*logical_axes, rules=rules))
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# --------------------------------------------------- gradient bucket plumbing
#
# Pytree plumbing for the bucketed-DDP gradient sync (train/ddp.py):
# flatten a grad pytree in jax's canonical deterministic order, plan
# size-targeted buckets over the leaves, and pack/unpack each bucket as
# one contiguous array the collective plane can move. Planning depends
# ONLY on the tree structure + leaf shapes/dtypes, so every rank of a
# data-parallel gang derives byte-identical buckets locally — the
# precondition for the allreduce results to agree.


def flatten_tree(tree):
    """(leaves, treedef) in jax's canonical flatten order (sorted dict
    keys, registered-pytree field order) — deterministic across ranks
    for identical model structures."""
    import jax

    return jax.tree_util.tree_flatten(tree)


def unflatten_tree(treedef, leaves):
    import jax

    return jax.tree_util.tree_unflatten(treedef, leaves)


def plan_buckets(leaves, bucket_bytes: int) -> list[list[int]]:
    """Partition leaf indices into size-targeted buckets.

    Leaves are grouped by dtype (first-appearance order — a bucket is
    packed into ONE contiguous array, so members must share a dtype)
    and, within each dtype, kept in flatten order and greedily filled
    up to ``bucket_bytes``. A single leaf larger than the target gets
    its own bucket (never split: the collective plane's segmented ring
    already pipelines within one op). Every rank derives the same plan
    from the same tree."""
    bucket_bytes = max(1, int(bucket_bytes))
    by_dtype: dict = {}
    order: list = []
    for i, leaf in enumerate(leaves):
        dt = str(getattr(leaf, "dtype", "object"))
        if dt not in by_dtype:
            by_dtype[dt] = []
            order.append(dt)
        by_dtype[dt].append(i)
    plan: list[list[int]] = []
    for dt in order:
        cur: list[int] = []
        cur_bytes = 0
        for i in by_dtype[dt]:
            nbytes = int(getattr(leaves[i], "nbytes", 0))
            if cur and cur_bytes + nbytes > bucket_bytes:
                plan.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            plan.append(cur)
    return plan


def pack_bucket(leaves, indices):
    """One contiguous 1-D array holding the raveled members of a bucket
    (C order). Materializes device-resident leaves (``np.asarray`` is
    the device→host fetch for jax arrays) member by member, so packing
    bucket k+1 can overlap bucket k's in-flight allreduce."""
    import numpy as np

    total = 0
    for i in indices:
        n = 1
        for d in getattr(leaves[i], "shape", ()):
            n *= int(d)
        total += n
    out = np.empty(total,
                   dtype=np.dtype(getattr(leaves[indices[0]], "dtype",
                                          np.float64)))
    pos = 0
    for i in indices:
        arr = np.asarray(leaves[i]).reshape(-1)
        out[pos:pos + arr.size] = arr
        pos += arr.size
    return out


def unpack_bucket(flat, leaves, indices, out_leaves):
    """Scatter one reduced bucket back into per-leaf arrays (shapes
    taken from the original leaves); writes into ``out_leaves`` at the
    bucket's indices."""
    import numpy as np

    pos = 0
    for i in indices:
        shape = tuple(getattr(leaves[i], "shape", ()))
        n = 1
        for d in shape:
            n *= int(d)
        out_leaves[i] = np.asarray(flat[pos:pos + n]).reshape(shape)
        pos += n


def shard_bounds(total: int, parts: int) -> list:
    """Split ``total`` elements into ``parts`` contiguous ``[lo, hi)``
    chunks; the first ``total % parts`` chunks are one element longer.
    This is the SAME divmod math as the host collective backend's
    ``_split_bounds`` (pinned equal by test): a reducescatter over a
    packed bucket hands rank r exactly elements ``bounds[r]``, so the
    sharded-optimizer map below and the wire layer always agree on
    where a rank's shard of each bucket lives."""
    total = int(total)
    parts = max(1, int(parts))
    base, extra = divmod(total, parts)
    bounds = []
    lo = 0
    for r in range(parts):
        hi = lo + base + (1 if r < extra else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def plan_shard_map(leaves, plan, world: int) -> list:
    """Per-bucket shard map for ZeRO-style sharded DDP: one dict per
    bucket of ``plan`` (from :func:`plan_buckets`) with the bucket's
    packed element count, dtype, and per-rank ``[lo, hi)`` shard bounds
    (``shard_bounds(elems, world)``). Depends ONLY on leaf shapes +
    dtypes + the plan + world size — every rank derives a byte-identical
    map locally, which is the precondition for each rank to own (and be
    the sole updater of) the same optimizer-state shard every step."""
    import numpy as np

    out = []
    for indices in plan:
        elems = 0
        for i in indices:
            n = 1
            for d in getattr(leaves[i], "shape", ()):
                n *= int(d)
            elems += n
        dt = np.dtype(getattr(leaves[indices[0]], "dtype", np.float64))
        out.append({
            "indices": list(indices),
            "elems": elems,
            "dtype": dt,
            "bounds": shard_bounds(elems, world),
        })
    return out


def plan_fingerprint(leaves, plan) -> str:
    """Deterministic sha256 hex digest of the bucket plan's full
    identity: per-leaf (shape, dtype) in flatten order plus the plan's
    bucket membership. Depends ONLY on leaf shapes + dtypes + the plan —
    NOT on world size — so a gang restarting at a different world size
    derives the SAME fingerprint from the same model, which is what
    makes a saved shard set re-sliceable: matching fingerprints mean the
    packed element streams are byte-compatible and restore reduces to
    pure index math (:func:`reslice_spans`)."""
    import hashlib

    h = hashlib.sha256()
    for leaf in leaves:
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
        dt = str(getattr(leaf, "dtype", "object"))
        h.update(repr((shape, dt)).encode())
    for indices in plan:
        h.update(repr(tuple(indices)).encode())
    return h.hexdigest()


def reslice_spans(elems: int, old_world: int, new_world: int,
                  new_rank: int) -> list:
    """Pure index math for world-elastic restore of ONE packed bucket:
    which byte-compatible spans of which OLD ranks' shards concatenate
    into NEW rank ``new_rank``'s shard. Returns
    ``[(old_rank, old_lo, old_hi), ...]`` in order, where
    ``[old_lo, old_hi)`` indexes INTO that old rank's saved shard array
    (not the bucket). Both layouts come from :func:`shard_bounds` over
    the same ``elems``, so the concatenated spans are exactly the new
    rank's ``[lo, hi)`` slice of the packed bucket — bit-identical to
    what a same-world save/restore would hand it."""
    new_lo, new_hi = shard_bounds(elems, new_world)[int(new_rank)]
    spans = []
    for old_rank, (old_lo, old_hi) in enumerate(
            shard_bounds(elems, old_world)):
        lo = max(new_lo, old_lo)
        hi = min(new_hi, old_hi)
        if lo < hi:
            spans.append((old_rank, lo - old_lo, hi - old_lo))
    return spans


def axis_size(mesh: Mesh, axis: Optional[str]) -> int:
    if axis is None:
        return 1
    return dict(mesh.shape).get(axis, 1)
