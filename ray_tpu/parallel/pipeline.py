"""Pipeline parallelism over the `pp` mesh axis (SPMD GPipe).

The reference delegates intra-model parallelism to user frameworks
(SURVEY.md §2.4 — TP/PP absent); here pipelining is a first-class mesh
axis. Unlike the reference's would-be MPMD (one process per stage over
NCCL p2p), the TPU-native design keeps a single SPMD program: every device
runs the same `lax.scan` schedule, stage parameters are sharded over `pp`,
and activations hop stages via `jax.lax.ppermute` (which XLA lowers to ICI
neighbor transfers). See PAPERS.md "Scaling Deep Learning Training with
MPMD Pipeline Parallelism" for the design space; this is the simpler SPMD
point in it.

Composability: the shard_map is manual over `pp` plus any `manual_axes`
the caller adds — inside a stage, arrays keep their global dp/tp shardings
and GSPMD still inserts tensor-parallel collectives. For pp×sp joint
training, pass manual_axes=("sp",) and use the PER-SHARD ring attention
(ring_attention_local / impl="ring_local") inside the stage: one flat
manual region differentiates cleanly, where a nested sp-shard_map inside
the pp scan used to trip DuplicateSpecError in transpose (jax 0.9).

Schedule: GPipe with M microbatches over P stages — T = M + P - 1 ticks;
stage s works on microbatch t - s at tick t. Bubble fraction (P-1)/T.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def gpipe_local(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    microbatches: jnp.ndarray,
    *,
    axis_name: str = "pp",
    carry_dtype=None,
):
    """Per-shard GPipe schedule. MUST run inside shard_map with `axis_name`
    manual.

    stage_fn(params, x) -> y applies THIS device's stage; y must have x's
    shape/dtype (transformer-block invariant).
    stage_params: this stage's parameter pytree (stage dim already sliced
        away by shard_map in_specs).
    microbatches: [M, B_mb, ...] — every stage sees the stream; only stage 0
        consumes it.

    Returns [M, B_mb, ...]: the last stage's outputs, psum-replicated over
    `axis_name` (zeros contributed by other stages).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + n_stages - 1
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    compute_dtype = microbatches.dtype
    if carry_dtype is None and jax.default_backend() != "tpu":
        # XLA:CPU miscompiles bf16 select/ppermute chains in this schedule
        # ("Invalid binary instruction opcode copy" check-fail); carry f32
        # off-TPU. On TPU the native dtype rides ICI (half the bytes).
        if compute_dtype == jnp.bfloat16:
            carry_dtype = jnp.float32
    if carry_dtype is not None:
        microbatches = microbatches.astype(carry_dtype)
        inner_stage_fn = stage_fn
        stage_fn = lambda p, x: inner_stage_fn(p, x.astype(compute_dtype)).astype(
            carry_dtype
        )
    # Mark the stream as varying over pp: stages read different elements.
    microbatches = jax.lax.pcast(microbatches, axis_name, to="varying")

    def tick(carry, t):
        buf, carry_in = carry
        # Stage 0 reads microbatch t from the stream; others read the
        # activation forwarded by their predecessor last tick.
        mb_idx = jnp.clip(t, 0, M - 1)
        x_in = jnp.where(
            stage == 0,
            jax.lax.dynamic_index_in_dim(microbatches, mb_idx, keepdims=False),
            carry_in,
        )
        y = stage_fn(stage_params, x_in)
        # Last stage writes microbatch (t - n_stages + 1) to the output
        # buffer once it's valid.
        out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
        valid = (t >= n_stages - 1) & (stage == n_stages - 1)
        cur = jax.lax.dynamic_index_in_dim(buf, out_idx, keepdims=False)
        buf = jax.lax.dynamic_update_index_in_dim(
            buf, jnp.where(valid, y, cur), out_idx, 0
        )
        carry_out = jax.lax.ppermute(y, axis_name, perm_fwd)
        return (buf, carry_out), None

    buf0 = microbatches * 0
    carry0 = microbatches[0] * 0
    (buf, _), _ = jax.lax.scan(tick, (buf0, carry0), jnp.arange(T))
    # Zero every stage but the last, then psum -> replicated final outputs.
    buf = jnp.where(stage == n_stages - 1, buf, jnp.zeros_like(buf))
    return jax.lax.psum(buf, axis_name).astype(compute_dtype)


def gpipe(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    microbatches: jnp.ndarray,
    mesh: Optional[Mesh] = None,
    *,
    axis_name: str = "pp",
    manual_axes: tuple = (),
    mb_spec: Optional[P] = None,
) -> jnp.ndarray:
    """Global entry: params have a leading [n_stages] dim (sharded over
    `axis_name`), microbatches [M, B, ...] (any dp/tp sharding — preserved).
    Returns [M, B, ...] outputs of the final stage.

    manual_axes/mb_spec: extra mesh axes to manualize alongside pp (e.g.
    ("sp",) with mb_spec=P(None, None, "sp") for sequence-parallel stages
    whose stage_fn uses per-shard collectives like ring_attention_local).
    """
    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    io_spec = mb_spec if mb_spec is not None else P()

    def body(params, mb):
        params = jax.tree_util.tree_map(lambda p: p[0], params)  # drop stage dim
        if manual_axes:
            # Params are replicated over the extra manual axes, but their
            # cotangents are axis-varying partial sums — mark the primals
            # varying too so the backward scan carry has consistent VMA
            # (the psum of the partials happens at shard_map transpose).
            params = jax.tree_util.tree_map(
                lambda p: jax.lax.pcast(p, tuple(manual_axes),
                                        to="varying"), params)
        return gpipe_local(stage_fn, params, mb, axis_name=axis_name)

    mapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, io_spec),
        out_specs=io_spec,
        axis_names={axis_name, *manual_axes},
    )
    return mapped(stacked_params, microbatches)


def microbatch(x: jnp.ndarray, n_microbatches: int) -> jnp.ndarray:
    """[B, ...] -> [M, B/M, ...]."""
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by {n_microbatches} microbatches")
    return x.reshape((n_microbatches, B // n_microbatches) + x.shape[1:])


def unmicrobatch(x: jnp.ndarray) -> jnp.ndarray:
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def stack_stage_params(per_stage_params: list) -> Any:
    """List of per-stage pytrees -> single pytree with leading stage dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)
