"""Ring attention: context parallelism over the `sp` mesh axis.

The reference has NO sequence/context parallelism (SURVEY.md §2.4 / §5 —
verified absent); this is a greenfield TPU capability. Design: the sequence
dimension is sharded over the `sp` axis; each device holds a Q block and
rotates the K/V blocks around the ICI ring with `jax.lax.ppermute`,
accumulating attention with a numerically-stable online softmax (the
flash-attention recurrence), so full attention over sequences of length
`sp * S_local` is computed with only nearest-neighbor communication and
O(S_local) memory.

Composability: `ring_attention` is a PARTIAL-manual shard_map — manual only
over `sp`, so `dp`/`tp` sharding of batch/heads stays in GSPMD (XLA) hands
and the op nests inside the `pp` pipeline shard_map (pipeline.py).

Reference pattern: Liu et al., "Ring Attention with Blockwise Transformers
for Near-Infinite Context" (see PAPERS.md); implementation is original and
jax-idiomatic (scan + ppermute, differentiable end-to-end).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, *, scale, mask):
    """One (Q-block, KV-block) attention tile.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; mask: [Sq, Sk] bool or None.
    Returns (scores_max [B,H,Sq], exp_scores [B,H,Sq,Sk], pv [B,H,Sq,D]).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    e = jnp.exp(s - m[..., None])
    # Fully-masked rows produce e≈0 everywhere; m is NEG_INF there, which the
    # combine step handles (its correction factor underflows to 0).
    pv = jnp.einsum("bhqk,bkhd->bhqd", e, v)
    return m, e, pv


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-shard ring attention body. MUST run inside shard_map with
    `axis_name` manual.

    q, k, v: [B, S_local, H, D] — the local sequence shard.
    Returns [B, S_local, H, D].
    """
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D**0.5)
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    # Online-softmax accumulators, derived from q so their varying-axes type
    # matches the scan outputs under check_vma.
    m0 = jnp.transpose(q[..., 0] * 0, (0, 2, 1)).astype(jnp.float32) + NEG_INF
    l0 = jnp.transpose(q[..., 0] * 0, (0, 2, 1)).astype(jnp.float32)
    acc0 = jnp.transpose(q * 0, (0, 2, 1, 3)).astype(jnp.float32)

    tri = jnp.tril(jnp.ones((S, S), dtype=bool))  # intra-block causal mask

    def step(carry, r):
        m, l, acc, kv = carry
        k_r, v_r = kv
        # The block arriving at step r originated on device (my_idx - r) mod n.
        kv_idx = (my_idx - r) % n
        if causal:
            # kv block strictly earlier: full attention; same block:
            # triangular; later block: fully masked.
            full = kv_idx < my_idx
            same = kv_idx == my_idx
            mask = jnp.where(same, tri, jnp.where(full, True, False))
        else:
            mask = jnp.ones((S, S), dtype=bool)
        bm, be, bpv = _block_attend(
            q, k_r.astype(q.dtype), v_r.astype(q.dtype), scale=scale, mask=mask
        )
        bm = bm.astype(jnp.float32)
        m_new = jnp.maximum(m, bm)
        # Correction factors; fully-masked tiles (bm == NEG_INF) contribute 0.
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(bm - m_new)
        l_new = l * c_old + jnp.sum(be, axis=-1).astype(jnp.float32) * c_new
        acc_new = acc * c_old[..., None] + bpv.astype(jnp.float32) * c_new[..., None]
        # Rotate KV one hop around the ring (device i -> i+1).
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_r, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_r, axis_name, perm)
        return (m_new, l_new, acc_new, (k_nxt, v_nxt)), None

    (m, l, acc, _), _ = jax.lax.scan(step, (m0, l0, acc0, (k, v)), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,H,S,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Optional[Mesh] = None,
    *,
    causal: bool = True,
    seq_axis: str = "sp",
) -> jnp.ndarray:
    """Context-parallel attention over GLOBAL [B, S, H, D] arrays.

    shard_map is manual over `seq_axis` ONLY: batch/head sharding (dp/tp)
    remains visible to XLA/GSPMD, so this call composes with tensor
    parallelism and can be nested inside the pipeline shard_map (which is
    manual over `pp`). Pass mesh=None to use the ambient mesh (required when
    nested inside another shard_map).
    """
    io_spec = P(None, seq_axis, None, None)
    fn = functools.partial(ring_attention_local, axis_name=seq_axis, causal=causal)
    mapped = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(io_spec, io_spec, io_spec),
        out_specs=io_spec,
        axis_names={seq_axis},
    )
    return mapped(q, k, v)


def reference_attention(q, k, v, *, causal=True, scale=None):
    """Plain full attention, the correctness oracle for tests."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)
