"""Sharded training step: the compiled unit every Train worker runs.

Design: a single jitted function over a global mesh — params/opt-state
sharded by the model's logical-axis rules, batch sharded (dp, sp), grads
psum'd implicitly by XLA (dp axis appears in batch but not params), donated
state. The reference's equivalent is the user's torch DDP loop driven by
Ray Train (train/torch/config.py:69 + data_parallel_trainer.py); here the
"backend setup" is just mesh construction — no process groups.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel import sharding as sh
from ray_tpu.parallel.compile_watch import CompiledFunction


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any


def default_optimizer(
    learning_rate: float = 3e-4,
    *,
    warmup_steps: int = 100,
    total_steps: int = 10_000,
    weight_decay: float = 0.1,
    b2: float = 0.95,
    grad_clip: float = 1.0,
) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b2=b2, weight_decay=weight_decay),
    )


def make_train_state(
    init_params_fn: Callable[[jax.Array], Any],
    rng: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    param_specs: Any = None,
) -> TrainState:
    """Initialize params + opt state ON-DEVICE with the right shardings:
    params are sharding-constrained inside the jitted init so large models
    never materialize unsharded; opt-state shardings propagate from params
    (mu/nu are zeros_like(params))."""

    def init_fn(rng):
        params = init_params_fn(rng)
        if mesh is not None and param_specs is not None:
            params = jax.tree_util.tree_map(
                lambda x, s: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, s)
                ),
                params,
                param_specs,
            )
        opt_state = optimizer.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)

    state = CompiledFunction(jax.jit(init_fn), "train_state_init")(rng)
    _note_state_bytes(state)
    return state


def make_zero_train_state(
    init_params_fn: Callable[[jax.Array], Any],
    rng: jax.Array,
    mesh: Optional[Mesh] = None,
    param_specs: Any = None,
) -> TrainState:
    """ZeRO variant of :func:`make_train_state`: no on-device optimizer
    state. The state lives in a ``train.ddp.ZeroOptimizer`` instead —
    sharded over the bucket plan, materialized per rank, and stamped
    into the ``opt_state`` gauge at shard granularity — so
    ``TrainState.opt_state`` is the empty tuple and this process's
    replicated-state footprint is params only."""

    def init_fn(rng):
        params = init_params_fn(rng)
        if mesh is not None and param_specs is not None:
            params = jax.tree_util.tree_map(
                lambda x, s: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, s)
                ),
                params,
                param_specs,
            )
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=())

    state = CompiledFunction(jax.jit(init_fn), "train_state_init")(rng)
    _note_state_bytes(state)
    return state


def _note_state_bytes(state: TrainState):
    """Stamp ``ray_tpu_train_state_bytes{kind=params|opt_state,rank}``
    from the deterministic flatten — the exact resident footprint of the
    state this process just materialized (memory-anatomy plane)."""
    try:
        from ray_tpu._private import memory_anatomy as _ma
        from ray_tpu._private import telemetry as _tm

        if not _tm.ENABLED:
            return
        rank = 0
        try:
            from ray_tpu.util import collective as col

            for g in ("train_dp", "default"):
                if col.is_group_initialized(g):
                    rank = col.get_rank(g)
                    break
        except Exception:
            rank = 0
        for kind, tree in (("params", state.params),
                           ("opt_state", state.opt_state)):
            leaves, _ = sh.flatten_tree(tree)
            _ma.LEDGER.note_train_state(
                kind, rank, sum(int(l.nbytes) for l in leaves))
    except Exception:
        pass


def make_train_step(
    loss_fn: Callable[[Any, Any], tuple],
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    *,
    batch_spec: P = P(("dp",), "sp"),
    donate: bool = True,
    host_grad_sync: Optional[Callable[[Any], Any]] = None,
    host_optimizer: Any = None,
):
    """loss_fn(params, batch) -> (scalar_loss, metrics_dict).

    Returns jitted step(state, batch) -> (state, metrics).

    ``host_grad_sync`` (optional) is the host-DP hook: a callable
    ``grads_pytree -> synced_grads_pytree`` (canonically
    ``ray_tpu.train.ddp.sync_gradients``) run OUTSIDE the compiled
    program, between a jitted grad computation and a jitted optimizer
    apply. This is the regime where each gang member owns its local
    devices and grads cross hosts over the collective plane (the
    reference's torch-DDP shape) instead of an XLA psum — the step
    splits into two compiled functions so the host collective can run
    in the middle, and the bucketed-DDP plane can overlap that comm
    with the unpack/pack work around it.

    ``host_optimizer`` (a ``train.ddp.ZeroOptimizer``; mutually
    exclusive with ``host_grad_sync`` and ``optimizer``-driven apply)
    selects the ZeRO-sharded host path: the jitted function computes
    grads only, the sharded optimizer reducescatters them, applies this
    rank's shards, and allgathers updated params ASYNC — the returned
    ``step`` waits those gathers at the START of the next call (first
    use), so everything between steps overlaps the gather comm. The
    step function exposes ``step.finalize(state)`` — call it once after
    the loop to fold the last step's in-flight params into the state.
    ``metrics["grad_norm"]`` in this mode is the LOCAL pre-sync norm
    (the synced grads exist only as shards).
    """

    def _constrain_batch(batch):
        if mesh is not None:
            batch = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, batch_spec)
                ),
                batch,
            )
        return batch

    if host_optimizer is not None:
        if host_grad_sync is not None:
            raise ValueError("host_optimizer and host_grad_sync are "
                             "mutually exclusive — the sharded "
                             "optimizer owns the gradient sync")

        def zgrad_step(params, batch):
            batch = _constrain_batch(batch)
            (_loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            return dict(metrics), grads, optax.global_norm(grads)

        zgrad_fn = CompiledFunction(jax.jit(zgrad_step),
                                    "train_grad_step")
        box = {"pending": None}

        def resolve(state: TrainState) -> TrainState:
            pending = box["pending"]
            if pending is None:
                return state
            box["pending"] = None
            # first use of the previous step's params: the allgathers
            # rode the issue thread through everything the caller did
            # since step_async returned; only the residue blocks here.
            # timeout=None defers to the per-op collective deadline so
            # a dead peer surfaces as CollectiveGroupError, not a hang
            return dataclasses.replace(
                state, params=pending.result(timeout=None))

        def step(state: TrainState, batch):
            state = resolve(state)
            metrics, grads, grad_norm = zgrad_fn(state.params, batch)
            box["pending"] = host_optimizer.step_async(state.params,
                                                       grads)
            metrics = dict(metrics)
            metrics["grad_norm"] = grad_norm
            return (
                TrainState(step=state.step + 1, params=state.params,
                           opt_state=state.opt_state),
                metrics,
            )

        step.finalize = resolve
        return step

    if host_grad_sync is None:
        def step(state: TrainState, batch):
            batch = _constrain_batch(batch)
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, batch)
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            metrics = dict(metrics)
            metrics["grad_norm"] = optax.global_norm(grads)
            return (
                TrainState(step=state.step + 1, params=params,
                           opt_state=opt_state),
                metrics,
            )

        # compile observability: cache hit/miss counters, compile timing,
        # COMPILE_BEGIN/END events — a slow step becomes attributable to
        # recompilation (shape churn) instead of guessed at
        return CompiledFunction(
            jax.jit(step, donate_argnums=(0,) if donate else ()),
            "train_step")

    def grad_step(params, batch):
        batch = _constrain_batch(batch)
        # metrics pass through exactly as loss_fn returned them — the
        # no-hook path adds only grad_norm, and the two modes must
        # expose the same metric schema for the same loss_fn
        (_loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        return dict(metrics), grads

    def apply_step(state: TrainState, grads):
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(step=state.step + 1, params=params,
                       opt_state=opt_state),
            optax.global_norm(grads),
        )

    grad_fn = CompiledFunction(jax.jit(grad_step), "train_grad_step")
    apply_fn = CompiledFunction(
        jax.jit(apply_step, donate_argnums=(0,) if donate else ()),
        "train_apply_step")

    def step(state: TrainState, batch):
        metrics, grads = grad_fn(state.params, batch)
        # the hook receives the device grads pytree; the bucketed sync
        # materializes leaves per bucket (np.asarray is the device→host
        # fetch), so later buckets' transfers overlap earlier buckets'
        # allreduce. grad_norm is computed from the SYNCED grads — the
        # quantity the optimizer actually applies.
        synced = host_grad_sync(grads)
        state, grad_norm = apply_fn(state, synced)
        metrics = dict(metrics)
        metrics["grad_norm"] = grad_norm
        return state, metrics

    return step


def eval_step(loss_fn, mesh: Optional[Mesh] = None, batch_spec: P = P(("dp",), "sp")):
    def step(params, batch):
        if mesh is not None:
            batch = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, batch_spec)
                ),
                batch,
            )
        _, metrics = loss_fn(params, batch)
        return metrics

    return CompiledFunction(jax.jit(step), "eval_step")
