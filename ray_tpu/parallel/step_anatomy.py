"""Step anatomy: per-step, per-rank attribution of train-loop wall clock.

The telemetry planes this framework grew (collective spans + rank
timings, data-wait stamps, compile events, chrome-timeline spans,
tracing spans) each answer their own question, but none of them can
answer the one the ROADMAP's overlap arc hangs on: *for step N, where
did the wall clock go on each rank, and how much of the auxiliary work
was actually hidden under compute?* ("Exploring the limits of
Concurrency in ML Training on Google TPUs", arXiv:2011.03641 — overlap
fraction is the metric that decides whether pipelining paid off.)

This module is the join key and the fusion. The train loop stamps a
monotonically increasing ``step_id`` into a process-global step context
(``start``/``advance``/``finish`` — wired into the Train worker and
``session.report``); every instrumented plane that runs while a step is
active appends a small *activity record* (``record_activity``) tagged
with that step id:

- ``collective``    one collective op (util/collective/telemetry.py);
  blocking when issued on the step's own thread, background when a
  helper thread ran it (a future async-bucketed DDP records these);
- ``data_wait``     consumer-blocked time for one batch (streaming
  iterator) — always exposed;
- ``data_produce``  the double-buffer producer thread's batch
  conversion + device_put dispatch — background by construction, the
  part of ingest that hides under compute;
- ``compile``       a pjit trace+compile (parallel/compile_watch.py).

Records carry intervals on the **producing process's own monotonic
clock**. Fusion NEVER joins by wall-clock windows: records fuse by
``step_id`` (and phases are computed per rank from that rank's own
clock), so NTP skew between hosts cannot smear attribution — the only
cross-rank comparisons are durations.

Per (step, rank) the fusion yields: ``compute_s`` (step wall minus all
exposed aux), ``comm_exposed_s`` / ``comm_hidden_s``, ``data_wait_s`` /
``data_hidden_s``, ``compile_s``, ``other_s``, and an
``overlap_fraction`` = hidden / (hidden + exposed). Per step it names
the cross-rank critical path: the slowest rank and the phase that
dominated it. A rolling-baseline regression detector watches p50 step
time and emits a ``STEP_REGRESSION`` cluster event plus
``ray_tpu_step_regressions_total`` when the recent p50 drifts beyond
``step_regression_multiple`` x the prior window's p50.

Everything is behind ``RAY_TPU_INTERNAL_TELEMETRY=0`` (checked live on
every entry point); with the plane off, the hot paths pay one bool.
With it on, a collective op pays one tuple read + one lock'd append —
see the <5% guard in tests/test_zz_step_anatomy.py.
"""
from __future__ import annotations

import collections
import os
import statistics
import threading
import time

from ray_tpu._private import telemetry as _tm

_MAX_STEPS = 2048          # per-process step-record ring (drop-oldest)
_MAX_ACTIVITIES = 16384    # per-process activity ring (drop-oldest)

# cached per process (workers are spawned, never forked) — same
# rationale as events.py/profiling.py
_PID = os.getpid()
_NODE = os.uname().nodename

_lock = threading.Lock()
_steps: collections.deque = collections.deque(maxlen=_MAX_STEPS)
_acts: collections.deque = collections.deque(maxlen=_MAX_ACTIVITIES)
_steps_dropped = 0
_acts_dropped = 0
_seq = 0

# the active step, swapped atomically as one tuple so hot-path readers
# (collective ops, data stamps — possibly on other threads) never see a
# half-updated context: (step_id, rank, t0_monotonic, t0_wall)
_cur: tuple | None = None
_cur_thread: int | None = None    # ident of the thread driving the loop

# regression detector state (per process; the train thread owns it).
# The window/multiple knobs are cached once per loop (invalidated by
# start()/clear()): a live os.environ read per step is measurable
# against the per-step overhead budget.
_durations: collections.deque = collections.deque()
_regressions = 0
_reg_params: tuple | None = None


def _regression_params() -> tuple:
    global _reg_params
    params = _reg_params
    if params is None:
        from ray_tpu._private.config import get_config

        params = _reg_params = (
            int(get_config("step_regression_window")),
            float(get_config("step_regression_multiple")))
    return params


def _enabled() -> bool:
    # read the module attribute live (not a from-import) so the
    # RAY_TPU_INTERNAL_TELEMETRY kill switch and test monkeypatching of
    # telemetry.ENABLED govern this plane too
    return _tm.ENABLED


def current() -> tuple | None:
    """(step_id, rank) of the active step, or None. One attribute read —
    safe on hot paths."""
    cur = _cur
    if cur is None:
        return None
    return (cur[0], cur[1])


def current_step_id():
    cur = _cur
    return None if cur is None else cur[0]


def start(rank: int = 0, step_id: int = 1):
    """Begin step anatomy for this process's train loop: step ``step_id``
    is active from now until ``advance``/``finish``. Called by the Train
    worker right before the user's train function runs."""
    global _cur, _cur_thread, _reg_params
    if not _enabled():
        return
    _cur = (int(step_id), int(rank), time.monotonic(), time.time())
    _cur_thread = threading.get_ident()
    _durations.clear()
    _reg_params = None      # re-read the knobs once per loop


def advance(step_id: int | None = None):
    """End the active step (recording its span) and begin the next.
    ``session.report`` calls this once per iteration, which makes the
    interval between reports the step and the report's iteration number
    the step id. No-op when no step is active (report outside a train
    loop, e.g. Tune function trainables on the driver)."""
    global _cur
    cur = _cur
    if cur is None or not _enabled():
        return
    now_m, now_w = time.monotonic(), time.time()
    sid, rank, t0_m, t0_w = cur
    _record_step(sid, rank, t0_m, now_m, t0_w, now_w)
    nxt = int(step_id) + 1 if step_id is not None else sid + 1
    # keep ids monotonically increasing even if a caller hands back a
    # stale iteration number (a resumed gang restarts its session
    # counter; the anatomy ring must never reuse a live id)
    if nxt <= sid:
        nxt = sid + 1
    _cur = (nxt, rank, now_m, now_w)
    _check_regression(now_m - t0_m, sid, rank)


def finish():
    """End step anatomy (train function returned/raised): records the
    final partial step and clears the context."""
    global _cur, _cur_thread
    cur = _cur
    _cur = None
    _cur_thread = None
    if cur is None or not _enabled():
        return
    sid, rank, t0_m, t0_w = cur
    _record_step(sid, rank, t0_m, time.monotonic(), t0_w, time.time())


def _record_step(sid, rank, start_m, end_m, start_w, end_w):
    global _steps_dropped, _seq
    dur = max(0.0, end_m - start_m)
    with _lock:
        _seq += 1
        if len(_steps) == _steps.maxlen:
            _steps_dropped += 1
        _steps.append({"step_id": sid, "rank": rank, "node": _NODE,
                       "pid": _PID, "seq": _seq, "start": start_m,
                       "end": end_m, "wall_start": start_w,
                       "wall_end": end_w})
    _tm.observe("ray_tpu_step_seconds", dur)
    try:
        from ray_tpu._private import profiling as _prof

        _prof.record_completed_span("step", f"step::{sid}", start_w, dur,
                                    {"step": sid, "rank": rank})
    except Exception:
        pass


def record_activity(kind: str, start_m: float, end_m: float,
                    blocking: bool = True, **meta):
    """Attribute one interval of auxiliary work to the active step.
    ``start_m``/``end_m`` are time.monotonic() on THIS process. No-op
    (one tuple read) when no step is active or the plane is off."""
    global _acts_dropped, _seq
    cur = _cur
    if cur is None or not _enabled():
        return
    rec = {"step_id": cur[0], "rank": cur[1], "node": _NODE, "pid": _PID,
           "kind": kind, "start": start_m, "end": end_m,
           "blocking": bool(blocking)}
    if meta:
        rec["meta"] = meta
    with _lock:
        _seq += 1
        rec["seq"] = _seq
        if len(_acts) == _acts.maxlen:
            _acts_dropped += 1
        _acts.append(rec)


def _check_regression(dur_s: float, step_id: int | None = None,
                      rank: int | None = None):
    """Rolling-baseline p50 drift detector, amortized to stay off the
    per-step budget: durations accumulate cheaply (one append); the
    median comparison runs only when a full window of NEW steps has
    arrived since the last evaluation (cost ~1/window per step — the
    per-step overhead guard in tests/test_zz_step_anatomy.py is why).
    Fires when p50(last window) > multiple * p50(window before it);
    after firing the history resets, so one sustained slowdown emits
    one event per re-filled window, not one per step. After a quiet
    evaluation the baseline rolls forward by one window."""
    global _regressions
    _durations.append(dur_s)
    window, multiple = _regression_params()
    if window <= 0:
        _durations.clear()
        return
    if len(_durations) < 2 * window:
        return
    hist = list(_durations)[-2 * window:]
    base = statistics.median(hist[:window])
    recent = statistics.median(hist[window:])
    if base <= 0 or recent <= multiple * base:
        # quiet: keep only the recent window as the next baseline
        recent_hist = hist[window:]
        _durations.clear()
        _durations.extend(recent_hist)
        return
    _regressions += 1
    from ray_tpu._private import events as _events

    # step_id is the step that COMPLETED the regressed window (advance
    # has already opened the next one by the time this runs) — the id
    # an operator should look up in summarize_steps()
    _events.record("STEP_REGRESSION", rank=rank, step_id=step_id,
                   p50_recent_s=round(recent, 6),
                   p50_baseline_s=round(base, 6),
                   multiple=multiple, window=window)
    _tm.counter_inc("ray_tpu_step_regressions_total")
    _durations.clear()


def local_records() -> dict:
    """This process's step + activity records (each a copy), plus drop
    counts so a fused report can flag incomplete windows instead of
    silently reporting wrong attribution."""
    with _lock:
        return {"node": _NODE, "pid": _PID,
                "steps": [dict(s) for s in _steps],
                "activities": [dict(a) for a in _acts],
                "steps_dropped": _steps_dropped,
                "activities_dropped": _acts_dropped}


def clear():
    global _steps_dropped, _acts_dropped, _regressions, _reg_params
    with _lock:
        _steps.clear()
        _acts.clear()
        _steps_dropped = 0
        _acts_dropped = 0
    _durations.clear()
    _regressions = 0
    _reg_params = None


# ------------------------------------------------------------------ fusion
#
# Pure functions over exported record sets — usable post-hoc on a flight
# recorder dump as well as live through summarize_steps().


def _merge(intervals: list[tuple]) -> list[tuple]:
    """Union of [s, e) intervals as a sorted disjoint list."""
    out: list[list] = []
    for s, e in sorted(i for i in intervals if i[1] > i[0]):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _total(intervals: list[tuple]) -> float:
    return sum(e - s for s, e in intervals)


def _subtract(intervals: list[tuple], cover: list[tuple]) -> float:
    """Total length of ``intervals`` (disjoint, sorted) not covered by
    ``cover`` (disjoint, sorted)."""
    total = 0.0
    ci = 0
    for s, e in intervals:
        pos = s
        while pos < e:
            while ci < len(cover) and cover[ci][1] <= pos:
                ci += 1
            if ci == len(cover) or cover[ci][0] >= e:
                total += e - pos
                break
            cs, ce = cover[ci]
            if cs > pos:
                total += cs - pos
            pos = max(pos, ce)
    return total


_EXPOSED_KINDS = {"collective": "comm_exposed_s", "data_wait":
                  "data_wait_s", "compile": "compile_s",
                  # pipeline-parallel schedule stall: wall clock a stage
                  # spent parked waiting for an upstream activation /
                  # downstream gradient / in-flight-window credit (the
                  # train/pipeline loop stamps these). Kept distinct
                  # from generic comm so the measured per-stage bubble
                  # fraction can be checked against (P-1)/(M+P-1)
                  # schedule theory.
                  "pipeline_bubble": "bubble_s"}
_HIDDEN_KINDS = {"collective": "comm_hidden_s", "data_produce":
                 "data_hidden_s"}


def anatomize_rank_step(step: dict, acts: list[dict]) -> dict:
    """Phase breakdown for one rank's one step from that rank's own
    records (single clock domain). Exposed time = union of blocking
    intervals; hidden time = background intervals minus their overlap
    with exposed time (work genuinely riding under compute); compute =
    wall - exposed."""
    s0, s1 = step["start"], step["end"]
    wall = max(0.0, s1 - s0)
    clip = lambda a: (max(s0, a["start"]), min(s1, a["end"]))  # noqa: E731
    exposed_by: dict[str, list] = {}
    hidden_by: dict[str, list] = {}
    for a in acts:
        iv = clip(a)
        if iv[1] <= iv[0]:
            continue
        if a.get("blocking", True):
            key = _EXPOSED_KINDS.get(a["kind"], "other_s")
            exposed_by.setdefault(key, []).append(iv)
        else:
            key = _HIDDEN_KINDS.get(a["kind"], "other_hidden_s")
            hidden_by.setdefault(key, []).append(iv)
    exposed_union = _merge([iv for ivs in exposed_by.values()
                            for iv in ivs])
    out = {"wall_s": wall, "comm_exposed_s": 0.0, "comm_hidden_s": 0.0,
           "data_wait_s": 0.0, "data_hidden_s": 0.0, "compile_s": 0.0,
           "bubble_s": 0.0, "other_s": 0.0, "other_hidden_s": 0.0}
    for key, ivs in exposed_by.items():
        out[key] = _total(_merge(ivs))
    if out["bubble_s"] and out["comm_exposed_s"]:
        # a pipeline schedule stall IS a blocking recv, so the same wall
        # interval arrives under both kinds (the collective op records
        # itself, and the pipeline loop stamps the stall). Keep the two
        # phases DISJOINT: bubble owns the stall, comm_exposed keeps
        # only communication that wasn't a schedule stall — otherwise
        # the per-rank phases sum past wall_s and comm stops measuring
        # the network.
        out["comm_exposed_s"] = _subtract(
            _merge(exposed_by["comm_exposed_s"]),
            _merge(exposed_by["bubble_s"]))
    for key, ivs in hidden_by.items():
        out[key] = _subtract(_merge(ivs), exposed_union)
    exposed_total = _total(exposed_union)
    # overlap accounting uses the UNION of all background intervals
    # minus exposed time, NEVER the sum of the per-kind values: two
    # concurrent async grad buckets (or a background bucket riding
    # under a data_produce window) cover the same wall clock once, and
    # a per-kind sum would double-count it — with enough concurrent
    # comm, "hidden" would exceed the step wall. The per-kind fields
    # above stay as attribution (they may legitimately overlap each
    # other); the fraction is computed from real wall-clock coverage.
    hidden_total = _subtract(
        _merge([iv for ivs in hidden_by.values() for iv in ivs]),
        exposed_union)
    out["compute_s"] = max(0.0, wall - exposed_total)
    out["overlap_fraction"] = (
        hidden_total / (hidden_total + exposed_total)
        if (hidden_total + exposed_total) > 0 else None)
    return out


_SELF_PHASES = ("compute_s", "data_wait_s", "compile_s", "other_s")


def _self_time(br: dict) -> float:
    """A rank's non-communication time in a step. In a bulk-synchronous
    gang the collective EQUALIZES wall clocks (fast ranks absorb the
    straggler's lateness as comm wait), so raw wall time cannot name
    the straggler — the rank the others waited on is the one with the
    most wall clock spent NOT communicating."""
    return max(0.0, br["wall_s"] - br["comm_exposed_s"])


def fuse(exports: list[dict]) -> dict:
    """Fuse per-process record exports into per-step anatomy. Joining is
    by ``step_id`` exactly — never by wall-clock windows — so records
    from hosts with skewed clocks still pair correctly. Returns::

        {"steps": [{"step_id", "ranks": {rank: breakdown},
                    "critical_path": {"rank", "phase", "wall_s"},
                    "overlap_fraction"}],
         "ranks": {rank: rollup}, "incomplete": bool,
         "dropped": {"steps": n, "activities": n}}
    """
    # dedup by (node, pid): the driver answers both locally and through
    # a raylet fan-out in in-process clusters — keep the richer export
    by_proc: dict[tuple, dict] = {}
    for ex in exports:
        if not ex:
            continue
        key = (ex.get("node"), ex.get("pid"))
        old = by_proc.get(key)
        if old is None or len(ex.get("steps", ())) > len(
                old.get("steps", ())):
            by_proc[key] = ex
    steps_by_id: dict[int, dict[int, dict]] = {}
    # activities keyed by (step_id, rank, node, pid): a gang restart
    # re-reports the same (step_id, rank) from a NEW process, and
    # interval math may only ever mix records from ONE process (one
    # monotonic clock domain) — the phase breakdown below pairs each
    # step record with activities from ITS OWN process exclusively
    acts_by: dict[tuple, list] = {}
    dropped = {"steps": 0, "activities": 0}
    for ex in by_proc.values():
        dropped["steps"] += int(ex.get("steps_dropped", 0))
        dropped["activities"] += int(ex.get("activities_dropped", 0))
        for s in ex.get("steps", ()):
            # a rank may re-report a step id after a gang restart:
            # last writer wins, and its activities follow it via the
            # (node, pid) part of the activity key
            steps_by_id.setdefault(int(s["step_id"]), {})[
                int(s["rank"])] = s
        for a in ex.get("activities", ()):
            acts_by.setdefault((int(a["step_id"]), int(a["rank"]),
                                a.get("node"), a.get("pid")),
                               []).append(a)
    all_ranks = {r for per in steps_by_id.values() for r in per}
    out_steps = []
    rank_roll: dict[int, dict] = {}
    for sid in sorted(steps_by_id):
        per_rank = {}
        for rank, srec in sorted(steps_by_id[sid].items()):
            br = anatomize_rank_step(
                srec, acts_by.get((sid, rank, srec.get("node"),
                                   srec.get("pid")), []))
            per_rank[rank] = br
            roll = rank_roll.setdefault(rank, collections.Counter())
            for k, v in br.items():
                if isinstance(v, (int, float)) and v is not None:
                    roll[k] += v
            roll["steps"] += 1
        crit_rank = max(per_rank,
                        key=lambda r: _self_time(per_rank[r]))
        crit = per_rank[crit_rank]
        phase = max(_SELF_PHASES, key=lambda p: crit.get(p, 0.0))
        fracs = [br["overlap_fraction"] for br in per_rank.values()
                 if br["overlap_fraction"] is not None]
        out_steps.append({
            "step_id": sid, "ranks": per_rank,
            "complete": set(per_rank) == all_ranks,
            "critical_path": {"rank": crit_rank, "phase": phase,
                              "wall_s": crit["wall_s"],
                              "self_s": _self_time(crit)},
            "overlap_fraction": (sum(fracs) / len(fracs)
                                 if fracs else None),
        })
    ranks = {}
    for rank, roll in sorted(rank_roll.items()):
        n = roll.pop("steps", 0) or 1
        roll.pop("overlap_fraction", None)
        ranks[rank] = {**{k: roll.get(k, 0.0) for k in
                          ("wall_s", "compute_s", "comm_exposed_s",
                           "comm_hidden_s", "data_wait_s",
                           "data_hidden_s", "compile_s", "bubble_s",
                           "other_s")},
                       "steps": n,
                       "mean_step_s": roll.get("wall_s", 0.0) / n}
    return {"steps": out_steps, "ranks": ranks,
            "incomplete": bool(dropped["steps"] or dropped["activities"]),
            "dropped": dropped}
