"""A real multi-node cluster inside one machine — the central test fixture.

Reference: python/ray/cluster_utils.py:99 (Cluster.add_node at :165,
remove_node at :238). Each added node is a full Raylet with its own
shared-memory store segment and worker pool; removing a node kills its
workers and drops it from GCS, driving the same failure paths a real node
death would (actor restart, object loss, lease failure).
"""
from __future__ import annotations

import os
import time

from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet, detect_resources


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: dict | None = None,
                 connect: bool = False):
        self.gcs = GcsServer().start()
        self._raylets: dict[str, Raylet] = {}
        self.head_node = None
        if initialize_head:
            self.head_node = self.add_node(**(head_node_args or {}))
        if connect:
            self.connect()

    @property
    def address(self) -> str:
        return f"{self.gcs.addr[0]}:{self.gcs.addr[1]}"

    def add_node(self, num_cpus: int = 1, num_tpus: int = 0,
                 resources: dict | None = None,
                 object_store_memory: int = 64 * 1024 * 1024,
                 tpu_topology: dict | None = None,
                 **_ignored) -> Raylet:
        """tpu_topology: inject a fake slice/worker layout for topology
        tests, e.g. {"slice_id": "s0", "worker_id": 2, "chips": 4}."""
        raylet = Raylet(
            self.gcs.addr,
            resources=detect_resources(num_cpus, num_tpus,
                                       resources=resources),
            store_size=object_store_memory,
            tpu_topology=tpu_topology,
        )
        self._raylets[raylet.node_id] = raylet
        return raylet

    def remove_node(self, node: Raylet, allow_graceful: bool = False):
        """Simulates node failure: kill the raylet's workers, drop its GCS
        connection (GCS marks it dead via on_disconnect)."""
        self._raylets.pop(node.node_id, None)
        node.stop(kill_workers=True)
        # give GCS a beat to process the disconnect
        deadline = time.time() + 5.0
        while time.time() < deadline:
            alive = {n["NodeID"] for n in self._gcs_nodes() if n["Alive"]}
            if node.node_id not in alive:
                return
            time.sleep(0.02)

    def _gcs_nodes(self):
        from ray_tpu._private.protocol import RpcClient

        c = RpcClient(self.gcs.addr)
        try:
            return c.call("get_nodes")
        finally:
            c.close()

    def connect(self, namespace: str | None = None):
        from ray_tpu._private import api

        assert self.head_node is not None, "no head node"
        # connect() needs the driver on a specific raylet; bypass address
        # discovery and attach to the head raylet directly.
        from ray_tpu._private.worker_runtime import CoreWorker, \
            current_worker, set_current_worker

        if current_worker() is not None:
            raise RuntimeError("already connected")
        worker = CoreWorker(self.gcs.addr, self.head_node.addr, mode="driver")
        set_current_worker(worker)
        if namespace:
            api._namespace = namespace
        return worker

    def disconnect(self):
        from ray_tpu._private.worker_runtime import current_worker, \
            set_current_worker

        worker = current_worker()
        if worker is not None:
            worker.shutdown()
            set_current_worker(None)

    def shutdown(self):
        self.disconnect()
        for raylet in list(self._raylets.values()):
            raylet.stop(kill_workers=True)
        self._raylets.clear()
        self.gcs.stop()

    def wait_for_nodes(self, timeout: float = 30.0):
        deadline = time.time() + timeout
        expected = len(self._raylets)
        while time.time() < deadline:
            alive = [n for n in self._gcs_nodes() if n["Alive"]]
            if len(alive) >= expected:
                return True
            time.sleep(0.05)
        return False
