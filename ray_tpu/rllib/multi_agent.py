"""Multi-agent RL (reference: rllib/env/multi_agent_env.py + the
multi-policy training path of rllib/algorithms/ppo with
config.multi_agent(policies=..., policy_mapping_fn=...)).

Contract (reference MultiAgentEnv): ``reset() -> (obs_dict, infos)``,
``step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)``
— all keyed by agent id, with terminateds["__all__"] ending the
episode. Agents may finish early; finished agents stop producing
transitions until the episode resets.

Training: every agent id maps to a POLICY id via policy_mapping_fn;
rollouts group per-policy sample batches (GAE per agent stream), and
MultiAgentPPO keeps independent params/optimizer per policy — shared
policies (all agents → one id) give parameter sharing for free.
"""
from __future__ import annotations

import time

import jax
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.models import init_policy, policy_apply


class MultiAgentEnv:
    """Base contract; subclasses define agent_ids/spaces and dynamics."""

    agent_ids: list[str]

    def reset(self, seed: int | None = None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    def spaces(self) -> dict:
        """{agent_id: (obs_size, num_actions)}"""
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentEnv):
    """N independent cartpoles, one per agent (the reference's
    MultiAgentCartPole example env): per-agent rewards, episode ends
    for everyone when every pole has dropped (or max steps)."""

    def __init__(self, num_agents: int = 2, seed: int | None = None,
                 max_steps: int = 200):
        from ray_tpu.rllib.env import CartPole

        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {aid: CartPole(seed=(seed or 0) * 100 + i,
                                    max_steps=max_steps)
                      for i, aid in enumerate(self.agent_ids)}
        self._alive: set[str] = set()

    def spaces(self):
        return {aid: (env.observation_size, env.num_actions)
                for aid, env in self._envs.items()}

    def reset(self, seed: int | None = None):
        self._alive = set(self.agent_ids)
        obs = {aid: env.reset()[0] for aid, env in self._envs.items()}
        return obs, {}

    def step(self, action_dict: dict):
        obs, rewards, terms, truncs = {}, {}, {}, {}
        for aid in list(self._alive):
            o, r, term, trunc, _ = self._envs[aid].step(
                int(action_dict[aid]))
            obs[aid] = o
            rewards[aid] = r
            terms[aid] = term
            truncs[aid] = trunc
            if term or trunc:
                self._alive.discard(aid)
        terms["__all__"] = not self._alive
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


class MultiAgentRolloutWorker:
    """Sample per-policy batches from multi-agent episodes. One stream
    per (env, agent); GAE runs per stream, then streams concatenate by
    the POLICY their agent maps to."""

    def __init__(self, env_fn, *, policy_mapping_fn, num_envs: int = 1,
                 seed: int = 0, gamma: float = 0.99,
                 gae_lambda: float = 0.95):
        self.envs = [env_fn(seed=seed * 1000 + i)
                     for i in range(num_envs)]
        self.policy_mapping_fn = policy_mapping_fn
        self.gamma = gamma
        self.gae_lambda = gae_lambda
        self._rng = np.random.default_rng(seed)
        self._fwd = jax.jit(policy_apply)
        self._obs = []
        self._returns = []
        for env in self.envs:
            obs, _ = env.reset()
            self._obs.append(obs)
            self._returns.append({aid: 0.0 for aid in env.agent_ids})
        self._completed: list[float] = []

    def spaces(self):
        """{policy_id: (obs_size, num_actions)} over the mapped agents."""
        out = {}
        for aid, sp in self.envs[0].spaces().items():
            out[self.policy_mapping_fn(aid)] = sp
        return out

    def _forward_by_policy(self, params_by_policy: dict,
                           keyed_obs: list) -> dict:
        """One BATCHED jitted forward per policy across every live
        (env, agent) pair — per-agent singleton dispatches would pay
        num_agents x num_envs jit round trips per step. Returns
        {key: (logits, value)} for key = (env_idx, agent_id)."""
        by_pid: dict[str, list] = {}
        for key, aid, obs in keyed_obs:
            by_pid.setdefault(self.policy_mapping_fn(aid),
                              []).append((key, obs))
        out = {}
        for pid, entries in by_pid.items():
            stacked = np.stack([obs for _, obs in entries])
            logits, values = self._fwd(params_by_policy[pid], stacked)
            logits = np.asarray(logits)
            values = np.asarray(values)
            for i, (key, _) in enumerate(entries):
                out[key] = (logits[i], float(values[i]))
        return out

    def sample(self, params_by_policy: dict, steps_per_env: int) -> dict:
        from ray_tpu.rllib.rollout_worker import _logsumexp

        streams = {}   # (env_idx, aid) -> per-step lists

        def stream(e, aid):
            key = (e, aid)
            if key not in streams:
                streams[key] = {"obs": [], "actions": [], "logp": [],
                                "values": [], "rewards": [], "dones": []}
            return streams[key]

        for _ in range(steps_per_env):
            keyed_obs = [((e, aid), aid,
                          np.asarray(self._obs[e][aid], np.float32))
                         for e, env in enumerate(self.envs)
                         for aid in env.agent_ids if aid in self._obs[e]]
            if not keyed_obs:
                continue
            fwd = self._forward_by_policy(params_by_policy, keyed_obs)
            acts_by_env: dict[int, dict] = {}
            for (e, aid), _aid, obs in keyed_obs:
                logits, v = fwd[(e, aid)]
                z = self._rng.gumbel(size=logits.shape)
                act = int(np.argmax(logits + z))
                logp = float((logits - _logsumexp(logits))[act])
                st = stream(e, aid)
                st["obs"].append(obs)
                st["actions"].append(act)
                st["logp"].append(logp)
                st["values"].append(v)
                acts_by_env.setdefault(e, {})[aid] = act
            for e, actions in acts_by_env.items():
                env = self.envs[e]
                nobs, rewards, terms, truncs, _ = env.step(actions)
                for aid in actions:
                    st = stream(e, aid)
                    st["rewards"].append(rewards.get(aid, 0.0))
                    done = terms.get(aid) or truncs.get(aid)
                    st["dones"].append(1.0 if done else 0.0)
                    self._returns[e][aid] += rewards.get(aid, 0.0)
                    if done:
                        self._completed.append(self._returns[e][aid])
                        self._returns[e][aid] = 0.0
                if terms.get("__all__") or truncs.get("__all__"):
                    obs, _ = env.reset()
                    self._obs[e] = obs
                else:
                    self._obs[e] = {aid: nobs[aid] for aid in nobs
                                    if not (terms.get(aid)
                                            or truncs.get(aid))}

        # V(s_T) bootstrap for still-alive streams, batched per policy
        alive_keys = [((e, aid), aid,
                       np.asarray(self._obs[e][aid], np.float32))
                      for e, env in enumerate(self.envs)
                      for aid in env.agent_ids if aid in self._obs[e]]
        boot = {}
        if alive_keys:
            fwd = self._forward_by_policy(params_by_policy, alive_keys)
            boot = {key: v for key, (_logits, v) in fwd.items()}

        by_policy: dict[str, dict] = {}
        for (e, aid), st in streams.items():
            if not st["obs"]:
                continue
            pid = self.policy_mapping_fn(aid)
            batch = self._gae(st, boot.get((e, aid), 0.0))
            agg = by_policy.setdefault(pid, {k: [] for k in batch})
            for k, v in batch.items():
                agg[k].append(v)
        out = {pid: {k: np.concatenate(v) for k, v in agg.items()}
               for pid, agg in by_policy.items()}
        completed, self._completed = self._completed, []
        return {"policies": out,
                "episode_returns": np.asarray(completed, np.float32)}

    def _gae(self, st: dict, bootstrap_v: float) -> dict:
        T = len(st["obs"])
        rewards = np.asarray(st["rewards"], np.float32)
        values = np.asarray(st["values"], np.float32)
        dones = np.asarray(st["dones"], np.float32)
        # bootstrap_v = V(s_T) under the CURRENT policy for a still-alive
        # stream (0.0 when the final transition terminated)
        last_v = bootstrap_v if dones[-1] == 0.0 else 0.0
        adv = np.zeros(T, np.float32)
        last_gae = 0.0
        for t in reversed(range(T)):
            next_v = last_v if t == T - 1 else values[t + 1]
            nonterminal = 1.0 - dones[t]
            delta = (rewards[t] + self.gamma * next_v * nonterminal
                     - values[t])
            last_gae = delta + (self.gamma * self.gae_lambda
                                * nonterminal * last_gae)
            adv[t] = last_gae
        return {"obs": np.stack(st["obs"]),
                "actions": np.asarray(st["actions"], np.int32),
                "logp": np.asarray(st["logp"], np.float32),
                "advantages": adv,
                "value_targets": adv + values}


class MultiAgentPPO:
    """Clipped-surrogate PPO over N policies (reference: the multi-agent
    configuration of rllib PPO — one learner pass per policy per
    iteration, sampling shared across rollout actors)."""

    def __init__(self, env_fn, *, policy_mapping_fn=lambda aid: "shared",
                 num_rollout_workers: int = 2, num_envs_per_worker: int = 1,
                 rollout_fragment_length: int = 64, lr: float = 3e-4,
                 clip_param: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, train_batch_epochs: int = 4,
                 minibatch_size: int = 128, gamma: float = 0.99,
                 gae_lambda: float = 0.95, seed: int = 0):
        self.cfg = dict(clip=clip_param, vf=vf_coeff, ent=entropy_coeff,
                        epochs=train_batch_epochs, mbs=minibatch_size)
        self.rollout_fragment_length = rollout_fragment_length
        worker_cls = ray_tpu.remote(MultiAgentRolloutWorker)
        self.workers = [
            worker_cls.options(num_cpus=0).remote(
                env_fn, policy_mapping_fn=policy_mapping_fn,
                num_envs=num_envs_per_worker, seed=seed + i,
                gamma=gamma, gae_lambda=gae_lambda)
            for i in range(num_rollout_workers)
        ]
        spaces = ray_tpu.get(self.workers[0].spaces.remote())
        self.params = {}
        self.opt_states = {}
        self.optimizer = optax.adam(lr)
        for i, (pid, (obs_size, num_actions)) in enumerate(
                sorted(spaces.items())):
            self.params[pid] = init_policy(
                jax.random.PRNGKey(seed + i), obs_size, num_actions)
            self.opt_states[pid] = self.optimizer.init(self.params[pid])
        from ray_tpu.rllib.algorithm import (
            _jit_sgd_update,
            ppo_surrogate_loss,
        )

        self._update = _jit_sgd_update(
            ppo_surrogate_loss(clip_param, vf_coeff, entropy_coeff),
            self.optimizer)
        self.iteration = 0
        self._recent_returns: list = []
        self._seed = seed

    def train(self) -> dict:
        t0 = time.time()
        self.iteration += 1
        refs = [w.sample.remote(self.params, self.rollout_fragment_length)
                for w in self.workers]
        results = ray_tpu.get(refs, timeout=300)
        merged: dict[str, dict] = {}
        for r in results:
            self._recent_returns.extend(r["episode_returns"].tolist())
            for pid, batch in r["policies"].items():
                agg = merged.setdefault(pid, {k: [] for k in batch})
                for k, v in batch.items():
                    agg[k].append(v)
        self._recent_returns = self._recent_returns[-200:]
        # metrics labeled PER POLICY: an unlabeled last-minibatch aux
        # would describe one arbitrary policy while looking global
        metrics: dict = {}
        rng = np.random.default_rng(self._seed + self.iteration)
        for pid, agg in merged.items():
            batch = {k: np.concatenate(v) for k, v in agg.items()}
            n = len(batch["obs"])
            mbs = min(self.cfg["mbs"], n)
            aux = {}
            for _ in range(self.cfg["epochs"]):
                perm = rng.permutation(n)
                for start in range(0, n - mbs + 1, mbs):
                    idx = perm[start:start + mbs]
                    mb = {k: v[idx] for k, v in batch.items()}
                    (self.params[pid], self.opt_states[pid],
                     aux) = self._update(self.params[pid],
                                         self.opt_states[pid], mb)
            for k, v in aux.items():
                metrics[f"{pid}/{k}"] = float(v)
        return {"training_iteration": self.iteration,
                "episode_reward_mean": (float(np.mean(
                    self._recent_returns))
                    if self._recent_returns else 0.0),
                "policies_trained": sorted(merged),
                **metrics,
                "time_this_iter_s": time.time() - t0}

    def save(self) -> dict:
        return {"params": self.params, "iteration": self.iteration}

    def restore(self, state: dict):
        self.params = state["params"]
        self.iteration = state["iteration"]

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
