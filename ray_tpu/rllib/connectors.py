"""Connectors — observation/reward transform pipelines between env and
policy (reference: rllib/connectors/ — agent/env connector pipelines
that preprocess observations and postprocess experiences so the policy
sees a stable, normalized view).

A ConnectorPipeline sits inside the rollout worker: every raw
observation passes through `transform_obs` before the policy forward
(and before being recorded in the sample batch), rewards pass through
`transform_reward` before GAE. Connectors may be stateful per agent
stream (FrameStack) or globally adaptive (MeanStdObsNormalizer's
running statistics — per-worker, like the reference's per-worker
filters, synced only through learned behavior)."""
from __future__ import annotations

import numpy as np


class Connector:
    def transform_obs(self, obs: np.ndarray, stream_key=None) -> np.ndarray:
        return obs

    def transform_reward(self, reward: float, stream_key=None) -> float:
        return reward

    def obs_size(self, raw_size: int) -> int:
        """Output obs width given the raw width (FrameStack widens)."""
        return raw_size

    def reset(self, stream_key=None):
        """Episode boundary for one stream (clears per-stream state)."""


class MeanStdObsNormalizer(Connector):
    """Running mean/std observation filter (reference:
    rllib/utils/filter.py MeanStdFilter via connectors)."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0):
        self._count = 0
        self._mean = None
        self._m2 = None
        self.eps = eps
        self.clip = clip

    def transform_obs(self, obs, stream_key=None):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            self._mean = np.zeros_like(obs)
            self._m2 = np.zeros_like(obs)
        self._count += 1
        delta = obs - self._mean
        self._mean = self._mean + delta / self._count
        self._m2 = self._m2 + delta * (obs - self._mean)
        var = (self._m2 / max(1, self._count - 1)
               if self._count > 1 else np.ones_like(obs))
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)


class ClipReward(Connector):
    """Reward clipping (reference: connectors ClipReward / the Atari
    sign-clip convention)."""

    def __init__(self, limit: float = 1.0):
        self.limit = limit

    def transform_reward(self, reward, stream_key=None):
        return float(np.clip(reward, -self.limit, self.limit))


class FrameStack(Connector):
    """Stack the last k observations per stream (reference: connectors
    FrameStackingConnector) — gives a feedforward policy short-term
    memory."""

    def __init__(self, k: int = 4):
        self.k = k
        self._stacks: dict = {}

    def obs_size(self, raw_size: int) -> int:
        return raw_size * self.k

    def transform_obs(self, obs, stream_key=None):
        obs = np.asarray(obs, np.float32)
        stack = self._stacks.get(stream_key)
        if stack is None:
            stack = [obs] * self.k
        else:
            stack = stack[1:] + [obs]
        self._stacks[stream_key] = stack
        return np.concatenate(stack)

    def reset(self, stream_key=None):
        if stream_key is None:
            self._stacks.clear()
        else:
            self._stacks.pop(stream_key, None)


class ConnectorPipeline(Connector):
    def __init__(self, connectors: list):
        self.connectors = list(connectors)

    def transform_obs(self, obs, stream_key=None):
        for c in self.connectors:
            obs = c.transform_obs(obs, stream_key)
        return obs

    def transform_reward(self, reward, stream_key=None):
        for c in self.connectors:
            reward = c.transform_reward(reward, stream_key)
        return reward

    def obs_size(self, raw_size: int) -> int:
        for c in self.connectors:
            raw_size = c.obs_size(raw_size)
        return raw_size

    def reset(self, stream_key=None):
        for c in self.connectors:
            c.reset(stream_key)
