"""Algorithm base + PPO.

Reference: rllib/algorithms/algorithm.py:148 (Algorithm(Trainable),
train/step), ppo/ppo.py:307, and the learner pattern of
execution/multi_gpu_learner_thread.py:20 — sampling actors feed batches
through the object store, the driver-side jax learner runs jitted
minibatch updates (on TPU the update is the compiled program; the host
ring buffer is the object store itself).
"""
from __future__ import annotations

import copy
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.models import init_policy, policy_apply
from ray_tpu.rllib.rollout_worker import (
    RolloutWorker,
    TransitionWorker,
    concat_batches,
)


class AlgorithmConfig:
    """Builder-style config (reference: algorithm_config.py)."""

    def __init__(self, algo_class=None):
        self.algo_class = algo_class
        self.env_spec = "CartPole-v1"
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 2
        self.rollout_fragment_length = 128
        self.connectors = None   # list of Connector factories (per worker)
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.lr = 3e-4
        self.train_batch_epochs = 4
        self.minibatch_size = 128
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.seed = 0
        # value-based (DQN-family) knobs
        self.buffer_capacity = 50_000
        self.learning_starts = 500
        self.target_update_freq = 4
        self.num_sgd_steps = 32
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_anneal_iters = 15
        self.double_q = True
        self.prioritized_replay = False
        # SAC (continuous off-policy) knobs
        self.tau = 0.005                # polyak target coefficient
        self.init_alpha = 0.1           # initial entropy temperature
        self.alpha_lr = 3e-4
        # APEX (distributed prioritized replay) knobs
        self.num_replay_shards = 2
        # IMPALA (async learner) knobs
        self.learner_queue_size = 8
        self.learner_min_step_s = 0.0   # test hook: artificial step floor
        # BC / offline RL: {"obs", "actions"} arrays or a Dataset
        self.offline_data = None

    def environment(self, env):
        self.env_spec = env
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None, connectors=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if connectors is not None:
            # factories, one call per worker: stateful connectors
            # (FrameStack, running filters) must not share state across
            # worker processes
            self.connectors = connectors
        return self

    def training(self, **kwargs):
        for name, v in kwargs.items():
            if not hasattr(self, name):
                raise ValueError(f"unknown training option {name!r}")
            if v is not None:
                setattr(self, name, v)
        return self

    def build(self):
        return (self.algo_class or PPO)(self)


class Algorithm:
    """Own a WorkerSet of rollout actors + a jax learner state."""

    worker_cls = RolloutWorker

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        worker_cls = ray_tpu.remote(type(self).worker_cls)
        self.workers = [
            worker_cls.options(num_cpus=0).remote(
                config.env_spec, num_envs=config.num_envs_per_worker,
                seed=config.seed + i, gamma=config.gamma,
                gae_lambda=config.gae_lambda,
                connectors=([f() for f in config.connectors]
                            if config.connectors else None))
            for i in range(config.num_rollout_workers)
        ]
        obs_size, num_actions = ray_tpu.get(self.workers[0].spaces.remote())
        self.params = init_policy(
            jax.random.PRNGKey(config.seed), obs_size, num_actions)
        self.iteration = 0
        self._recent_returns: list = []

    def train(self) -> dict:
        t0 = time.time()
        self.iteration += 1
        batch_refs = [self._sample_call(w) for w in self.workers]
        batches = ray_tpu.get(batch_refs, timeout=300)
        batch = concat_batches(batches)
        returns = batch.pop("episode_returns")
        self._recent_returns.extend(returns.tolist())
        self._recent_returns = self._recent_returns[-100:]
        metrics = self.training_step(batch)
        metrics.update({
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else 0.0),
            "episodes_this_iter": len(returns),
            "num_env_steps_sampled": len(batch["obs"]),
            "time_this_iter_s": time.time() - t0,
        })
        return metrics

    def _sample_call(self, worker):
        """One worker's async sample submission; subclasses override to
        change the sampling mode (e.g. epsilon-greedy transitions)."""
        return worker.sample.remote(self.params,
                                    self.config.rollout_fragment_length)

    def training_step(self, batch) -> dict:
        raise NotImplementedError

    def save(self) -> dict:
        return {"params": self.params, "iteration": self.iteration}

    def restore(self, state: dict):
        self.params = state["params"]
        self.iteration = state["iteration"]

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


class PPO(Algorithm):
    """Clipped-surrogate PPO (reference: rllib/algorithms/ppo/ppo.py:307)."""

    def __init__(self, config: AlgorithmConfig):
        super().__init__(config)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = _jit_sgd_update(
            ppo_surrogate_loss(config.clip_param, config.vf_coeff,
                               config.entropy_coeff),
            self.optimizer)

    def training_step(self, batch) -> dict:
        n = len(batch["obs"])
        mbs = max(1, self.config.minibatch_size)
        rng = np.random.default_rng(self.config.seed + self.iteration)
        aux = {}
        for _ in range(self.config.train_batch_epochs):
            perm = rng.permutation(n)
            for start in range(0, n - mbs + 1, mbs):
                idx = perm[start:start + mbs]
                mb = {k: v[idx] for k, v in batch.items()}
                self.params, self.opt_state, aux = self._update(
                    self.params, self.opt_state, mb)
        return {k: float(v) for k, v in aux.items()}


def ppo_surrogate_loss(clip_param: float, vf_coeff: float,
                       entropy_coeff: float):
    """The clipped-surrogate PPO loss as a closure factory — ONE
    definition shared by single-agent PPO and MultiAgentPPO so the loss
    (and its aux metrics) cannot drift between them."""
    def loss_fn(params, mb):
        logits, values = policy_apply(params, mb["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, mb["actions"][:, None].astype(jnp.int32),
            axis=-1)[:, 0]
        ratio = jnp.exp(logp - mb["logp"])
        adv = mb["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
        pi_loss = -surr.mean()
        vf_loss = jnp.mean((values - mb["value_targets"]) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jax.nn.softmax(logits) * logp_all, axis=-1))
        total = (pi_loss + vf_coeff * vf_loss
                 - entropy_coeff * entropy)
        return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    return loss_fn


def _jit_sgd_update(loss_fn, optimizer):
    """The shared value_and_grad → optimizer.update → apply_updates step
    (one definition so PPO/A2C/BC can't drift on e.g. grad clipping)."""
    def update(params, opt_state, mb):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, mb)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        aux["total_loss"] = loss
        return params, opt_state, aux

    return jax.jit(update)


class A2C(Algorithm):
    """Synchronous advantage actor-critic (reference:
    rllib/algorithms/a2c/a2c.py — PPO minus the clipped surrogate and
    the epoch loop: one on-policy gradient step per sampled batch, so
    the whole update jits into a single XLA program per iteration)."""

    def __init__(self, config: AlgorithmConfig):
        super().__init__(config)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        cfg = config

        def loss_fn(params, mb):
            logits, values = policy_apply(params, mb["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, mb["actions"][:, None].astype(jnp.int32),
                axis=-1)[:, 0]
            adv = mb["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pi_loss = -(logp * adv).mean()
            vf_loss = jnp.mean((values - mb["value_targets"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jax.nn.softmax(logits) * logp_all, axis=-1))
            total = (pi_loss + cfg.vf_coeff * vf_loss
                     - cfg.entropy_coeff * entropy)
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        self._update = _jit_sgd_update(loss_fn, self.optimizer)

    def training_step(self, batch) -> dict:
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in aux.items()}


class BC(Algorithm):
    """Behavior cloning — offline RL (reference: rllib/algorithms/bc —
    supervised imitation of a dataset of (obs, action) pairs; no
    environment interaction during training). `config.offline_data` is
    either {"obs": (N, obs_size) array, "actions": (N,) array} or a
    ray_tpu Dataset of such rows. One rollout worker exists solely for
    evaluation (`evaluate()`)."""

    def __init__(self, config: AlgorithmConfig):
        data = config.offline_data
        if data is None:
            raise ValueError("BC needs config.training(offline_data=...)")
        if config.connectors:
            # connectors would resize/renormalize the EVALUATION worker's
            # observations while training sees the raw dataset — a
            # silently distribution-shifted policy. Preprocess the
            # dataset itself instead.
            raise ValueError("BC does not support rollout connectors; "
                             "apply transforms to offline_data directly")
        if hasattr(data, "take_all"):   # ray_tpu Dataset of row dicts
            rows = data.take_all()
            data = {"obs": np.stack([r["obs"] for r in rows]),
                    "actions": np.asarray([r["actions"] for r in rows])}
        self._data = {"obs": np.asarray(data["obs"], np.float32),
                      "actions": np.asarray(data["actions"], np.int32)}
        # evaluation needs exactly one sampler; don't mutate the CALLER's
        # config (it may build other algorithms later)
        config = copy.copy(config)
        config.num_rollout_workers = 1
        super().__init__(config)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, mb):
            logits, _ = policy_apply(params, mb["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, mb["actions"][:, None], axis=-1)[:, 0]
            loss = -logp.mean()
            acc = jnp.mean(
                (jnp.argmax(logits, axis=-1) == mb["actions"]))
            return loss, {"bc_loss": loss, "action_accuracy": acc}

        self._update = _jit_sgd_update(loss_fn, self.optimizer)

    def train(self) -> dict:
        """Offline: iterate minibatches over the dataset (no sampling)."""
        t0 = time.time()
        self.iteration += 1
        n = len(self._data["obs"])
        mbs = max(1, min(self.config.minibatch_size, n))
        rng = np.random.default_rng(self.config.seed + self.iteration)
        perm = rng.permutation(n)
        aux = {}
        trained = 0
        for start in range(0, n - mbs + 1, mbs):
            idx = perm[start:start + mbs]
            mb = {k: v[idx] for k, v in self._data.items()}
            self.params, self.opt_state, aux = self._update(
                self.params, self.opt_state, mb)
            trained += len(idx)
        return {**{k: float(v) for k, v in aux.items()},
                "training_iteration": self.iteration,
                # the n % minibatch tail is dropped this epoch (the next
                # epoch's fresh permutation covers it)
                "num_samples_trained": trained,
                "time_this_iter_s": time.time() - t0}

    def evaluate(self, min_episodes: int = 2,
                 max_rounds: int = 20) -> dict:
        """Roll the cloned policy in the real env (reference:
        Algorithm.evaluate with evaluation workers). A good policy can
        outlive one fragment (CartPole caps at 500 steps), so sampling
        continues until enough EPISODES complete to score."""
        returns: list = []
        for _ in range(max_rounds):
            batch = ray_tpu.get(self.workers[0].sample.remote(
                self.params, self.config.rollout_fragment_length),
                timeout=300)
            returns.extend(batch["episode_returns"].tolist())
            if len(returns) >= min_episodes:
                break
        return {"episode_reward_mean": (float(np.mean(returns))
                                        if returns else 0.0),
                "episodes": int(len(returns))}

    def training_step(self, batch) -> dict:  # pragma: no cover — offline
        raise NotImplementedError("BC trains from offline data")


class DQN(Algorithm):
    """Double DQN with (optionally prioritized) replay (reference:
    rllib/algorithms/dqn/dqn.py — sampling actors feed a replay buffer,
    the jitted learner does Q-updates against a periodically-synced
    target network)."""

    worker_cls = TransitionWorker

    def __init__(self, config: AlgorithmConfig):
        super().__init__(config)
        from ray_tpu.rllib.replay_buffer import (
            PrioritizedReplayBuffer,
            ReplayBuffer,
        )

        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.target_params = jax.tree_util.tree_map(
            jnp.copy, self.params)
        self.buffer = (PrioritizedReplayBuffer(config.buffer_capacity,
                                               seed=config.seed)
                       if config.prioritized_replay
                       else ReplayBuffer(config.buffer_capacity,
                                         seed=config.seed))
        cfg = config

        def loss_fn(params, target_params, mb):
            q, _ = policy_apply(params, mb["obs"])
            q_taken = jnp.take_along_axis(
                q, mb["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
            q_next_t, _ = policy_apply(target_params, mb["next_obs"])
            if cfg.double_q:
                q_next_o, _ = policy_apply(params, mb["next_obs"])
                next_a = jnp.argmax(q_next_o, axis=-1)
            else:
                next_a = jnp.argmax(q_next_t, axis=-1)
            next_q = jnp.take_along_axis(
                q_next_t, next_a[:, None], axis=-1)[:, 0]
            target = mb["rewards"] + cfg.gamma * (1.0 - mb["dones"]) * next_q
            td = q_taken - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) <= 1.0, 0.5 * td * td,
                              jnp.abs(td) - 0.5)
            weights = mb.get("weights")
            loss = (jnp.mean(huber * weights) if weights is not None
                    else jnp.mean(huber))
            return loss, {"td_error": td, "mean_q": jnp.mean(q_taken)}

        def update(params, target_params, opt_state, mb):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, mb)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(update)

    def epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_anneal_iters))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _sample_call(self, worker):
        return worker.sample_transitions.remote(
            self.params, self.config.rollout_fragment_length,
            self.epsilon())

    def training_step(self, batch) -> dict:
        self.buffer.add_batch(batch)
        metrics = {}
        if len(self.buffer) >= self.config.learning_starts:
            for _ in range(self.config.num_sgd_steps):
                mb = self.buffer.sample(self.config.minibatch_size)
                idx = mb.pop("batch_indexes", None)
                mb = {k: jnp.asarray(v) for k, v in mb.items()}
                self.params, self.opt_state, aux = self._update(
                    self.params, self.target_params, self.opt_state, mb)
                if idx is not None:
                    self.buffer.update_priorities(
                        idx, np.asarray(aux["td_error"]))
            metrics = {"loss": float(aux["loss"]),
                       "mean_q": float(aux["mean_q"])}
            if self.iteration % self.config.target_update_freq == 0:
                self.target_params = jax.tree_util.tree_map(
                    jnp.copy, self.params)
        metrics.update({"epsilon": self.epsilon(),
                        "replay_buffer_size": len(self.buffer)})
        return metrics

    def save(self) -> dict:
        return {"params": self.params, "iteration": self.iteration,
                "target_params": self.target_params}

    def restore(self, state: dict):
        super().restore(state)
        self.target_params = state.get("target_params", self.params)
