"""Policy/value networks — small jax MLPs (reference: rllib/models/).

One shared set of helpers: init_policy builds {pi, vf} MLP params;
policy_apply returns (logits, value). jit-compiled by callers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _init_mlp(key, sizes):
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / fan_in)
        params.append({
            "w": jax.random.normal(sub, (fan_in, fan_out)) * scale,
            "b": jnp.zeros((fan_out,)),
        })
    return params


def _apply_mlp(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


def init_policy(key, obs_size: int, num_actions: int,
                hidden: tuple = (64, 64)):
    kp, kv = jax.random.split(key)
    return {
        "pi": _init_mlp(kp, (obs_size, *hidden, num_actions)),
        "vf": _init_mlp(kv, (obs_size, *hidden, 1)),
    }


def policy_apply(params, obs):
    """obs [B, obs_size] -> (logits [B, A], value [B])."""
    logits = _apply_mlp(params["pi"], obs)
    value = _apply_mlp(params["vf"], obs)[..., 0]
    return logits, value
