"""Policy/value networks — small jax MLPs (reference: rllib/models/).

One shared set of helpers: init_policy builds {pi, vf} MLP params;
policy_apply returns (logits, value). jit-compiled by callers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _init_mlp(key, sizes):
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / fan_in)
        params.append({
            "w": jax.random.normal(sub, (fan_in, fan_out)) * scale,
            "b": jnp.zeros((fan_out,)),
        })
    return params


def _apply_mlp(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


def init_policy(key, obs_size: int, num_actions: int,
                hidden: tuple = (64, 64)):
    kp, kv = jax.random.split(key)
    return {
        "pi": _init_mlp(kp, (obs_size, *hidden, num_actions)),
        "vf": _init_mlp(kv, (obs_size, *hidden, 1)),
    }


def policy_apply(params, obs):
    """obs [B, obs_size] -> (logits [B, A], value [B])."""
    logits = _apply_mlp(params["pi"], obs)
    value = _apply_mlp(params["vf"], obs)[..., 0]
    return logits, value


# ------------------------------------------------- SAC (continuous control)

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def init_sac_networks(key, obs_size: int, action_size: int,
                      hidden: tuple = (64, 64)):
    """Squashed-Gaussian actor (outputs [mean, log_std]) + twin Q nets
    over (obs, action) (reference: rllib/algorithms/sac/sac_tf_model.py
    — policy net and two Q nets)."""
    kp, k1, k2 = jax.random.split(key, 3)
    return {
        "pi": _init_mlp(kp, (obs_size, *hidden, 2 * action_size)),
        "q1": _init_mlp(k1, (obs_size + action_size, *hidden, 1)),
        "q2": _init_mlp(k2, (obs_size + action_size, *hidden, 1)),
    }


def sac_actor_apply(params, obs):
    """-> (mean [B, A], log_std [B, A]), log_std clamped."""
    out = _apply_mlp(params["pi"], obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)


def sac_q_apply(q_params, obs, action):
    """Q(s, a) [B] for one critic's params (pass params["q1"]/["q2"])."""
    return _apply_mlp(q_params, jnp.concatenate([obs, action],
                                                axis=-1))[..., 0]


def sac_sample_action(params, obs, key):
    """Reparameterized tanh-squashed sample -> (action in [-1,1]^A,
    log_prob [B]) with the tanh jacobian correction."""
    mean, log_std = sac_actor_apply(params, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mean.shape)
    pre = mean + std * eps
    action = jnp.tanh(pre)
    # N(pre; mean, std) log-density minus log|d tanh/d pre|
    logp = (-0.5 * (eps ** 2) - log_std
            - 0.5 * jnp.log(2 * jnp.pi)).sum(-1)
    logp -= jnp.log(1 - action ** 2 + 1e-6).sum(-1)
    return action, logp


# ----------------------------------------------------- model zoo (CNN/LSTM)

def init_cnn_policy(key, obs_shape: tuple, num_actions: int,
                    channels: tuple = (16, 32), hidden: int = 128):
    """Conv policy for image observations [H, W, C] (reference:
    rllib/models/ VisionNetwork). Convs are lax.conv_general_dilated
    with 3x3 stride-2 kernels — shapes stay static so XLA tiles them
    onto the MXU."""
    params = {"conv": []}
    h, w, c_in = obs_shape
    for c_out in channels:
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / (3 * 3 * c_in))
        params["conv"].append({
            "w": jax.random.normal(sub, (3, 3, c_in, c_out)) * scale,
            "b": jnp.zeros((c_out,)),
        })
        h, w, c_in = (h + 1) // 2, (w + 1) // 2, c_out
    flat = h * w * c_in
    kp, kv = jax.random.split(key)
    params["pi"] = _init_mlp(kp, (flat, hidden, num_actions))
    params["vf"] = _init_mlp(kv, (flat, hidden, 1))
    return params


def cnn_policy_apply(params, obs):
    """obs [B, H, W, C] -> (logits [B, A], value [B])."""
    x = obs
    for layer in params["conv"]:
        x = jax.lax.conv_general_dilated(
            x, layer["w"], window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + layer["b"]
        x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    logits = _apply_mlp(params["pi"], x)
    value = _apply_mlp(params["vf"], x)[..., 0]
    return logits, value


def init_lstm_policy(key, obs_size: int, num_actions: int,
                     hidden: int = 64):
    """Recurrent policy (reference: rllib/models/ use_lstm=True): one
    LSTM cell over the observation encoding, heads on the cell
    output."""
    ke, kl, kp, kv = jax.random.split(key, 4)
    scale_in = jnp.sqrt(2.0 / obs_size)
    scale_h = jnp.sqrt(2.0 / hidden)
    return {
        "enc": _init_mlp(ke, (obs_size, hidden)),
        "lstm": {
            "wi": jax.random.normal(kl, (hidden, 4 * hidden)) * scale_in,
            "wh": jax.random.normal(kl, (hidden, 4 * hidden)) * scale_h,
            "b": jnp.zeros((4 * hidden,)),
        },
        "pi": _init_mlp(kp, (hidden, num_actions)),
        "vf": _init_mlp(kv, (hidden, 1)),
    }


def lstm_policy_initial_state(hidden: int = 64, batch: int = 1):
    return (jnp.zeros((batch, hidden)), jnp.zeros((batch, hidden)))


def lstm_policy_apply(params, obs, state):
    """One recurrent step: obs [B, obs_size], state (h, c) ->
    (logits, value, new_state)."""
    h, c = state
    x = jnp.tanh(_apply_mlp(params["enc"], obs))
    gates = x @ params["lstm"]["wi"] + h @ params["lstm"]["wh"] \
        + params["lstm"]["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    logits = _apply_mlp(params["pi"], h)
    value = _apply_mlp(params["vf"], h)[..., 0]
    return logits, value, (h, c)


def lstm_policy_unroll(params, obs_seq, state):
    """Scan the cell over a [T, B, obs] sequence (lax.scan — one
    compiled loop, no per-step dispatch)."""
    def step(carry, obs_t):
        logits, value, carry = lstm_policy_apply(params, obs_t, carry)
        return carry, (logits, value)

    final, (logits, values) = jax.lax.scan(step, state, obs_seq)
    return logits, values, final
