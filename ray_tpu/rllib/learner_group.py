"""LearnerGroup — multi-learner (data-parallel) RL updates over a Mesh.

Reference: rllib/core/learner/learner_group.py — N learner workers each
take a shard of the train batch, compute gradients, and all-reduce
before applying. TPU-first inversion: instead of N processes + NCCL
all-reduce, the whole update is ONE jitted SPMD program over a
jax.sharding.Mesh — the batch shards over the `dp` axis, gradients
psum over ICI inside the compiled step, and parameters stay replicated.
The same program scales from 1 chip to a pod slice by changing the
mesh.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax


class LearnerGroup:
    """Data-parallel learner: `update(batch)` runs one SPMD step with
    per-device batch shards and psum'd gradients.

    loss_fn(params, minibatch) -> (loss, aux_dict) — same signature the
    single-learner algorithms use, so any of them can hand its loss
    here to scale out.
    """

    def __init__(self, loss_fn, params, *, lr: float = 3e-4,
                 optimizer=None, devices=None, axis: str = "dp"):
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.axis = axis
        self.mesh = jax.sharding.Mesh(self.devices, (axis,))
        self.optimizer = optimizer or optax.adam(lr)
        self.params = params
        self.opt_state = self.optimizer.init(params)
        self._loss_fn = loss_fn
        self._step = self._build_step()

    @property
    def num_learners(self) -> int:
        return len(self.devices)

    def _build_step(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        optimizer = self.optimizer
        loss_fn = self._loss_fn

        def per_shard(params, opt_state, shard):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, shard)
            # gradient all-reduce over ICI — the NCCL ring of the
            # reference's multi-learner, compiled into the step
            grads = jax.lax.pmean(grads, axis)
            loss = jax.lax.pmean(loss, axis)
            aux = jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, axis), aux)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        smapped = shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(P(), P(), P(axis)),
            out_specs=(P(), P(), P(), P()),
            check_rep=False)
        return jax.jit(smapped)

    def update(self, batch: dict) -> dict:
        """One data-parallel step over the full batch (leading dim must
        divide the learner count). Returns {"loss": float, **aux}."""
        n = self.num_learners
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        b = next(iter(batch.values())).shape[0]
        if b % n:
            # truncate the ragged tail so shards stay equal (static
            # shapes; the reference's learner group drops remainders
            # the same way)
            batch = {k: v[: b - b % n] for k, v in batch.items()}
        self.params, self.opt_state, loss, aux = self._step(
            self.params, self.opt_state, batch)
        out = {"loss": float(loss), "num_learners": n}
        out.update({k: float(v) for k, v in aux.items()})
        return out
