"""SAC — soft actor-critic for continuous control.

Reference: rllib/algorithms/sac/sac.py (+ sac_tf_policy.py losses):
off-policy maximum-entropy RL — a squashed-Gaussian actor, twin Q
critics with a polyak-averaged target pair, and automatic entropy
temperature tuning toward a -|A| target. The execution pattern is the
DQN family's (transition workers -> replay buffer -> jitted learner);
what SAC adds is the continuous-action model set and the three-way
actor/critic/alpha update, which compiles into ONE jitted step here
(the XLA fusion does what the reference's multi-GPU tower loop does by
hand).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.env import env_action_space, make_env
from ray_tpu.rllib.models import (
    init_sac_networks,
    sac_actor_apply,
    sac_q_apply,
    sac_sample_action,
)
from ray_tpu.rllib.replay_buffer import ReplayBuffer


class ContinuousTransitionWorker:
    """Sampling actor for continuous-action envs: steps with the current
    squashed-Gaussian actor, returns transition batches (reference:
    rollout_worker.py in transition mode, continuous branch)."""

    def __init__(self, env_spec, *, num_envs: int = 1, seed: int = 0):
        self.envs = [make_env(env_spec, seed=seed * 1000 + i)
                     for i in range(num_envs)]
        space = env_action_space(self.envs[0])
        self.obs_size = space["obs_size"]
        self.action_size = space["action_size"]
        self.low = np.asarray(space["low"], np.float32)
        self.high = np.asarray(space["high"], np.float32)
        self._key = jax.random.PRNGKey(seed)
        self._obs = [np.asarray(e.reset(seed=seed * 1000 + i)[0],
                                np.float32)
                     for i, e in enumerate(self.envs)]
        self._episode_returns = [0.0] * num_envs
        self._completed: list[float] = []
        self._sample = jax.jit(sac_sample_action)

    def spaces(self):
        return {"obs_size": self.obs_size,
                "action_size": self.action_size,
                "low": self.low, "high": self.high}

    def sample_transitions(self, params, steps_per_env: int,
                           random_warmup: bool = False) -> dict:
        E, T = len(self.envs), steps_per_env
        obs = np.zeros((T, E, self.obs_size), np.float32)
        actions = np.zeros((T, E, self.action_size), np.float32)
        rewards = np.zeros((T, E), np.float32)
        dones = np.zeros((T, E), np.float32)
        next_obs = np.zeros((T, E, self.obs_size), np.float32)
        scale = (self.high - self.low) / 2.0
        mid = (self.high + self.low) / 2.0
        for t in range(T):
            stacked = np.stack(self._obs)
            if random_warmup:
                a_unit = np.random.uniform(-1, 1, (E, self.action_size))
            else:
                self._key, sub = jax.random.split(self._key)
                a_unit = np.asarray(self._sample(params, stacked, sub)[0])
            a_env = a_unit * scale + mid
            for e in range(E):
                obs[t, e] = self._obs[e]
                actions[t, e] = a_unit[e]
                nobs, r, term, trunc, _ = self.envs[e].step(a_env[e])
                self._episode_returns[e] += r
                rewards[t, e] = r
                # time-limit truncation is NOT a true terminal: bootstrap
                dones[t, e] = float(term)
                next_obs[t, e] = np.asarray(nobs, np.float32)
                if term or trunc:
                    self._completed.append(self._episode_returns[e])
                    self._episode_returns[e] = 0.0
                    self._obs[e] = np.asarray(self.envs[e].reset()[0],
                                              np.float32)
                else:
                    self._obs[e] = next_obs[t, e]
        flat = {
            "obs": obs.reshape(T * E, -1),
            "actions": actions.reshape(T * E, -1),
            "rewards": rewards.reshape(T * E),
            "dones": dones.reshape(T * E),
            "next_obs": next_obs.reshape(T * E, -1),
        }
        flat["episode_returns"] = np.asarray(self._completed, np.float64)
        self._completed = []
        return flat


class SAC(Algorithm):
    """Soft actor-critic (reference: rllib/algorithms/sac/sac.py)."""

    def __init__(self, config: AlgorithmConfig):
        # bespoke worker set (continuous spaces) — skip Algorithm.__init__
        self.config = config
        worker_cls = ray_tpu.remote(ContinuousTransitionWorker)
        self.workers = [
            worker_cls.options(num_cpus=0).remote(
                config.env_spec, num_envs=config.num_envs_per_worker,
                seed=config.seed + i)
            for i in range(config.num_rollout_workers)
        ]
        space = ray_tpu.get(self.workers[0].spaces.remote())
        self.action_size = space["action_size"]
        self.params = init_sac_networks(
            jax.random.PRNGKey(config.seed), space["obs_size"],
            self.action_size)
        self.target_q = jax.tree_util.tree_map(
            jnp.copy, {"q1": self.params["q1"], "q2": self.params["q2"]})
        self.log_alpha = jnp.asarray(float(np.log(config.init_alpha)))
        self.target_entropy = -float(self.action_size)
        self.buffer = ReplayBuffer(config.buffer_capacity,
                                   seed=config.seed)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.alpha_opt = optax.adam(config.alpha_lr)
        self.alpha_opt_state = self.alpha_opt.init(self.log_alpha)
        self.iteration = 0
        self._recent_returns: list = []
        self._key = jax.random.PRNGKey(config.seed + 7)
        cfg = config

        def critic_loss(params, target_q, log_alpha, mb, key):
            next_a, next_logp = sac_sample_action(
                params, mb["next_obs"], key)
            tq1 = sac_q_apply(target_q["q1"], mb["next_obs"], next_a)
            tq2 = sac_q_apply(target_q["q2"], mb["next_obs"], next_a)
            alpha = jnp.exp(log_alpha)
            soft_q = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = mb["rewards"] + cfg.gamma * (1 - mb["dones"]) * soft_q
            target = jax.lax.stop_gradient(target)
            q1 = sac_q_apply(params["q1"], mb["obs"], mb["actions"])
            q2 = sac_q_apply(params["q2"], mb["obs"], mb["actions"])
            return jnp.mean((q1 - target) ** 2 + (q2 - target) ** 2)

        def actor_loss(params, log_alpha, mb, key):
            a, logp = sac_sample_action(params, mb["obs"], key)
            q = jnp.minimum(sac_q_apply(params["q1"], mb["obs"], a),
                            sac_q_apply(params["q2"], mb["obs"], a))
            return jnp.mean(jnp.exp(log_alpha) * logp - q), logp

        def update(params, target_q, log_alpha, opt_state,
                   alpha_opt_state, mb, key):
            kc, ka = jax.random.split(key)
            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                params, target_q, log_alpha, mb, kc)
            (a_loss, logp), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(params, log_alpha, mb, ka)
            # one optimizer over the whole param tree: critic grads drive
            # q1/q2, actor grads drive pi — mask the cross terms
            grads = {
                "pi": a_grads["pi"],
                "q1": c_grads["q1"],
                "q2": c_grads["q2"],
            }
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            # temperature: pull entropy toward -|A|
            alpha_grad = jax.grad(
                lambda la: -jnp.mean(
                    la * jax.lax.stop_gradient(
                        logp + self.target_entropy)))(log_alpha)
            a_updates, alpha_opt_state = self.alpha_opt.update(
                alpha_grad, alpha_opt_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, a_updates)
            # polyak target update
            target_q = jax.tree_util.tree_map(
                lambda t, s: (1 - cfg.tau) * t + cfg.tau * s,
                target_q, {"q1": params["q1"], "q2": params["q2"]})
            aux = {"critic_loss": c_loss, "actor_loss": a_loss,
                   "alpha": jnp.exp(log_alpha),
                   "entropy": -jnp.mean(logp)}
            return params, target_q, log_alpha, opt_state, \
                alpha_opt_state, aux

        self._update = jax.jit(update)

    def _sample_call(self, worker):
        warmup = len(self.buffer) < self.config.learning_starts
        return worker.sample_transitions.remote(
            self.params, self.config.rollout_fragment_length,
            random_warmup=warmup)

    def training_step(self, batch) -> dict:
        self.buffer.add_batch(batch)
        metrics = {"replay_buffer_size": len(self.buffer)}
        if len(self.buffer) < self.config.learning_starts:
            return metrics
        for _ in range(self.config.num_sgd_steps):
            mb = {k: jnp.asarray(v)
                  for k, v in self.buffer.sample(
                      self.config.minibatch_size).items()}
            self._key, sub = jax.random.split(self._key)
            (self.params, self.target_q, self.log_alpha, self.opt_state,
             self.alpha_opt_state, aux) = self._update(
                self.params, self.target_q, self.log_alpha,
                self.opt_state, self.alpha_opt_state, mb, sub)
        metrics.update({k: float(v) for k, v in aux.items()})
        return metrics

    def evaluate(self, num_episodes: int = 3, seed: int = 123) -> dict:
        """Deterministic-policy evaluation (tanh(mean), no sampling) on
        fresh local envs — the reference's evaluation_config
        explore=False rollouts."""
        from ray_tpu.rllib.models import sac_actor_apply

        env = make_env(self.config.env_spec, seed=seed)
        space = env_action_space(env)
        scale = (np.asarray(space["high"]) - space["low"]) / 2.0
        mid = (np.asarray(space["high"]) + space["low"]) / 2.0
        fwd = jax.jit(sac_actor_apply)
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            total, done = 0.0, False
            while not done:
                mean, _ = fwd(self.params, np.asarray(obs,
                                                      np.float32)[None])
                a = np.tanh(np.asarray(mean))[0] * scale + mid
                obs, r, term, trunc, _ = env.step(a)
                total += r
                done = term or trunc
            returns.append(total)
        return {"episode_reward_mean": float(np.mean(returns)),
                "episodes": num_episodes}

    def save(self) -> dict:
        return {"params": self.params, "iteration": self.iteration,
                "target_q": self.target_q,
                "log_alpha": self.log_alpha}

    def restore(self, state: dict):
        self.params = state["params"]
        self.iteration = state["iteration"]
        self.target_q = state.get("target_q", self.target_q)
        self.log_alpha = state.get("log_alpha", self.log_alpha)
