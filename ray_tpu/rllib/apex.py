"""APEX-DQN — distributed prioritized experience replay.

Reference: rllib/algorithms/apex_dqn/apex_dqn.py: many sampling actors
feed SHARDED replay-buffer actors; the learner pulls sample batches
from the shards, updates, and sends new TD-error priorities back to the
owning shard (the priority-update round trip). The decoupling means
sampling throughput and learning throughput scale independently — the
same reason the reference runs its replay buffers as actors.

Execution here: TransitionWorkers sample continuously (in-flight refs,
no barrier with the learner), batches round-robin into >=2 ReplayShard
actors, the learner samples each shard in turn and routes
update_priorities back by shard index.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.models import policy_apply
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer
from ray_tpu.rllib.rollout_worker import TransitionWorker


class ReplayShard:
    """One shard of the distributed prioritized replay (reference:
    apex_dqn's ReplayActor over PrioritizedReplayBuffer)."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        self.buffer = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                              beta=beta, seed=seed)
        self.adds = 0
        self.priority_updates = 0

    def add_batch(self, batch: dict):
        batch.pop("episode_returns", None)
        self.buffer.add_batch(batch)
        self.adds += 1
        return len(self.buffer)

    def sample(self, batch_size: int):
        if len(self.buffer) < batch_size:
            return None
        return self.buffer.sample(batch_size)

    def update_priorities(self, indexes, td_errors):
        self.buffer.update_priorities(np.asarray(indexes),
                                      np.asarray(td_errors))
        self.priority_updates += 1
        return True

    def stats(self):
        return {"size": len(self.buffer), "adds": self.adds,
                "priority_updates": self.priority_updates}


class ApexDQN(Algorithm):
    """Distributed prioritized DQN (reference: apex_dqn.py)."""

    worker_cls = TransitionWorker

    def __init__(self, config: AlgorithmConfig):
        super().__init__(config)
        shard_cls = ray_tpu.remote(ReplayShard)
        n = max(2, config.num_replay_shards)
        per_shard = max(1, config.buffer_capacity // n)
        self.shards = [
            shard_cls.options(num_cpus=0).remote(
                per_shard, seed=config.seed + 100 + i)
            for i in range(n)
        ]
        self._next_shard = 0
        self._sample_cursor = 0
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        cfg = config

        def loss_fn(params, target_params, mb):
            q, _ = policy_apply(params, mb["obs"])
            q_taken = jnp.take_along_axis(
                q, mb["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
            q_next_t, _ = policy_apply(target_params, mb["next_obs"])
            q_next_o, _ = policy_apply(params, mb["next_obs"])
            next_a = jnp.argmax(q_next_o, axis=-1)     # double-Q
            next_q = jnp.take_along_axis(
                q_next_t, next_a[:, None], axis=-1)[:, 0]
            target = mb["rewards"] + cfg.gamma * (1 - mb["dones"]) * next_q
            td = q_taken - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) <= 1.0, 0.5 * td * td,
                              jnp.abs(td) - 0.5)
            return jnp.mean(huber * mb["weights"]), td

        def update(params, target_params, opt_state, mb):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, mb)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._update = jax.jit(update)

    def epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_anneal_iters))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _sample_call(self, worker):
        return worker.sample_transitions.remote(
            self.params, self.config.rollout_fragment_length,
            self.epsilon())

    def training_step(self, batch) -> dict:
        # route the fresh batch to the next shard (round-robin); the
        # base-class train() already pulled it off the workers
        self.shards[self._next_shard].add_batch.remote(batch)
        self._next_shard = (self._next_shard + 1) % len(self.shards)

        loss = None
        trained = 0
        for _ in range(self.config.num_sgd_steps):
            shard_i = self._sample_cursor % len(self.shards)
            self._sample_cursor += 1
            mb = ray_tpu.get(self.shards[shard_i].sample.remote(
                self.config.minibatch_size), timeout=60)
            if mb is None:
                continue   # shard still warming up
            idx = mb.pop("batch_indexes")
            jmb = {k: jnp.asarray(v) for k, v in mb.items()}
            self.params, self.opt_state, loss, td = self._update(
                self.params, self.target_params, self.opt_state, jmb)
            # priority-update round trip to the shard that OWNS the rows
            self.shards[shard_i].update_priorities.remote(
                idx, np.asarray(td))
            trained += 1
        if self.iteration % self.config.target_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(
                jnp.copy, self.params)
        metrics = {"epsilon": self.epsilon(), "sgd_steps": trained}
        if loss is not None:
            metrics["loss"] = float(loss)
        return metrics

    def replay_stats(self) -> list[dict]:
        return ray_tpu.get([s.stats.remote() for s in self.shards],
                           timeout=60)

    def save(self) -> dict:
        return {"params": self.params, "iteration": self.iteration,
                "target_params": self.target_params}

    def restore(self, state: dict):
        super().restore(state)
        self.target_params = state.get("target_params", self.params)
