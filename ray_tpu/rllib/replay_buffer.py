"""Replay buffers for off-policy algorithms.

Reference: rllib/utils/replay_buffers/replay_buffer.py (ring storage,
uniform sampling) and prioritized_episode_replay_buffer.py. Storage here
is preallocated numpy rings per column — batches slice out without any
per-row Python, matching the columnar block convention of ray_tpu.data.
"""
from __future__ import annotations

import numpy as np


class ReplayBuffer:
    """Uniform ring-buffer over columnar transition batches."""

    def __init__(self, capacity: int = 100_000, seed: int | None = None):
        self.capacity = capacity
        self._cols: dict[str, np.ndarray] | None = None
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: dict):
        """Append a columnar batch {name: array[N, ...]}; oldest rows are
        overwritten once capacity is reached."""
        n = len(next(iter(batch.values())))
        if self._cols is None:
            self._cols = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()
            }
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> dict:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._cols.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    rllib/utils/replay_buffers/prioritized_replay_buffer.py). Priorities
    are stored per-row; `sample` returns importance weights and the row
    indices so the learner can call `update_priorities` with new TD
    errors."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int | None = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._prios = np.zeros((capacity,), np.float64)
        self._max_prio = 1.0

    def add_batch(self, batch: dict):
        n = len(next(iter(batch.values())))
        idx = (self._next + np.arange(n)) % self.capacity
        self._prios[idx] = self._max_prio
        super().add_batch(batch)

    def sample(self, batch_size: int) -> dict:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        p = self._prios[: self._size] ** self.alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, batch_size, p=p)
        out = {k: v[idx] for k, v in self._cols.items()}
        weights = (self._size * p[idx]) ** (-self.beta)
        out["weights"] = (weights / weights.max()).astype(np.float32)
        out["batch_indexes"] = idx.astype(np.int64)
        return out

    def update_priorities(self, indexes: np.ndarray, td_errors: np.ndarray):
        prios = np.abs(td_errors) + 1e-6
        self._prios[indexes] = prios
        self._max_prio = max(self._max_prio, float(prios.max()))
