"""RolloutWorker — the sampling actor.

Reference: rllib/evaluation/rollout_worker.py:150 (sample at :849). Each
worker owns env instances + a jitted policy forward; `sample(params, n)`
steps the envs for n transitions per env, computes GAE advantages, and
returns a SampleBatch (dict of numpy arrays) through the object store —
the learner never touches an environment.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.env import env_spaces, make_env
from ray_tpu.rllib.connectors import ConnectorPipeline
from ray_tpu.rllib.models import policy_apply


class RolloutWorker:
    def __init__(self, env_spec, *, num_envs: int = 2, seed: int = 0,
                 gamma: float = 0.99, gae_lambda: float = 0.95,
                 connectors=None):
        self.envs = [make_env(env_spec, seed=seed * 1000 + i)
                     for i in range(num_envs)]
        raw_obs_size, self.num_actions = env_spaces(self.envs[0])
        # connectors transform every observation/reward between env and
        # policy (reference: rllib/connectors/); the policy's obs width
        # follows the pipeline (FrameStack widens it)
        self.connectors = ConnectorPipeline(connectors or [])
        self.obs_size = self.connectors.obs_size(raw_obs_size)
        self.gamma = gamma
        self.gae_lambda = gae_lambda
        self._rng = np.random.default_rng(seed)
        self._obs = [
            self.connectors.transform_obs(
                env.reset(seed=seed * 1000 + i)[0], stream_key=i)
            for i, env in enumerate(self.envs)]
        self._episode_returns = [0.0] * num_envs
        self._completed: list[float] = []
        import jax

        self._fwd = jax.jit(policy_apply)

    def spaces(self):
        return self.obs_size, self.num_actions

    def _env_step(self, e: int, action: int):
        """Step env e, handle episode bookkeeping + auto-reset. Returns
        (next_obs_before_reset, TRANSFORMED reward, terminated,
        truncated); self._obs[e] ends up at the (transformed) obs the
        NEXT action should see."""
        nobs, r, terminated, truncated, _ = self.envs[e].step(int(action))
        self._episode_returns[e] += r   # true return, pre-transform
        r = self.connectors.transform_reward(r, stream_key=e)
        if terminated or truncated:
            self._completed.append(self._episode_returns[e])
            self._episode_returns[e] = 0.0
            self.connectors.reset(stream_key=e)
            self._obs[e] = self.connectors.transform_obs(
                self.envs[e].reset()[0], stream_key=e)
        else:
            self._obs[e] = self.connectors.transform_obs(
                nobs, stream_key=e)
        return nobs, r, terminated, truncated

    def sample(self, params, steps_per_env: int) -> dict:
        """Collect steps_per_env transitions from every env; returns a
        SampleBatch with GAE advantages and value targets."""
        E = len(self.envs)
        T = steps_per_env
        obs = np.zeros((T, E, self.obs_size), np.float32)
        actions = np.zeros((T, E), np.int32)
        rewards = np.zeros((T, E), np.float32)
        dones = np.zeros((T, E), np.float32)
        logps = np.zeros((T, E), np.float32)
        values = np.zeros((T, E), np.float32)

        for t in range(T):
            stacked = np.stack(self._obs)
            logits, v = self._fwd(params, stacked)
            logits = np.asarray(logits)
            v = np.asarray(v)
            # sample actions from the categorical policy
            z = self._rng.gumbel(size=logits.shape)
            act = np.argmax(logits + z, axis=-1)
            logp_all = logits - _logsumexp(logits)
            obs[t] = stacked
            actions[t] = act
            values[t] = v
            logps[t] = logp_all[np.arange(E), act]
            for e in range(E):
                _, r, terminated, truncated = self._env_step(e, act[e])
                rewards[t, e] = r
                if terminated or truncated:
                    dones[t, e] = 1.0

        # bootstrap value for the final observation
        _, last_v = self._fwd(params, np.stack(self._obs))
        last_v = np.asarray(last_v)
        adv = np.zeros((T, E), np.float32)
        last_gae = np.zeros(E, np.float32)
        for t in reversed(range(T)):
            next_v = last_v if t == T - 1 else values[t + 1]
            nonterminal = 1.0 - dones[t]
            delta = rewards[t] + self.gamma * next_v * nonterminal - values[t]
            last_gae = delta + \
                self.gamma * self.gae_lambda * nonterminal * last_gae
            adv[t] = last_gae
        targets = adv + values

        flat = lambda a: a.reshape((T * E,) + a.shape[2:])
        completed, self._completed = self._completed, []
        return {
            "obs": flat(obs),
            "actions": flat(actions),
            "logp": flat(logps),
            "advantages": flat(adv),
            "value_targets": flat(targets),
            "episode_returns": np.asarray(completed, np.float32),
        }


def _logsumexp(x):
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))


def concat_batches(batches: list[dict]) -> dict:
    return {k: np.concatenate([b[k] for b in batches])
            for k in batches[0]}


class TransitionWorker(RolloutWorker):
    """Sampling actor for value-based algorithms (DQN family): returns raw
    (s, a, r, s', done) transitions for a replay buffer instead of
    GAE-processed on-policy batches (reference:
    rllib/evaluation/rollout_worker.py used with _disable_preprocessing +
    ReplayBuffer connectors)."""

    def sample_transitions(self, params, steps_per_env: int,
                           epsilon: float) -> dict:
        E = len(self.envs)
        T = steps_per_env
        obs = np.zeros((T, E, self.obs_size), np.float32)
        next_obs = np.zeros((T, E, self.obs_size), np.float32)
        actions = np.zeros((T, E), np.int32)
        rewards = np.zeros((T, E), np.float32)
        dones = np.zeros((T, E), np.float32)

        for t in range(T):
            stacked = np.stack(self._obs)
            q, _ = self._fwd(params, stacked)
            act = np.asarray(np.argmax(q, axis=-1))
            explore = self._rng.random(E) < epsilon
            act = np.where(explore,
                           self._rng.integers(0, self.num_actions, E), act)
            obs[t] = stacked
            actions[t] = act
            for e in range(E):
                _nobs, r, terminated, _ = self._env_step(e, act[e])
                rewards[t, e] = r
                # truncation is not a true terminal: bootstrapping through
                # it is correct, so done=terminated only
                dones[t, e] = 1.0 if terminated else 0.0
                # the TRANSFORMED next obs the target network will see
                # (raw _nobs has the wrong width/statistics under
                # connectors). On episode end self._obs[e] is the reset
                # obs — fine: the TD target masks next_obs by done.
                next_obs[t, e] = self._obs[e]

        flat = lambda a: a.reshape((T * E,) + a.shape[2:])
        completed, self._completed = self._completed, []
        return {
            "obs": flat(obs),
            "actions": flat(actions),
            "rewards": flat(rewards),
            "next_obs": flat(next_obs),
            "dones": flat(dones),
            "episode_returns": np.asarray(completed, np.float32),
        }
