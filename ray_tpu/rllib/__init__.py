"""ray_tpu.rllib — reinforcement learning on the runtime's actors.

Thin capability-parity core of the reference's RLlib (rllib/, 156k LoC;
SURVEY.md §2.3): AlgorithmConfig builder → Algorithm owning a WorkerSet of
RolloutWorker actors (sampling + GAE on CPU hosts) and a jitted jax
learner (PPO's clipped surrogate). Sample batches flow through the object
store — the async sample/learn split of
rllib/execution/multi_gpu_learner_thread.py:20 with the object store as
the ring buffer and the compiled jax update as the device step.
"""
from ray_tpu.rllib.algorithm import A2C, BC, DQN, Algorithm, AlgorithmConfig, PPO
from ray_tpu.rllib.multi_agent import (
    MultiAgentCartPole,
    MultiAgentEnv,
    MultiAgentPPO,
    MultiAgentRolloutWorker,
)
from ray_tpu.rllib.connectors import (
    ClipReward,
    Connector,
    ConnectorPipeline,
    FrameStack,
    MeanStdObsNormalizer,
)
from ray_tpu.rllib.apex import ApexDQN, ReplayShard
from ray_tpu.rllib.learner_group import LearnerGroup
from ray_tpu.rllib.env import CartPole, Pendulum, make_env
from ray_tpu.rllib.sac import SAC, ContinuousTransitionWorker
from ray_tpu.rllib.models import init_policy, policy_apply
from ray_tpu.rllib.replay_buffer import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.rollout_worker import (
    RolloutWorker,
    TransitionWorker,
    concat_batches,
)

__all__ = ["A2C", "Algorithm", "AlgorithmConfig", "ApexDQN", "BC",
           "CartPole", "ContinuousTransitionWorker", "Pendulum",
           "ReplayShard", "SAC",
           "ClipReward", "Connector", "ConnectorPipeline", "DQN",
           "FrameStack", "MeanStdObsNormalizer",
           "MultiAgentCartPole", "MultiAgentEnv", "MultiAgentPPO",
           "MultiAgentRolloutWorker",
           "LearnerGroup", "PPO", "PrioritizedReplayBuffer", "ReplayBuffer",
           "RolloutWorker", "TransitionWorker", "concat_batches",
           "init_policy", "make_env", "policy_apply"]
