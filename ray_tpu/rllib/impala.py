"""IMPALA — decoupled async sampling / V-trace learner.

Reference: rllib/algorithms/impala/impala.py:549 and
rllib/execution/multi_gpu_learner_thread.py:20,187 — rollout actors
sample continuously with whatever (stale) policy they last received,
batches flow through a bounded host queue into a learner thread that
double-buffers device transfers, and the off-policy gap is corrected by
V-trace importance weighting (Espeholt et al. 2018). TPU-native shape:
the learner is one jitted update (V-trace is a `lax.scan`, so the whole
step compiles to a single XLA program); the host ring buffer of the
reference's pinned-memory loader threads becomes a queue.Queue of numpy
batches with `jax.device_put` prefetch — on TPU the transfer overlaps
the previous step's compute exactly like the reference's CUDA streams.

Decoupling invariant (what "async" buys): samplers are resubmitted the
moment their batch is collected, BEFORE the learner consumes it, so a
slow learner never idles the samplers — the queue absorbs the skew and
`sampled_while_learning` counts the overlap as proof.
"""
from __future__ import annotations

import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.models import policy_apply
from ray_tpu.rllib.rollout_worker import RolloutWorker


class VTraceWorker(RolloutWorker):
    """Sampler returning time-major trajectories for V-trace (behavior
    log-probs + bootstrap obs instead of GAE postprocessing)."""

    def sample_vtrace(self, params, steps_per_env: int) -> dict:
        E = len(self.envs)
        T = steps_per_env
        obs = np.zeros((T, E, self.obs_size), np.float32)
        actions = np.zeros((T, E), np.int32)
        rewards = np.zeros((T, E), np.float32)
        dones = np.zeros((T, E), np.float32)
        logps = np.zeros((T, E), np.float32)

        for t in range(T):
            stacked = np.stack(self._obs)
            logits, _ = self._fwd(params, stacked)
            logits = np.asarray(logits)
            z = self._rng.gumbel(size=logits.shape)
            act = np.argmax(logits + z, axis=-1)
            m = logits.max(axis=-1, keepdims=True)
            logp_all = logits - (
                m + np.log(np.exp(logits - m).sum(axis=-1, keepdims=True)))
            obs[t] = stacked
            actions[t] = act
            logps[t] = logp_all[np.arange(E), act]
            for e in range(E):
                _, r, terminated, truncated = self._env_step(e, act[e])
                rewards[t, e] = r
                if terminated or truncated:
                    dones[t, e] = 1.0

        completed, self._completed = self._completed, []
        return {
            "obs": obs, "actions": actions, "rewards": rewards,
            "dones": dones, "behavior_logp": logps,
            "bootstrap_obs": np.stack(self._obs).astype(np.float32),
            "episode_returns": np.asarray(completed, np.float32),
        }


def vtrace_returns(target_logp, behavior_logp, rewards, dones, values,
                   bootstrap_v, gamma, rho_bar=1.0, c_bar=1.0):
    """V-trace targets vs_t and policy-gradient advantages (time-major
    (T, E) arrays). One backward `lax.scan` — compiles into the learner's
    XLA program rather than a host loop."""
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_bar)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_bar)
    nonterminal = 1.0 - dones
    values_tp1 = jnp.concatenate([values[1:], bootstrap_v[None]], axis=0)
    deltas = rho * (rewards + gamma * nonterminal * values_tp1 - values)

    def backward(carry, xs):
        delta_t, c_t, nt_t = xs
        acc = delta_t + gamma * nt_t * c_t * carry
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_v),
        (deltas, c, nonterminal), reverse=True)
    vs = vs_minus_v + values
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_v[None]], axis=0)
    pg_adv = rho * (rewards + gamma * nonterminal * vs_tp1 - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class IMPALA(Algorithm):
    """Async learner: samplers feed a bounded queue; a learner thread
    consumes it with device-transfer double-buffering."""

    worker_cls = VTraceWorker

    def __init__(self, config: AlgorithmConfig):
        super().__init__(config)
        cfg = config
        self.optimizer = optax.rmsprop(cfg.lr, decay=0.99, eps=1e-5)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, batch):
            T, E = batch["actions"].shape
            obs_flat = batch["obs"].reshape(T * E, -1)
            logits, values = policy_apply(params, obs_flat)
            logits = logits.reshape(T, E, -1)
            values = values.reshape(T, E)
            _, bootstrap_v = policy_apply(params, batch["bootstrap_obs"])
            logp_all = jax.nn.log_softmax(logits)
            target_logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            vs, pg_adv = vtrace_returns(
                target_logp, batch["behavior_logp"], batch["rewards"],
                batch["dones"], values, bootstrap_v, cfg.gamma)
            pi_loss = -jnp.mean(target_logp * pg_adv)
            vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jax.nn.softmax(logits) * logp_all, axis=-1))
            total = (pi_loss + cfg.vf_coeff * vf_loss
                     - cfg.entropy_coeff * entropy)
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(update)

        # learner plumbing
        self._queue: queue.Queue = queue.Queue(
            maxsize=getattr(cfg, "learner_queue_size", 8))
        self._params_lock = threading.Lock()
        self._learner_stop = threading.Event()
        self._learner_error: BaseException | None = None
        self._learner_steps = 0
        self._learner_busy = False
        self._sampled_while_learning = 0
        self._last_aux: dict = {}
        # test/diagnostic hook: artificial per-step learner latency, to
        # demonstrate samplers keep running while the learner lags
        self._learner_min_step_s = getattr(cfg, "learner_min_step_s", 0.0)
        self._learner = threading.Thread(
            target=self._learner_loop, daemon=True, name="impala-learner")
        self._learner.start()
        self._in_flight: dict = {}

    # ------------------------------------------------------------- learner
    def _learner_loop(self):
        pending = None   # device-resident next batch (double buffer)
        try:
            while not self._learner_stop.is_set():
                if pending is None:
                    try:
                        host = self._queue.get(timeout=0.2)
                    except queue.Empty:
                        continue
                    pending = jax.device_put(host)
                dev, pending = pending, None
                try:
                    # start the NEXT transfer before this update blocks:
                    # on TPU device_put is async, so H2D rides under the
                    # current step (the reference's pinned-memory double
                    # buffer, multi_gpu_learner_thread.py:187)
                    nxt = self._queue.get_nowait()
                    pending = jax.device_put(nxt)
                except queue.Empty:
                    pass
                self._learner_busy = True
                t0 = time.perf_counter()
                with self._params_lock:
                    params, opt_state = self.params, self.opt_state
                params, opt_state, aux = self._update(params, opt_state, dev)
                aux = {k: float(v) for k, v in aux.items()}
                with self._params_lock:
                    self.params, self.opt_state = params, opt_state
                if self._learner_min_step_s:
                    spare = self._learner_min_step_s - (
                        time.perf_counter() - t0)
                    if spare > 0:
                        time.sleep(spare)
                self._learner_busy = False
                self._last_aux = aux
                self._learner_steps += 1
        except BaseException as e:  # noqa: BLE001 — surface in train()
            self._learner_error = e
            self._learner_busy = False

    # ------------------------------------------------------------- sampling
    def _submit(self, worker):
        with self._params_lock:
            params = self.params
        return worker.sample_vtrace.remote(
            params, self.config.rollout_fragment_length)

    def train(self) -> dict:
        """One iteration = `num_sgd_steps` learner steps of continuous
        sampling. Samplers are resubmitted the moment their batch lands
        in the queue — never gated on the learner."""
        t0 = time.time()
        self.iteration += 1
        target = self._learner_steps + max(1, self.config.num_sgd_steps)
        if not self._in_flight:
            self._in_flight = {self._submit(w): w for w in self.workers}
        samples = 0
        while self._learner_steps < target:
            if self._learner_error is not None:
                raise self._learner_error
            ready, _ = ray_tpu.wait(list(self._in_flight),
                                    num_returns=1, timeout=1.0)
            for ref in ready:
                worker = self._in_flight.pop(ref)
                batch = ray_tpu.get(ref)
                returns = batch.pop("episode_returns")
                self._recent_returns.extend(returns.tolist())
                self._recent_returns = self._recent_returns[-100:]
                # resubmit FIRST: the sampler must never wait on the
                # learner-side queue put below
                self._in_flight[self._submit(worker)] = worker
                samples += 1
                if self._learner_busy:
                    self._sampled_while_learning += 1
                while True:
                    try:
                        self._queue.put(batch, timeout=5.0)
                        break
                    except queue.Full:
                        if self._learner_error is not None:
                            raise self._learner_error
        metrics = dict(self._last_aux)
        metrics.update({
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else 0.0),
            "learner_steps": self._learner_steps,
            "sample_batches_this_iter": samples,
            "sampled_while_learning": self._sampled_while_learning,
            "learner_queue_size": self._queue.qsize(),
            "time_this_iter_s": time.time() - t0,
        })
        return metrics

    def training_step(self, batch) -> dict:  # pragma: no cover — unused
        raise NotImplementedError("IMPALA trains via its learner thread")

    def save(self) -> dict:
        with self._params_lock:
            return {"params": self.params, "iteration": self.iteration}

    def stop(self):
        self._learner_stop.set()
        self._learner.join(timeout=10.0)
        super().stop()
