"""Environment API + built-in CartPole.

Reference: rllib/env/ (gym-style envs, vectorized wrappers). The API is
gymnasium's reset/step; `make_env` accepts a spec string ("CartPole-v1"
uses the built-in numpy implementation so tests are hermetic; any other
string is resolved through gymnasium when installed) or a callable.
"""
from __future__ import annotations

import numpy as np


class CartPole:
    """Classic cart-pole balancing, pure numpy (dynamics follow the classic
    control formulation; public-domain physics)."""

    def __init__(self, seed: int | None = None, max_steps: int = 500):
        self._rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.observation_size = 4
        self.num_actions = 2
        self._state = None
        self._t = 0

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32), {}

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = 10.0 if action == 1 else -10.0
        g, mc, mp, length = 9.8, 1.0, 0.1, 0.5
        total_mass = mc + mp
        polemass_length = mp * length
        tau = 0.02

        costh, sinth = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sinth) / total_mass
        theta_acc = (g * sinth - costh * temp) / (
            length * (4.0 / 3.0 - mp * costh**2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass

        x += tau * x_dot
        x_dot += tau * x_acc
        theta += tau * theta_dot
        theta_dot += tau * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._t += 1

        terminated = bool(abs(x) > 2.4 or abs(theta) > 12 * np.pi / 180)
        truncated = self._t >= self.max_steps
        return (self._state.astype(np.float32), 1.0, terminated, truncated,
                {})


def make_env(env_spec, seed: int | None = None):
    """env_spec: "CartPole-v1" (built-in), a gymnasium id, or a zero-arg
    callable returning a reset/step env."""
    if callable(env_spec):
        return env_spec()
    if env_spec in ("CartPole-v1", "CartPole-v0"):
        return CartPole(seed=seed,
                        max_steps=500 if env_spec.endswith("v1") else 200)
    import gymnasium

    env = gymnasium.make(env_spec)
    if seed is not None:
        env.reset(seed=seed)
    return env


def env_spaces(env) -> tuple[int, int]:
    """(observation_size, num_actions) for a discrete-action env."""
    if hasattr(env, "observation_size"):
        return env.observation_size, env.num_actions
    obs_size = int(np.prod(env.observation_space.shape))
    return obs_size, int(env.action_space.n)
