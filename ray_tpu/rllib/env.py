"""Environment API + built-in CartPole.

Reference: rllib/env/ (gym-style envs, vectorized wrappers). The API is
gymnasium's reset/step; `make_env` accepts a spec string ("CartPole-v1"
uses the built-in numpy implementation so tests are hermetic; any other
string is resolved through gymnasium when installed) or a callable.
"""
from __future__ import annotations

import numpy as np


class CartPole:
    """Classic cart-pole balancing, pure numpy (dynamics follow the classic
    control formulation; public-domain physics)."""

    def __init__(self, seed: int | None = None, max_steps: int = 500):
        self._rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.observation_size = 4
        self.num_actions = 2
        self._state = None
        self._t = 0

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32), {}

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = 10.0 if action == 1 else -10.0
        g, mc, mp, length = 9.8, 1.0, 0.1, 0.5
        total_mass = mc + mp
        polemass_length = mp * length
        tau = 0.02

        costh, sinth = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sinth) / total_mass
        theta_acc = (g * sinth - costh * temp) / (
            length * (4.0 / 3.0 - mp * costh**2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass

        x += tau * x_dot
        x_dot += tau * x_acc
        theta += tau * theta_dot
        theta_dot += tau * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._t += 1

        terminated = bool(abs(x) > 2.4 or abs(theta) > 12 * np.pi / 180)
        truncated = self._t >= self.max_steps
        return (self._state.astype(np.float32), 1.0, terminated, truncated,
                {})


class Pendulum:
    """Classic pendulum swing-up, pure numpy — the hermetic
    continuous-control test env (classic control formulation; the
    reference exercises SAC on the gym version of the same problem).

    State (theta, theta_dot); observation (cos, sin, theta_dot); action:
    torque in [-2, 2]; reward -(theta^2 + 0.1*theta_dot^2 + 0.001*a^2).
    """

    def __init__(self, seed: int | None = None, max_steps: int = 200):
        self._rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.observation_size = 3
        self.action_size = 1
        self.action_low = np.array([-2.0], np.float32)
        self.action_high = np.array([2.0], np.float32)
        self.continuous = True
        self._th = self._thdot = 0.0
        self._t = 0

    def _obs(self):
        return np.array([np.cos(self._th), np.sin(self._th),
                         self._thdot], np.float32)

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -2.0, 2.0))
        g, m, length, dt = 10.0, 1.0, 1.0, 0.05
        th = ((self._th + np.pi) % (2 * np.pi)) - np.pi   # normalize
        cost = th ** 2 + 0.1 * self._thdot ** 2 + 0.001 * u ** 2
        self._thdot += (3 * g / (2 * length) * np.sin(self._th)
                        + 3.0 / (m * length ** 2) * u) * dt
        self._thdot = float(np.clip(self._thdot, -8.0, 8.0))
        self._th += self._thdot * dt
        self._t += 1
        truncated = self._t >= self.max_steps
        return self._obs(), -float(cost), False, truncated, {}


def make_env(env_spec, seed: int | None = None):
    """env_spec: "CartPole-v1" (built-in), a gymnasium id, or a zero-arg
    callable returning a reset/step env."""
    if callable(env_spec):
        return env_spec()
    if env_spec in ("CartPole-v1", "CartPole-v0"):
        return CartPole(seed=seed,
                        max_steps=500 if env_spec.endswith("v1") else 200)
    if env_spec in ("Pendulum-v1", "Pendulum-v0"):
        return Pendulum(seed=seed)
    import gymnasium

    env = gymnasium.make(env_spec)
    if seed is not None:
        env.reset(seed=seed)
    return env


def env_spaces(env) -> tuple[int, int]:
    """(observation_size, num_actions) for a discrete-action env."""
    if hasattr(env, "observation_size"):
        return env.observation_size, env.num_actions
    obs_size = int(np.prod(env.observation_space.shape))
    return obs_size, int(env.action_space.n)


def env_action_space(env) -> dict:
    """Structured space info covering continuous-action envs
    {obs_size, action_size, low, high} (reference: gym Box spaces)."""
    if getattr(env, "continuous", False):
        return {"obs_size": env.observation_size,
                "action_size": env.action_size,
                "low": env.action_low, "high": env.action_high}
    if hasattr(env, "action_space") and \
            hasattr(env.action_space, "shape") and \
            env.action_space.shape:
        return {"obs_size": int(np.prod(env.observation_space.shape)),
                "action_size": int(np.prod(env.action_space.shape)),
                "low": np.asarray(env.action_space.low, np.float32),
                "high": np.asarray(env.action_space.high, np.float32)}
    raise ValueError("env has no continuous action space")
