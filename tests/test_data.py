"""Dataset tests (the reference's python/ray/data/tests tier): transforms,
shuffle, sort, split, batching, groupby, IO."""
import numpy as np
import pytest


@pytest.fixture
def ds_env(ray_start_regular):
    yield ray_start_regular


def test_range_map_filter(ds_env):
    from ray_tpu import data

    ds = data.range(100, parallelism=4)
    out = ds.map(lambda x: x * 2).filter(lambda x: x % 10 == 0).take_all()
    assert out == [x * 2 for x in range(100) if (x * 2) % 10 == 0]


def test_flat_map_and_count(ds_env):
    from ray_tpu import data

    ds = data.from_items([1, 2, 3], parallelism=2)
    out = ds.flat_map(lambda x: [x] * x)
    assert out.count() == 6
    assert sorted(out.take_all()) == [1, 2, 2, 3, 3, 3]


def test_map_batches_numpy(ds_env):
    from ray_tpu import data

    ds = data.from_numpy(np.arange(32.0), parallelism=4)
    out = ds.map_batches(lambda arr: arr * 10).to_numpy()
    assert (np.sort(out) == np.arange(32.0) * 10).all()


def test_map_batches_actor_pool(ds_env):
    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    ds = data.range(16, parallelism=4).map(lambda x: x + 1)
    mat = ds.materialize(compute=ActorPoolStrategy(size=2))
    assert sorted(mat.take_all()) == list(range(1, 17))


def test_random_shuffle(ds_env):
    from ray_tpu import data

    ds = data.range(64, parallelism=4)
    shuffled = ds.random_shuffle(seed=7).take_all()
    assert sorted(shuffled) == list(range(64))
    assert shuffled != list(range(64)), "shuffle left data ordered"


def test_sort(ds_env):
    from ray_tpu import data

    rng = np.random.default_rng(0)
    values = [int(v) for v in rng.integers(0, 1000, size=80)]
    ds = data.from_items(values, parallelism=4)
    out = ds.sort()
    assert out.take_all() == sorted(values)
    out_desc = data.from_items(values, parallelism=4).sort(descending=True)
    assert out_desc.take_all() == sorted(values, reverse=True)


def test_sort_by_key(ds_env):
    from ray_tpu import data

    rows = [{"k": i % 5, "v": i} for i in range(20)]
    out = data.from_items(rows, parallelism=3).sort(key="k").take_all()
    assert [r["k"] for r in out] == sorted(r["k"] for r in rows)


def test_split_for_workers(ds_env):
    from ray_tpu import data

    ds = data.range(40, parallelism=4)
    shards = ds.split(2)
    assert len(shards) == 2
    all_rows = sorted(shards[0].take_all() + shards[1].take_all())
    assert all_rows == list(range(40))


def test_iter_batches(ds_env):
    from ray_tpu import data

    ds = data.from_numpy(np.arange(100.0), parallelism=4)
    batches = list(ds.iter_batches(batch_size=32))
    sizes = [len(b) for b in batches]
    assert sum(sizes) == 100
    assert sizes[:-1] == [32, 32, 32]


def test_iter_batches_device_put(ds_env):
    import jax

    from ray_tpu import data

    ds = data.range(32, parallelism=2)
    batches = list(ds.iter_batches(batch_size=16, device_put=True))
    assert all(isinstance(b, jax.Array) for b in batches)
    total = sum(float(b.sum()) for b in batches)
    assert total == sum(range(32))


def test_groupby(ds_env):
    from ray_tpu import data

    rows = [{"team": t, "score": s}
            for t, s in [("a", 1), ("b", 2), ("a", 3), ("b", 4), ("a", 5)]]
    counts = {r["key"]: r["count"]
              for r in data.from_items(rows).groupby("team").count()
              .take_all()}
    assert counts == {"a": 3, "b": 2}
    sums = {r["key"]: r["value"]
            for r in data.from_items(rows).groupby("team")
            .aggregate(lambda g: sum(r["score"] for r in g)).take_all()}
    assert sums == {"a": 9, "b": 6}


def test_union_zip_repartition(ds_env):
    from ray_tpu import data

    a = data.range(5, parallelism=2)
    b = data.from_items([10, 11], parallelism=1)
    assert sorted(a.union(b).take_all()) == [0, 1, 2, 3, 4, 10, 11]
    zipped = data.from_items([1, 2]).zip(data.from_items(["x", "y"]))
    assert zipped.take_all() == [(1, "x"), (2, "y")]
    rp = data.range(10, parallelism=5).repartition(2)
    assert rp.num_blocks == 2
    assert sorted(rp.take_all()) == list(range(10))


def test_pandas_io_roundtrip(ds_env, tmp_path):
    import pandas as pd

    from ray_tpu import data

    df = pd.DataFrame({"x": range(10), "y": [f"s{i}" for i in range(10)]})
    csv = tmp_path / "t.csv"
    df.to_csv(csv, index=False)
    ds = data.read_csv(str(csv))
    assert ds.count() == 10
    back = ds.to_pandas()
    assert list(back["x"]) == list(range(10))

    pq = tmp_path / "t.parquet"
    df.to_parquet(pq)
    assert data.read_parquet(str(pq)).count() == 10


def test_json_text_io(ds_env, tmp_path):
    from ray_tpu import data

    j = tmp_path / "t.jsonl"
    j.write_text('{"a": 1}\n{"a": 2}\n')
    assert [r["a"] for r in data.read_json(str(j)).take_all()] == [1, 2]
    t = tmp_path / "t.txt"
    t.write_text("hello\nworld\n")
    assert data.read_text(str(t)).take_all() == ["hello", "world"]


def test_dataset_in_trainer(ds_env):
    ray = ds_env
    from ray_tpu import data
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import JaxTrainer

    def loop(config):
        from ray_tpu.air import session

        shard = session.get_dataset_shard("train")
        total = sum(shard.take_all())
        session.report({"total": total})

    trainer = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": data.range(20, parallelism=4)})
    result = trainer.fit()
    assert result.error is None


def test_arrow_interop(ray_start_regular):
    import pyarrow as pa

    from ray_tpu import data

    table = pa.table({"a": [1, 2, 3, 4], "b": [10.0, 20.0, 30.0, 40.0]})
    ds = data.from_arrow(table)
    assert ds.count() == 4
    back = ds.map(lambda r: {"a": r["a"] * 2, "b": r["b"]}).to_arrow()
    assert back.column("a").to_pylist() == [2, 4, 6, 8]


def test_iter_torch_batches(ray_start_regular):
    import torch

    from ray_tpu import data

    ds = data.from_items([{"x": float(i), "y": i % 2} for i in range(10)])
    batches = list(ds.iter_torch_batches(batch_size=4))
    assert all(isinstance(b["x"], torch.Tensor) for b in batches)
    assert sum(len(b["x"]) for b in batches) == 10
    pairs = list(ds.to_torch(label_column="y", batch_size=5))
    feats, label = pairs[0]
    assert set(feats) == {"x"} and label.shape == (5,)


def test_write_read_roundtrip(ray_start_regular, tmp_path):
    from ray_tpu import data

    ds = data.from_items([{"a": i, "b": float(i) / 2} for i in range(20)],
                         parallelism=3)
    files = ds.write_parquet(str(tmp_path / "pq"))
    assert len(files) == 3
    back = data.read_parquet(files)
    assert back.count() == 20

    csv_files = ds.write_csv(str(tmp_path / "csv"))
    assert data.read_csv(csv_files).count() == 20

    json_files = ds.write_json(str(tmp_path / "js"))
    assert data.read_json(json_files).count() == 20


def test_actor_pool_autoscaling_bounds(ray_start_regular):
    from ray_tpu import data
    from ray_tpu.data.dataset import ActorPoolStrategy

    ds = data.from_items(list(range(40)), parallelism=8)
    out = ds.map_batches(
        lambda b: b, compute=ActorPoolStrategy(min_size=1, max_size=3),
    ).materialize()
    assert sorted(x for blk in out.blocks() for x in blk) == list(range(40))


def test_dataset_aggregations(ray_start_regular):
    import math

    from ray_tpu import data

    ds = data.from_items([{"v": float(i)} for i in range(10)],
                         parallelism=3)
    assert ds.sum("v") == 45.0
    assert ds.mean("v") == 4.5
    assert ds.min("v") == 0.0
    assert ds.max("v") == 9.0
    vals = list(range(10))
    expected_std = math.sqrt(
        sum((x - 4.5) ** 2 for x in vals) / 9)
    assert abs(ds.std("v") - expected_std) < 1e-9

    plain = data.from_numpy(np.arange(8.0), parallelism=2)
    assert plain.sum() == 28.0

    grouped = data.from_items(
        [{"g": i % 2, "v": float(i)} for i in range(8)],
        parallelism=2).groupby("g")
    rows = sorted(grouped.mean("v").take_all(), key=lambda r: r["key"])
    assert rows[0] == {"key": 0, "mean(v)": 3.0}
    assert rows[1] == {"key": 1, "mean(v)": 4.0}


def test_aggregation_numerics_and_errors(ray_start_regular):
    from ray_tpu import data

    # large mean offset: the naive sum-of-squares formula returns 0 here
    ds = data.from_items([{"v": 1e8}, {"v": 1e8 + 1}, {"v": 1e8 + 2}],
                         parallelism=2)
    assert abs(ds.std("v") - 1.0) < 1e-6

    with pytest.raises(Exception, match="named columns"):
        data.from_items([{"v": 1.0}]).sum()       # on= required
    with pytest.raises(Exception, match="plain values"):
        data.from_numpy(np.arange(4.0)).sum("nope")
    with pytest.raises(Exception, match="plain values"):
        data.from_items([1.0, 2.0]).groupby(
            lambda r: 0).sum("price").take_all()
