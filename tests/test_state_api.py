"""State API: filters, summaries, per-entity detail.

Reference tier: python/ray/experimental/state/ tests — list_* with
(key, op, value) filters, `ray summary`-style rollups, and get_* detail
lookups.
"""
import time

import pytest


def test_filters_and_limit():
    from ray_tpu.experimental.state.api import _apply_filters

    rows = [{"State": "ALIVE", "n": 1}, {"State": "DEAD", "n": 2},
            {"State": "ALIVE", "n": 3}]
    assert len(_apply_filters(rows, [("State", "=", "ALIVE")], None)) == 2
    assert len(_apply_filters(rows, [("State", "!=", "ALIVE")], None)) == 1
    assert len(_apply_filters(rows, [("n", ">", 1)], None)) == 2
    assert len(_apply_filters(rows, [("n", ">=", 1)], 2)) == 2
    assert _apply_filters(rows, [("State", "contains", "LIV")], None)[0][
        "n"] == 1
    with pytest.raises(ValueError, match="unknown filter op"):
        _apply_filters(rows, [("State", "~", "x")], None)
    with pytest.raises(ValueError, match="key, op, value"):
        _apply_filters(rows, ["State"], None)


def test_list_actors_filtered_and_get(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.experimental.state import api as state

    @ray.remote
    class Alpha:
        def ping(self):
            return 1

    @ray.remote
    class Beta:
        def ping(self):
            return 1

    a = Alpha.remote()
    b = Beta.remote()
    ray.get([a.ping.remote(), b.ping.remote()])

    alive = state.list_actors(filters=[("State", "=", "ALIVE")])
    assert len(alive) == 2
    alphas = state.list_actors(filters=[("ClassName", "=", "Alpha")])
    assert len(alphas) == 1
    detail = state.get_actor(alphas[0]["ActorID"])
    assert detail is not None and detail["ClassName"] == "Alpha"
    assert state.get_actor("f" * 32) is None
    assert len(state.list_actors(limit=1)) == 1

    summary = state.summarize_actors()
    assert summary["Alpha"]["ALIVE"] == 1
    assert summary["Beta"]["ALIVE"] == 1


def test_task_detail_and_summary(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.experimental.state import api as state

    @ray.remote
    def camp(n):
        time.sleep(n)
        return 1

    ref = camp.remote(8)
    # wait for it to actually start
    deadline = time.time() + 30
    rows = []
    while time.time() < deadline:
        rows = state.list_tasks(detail=True)
        if any(r.get("task_desc") for r in rows):
            break
        time.sleep(0.2)
    running = [r for r in rows if r.get("task_desc")]
    assert running, f"no running task detail: {rows}"
    assert "camp" in running[0]["task_desc"]
    assert running[0]["task_id"]

    # per-task lookup round-trips through the id
    got = state.get_task(running[0]["task_id"])
    assert got is not None and got["task_desc"] == running[0]["task_desc"]

    summary = state.summarize_tasks()
    assert any("camp" in k for k in summary["running"]), summary
    ray.cancel(ref, force=True)


def test_summarize_objects(ray_start_regular):
    ray = ray_start_regular
    import numpy as np

    from ray_tpu.experimental.state import api as state

    refs = [ray.put(np.zeros(300_000, np.uint8)) for _ in range(3)]
    summary = state.summarize_objects()
    assert summary["total_objects"] >= 3
    assert summary["total_bytes"] >= 3 * 300_000
    assert summary["per_node"]
    oid = state.list_objects()[0]["ObjectID"]
    assert state.get_objects(oid)
    del refs
