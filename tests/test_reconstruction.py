"""Object reconstruction (lineage re-execution) + chunked transfer tests.

Reference tier: python/ray/tests/test_reconstruction*.py — kill the node
holding the only copy of a task result; a retryable task's output is
transparently recomputed; a non-retryable one raises ObjectLostError
(that case is pinned in test_cluster.py).
"""
import os

import numpy as np
import pytest


def test_lost_object_reconstructed_on_node_death(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)          # head: driver-only
    node2 = cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.connect()
    import ray_tpu

    marker = ray_tpu.put(0)   # noqa: F841  — keep driver store warm

    @ray_tpu.remote(num_cpus=0, resources={"side": 0.5}, max_retries=3)
    def produce(tag):
        import os as _os
        return {"data": np.full(300_000, 7.0), "pid": _os.getpid(), "tag": tag}

    ref = produce.remote("x")
    done, _ = ray_tpu.wait([ref], timeout=60, fetch_local=False)
    assert done, "produce task did not finish"
    cluster.remove_node(node2)
    # replacement capacity for the re-execution
    cluster.add_node(num_cpus=2, resources={"side": 1})

    out = ray_tpu.get(ref, timeout=60)
    assert out["tag"] == "x"
    np.testing.assert_array_equal(out["data"], np.full(300_000, 7.0))


def test_reconstruction_rebuilds_dependency_chain(ray_start_cluster):
    """A downstream task argument that was lost gets recomputed when the
    consumer runs (owner-side recovery serving borrowers)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    node2 = cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.connect()
    import ray_tpu

    @ray_tpu.remote(num_cpus=0, resources={"side": 0.5}, max_retries=2)
    def produce():
        return np.arange(200_000, dtype=np.float64)

    @ray_tpu.remote(num_cpus=0, resources={"side": 0.5}, max_retries=2)
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    done, _ = ray_tpu.wait([ref], timeout=60, fetch_local=False)
    assert done
    cluster.remove_node(node2)
    cluster.add_node(num_cpus=2, resources={"side": 1})

    total = ray_tpu.get(consume.remote(ref), timeout=60)
    assert total == float(np.arange(200_000, dtype=np.float64).sum())


def test_no_reconstruction_without_retries(ray_start_cluster):
    """max_retries=0 → loss is permanent (reference semantics: recovery
    consumes the retry budget)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    node2 = cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.connect()
    import ray_tpu
    from ray_tpu.exceptions import ObjectLostError

    @ray_tpu.remote(num_cpus=0, resources={"side": 0.5}, max_retries=0)
    def produce():
        return np.zeros(300_000)

    ref = produce.remote()
    done, _ = ray_tpu.wait([ref], timeout=60, fetch_local=False)
    assert done
    cluster.remove_node(node2)
    cluster.add_node(num_cpus=2, resources={"side": 1})
    with pytest.raises(ObjectLostError):
        ray_tpu.get(ref, timeout=30)


def test_chunked_transfer_large_object(ray_start_cluster):
    """A multi-chunk object crosses nodes intact (chunk size forced small
    via config override)."""
    os.environ["RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES"] = str(256 * 1024)
    try:
        cluster = ray_start_cluster
        cluster.add_node(num_cpus=1)
        cluster.add_node(num_cpus=2, resources={"side": 1})
        cluster.connect()
        import ray_tpu

        rng = np.random.default_rng(0)
        payload = rng.standard_normal(1_200_000)  # ~9.6 MB → ~38 chunks

        @ray_tpu.remote(num_cpus=0, resources={"side": 0.5})
        def produce():
            return payload

        out = ray_tpu.get(produce.remote(), timeout=60)
        np.testing.assert_array_equal(out, payload)
    finally:
        os.environ.pop("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", None)


def test_native_data_server_transfer(ray_start_cluster, monkeypatch):
    """Cross-node pulls ride the C++ data server (src/store/data_server.cc):
    with the Python-RPC fallback disabled, the fetch still succeeds."""
    os.environ["RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES"] = str(128 * 1024)
    try:
        cluster = ray_start_cluster
        cluster.add_node(num_cpus=1)
        cluster.add_node(num_cpus=2, resources={"side": 1})
        cluster.connect()
        import ray_tpu
        from ray_tpu._private.worker_runtime import CoreWorker, current_worker

        # the driver must not be able to fall back to the RPC plane
        monkeypatch.setattr(
            CoreWorker, "_pull_rpc",
            lambda self, *a, **k: (_ for _ in ()).throw(
                AssertionError("RPC fallback used — native path skipped")))

        # sanity: nodes advertise the native port
        assert all(n.get("object_data_port") for n in ray_tpu.nodes()
                   if n["Alive"])

        rng = np.random.default_rng(1)
        payload = rng.standard_normal(400_000)   # ~3.2 MB → ~25 chunks

        @ray_tpu.remote(num_cpus=0, resources={"side": 0.5})
        def produce():
            return payload

        out = ray_tpu.get(produce.remote(), timeout=60)
        np.testing.assert_array_equal(out, payload)
    finally:
        os.environ.pop("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", None)
