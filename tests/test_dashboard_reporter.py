"""Dashboard reporter-agent + Grafana factory tests (reference:
dashboard/modules/reporter/reporter_agent.py,
dashboard/modules/metrics/grafana_dashboard_factory.py)."""
import json
import os
import time
import urllib.request

import pytest


def test_collect_stats_shape():
    from ray_tpu.dashboard.reporter import collect_stats, cpu_percent

    cpu_percent()            # prime the interval
    time.sleep(0.2)
    s = collect_stats([os.getpid()])
    assert s["cpus"] >= 1
    assert 0 < s["memory"]["used_bytes"] <= s["memory"]["total_bytes"]
    assert s["disk"]["total_bytes"] > 0
    assert s["workers"] and s["workers"][0]["rss_bytes"] > 0
    assert s["workers"][0]["cpu_seconds"] is not None
    # dead pid rows are dropped, not fabricated
    assert collect_stats([99999999])["workers"] == []


def test_grafana_dashboard_importable_json(tmp_path):
    from ray_tpu.dashboard.grafana import (
        generate_default_dashboard,
        save_default_dashboard,
    )

    d = generate_default_dashboard(datasource="prom-ds")
    assert d["uid"] and len(d["panels"]) >= 8
    for p in d["panels"]:
        assert p["targets"][0]["expr"]
        assert p["datasource"] == "prom-ds"
    path = save_default_dashboard(str(tmp_path / "dash.json"))
    reloaded = json.load(open(path))
    assert reloaded["title"] == "ray_tpu"


def test_reporter_route_aggregates_nodes(ray_start_regular):
    """/api/reporter returns one physical-stats row per alive node,
    including per-worker RSS (the head + per-node agent view)."""
    import ray_tpu
    from ray_tpu._private.worker_runtime import current_worker
    from ray_tpu.dashboard.server import DashboardServer

    # a worker must exist so the per-worker table is non-trivial
    @ray_tpu.remote
    def touch():
        return os.getpid()

    wpid = ray_tpu.get(touch.remote(), timeout=60)
    gcs = current_worker().gcs.addr
    dash = DashboardServer(f"{gcs[0]}:{gcs[1]}", port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{dash.port}/api/reporter",
                timeout=30) as resp:
            rows = json.loads(resp.read())
        assert len(rows) == 1
        row = rows[0]
        assert row["memory"]["total_bytes"] > 0
        pids = [w["pid"] for w in row["workers"]]
        assert wpid in pids, (wpid, pids)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{dash.port}/api/grafana_dashboard",
                timeout=30) as resp:
            dash_json = json.loads(resp.read())
        assert dash_json["uid"] == "ray-tpu-default"
    finally:
        dash.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
