"""Step anatomy + cluster flight recorder (PR 11).

Late-alphabet on purpose (tier-1 wall-clock budget; the E2E gang tests
here cost seconds each). Structure:

- pure units: step lifecycle, interval clipping / hidden-vs-exposed
  math, fusion by step_id (clock-skew + pid-collision + out-of-order
  tolerance), the rolling-baseline regression detector, ring-drop
  counters, the serve-batch trace link, the telemetry kill switch;
- overhead guard: step-anatomy instrumentation on the host-allreduce
  hot path and on a real jitted train step stays <5% (PR 3 pattern:
  absolute instrumentation cost vs a lower-bound op cost);
- cluster acceptance: a 2-worker train run over the double-buffered
  data feed yields a summarize_steps() report with data work hidden
  under compute and a seeded slow rank named on the critical path; a
  seeded kill_actor gang failure auto-produces a black-box dump with
  the GANG_FAILED event and final collective spans from >= 2 distinct
  processes merged into one loadable chrome timeline.
"""
import collections
import json
import os
import time

import numpy as np
import pytest

from ray_tpu._private import telemetry as _tm
from ray_tpu.parallel import step_anatomy as sa

pytestmark = pytest.mark.skipif(
    not _tm.ENABLED,
    reason="RAY_TPU_INTERNAL_TELEMETRY=0 disables the plane under test")


@pytest.fixture(autouse=True)
def _clean_anatomy():
    sa.finish()         # close any leaked context BEFORE clearing
    sa.clear()
    yield
    sa.finish()
    sa.clear()


# ------------------------------------------------------------ step context


def test_step_lifecycle_monotonic_ids():
    sa.start(rank=3)
    assert sa.current() == (1, 3)
    sa.record_activity("collective", 0.0, 1.0, blocking=True)
    sa.advance(1)                      # report #1 ends step 1
    assert sa.current() == (2, 3)
    sa.advance(0)                      # stale iteration: still monotonic
    assert sa.current() == (3, 3)
    sa.finish()
    assert sa.current() is None
    rec = sa.local_records()
    assert [s["step_id"] for s in rec["steps"]] == [1, 2, 3]
    assert all(s["rank"] == 3 for s in rec["steps"])
    assert rec["activities"][0]["step_id"] == 1
    # no context: recording is a no-op, not a crash
    sa.record_activity("collective", 0.0, 1.0)
    assert len(sa.local_records()["activities"]) == 1


def test_advance_without_start_is_noop():
    sa.advance()                       # e.g. Tune trainable on the driver
    sa.finish()
    assert sa.local_records()["steps"] == []


def test_step_metric_observed():
    from ray_tpu.util.metrics import registry_snapshot

    sa.start(rank=0)
    sa.advance()
    sa.finish()
    fam = next(m for m in registry_snapshot()
               if m["name"] == "ray_tpu_step_seconds")
    assert any(sum(row["counts"]) >= 2 for row in fam["counts"])


# --------------------------------------------------------------- breakdown


def _step(sid, rank, start, end, **kw):
    return {"step_id": sid, "rank": rank, "node": kw.get("node", "n0"),
            "pid": kw.get("pid", 1), "start": start, "end": end}


def _act(sid, rank, kind, start, end, blocking=True, **kw):
    return {"step_id": sid, "rank": rank, "kind": kind, "start": start,
            "end": end, "blocking": blocking,
            "node": kw.get("node", "n0"), "pid": kw.get("pid", 1)}


def test_hidden_vs_exposed_interval_math():
    """Step [0, 1]: blocking comm [0.1, 0.3] is exposed; background
    produce [0.2, 0.6] hides only where it is NOT covered by exposed
    time ([0.3, 0.6] = 0.3); compute is wall minus exposed."""
    step = _step(1, 0, 0.0, 1.0)
    acts = [_act(1, 0, "collective", 0.1, 0.3),
            _act(1, 0, "data_produce", 0.2, 0.6, blocking=False)]
    br = sa.anatomize_rank_step(step, acts)
    assert br["comm_exposed_s"] == pytest.approx(0.2)
    assert br["data_hidden_s"] == pytest.approx(0.3)
    assert br["compute_s"] == pytest.approx(0.8)
    assert br["overlap_fraction"] == pytest.approx(0.3 / 0.5)


def test_overlapping_blocking_intervals_not_double_counted():
    step = _step(1, 0, 0.0, 1.0)
    acts = [_act(1, 0, "collective", 0.0, 0.4),
            _act(1, 0, "collective", 0.3, 0.5),
            _act(1, 0, "data_wait", 0.45, 0.7)]
    br = sa.anatomize_rank_step(step, acts)
    # per-category totals may overlap each other, but compute uses the
    # UNION of exposed time (0.0-0.7), never going negative
    assert br["comm_exposed_s"] == pytest.approx(0.5)
    assert br["data_wait_s"] == pytest.approx(0.25)
    assert br["compute_s"] == pytest.approx(0.3)


def test_activity_clipped_to_step_window():
    step = _step(2, 0, 10.0, 11.0)
    acts = [_act(2, 0, "collective", 9.5, 10.25),    # straddles start
            _act(2, 0, "collective", 11.5, 12.0)]    # entirely outside
    br = sa.anatomize_rank_step(step, acts)
    assert br["comm_exposed_s"] == pytest.approx(0.25)


def test_fusion_joins_by_step_id_never_wall_clock():
    """Two ranks whose monotonic clocks differ by ~1e6 seconds (two
    hosts, arbitrary boot times / NTP skew): steps still pair by
    step_id, and per-rank phases stay correct because each rank's math
    uses only its own clock."""
    r0 = {"node": "hostA", "pid": 7, "steps_dropped": 0,
          "activities_dropped": 0,
          "steps": [_step(1, 0, 100.0, 100.5, node="hostA", pid=7),
                    _step(2, 0, 100.5, 101.0, node="hostA", pid=7)],
          "activities": [_act(1, 0, "collective", 100.1, 100.2,
                              node="hostA", pid=7)]}
    base = 1_000_000.0
    r1 = {"node": "hostB", "pid": 7, "steps_dropped": 0,
          "activities_dropped": 0,
          "steps": [_step(1, 1, base, base + 0.8, node="hostB", pid=7),
                    _step(2, 1, base + 0.8, base + 1.6, node="hostB",
                          pid=7)],
          "activities": [_act(1, 1, "data_wait", base + 0.1, base + 0.3,
                              node="hostB", pid=7)]}
    fused = sa.fuse([r0, r1])
    assert [s["step_id"] for s in fused["steps"]] == [1, 2]
    s1 = fused["steps"][0]
    assert set(s1["ranks"]) == {0, 1} and s1["complete"]
    assert s1["ranks"][0]["comm_exposed_s"] == pytest.approx(0.1)
    assert s1["ranks"][1]["data_wait_s"] == pytest.approx(0.2)
    # rank 1 is slower by SELF time -> named on the critical path
    assert s1["critical_path"]["rank"] == 1
    assert not fused["incomplete"]


def test_fusion_out_of_order_and_duplicate_exports():
    """Out-of-order record arrival and a duplicated per-process export
    (two collection paths reaching the same process) change nothing."""
    import random

    steps = [_step(i, 0, float(i), i + 1.0) for i in range(1, 6)]
    acts = [_act(i, 0, "collective", i + 0.1, i + 0.4)
            for i in range(1, 6)]
    export = {"node": "n0", "pid": 1, "steps": steps,
              "activities": acts, "steps_dropped": 0,
              "activities_dropped": 0}
    shuffled = dict(export)
    shuffled["steps"] = list(steps)
    shuffled["activities"] = list(acts)
    random.Random(7).shuffle(shuffled["steps"])
    random.Random(8).shuffle(shuffled["activities"])
    a = sa.fuse([export, dict(export)])     # duplicate (node, pid)
    b = sa.fuse([shuffled])
    assert [s["step_id"] for s in a["steps"]] == list(range(1, 6))
    for x, y in zip(a["steps"], b["steps"]):
        assert x["ranks"][0]["comm_exposed_s"] == \
            pytest.approx(y["ranks"][0]["comm_exposed_s"])


def test_fusion_critical_path_names_straggler_despite_equal_walls():
    """Bulk-synchronous gang: the allreduce equalizes wall clocks (the
    fast rank absorbs the straggler's lateness as comm wait), so the
    critical path must rank by SELF time, not wall."""
    exports = []
    for rank, comm in ((0, 0.4), (1, 0.01)):   # rank 1 barely waits
        exports.append({
            "node": f"h{rank}", "pid": 1, "steps_dropped": 0,
            "activities_dropped": 0,
            "steps": [_step(1, rank, 0.0, 1.0, node=f"h{rank}")],
            "activities": [_act(1, rank, "collective", 1.0 - comm, 1.0,
                                node=f"h{rank}")]})
    fused = sa.fuse(exports)
    crit = fused["steps"][0]["critical_path"]
    assert crit["rank"] == 1 and crit["phase"] == "compute_s"


def test_fusion_never_mixes_clock_domains_across_processes():
    """Gang restart: the SAME (step_id, rank) re-reported from a NEW
    process must not have the old process's activities (a foreign
    monotonic clock base) clipped into its step window — activities
    follow their own process's step record exclusively."""
    old = {"node": "n0", "pid": 10, "steps_dropped": 0,
           "activities_dropped": 0,
           "steps": [_step(1, 0, 50.0, 51.0, pid=10)],
           "activities": [_act(1, 0, "collective", 50.2, 50.9, pid=10)]}
    new = {"node": "n0", "pid": 20, "steps_dropped": 0,
           "activities_dropped": 0,
           # restarted process: fresh clock base, same (step_id, rank)
           "steps": [_step(1, 0, 7000.0, 7001.0, pid=20)],
           "activities": [_act(1, 0, "data_wait", 7000.1, 7000.3,
                               pid=20)]}
    fused = sa.fuse([old, new])
    br = fused["steps"][0]["ranks"][0]
    # only the winning (last) process's own activities count
    assert br["data_wait_s"] == pytest.approx(0.2)
    assert br["comm_exposed_s"] == 0.0, (
        "old incarnation's comm leaked into the new step window")


def test_fusion_flags_incomplete_on_drops():
    export = {"node": "n0", "pid": 1, "steps": [_step(1, 0, 0.0, 1.0)],
              "activities": [], "steps_dropped": 3,
              "activities_dropped": 0}
    fused = sa.fuse([export])
    assert fused["incomplete"] and fused["dropped"]["steps"] == 3


def test_fusion_partial_step_not_complete():
    exports = [
        {"node": "a", "pid": 1, "steps_dropped": 0,
         "activities_dropped": 0, "activities": [],
         "steps": [_step(1, 0, 0.0, 1.0, node="a"),
                   _step(2, 0, 1.0, 2.0, node="a")]},
        {"node": "b", "pid": 1, "steps_dropped": 0,
         "activities_dropped": 0, "activities": [],
         "steps": [_step(1, 1, 0.0, 1.1, node="b")]},  # died before 2
    ]
    fused = sa.fuse(exports)
    by_id = {s["step_id"]: s for s in fused["steps"]}
    assert by_id[1]["complete"] and not by_id[2]["complete"]


# ------------------------------------------------------ regression detector


def test_regression_detector_fires_on_p50_drift(monkeypatch):
    from ray_tpu._private import events

    monkeypatch.setenv("RAY_TPU_STEP_REGRESSION_WINDOW", "3")
    monkeypatch.setenv("RAY_TPU_STEP_REGRESSION_MULTIPLE", "2.0")
    events.clear()
    sa._durations.clear()
    for d in [0.01, 0.011, 0.009]:
        sa._check_regression(d)
    assert not [e for e in events.snapshot()
                if e["kind"] == "STEP_REGRESSION"]
    for i, d in enumerate([0.1, 0.11, 0.09]):   # p50 10x the baseline
        sa._check_regression(d, step_id=100 + i, rank=2)
    evs = [e for e in events.snapshot() if e["kind"] == "STEP_REGRESSION"]
    assert len(evs) == 1
    assert evs[0]["p50_recent_s"] == pytest.approx(0.1)
    assert evs[0]["p50_baseline_s"] == pytest.approx(0.01)
    # stamped with the step that COMPLETED the window, and its rank
    assert evs[0]["step_id"] == 102 and evs[0]["rank"] == 2
    assert not sa._durations              # reset: no per-step re-firing
    from ray_tpu.util.metrics import registry_snapshot

    fam = next(m for m in registry_snapshot()
               if m["name"] == "ray_tpu_step_regressions_total")
    assert sum(v["value"] for v in fam["values"]) >= 1


def test_regression_detector_quiet_on_proportionate_noise(monkeypatch):
    from ray_tpu._private import events

    monkeypatch.setenv("RAY_TPU_STEP_REGRESSION_WINDOW", "4")
    events.clear()
    sa._durations.clear()
    for d in [0.01, 0.012, 0.011, 0.013] * 4:
        sa._check_regression(d)
    assert not [e for e in events.snapshot()
                if e["kind"] == "STEP_REGRESSION"]


# ----------------------------------------------------------- ring drops


def test_trace_ring_drop_counted_and_surfaced(monkeypatch):
    from ray_tpu.util import tracing
    from ray_tpu.util.metrics import registry_snapshot

    monkeypatch.setattr(tracing, "_spans",
                        collections.deque(maxlen=4))
    monkeypatch.setattr(tracing, "_dropped", 0)
    tracing.enable()
    try:
        for i in range(7):
            tracing.record_completed_span(f"s{i}", "INTERNAL", i, i + 1)
    finally:
        tracing.disable()
    st = tracing.stats()
    assert st["dropped"] == 3 and st["buffered"] == 4
    marked = tracing.local_spans(with_drop_marker=True)
    marker = [s for s in marked if "__drops__" in s]
    assert len(marker) == 1 and marker[0]["__drops__"] == 3
    assert len([s for s in marked if "__drops__" not in s]) == 4
    fam = next(m for m in registry_snapshot()
               if m["name"] == "ray_tpu_trace_dropped_total")
    assert sum(v["value"] for v in fam["values"]) >= 3


def test_timeline_ring_drop_marker_in_merge(monkeypatch):
    from ray_tpu._private import profiling

    monkeypatch.setattr(profiling, "_events",
                        collections.deque(maxlen=3))
    monkeypatch.setattr(profiling, "_dropped", 0)
    for i in range(5):
        profiling.record_completed_span("t", f"e{i}", float(i), 0.5)
    assert profiling.stats()["dropped"] == 2
    merged = profiling.to_chrome_trace(
        profiling.snapshot(with_drop_marker=True))
    # the marker is a chrome metadata row, sorted to the head
    assert merged[0]["ph"] == "M"
    assert merged[0]["name"] == "ray_tpu_timeline_dropped"
    assert merged[0]["args"]["dropped"] == 2
    assert all(e["ph"] == "X" for e in merged[1:])


def test_pid_collision_remapped_in_merged_timeline():
    """Same pid on two hosts must become two distinct chrome processes
    (chrome://tracing keys by pid alone), with the real identity in
    process_name metadata."""
    from ray_tpu._private import flight_recorder as fr

    snaps = [
        {"node": "hostA", "pid": 4242, "timeline": [
            {"ph": "X", "name": "opA", "pid": 4242, "ts": 10, "dur": 5}]},
        {"node": "hostB", "pid": 4242, "timeline": [
            {"ph": "X", "name": "opB", "pid": 4242, "ts": 3, "dur": 5}]},
    ]
    merged = fr.merged_timeline(snaps)
    names = {e["args"]["name"] for e in merged if e["ph"] == "M"}
    assert names == {"hostA/pid4242", "hostB/pid4242"}
    op_pids = {e["name"]: e["pid"] for e in merged if e["ph"] == "X"}
    assert op_pids["opA"] != op_pids["opB"]
    # sorted by ts: opB (ts 3) precedes opA (ts 10)
    xs = [e["name"] for e in merged if e["ph"] == "X"]
    assert xs == ["opB", "opA"]


def test_merged_timeline_carries_drop_marker():
    from ray_tpu._private import flight_recorder as fr

    snaps = [{"node": "h", "pid": 1, "timeline_dropped": 9,
              "timeline": [{"ph": "X", "name": "op", "pid": 1,
                            "ts": 5, "dur": 1}]}]
    merged = fr.merged_timeline(snaps)
    mark = [e for e in merged
            if e["ph"] == "M" and e["name"] == "ray_tpu_timeline_dropped"]
    assert len(mark) == 1 and mark[0]["args"]["dropped"] == 9
    # remapped to the same chrome process as the spans it qualifies
    op = next(e for e in merged if e.get("name") == "op")
    assert mark[0]["pid"] == op["pid"]


def test_dump_dirs_unique_within_one_second(tmp_path, monkeypatch):
    """Two dumps in the same wall-clock second (retrying gang + manual)
    must land in distinct directories, and the newest is discoverable
    from a FRESH process via the on-disk scan (`ray-tpu blackbox
    last`)."""
    from ray_tpu._private import flight_recorder as fr

    monkeypatch.setenv("RAY_TPU_FLIGHT_RECORDER_DIR", str(tmp_path))
    p1 = fr.dump("reason_a")
    p2 = fr.dump("reason_a")
    assert p1 and p2 and p1 != p2
    assert os.path.isdir(p1) and os.path.isdir(p2)
    latest = fr.find_latest_dump()
    assert latest in (p1, p2)
    assert fr.find_latest_dump(str(tmp_path / "nonexistent")) is None


# --------------------------------------------------------- plane stamping


def test_collective_op_stamped_with_step():
    from ray_tpu._private import profiling
    from ray_tpu.util import collective as col
    from ray_tpu.util.collective.collective import _GroupState, _manager

    class _Noop:
        def allreduce(self, arr, op, seq):
            return arr

    state = _GroupState("zzsa_stamp", 4, 0, "host", _Noop(), None)
    _manager._groups["zzsa_stamp"] = state
    try:
        sa.start(rank=0, step_id=41)
        col.allreduce(np.zeros(8), group_name="zzsa_stamp")
        sa.finish()
        acts = sa.local_records()["activities"]
        mine = [a for a in acts if a["kind"] == "collective"
                and a.get("meta", {}).get("group") == "zzsa_stamp"]
        assert len(mine) == 1
        assert mine[0]["step_id"] == 41 and mine[0]["blocking"]
        span = next(e for e in profiling.snapshot()
                    if e["name"] == "collective::allreduce"
                    and e["args"].get("group") == "zzsa_stamp")
        assert span["args"]["step"] == 41
    finally:
        _manager._groups.pop("zzsa_stamp", None)
        from ray_tpu.util.collective.telemetry import flush_timings

        flush_timings()   # drop buffered records for the fake group


def test_data_wait_stamped_with_step():
    from ray_tpu.data._internal.streaming.iterator import stamp_wait

    def gen():
        for i in range(3):
            time.sleep(0.002)
            yield i

    sa.start(rank=2)
    out = list(stamp_wait(gen(), "zzsa-consumer"))
    sa.finish()
    assert out == [0, 1, 2]
    waits = [a for a in sa.local_records()["activities"]
             if a["kind"] == "data_wait"
             and a.get("meta", {}).get("consumer") == "zzsa-consumer"]
    assert len(waits) == 3
    assert all(w["blocking"] and w["step_id"] == 1 for w in waits)
    assert all(w["end"] > w["start"] for w in waits)


def test_compile_stamped_as_blocking_activity():
    from ray_tpu.parallel.compile_watch import CompiledFunction

    fn = CompiledFunction(lambda x: x * 2, "zzsa_compile")
    sa.start(rank=0, step_id=5)
    fn(np.zeros(4))                    # miss: compile activity
    fn(np.ones(4))                     # hit: no activity
    sa.finish()
    comp = [a for a in sa.local_records()["activities"]
            if a["kind"] == "compile"]
    assert len(comp) == 1
    assert comp[0]["step_id"] == 5 and comp[0]["blocking"]


def test_serve_batch_links_caller_trace():
    """A traced request through @serve.batch shows its batching wait:
    a per-item span under the CALLER's trace, pointing at the shared
    batch-execution span."""
    from ray_tpu.serve import batching
    from ray_tpu.util import tracing

    @batching.batch(max_batch_size=4, batch_wait_timeout_s=0.005)
    def doubler(items):
        return [i * 2 for i in items]

    tracing.clear()
    tracing.enable()
    try:
        with tracing.span("request", "INTERNAL") as req:
            assert doubler(21) == 42
    finally:
        tracing.disable()
    spans = tracing.local_spans()
    item = [s for s in spans if s["name"] == "serve.batch doubler"]
    execs = [s for s in spans
             if s["name"] == "serve.batch_execute doubler"]
    assert len(item) == 1 and len(execs) == 1
    # the item span continues the CALLER's trace under the caller span
    assert item[0]["traceId"] == req["trace_id"]
    assert item[0]["parentSpanId"] == req["span_id"]
    assert item[0]["attributes"]["batch_span"] == execs[0]["spanId"]
    assert item[0]["attributes"]["batch_wait_s"] >= 0
    tracing.clear()


def test_serve_batch_untraced_pays_nothing():
    from ray_tpu.serve import batching
    from ray_tpu.util import tracing

    @batching.batch(max_batch_size=2, batch_wait_timeout_s=0.001)
    def ident(items):
        return list(items)

    tracing.clear()
    assert ident(5) == 5
    assert not [s for s in tracing.local_spans()
                if s["name"].startswith("serve.batch")]


# ------------------------------------------------------------- kill switch


def test_internal_telemetry_kill_switch_disables_everything(monkeypatch):
    """RAY_TPU_INTERNAL_TELEMETRY=0 must turn off step stamping, the
    anatomy rings, AND the flight recorder (snapshot + dump + trigger)."""
    from ray_tpu._private import flight_recorder as fr

    monkeypatch.setattr(_tm, "ENABLED", False)
    sa.start(rank=0)
    assert sa.current() is None           # no context was opened
    sa.record_activity("collective", 0.0, 1.0)
    sa.advance()
    sa.finish()
    assert sa.local_records()["steps"] == []
    assert sa.local_records()["activities"] == []
    assert fr.local_snapshot() == {}
    assert fr.dump("zz_killswitch") is None
    assert fr.trigger_dump("zz_killswitch", force=True) is None


# ---------------------------------------------------------- overhead guard


def test_overhead_guard_allreduce_and_train_step(monkeypatch):
    """PR 3-style guard: absolute per-call instrumentation cost (on
    minus off, medians of medians) vs a lower-bound hot-path cost.

    - allreduce: the step-anatomy stamp (tuple read + monotonic + one
      lock'd append) on top of the PR 3 telemetry must stay <5% of a
      deterministic numpy ring step;
    - train step: one advance() + typical per-step activity records
      must stay <5% of a small REAL jitted train step (loss + grad +
      adamw via make_train_step).

    Shows up in --durations by design."""
    import statistics

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.util import collective as col
    from ray_tpu.util.collective.collective import _GroupState, _manager

    class _Noop:
        def allreduce(self, arr, op, seq):
            return arr

    class _RingStep:
        def allreduce(self, arr, op, seq):
            out = arr
            for _ in range(4):
                out = out + out * 0.5
            return out

    _manager._groups["zzov_noop"] = _GroupState(
        "zzov_noop", 4, 0, "host", _Noop(), None)
    _manager._groups["zzov_ring"] = _GroupState(
        "zzov_ring", 4, 0, "host", _RingStep(), None)
    tiny = np.zeros(16)
    arr = np.zeros(200_000)

    def per_call(group, payload, n=60):
        samples = []
        for _ in range(n):
            t0 = time.perf_counter()
            col.allreduce(payload, group_name=group)
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples)

    try:
        sa.start(rank=0)                   # step ACTIVE: stamps fire
        for g, p in (("zzov_noop", tiny), ("zzov_ring", arr)):
            col.allreduce(p, group_name=g)
        rounds_on, rounds_off, op_rounds = [], [], []
        for _ in range(5):
            monkeypatch.setattr(_tm, "ENABLED", False)
            rounds_off.append(per_call("zzov_noop", tiny))
            op_rounds.append(per_call("zzov_ring", arr, n=20))
            monkeypatch.setattr(_tm, "ENABLED", True)
            rounds_on.append(per_call("zzov_noop", tiny))
        overhead = max(0.0, min(rounds_on) - min(rounds_off))
        op_cost = min(op_rounds)
        assert overhead < 0.05 * op_cost, (
            f"step-anatomy stamp adds {overhead * 1e6:.1f}µs/op — "
            f"{overhead / op_cost * 100:.1f}% of a {op_cost * 1e3:.2f}ms "
            f"host ring step (budget: 5%)")
    finally:
        sa.finish()
        _manager._groups.pop("zzov_noop", None)
        _manager._groups.pop("zzov_ring", None)
        from ray_tpu.util.collective.telemetry import flush_timings

        flush_timings()

    # ---- train-step guard: advance + per-step stamps vs a real step
    from ray_tpu.parallel.train_step import (
        default_optimizer,
        make_train_state,
        make_train_step,
    )

    def init_params(rng):
        k1, k2 = jax.random.split(rng)
        return {"w1": jax.random.normal(k1, (64, 128)) * 0.02,
                "w2": jax.random.normal(k2, (128, 8)) * 0.02}

    def loss_fn(params, batch):
        x, y = batch
        h = jnp.tanh(x @ params["w1"])
        logits = h @ params["w2"]
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, {"loss": loss}

    opt = default_optimizer(1e-3)
    state = make_train_state(init_params, jax.random.PRNGKey(0), opt)
    step_fn = make_train_step(loss_fn, opt, donate=False)
    batch = (jnp.ones((32, 64)), jnp.zeros((32,), jnp.int32))
    for _ in range(3):                      # warm the compile cache
        state, _ = step_fn(state, batch)

    def step_cost(n=30):
        nonlocal state
        samples = []
        for _ in range(n):
            t0 = time.perf_counter()
            out, metrics = step_fn(state, batch)
            jax.block_until_ready(metrics["loss"])
            state = out
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples)

    real_step = min(step_cost() for _ in range(3))

    def instr_cost(n=400):
        sa.start(rank=0)
        m = time.monotonic()
        t0 = time.perf_counter()
        for _ in range(n):
            sa.record_activity("collective", m, m + 1e-6)
            sa.record_activity("data_wait", m, m + 1e-6)
            sa.advance()
        total = time.perf_counter() - t0
        sa.finish()
        return total / n

    instr = min(instr_cost() for _ in range(3))
    assert instr < 0.05 * real_step, (
        f"per-step anatomy costs {instr * 1e6:.1f}µs — "
        f"{instr / real_step * 100:.1f}% of a {real_step * 1e3:.2f}ms "
        f"jitted train step (budget: 5%)")


# ------------------------------------------------------ cluster acceptance


def _overlap_loop(config):
    import time as _t

    import numpy as _np

    from ray_tpu.air import session
    from ray_tpu.util import collective as _col

    rank = session.get_world_rank()
    shard = session.get_dataset_shard("train")
    for batch in shard.iter_batches(batch_size=256, device_put=True):
        # rank 1 is the seeded slow rank: 3x the per-step compute
        _t.sleep(0.06 if rank == 1 else 0.02)
        _col.allreduce(_np.ones(64), "zzsa_gang")
        session.report({"rows": int(len(batch))})


def test_overlap_proof_two_worker_train(ray_start_regular):
    """Acceptance: a 2-worker train run over the double-buffered data
    feed (PR 9) yields a summarize_steps() report whose anatomy shows
    data work hidden under compute (hidden fraction > 0, wait
    consistent with ray_tpu_data_wait_seconds), and the seeded slow
    rank is named on the critical path. Collected BEFORE gang teardown
    (the records live in the worker processes)."""
    ray = ray_start_regular
    from ray_tpu import data
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.experimental.state.api import (
        metrics_summary,
        summarize_steps,
    )
    from ray_tpu.train.backend_executor import BackendExecutor, JaxConfig

    ds = data.from_numpy(np.arange(2048.0), parallelism=8)
    executor = BackendExecutor(
        JaxConfig(group_name="zzsa_gang"),
        ScalingConfig(num_workers=2,
                      resources_per_worker={"CPU": 1})).start()
    try:
        executor.set_dataset_shards("train", ds.split(2))
        executor.start_training(_overlap_loop, {})
        deadline = time.time() + 120
        while True:
            rows = executor.next_results()
            if all(r.get("done") for r in rows):
                assert not any(r.get("error") for r in rows), rows
                break
            assert time.time() < deadline, "train run wedged"
        summary = summarize_steps()
        snaps = {m["name"]: m for m in metrics_summary()}
    finally:
        executor.shutdown()

    complete = [s for s in summary["steps"]
                if s["complete"] and len(s["ranks"]) == 2]
    assert len(complete) >= 3, summary["steps"]
    # --- overlap: the double-buffer producer's work hid under compute
    hidden = sum(br["data_hidden_s"] for s in complete
                 for br in s["ranks"].values())
    assert hidden > 0, "no data work attributed as hidden under compute"
    fracs = [s["overlap_fraction"] for s in complete
             if s["overlap_fraction"] is not None]
    assert fracs and max(fracs) > 0
    # --- data wait consistency with the metric plane: anatomy counts a
    # subset of what the histogram saw (only waits inside active steps)
    anatomy_wait = sum(br["data_wait_s"] for s in summary["steps"]
                      for br in s["ranks"].values())
    fam = snaps.get("ray_tpu_data_wait_seconds", {})
    metric_wait = sum(
        v["value"] for v in fam.get("values", ())
        if str(v["tags"].get("consumer", "")).startswith("train/train/"))
    assert metric_wait > 0, "train consumers never stamped data wait"
    assert anatomy_wait <= metric_wait + 0.25, (anatomy_wait, metric_wait)
    # --- the seeded slow rank is named on the critical path
    crit_ranks = [s["critical_path"]["rank"] for s in complete]
    assert crit_ranks.count(1) > len(crit_ranks) / 2, crit_ranks
    # per-rank rollup agrees: rank 1's compute dominates rank 0's
    assert summary["ranks"][1]["compute_s"] > \
        summary["ranks"][0]["compute_s"]
    # the cluster span collection surfaces drop accounting alongside
    from ray_tpu.util import tracing

    spans = tracing.get_spans()
    assert isinstance(spans.dropped, dict)


def _blackbox_loop(config):
    import numpy as _np

    from ray_tpu.air import session
    from ray_tpu.util import collective as _col

    for step in range(3):
        _col.allreduce(_np.full(8, float(step + 1)), "zzsa_bb")
        session.report({"step": step})


@pytest.mark.chaos
@pytest.mark.fault_injection
def test_blackbox_dump_on_seeded_gang_kill(tmp_path, monkeypatch):
    """Acceptance: a seeded kill_actor gang failure auto-produces a
    black-box dump containing the GANG_FAILED event and the final
    collective spans of >= 2 distinct surviving processes, merged into
    one loadable chrome-timeline file."""
    import ray_tpu
    from ray_tpu._private import flight_recorder as fr
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.backend_executor import JaxConfig

    monkeypatch.setenv("RAY_TPU_FLIGHT_RECORDER_DIR", str(tmp_path))
    monkeypatch.setenv("RAY_TPU_FAULT_SEED", "7")
    monkeypatch.setenv("RAY_TPU_FAULT_SCHEDULE",
                       "kill_actor:rank1.next_result:#2")
    monkeypatch.setattr(fr, "_last_auto_dump_ts", 0.0)
    monkeypatch.setattr(fr, "_last_dump_path", None)
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        trainer = JaxTrainer(
            _blackbox_loop,
            backend_config=JaxConfig(group_name="zzsa_bb"),
            scaling_config=ScalingConfig(num_workers=3,
                                         resources_per_worker={"CPU": 1}),
            run_config=RunConfig(
                failure_config=FailureConfig(max_failures=1)))
        try:
            trainer.fit()        # the retry gets killed again: may raise
        except Exception:
            pass
        dumps = sorted(d for d in os.listdir(tmp_path)
                       if d.startswith("blackbox_"))
        assert dumps, "gang failure produced no flight-recorder dump"
        # find the (forced) GANG_FAILED dump and hold it to the contract
        best = None
        for d in reversed(dumps):
            ddir = tmp_path / d
            files = [f for f in os.listdir(ddir) if f.endswith(".jsonl")]
            blobs = {f: (ddir / f).read_text() for f in files}
            if any('"GANG_FAILED"' in b for b in blobs.values()):
                best = (ddir, blobs)
                break
        assert best is not None, f"no dump contains GANG_FAILED: {dumps}"
        ddir, blobs = best
        assert len(blobs) >= 2, "dump captured fewer than 2 processes"
        with_col_spans = [
            f for f, b in blobs.items()
            if '"collective::allreduce"' in b]
        assert len(with_col_spans) >= 2, (
            f"final collective spans from <2 processes: {list(blobs)}")
        # merged chrome timeline: loadable, and the collective spans of
        # distinct processes kept distinct (remapped) pids
        timeline = json.loads((ddir / "timeline.json").read_text())
        assert isinstance(timeline, list) and timeline
        col_pids = {e["pid"] for e in timeline
                    if e.get("name") == "collective::allreduce"}
        assert len(col_pids) >= 2, timeline[:5]
        # the dump event itself is in the cluster stream
        from ray_tpu._private import events

        assert any(e["kind"] == "FLIGHT_RECORDER_DUMP"
                   for e in events.snapshot())
    finally:
        ray_tpu.shutdown()


def test_cli_steps_and_blackbox_subcommands(monkeypatch):
    from ray_tpu.scripts import cli

    called = {}
    monkeypatch.setattr(
        cli, "cmd_steps",
        lambda args: called.update(steps=(args.address, args.last)) or 0)
    monkeypatch.setattr(
        cli, "cmd_blackbox",
        lambda args: called.update(bb=(args.action, args.out)) or 0)
    assert cli.main(["steps", "--address", "h:1", "--last", "5"]) == 0
    assert cli.main(["blackbox", "dump", "--out", "/tmp/x"]) == 0
    assert called == {"steps": ("h:1", 5), "bb": ("dump", "/tmp/x")}
