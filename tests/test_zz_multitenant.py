"""Multi-tenant control plane (late-alphabet; past the tier-1 timeout
horizon by design).

Covers PR 13 end to end: the named-job registry (quota + priority),
all-or-nothing quota admission at the GCS, the fair-share pending queue
(dominant-resource order, priority blocking), priority preemption with
the grace window riding the PR 5 gang teardown/checkpoint-resume path,
the `pg_state` pubsub waiter, the fault DSL's `preempt_job` primitive,
and the multi-tenant sim-cluster soak (competing jobs + seeded
preemption storms + node kills, byte-identical journals).

GCS-level tests drive a real in-process GcsServer over its RPC handler
surface with stub connections (no workers — deterministic, fast); the
chaos E2Es run a real single-node cluster like tests/test_zz_gang_ft.py.
"""
import os
import threading
import time

import numpy as np
import pytest

pytestmark = []

GRACE = "0.2"


class _Conn:
    """Stub RpcServer connection for direct GCS handler calls."""

    _n = 0

    def __init__(self):
        _Conn._n += 1
        self.id = f"stubconn{_Conn._n}"
        self.meta = {}
        self.alive = True

    def push(self, *a, **k):
        pass


@pytest.fixture
def gcs(monkeypatch):
    """In-process GcsServer + helpers; tiny preemption grace."""
    monkeypatch.setenv("RAY_TPU_GCS_PREEMPT_GRACE_S", GRACE)
    from ray_tpu._private.gcs import GcsServer

    server = GcsServer(port=0).start()
    conns = []

    def add_node(node_id, cpu=4.0):
        c = _Conn()
        conns.append(c)
        server.rpc_register_node(c, node_id=node_id,
                                 addr=("127.0.0.1", 1), resources={
                                     "CPU": float(cpu)}, meta={})
        return c

    def create_pg(pg_id, bundles, job="", strategy="SPREAD"):
        return server.rpc_create_placement_group(
            _Conn(), pg_id=pg_id, bundles=bundles, strategy=strategy,
            name=pg_id.decode(errors="replace"), job=job)

    def state_of(pg_id):
        return server.rpc_get_placement_group(_Conn(),
                                              pg_id=pg_id)["State"]

    server.add_node = add_node
    server.create_pg = create_pg
    server.state_of = state_of
    try:
        yield server
    finally:
        server.stop()


def _pgid(s: str) -> bytes:
    return s.encode().ljust(16, b"\x00")


# -------------------------------------------------------------- registry

def test_job_registry_validation_and_lifecycle(gcs):
    from ray_tpu.exceptions import JobQuotaError

    snap = gcs.rpc_register_job(_Conn(), name="a",
                                quota={"CPU": 4}, priority=3)
    assert snap["Priority"] == 3 and snap["Quota"] == {"CPU": 4.0}
    # idempotent re-register updates in place
    snap = gcs.rpc_register_job(_Conn(), name="a", priority=5)
    assert snap["Priority"] == 5 and snap["Quota"] == {"CPU": 4.0}
    with pytest.raises(JobQuotaError):
        gcs.rpc_register_job(_Conn(), name="", quota=None)
    with pytest.raises(JobQuotaError):
        gcs.rpc_register_job(_Conn(), name="b", quota={"CPU": -1})
    with pytest.raises(JobQuotaError):
        gcs.rpc_register_job(_Conn(), name="b", quota={"CPU": "lots"})
    with pytest.raises(JobQuotaError):
        gcs.rpc_update_job(_Conn(), name="nope", priority=1)
    assert gcs.rpc_get_job(_Conn(), name="a")["Job"] == "a"
    assert gcs.rpc_remove_job(_Conn(), name="a") is True
    assert gcs.rpc_get_job(_Conn(), name="a") is None


def test_job_registered_event_and_debug_state(gcs):
    from ray_tpu._private import events

    base = sum(1 for e in events.snapshot()
               if e["kind"] == "JOB_REGISTERED")
    gcs.rpc_register_job(_Conn(), name="evt", priority=1)
    gcs.rpc_register_job(_Conn(), name="evt", priority=2)  # update: no event
    assert sum(1 for e in events.snapshot()
               if e["kind"] == "JOB_REGISTERED") - base == 1
    st = gcs.rpc_debug_state(_Conn())
    assert st["jobs"] >= 1 and "pending_pgs" in st
    assert "jobs_over_quota" in st


# ------------------------------------------------------------ quota edges

def test_quota_exactly_met_places(gcs):
    gcs.add_node("n1", cpu=4)
    gcs.rpc_register_job(_Conn(), name="q", quota={"CPU": 2})
    gcs.create_pg(_pgid("q-exact"), [{"CPU": 1.0}, {"CPU": 1.0}], job="q")
    assert gcs.state_of(_pgid("q-exact")) == "CREATED"


def test_quota_exceeded_nth_bundle_all_or_nothing(gcs):
    """The 3rd bundle pushes the gang past quota: the WHOLE gang stays
    PENDING (no partial placement), the rejection is counted once, and
    capacity events don't sneak it in."""
    gcs.add_node("n1", cpu=8)
    gcs.rpc_register_job(_Conn(), name="q", quota={"CPU": 2})
    snap = gcs.create_pg(_pgid("q-over"),
                         [{"CPU": 1.0}] * 3, job="q")
    assert snap["State"] == "PENDING"
    assert snap["BundleNodes"] == [None, None, None]   # no partial gang
    # capacity events re-walk the queue but quota still blocks
    gcs.rpc_report_resources(_Conn(), node_id="n1",
                             available={"CPU": 8.0})
    assert gcs.state_of(_pgid("q-over")) == "PENDING"
    job = gcs.rpc_get_job(_Conn(), name="q")
    assert job["QuotaRejections"] >= 1
    assert job["Usage"] == {}   # nothing placed = nothing counted


def test_quota_raised_at_runtime_unblocks(gcs):
    gcs.add_node("n1", cpu=8)
    gcs.rpc_register_job(_Conn(), name="q", quota={"CPU": 2})
    gcs.create_pg(_pgid("q-blocked"), [{"CPU": 1.0}] * 3, job="q")
    assert gcs.state_of(_pgid("q-blocked")) == "PENDING"
    # raising the quota re-drives the queue ON THE SPOT (no capacity
    # event needed, no rate-limit stall)
    gcs.rpc_update_job(_Conn(), name="q", quota={"CPU": 4})
    assert gcs.state_of(_pgid("q-blocked")) == "CREATED"


def test_lease_usage_counts_against_quota(gcs):
    """Raylet-gossiped per-job lease usage feeds the same quota math as
    PG bundles, and pushes the job into the published over-quota set
    raylets throttle lease grants on."""
    gcs.add_node("n1", cpu=8)
    gcs.rpc_register_job(_Conn(), name="lq", quota={"CPU": 3})
    gcs.rpc_report_resources(_Conn(), node_id="n1",
                             available={"CPU": 4.0},
                             job_busy={"lq": {"CPU": 4.0}})
    job = gcs.rpc_get_job(_Conn(), name="lq")
    assert job["Usage"] == {"CPU": 4.0}
    assert job["OverQuota"] is True
    # the PUBLISHED throttle set is rate-limited (eventually consistent
    # by one 0.25s beat): the next gossip tick past the limit carries it
    time.sleep(0.3)
    gcs.rpc_report_resources(_Conn(), node_id="n1",
                             available={"CPU": 4.0},
                             job_busy={"lq": {"CPU": 4.0}})
    assert "lq" in gcs.rpc_debug_state(_Conn())["jobs_over_quota"]
    # a PG for the over-quota job queues rather than placing
    gcs.create_pg(_pgid("lq-pg"), [{"CPU": 1.0}], job="lq")
    assert gcs.state_of(_pgid("lq-pg")) == "PENDING"
    # leases returned -> usage drops -> throttle clears, PG places
    gcs.rpc_report_resources(_Conn(), node_id="n1",
                             available={"CPU": 8.0}, job_busy={})
    gcs.rpc_update_job(_Conn(), name="lq", quota={"CPU": 3})  # re-drive
    assert gcs.state_of(_pgid("lq-pg")) == "CREATED"
    assert "lq" not in gcs.rpc_debug_state(_Conn())["jobs_over_quota"]


# ----------------------------------------------------- fair share / queue

def test_fair_share_prefers_lower_dominant_share(gcs):
    """Equal priority, contended capacity: when one free slot opens,
    the job with the LOWER dominant share wins it even though the
    hog's gang entered the queue first (DRF order beats FIFO)."""
    gcs.add_node("n1", cpu=4)
    gcs.add_node("n2", cpu=2)
    gcs.rpc_register_job(_Conn(), name="hog", priority=1)
    gcs.rpc_register_job(_Conn(), name="meek", priority=1)
    # hog holds 4 of 6 CPUs; a no-job filler takes the other 2
    gcs.create_pg(_pgid("hog-big"), [{"CPU": 4.0}], job="hog")
    gcs.create_pg(_pgid("filler"), [{"CPU": 2.0}])
    assert gcs.state_of(_pgid("hog-big")) == "CREATED"
    assert gcs.state_of(_pgid("filler")) == "CREATED"
    # both tenants queue for capacity that does not exist yet — hog
    # FIRST, so FIFO would favor it
    gcs.create_pg(_pgid("hog-more"), [{"CPU": 2.0}], job="hog")
    time.sleep(0.02)
    gcs.create_pg(_pgid("meek-one"), [{"CPU": 2.0}], job="meek")
    assert gcs.state_of(_pgid("hog-more")) == "PENDING"
    assert gcs.state_of(_pgid("meek-one")) == "PENDING"
    time.sleep(0.3)   # past the per-PG attempt rate limit
    # the filler releases: ONE 2-CPU slot opens, and the fair-share
    # order hands it to meek (share 0) over hog (share 4/6)
    gcs.rpc_remove_placement_group(_Conn(), pg_id=_pgid("filler"))
    assert gcs.state_of(_pgid("meek-one")) == "CREATED"
    assert gcs.state_of(_pgid("hog-more")) == "PENDING"


def test_capacity_event_with_empty_queue_is_cheap(gcs):
    """The satellite hot-spot fix: a report_resources tick with nothing
    pending must not walk the PG table at all."""
    gcs.add_node("n1", cpu=4)
    for i in range(20):
        gcs.create_pg(_pgid(f"full{i:02d}"), [{"CPU": 0.1}])
    assert not gcs._pending_pgs
    calls = []
    orig = gcs._try_schedule_pg
    gcs._try_schedule_pg = lambda pg: calls.append(pg) or orig(pg)
    gcs.rpc_report_resources(_Conn(), node_id="n1",
                             available={"CPU": 2.0})
    assert calls == []   # empty queue -> zero scheduling work


# ------------------------------------------------------------- preemption

def _wait_state(gcs, pg_id, state, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if gcs.state_of(pg_id) == state:
            return True
        time.sleep(0.05)
    return False


def test_priority_inversion_preempts_lowest_first(gcs):
    """low holds ALL capacity; mid and high both want it. Victims must
    come from the LOWEST-priority job (newest gang first) and the
    waiters place in PRIORITY order: high first, then mid — low's gangs
    re-queue behind both."""
    from ray_tpu._private import events

    gcs.add_node("n1", cpu=4)
    gcs.add_node("n2", cpu=4)
    gcs.rpc_register_job(_Conn(), name="low", priority=0)
    gcs.rpc_register_job(_Conn(), name="mid", priority=5)
    gcs.rpc_register_job(_Conn(), name="high", priority=10)
    gcs.create_pg(_pgid("low-1"), [{"CPU": 4.0}], job="low")
    time.sleep(0.02)
    gcs.create_pg(_pgid("low-2"), [{"CPU": 4.0}], job="low")
    assert gcs.state_of(_pgid("low-1")) == "CREATED"
    assert gcs.state_of(_pgid("low-2")) == "CREATED"
    base_fired = [e for e in events.snapshot()
                  if e["kind"] == "PREEMPTION_FIRED"]
    gcs.create_pg(_pgid("high-1"), [{"CPU": 4.0}], job="high")
    time.sleep(0.3)
    gcs.create_pg(_pgid("mid-1"), [{"CPU": 4.0}], job="mid")
    assert _wait_state(gcs, _pgid("high-1"), "CREATED"), \
        "high-pri preemptor never placed"
    assert _wait_state(gcs, _pgid("mid-1"), "CREATED"), \
        "mid-pri never placed"
    fired = [e for e in events.snapshot()
             if e["kind"] == "PREEMPTION_FIRED"][len(base_fired):]
    assert len(fired) == 2
    assert all(e["job"] == "low" for e in fired), fired
    # low's gangs re-queued and now wait behind both tenants
    assert gcs.state_of(_pgid("low-1")) == "PENDING"
    assert gcs.state_of(_pgid("low-2")) == "PENDING"
    jobs = {r["Job"]: r for r in gcs.rpc_list_jobs(_Conn())}
    assert jobs["low"]["Preemptions"] == 2
    assert jobs["high"]["Preemptions"] == 0


def test_preemption_warning_precedes_fire_by_grace(gcs):
    from ray_tpu._private import events

    gcs.add_node("n1", cpu=4)
    gcs.rpc_register_job(_Conn(), name="v", priority=0)
    gcs.create_pg(_pgid("victim"), [{"CPU": 4.0}], job="v")
    assert gcs.state_of(_pgid("victim")) == "CREATED"
    assert gcs.rpc_preempt_job(_Conn(), name="v") is not None
    # inside the grace window the victim still holds its bundles
    assert gcs.state_of(_pgid("victim")) == "CREATED"
    assert _wait_state(gcs, _pgid("victim"), "PENDING", timeout=3.0)
    ev = {e["kind"]: e["ts"] for e in events.snapshot()
          if e["kind"] in ("PREEMPTION_WARNED", "PREEMPTION_FIRED")}
    assert ev["PREEMPTION_FIRED"] - ev["PREEMPTION_WARNED"] \
        >= float(GRACE) * 0.8
    # no preemptible gang left -> None
    assert gcs.rpc_preempt_job(_Conn(), name="v") is None


def test_infeasible_high_pri_does_not_preempt_or_block(gcs):
    """A gang that cannot fit even an empty cluster must not trigger
    preemption (pointless victim kill) nor barrier lower tenants."""
    gcs.add_node("n1", cpu=4)
    gcs.rpc_register_job(_Conn(), name="lo", priority=0)
    gcs.rpc_register_job(_Conn(), name="hi", priority=10)
    gcs.create_pg(_pgid("lo-1"), [{"CPU": 2.0}], job="lo")
    gcs.create_pg(_pgid("hi-huge"), [{"CPU": 64.0}], job="hi")
    time.sleep(0.5)
    assert gcs.state_of(_pgid("hi-huge")) == "PENDING"
    # lower-pri work still schedules under the infeasible giant
    time.sleep(0.3)
    gcs.create_pg(_pgid("lo-2"), [{"CPU": 2.0}], job="lo")
    assert _wait_state(gcs, _pgid("lo-2"), "CREATED")
    assert gcs.state_of(_pgid("lo-1")) == "CREATED"   # never preempted


def test_preempt_freed_ledger_consumed_by_post_fire_report(gcs):
    """Review pin on `_preempt_freed` accounting direction: a raylet
    report taken BEFORE a fire gets the freed bundles added back (the
    fire-boundary over-preemption fix), but the node's first POST-fire
    report already includes them — adding them again would over-commit
    (the scheduler admitting a gang onto capacity that does not exist).
    The entry is consumed per node by that first post-fire report and
    stays consumed even when later reports show the capacity taken."""
    gcs.add_node("n1", cpu=4.0)
    node = gcs.nodes["n1"]
    # pre-fire report: node completely full
    gcs.rpc_report_resources(_Conn(), node_id="n1", available={"CPU": 0.0})
    time.sleep(0.01)
    gcs._preempt_freed.append(
        (time.time(), [{"CPU": 4.0}], ["n1"], set()))
    avail = gcs._node_available_for_pg(node)
    assert avail.get("CPU", 0) == 4.0, \
        "report predating the fire must get the freed bundles added back"
    # post-fire report: the raylet's availability shows the freed CPUs
    time.sleep(0.01)
    gcs.rpc_report_resources(_Conn(), node_id="n1", available={"CPU": 4.0})
    avail = gcs._node_available_for_pg(node)
    assert avail.get("CPU", 0) == 4.0, \
        "freed bundles a post-fire report already shows were added AGAIN"
    # a later report showing the capacity re-taken must not resurrect it
    gcs.rpc_report_resources(_Conn(), node_id="n1", available={"CPU": 1.0})
    avail = gcs._node_available_for_pg(node)
    assert avail.get("CPU", 0) == 1.0


# ------------------------------------------------------------- fault DSL

def test_preempt_job_dsl_determinism():
    from ray_tpu._private.fault_injection import (ACTIONS, _JOB_ACTIONS,
                                                  FaultInjector)

    assert "preempt_job" in ACTIONS and "preempt_job" in _JOB_ACTIONS
    sched = "preempt_job:train.job_tick:%3;preempt_job:*.storm:p0.5:250"
    a = FaultInjector(21, sched)
    b = FaultInjector(21, sched)

    def drive(inj):
        out = []
        for n in range(12):
            for job in ("train", "batch"):
                for action, param_s in inj.on_job(job, "job_tick"):
                    out.append((n, job, action))
            for job in ("train", "batch"):
                for action, param_s in inj.on_job(job, "storm"):
                    out.append((n, job, action, param_s))
        return out

    ta, tb = drive(a), drive(b)
    assert ta == tb                       # same seed -> same storms
    # the job-scoped %3 rule fires ONLY for train (per-(job, method)
    # counter: calls 3, 6, 9, 12), never for batch
    train_ticks = [t for t in ta if t[1] == "train" and len(t) == 3]
    batch_ticks = [t for t in ta if t[1] == "batch" and len(t) == 3]
    assert len(train_ticks) == 4 and len(batch_ticks) == 0
    # the wildcard p-rule keeps an INDEPENDENT deterministic counter
    # per job — both jobs see storms, with their own sequences
    storms = {}
    for t in ta:
        if len(t) == 4:
            storms.setdefault(t[1], []).append(t[0])
            assert t[3] == 0.25           # param_ms=250 carried through
    assert set(storms) == {"train", "batch"}
    assert storms["train"] != storms["batch"]   # independent hashes
    # a different seed perturbs the probabilistic rule
    c = FaultInjector(22, sched)
    assert drive(c) != ta


# ------------------------------------------------- cluster E2E (chaos)

@pytest.fixture
def mt_cluster(monkeypatch):
    """Single-node runtime with a short preemption grace window."""
    monkeypatch.setenv("RAY_TPU_GCS_PREEMPT_GRACE_S", "1.0")
    try:
        import ray_tpu

        ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    except (ImportError, ModuleNotFoundError) as e:
        pytest.skip(f"runtime not built yet: {e}")
    yield ray_tpu
    ray_tpu.shutdown()


STEPS = 12
GROUP = "mt_dp"


def _checkpointed_loop(config):
    from ray_tpu.air import Checkpoint, session
    from ray_tpu.util import collective as col

    start, total = 0, 0.0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        st = ckpt.to_dict()
        start, total = int(st["step"]) + 1, float(st["total"])
    rank = session.get_world_rank()
    marker = config.get("warn_marker")
    for step in range(start, STEPS):
        contrib = np.full(2, float((step + 1) * (rank + 1)))
        s = col.allreduce(contrib, GROUP)
        total += float(s[0])
        if marker and session.preemption_warned() is not None:
            # checkpoint-then-yield visibility: prove the WARNING
            # reached the train loop inside the grace window
            with open(marker + f".rank{rank}", "w") as f:
                f.write(str(session.preemption_warned()["grace_s"]))
        time.sleep(0.35)
        session.report({"step": step, "total": total},
                       checkpoint=Checkpoint.from_dict(
                           {"step": step, "total": total}))


def _fit_in_thread(ray, tmp_path, job, marker=None, max_failures=0):
    from ray_tpu.air.config import (CheckpointConfig, FailureConfig,
                                    RunConfig, ScalingConfig)
    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.backend_executor import JaxConfig

    box = {}

    def run():
        try:
            box["result"] = JaxTrainer(
                _checkpointed_loop,
                train_loop_config={"warn_marker": marker},
                backend_config=JaxConfig(group_name=GROUP),
                scaling_config=ScalingConfig(
                    num_workers=2, resources_per_worker={"CPU": 1},
                    job=job),
                run_config=RunConfig(
                    name="mt_run", storage_path=str(tmp_path),
                    failure_config=FailureConfig(
                        max_failures=max_failures),
                    checkpoint_config=CheckpointConfig(num_to_keep=2)),
            ).fit()
        except BaseException as e:  # noqa: BLE001 — surfaced by test
            box["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, box


def _wait_checkpoints(tmp_path, n, timeout=60.0):
    ckdir = os.path.join(str(tmp_path), "mt_run")
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.isdir(ckdir):
            dirs = [d for d in os.listdir(ckdir)
                    if d.startswith("checkpoint_")]
            if len(dirs) >= n:
                return True
        time.sleep(0.1)
    return False


@pytest.mark.chaos
def test_preemption_checkpoint_resume_e2e(mt_cluster, tmp_path):
    """The tentpole acceptance, deterministic orchestration: a
    high-priority PG that cannot place preempts the running
    checkpointed gang — the victim's train loops SEE the grace-window
    warning, the preemptor places within grace + teardown bound, and
    when its capacity is released the victim resumes from its latest
    checkpoint and reaches the oracle total with only post-checkpoint
    steps re-executed."""
    ray = mt_cluster
    from ray_tpu._private import events
    from ray_tpu.experimental.state.api import summarize_jobs
    from ray_tpu.util import jobs
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    # the events ring is process-global: earlier in-process GCS tests
    # left PREEMPTION_* events behind — assert deltas, not totals
    base = [e["kind"] for e in events.snapshot()]
    jobs.register_job("mt_trainer", priority=1)
    jobs.register_job("mt_serve", priority=10)
    marker = str(tmp_path / "warned")
    t, box = _fit_in_thread(ray, tmp_path, "mt_trainer", marker=marker)
    # trigger on the FIRST persisted checkpoint: the fire must land
    # mid-run (steps left to lose) for resume-from-checkpoint to be
    # observable
    assert _wait_checkpoints(tmp_path, 1), "gang never checkpointed"

    # the Serve scale-up: cannot place on 4 CPUs with 2 held by the gang
    t0 = time.monotonic()
    pg = placement_group([{"CPU": 3.0}], strategy="PACK", job="mt_serve")
    assert pg.wait(timeout_seconds=20.0), "preemptor never placed"
    placed_s = time.monotonic() - t0
    # grace (1.0s) + detection/teardown/gossip bound
    assert placed_s < 10.0, f"preemptor took {placed_s:.1f}s"

    time.sleep(1.0)
    remove_placement_group(pg)       # capacity returns
    t.join(timeout=120)
    assert not t.is_alive(), "fit never finished after requeue"
    assert "error" not in box, box.get("error")
    res = box["result"]
    assert res.error is None, res.error
    oracle = 3.0 * STEPS * (STEPS + 1) / 2.0
    assert res.metrics["total"] == oracle
    assert res.metrics["step"] == STEPS - 1
    # resumed from checkpoint: the final attempt replayed only the
    # post-checkpoint steps
    assert 0 < len(res.metrics_history) < STEPS
    # the warning reached the train loop before the fire
    assert any(os.path.exists(marker + f".rank{r}") for r in (0, 1)), \
        "no rank observed session.preemption_warned()"
    kinds = [e["kind"] for e in events.snapshot()]

    def fresh(kind):
        return kinds.count(kind) - base.count(kind)

    assert fresh("PREEMPTION_WARNED") == 1
    assert fresh("PREEMPTION_FIRED") == 1
    assert fresh("GANG_FAILED") == 0   # graceful, not a failure
    summary = summarize_jobs()
    assert summary["quota_violations"] == []
    assert {r["Job"]: r["Preemptions"] for r in summary["jobs"]
            }["mt_trainer"] == 1


@pytest.mark.chaos
@pytest.mark.fault_injection
def test_seeded_preemption_storm_no_lost_work(mt_cluster, tmp_path):
    """Satellite: N seeded `preempt_job` firings against a checkpointed
    gang — the victim never loses accepted (reported+checkpointed)
    work: every resume continues from the latest checkpoint and the
    final total is the exact oracle."""
    ray = mt_cluster
    from ray_tpu._private import fault_injection as fi
    from ray_tpu.experimental.state.api import summarize_jobs
    from ray_tpu.util import jobs

    jobs.register_job("mt_chaos", priority=1)
    inj = fi.install(31, "preempt_job:mt_chaos.tick:#1,2")
    try:
        t, box = _fit_in_thread(ray, tmp_path, "mt_chaos")
        assert _wait_checkpoints(tmp_path, 1), "gang never checkpointed"
        fired = 0
        deadline = time.time() + 90
        while fired < 2 and time.time() < deadline:
            for action, param_s in inj.on_job("mt_chaos", "tick"):
                if action == "preempt_job":
                    victim = jobs.preempt_job("mt_chaos", grace_s=0.6)
                    if victim is not None:
                        fired += 1
            time.sleep(2.0)   # space storms: let each resume checkpoint
        assert fired == 2, f"schedule fired {fired}/2 preemptions"
        t.join(timeout=150)
        assert not t.is_alive(), "fit wedged after preemption storm"
        assert "error" not in box, box.get("error")
        res = box["result"]
        assert res.error is None, res.error
        oracle = 3.0 * STEPS * (STEPS + 1) / 2.0
        assert res.metrics["total"] == oracle, \
            "accepted work lost across seeded preemptions"
        assert summarize_jobs()["preemptions"] == 2
    finally:
        fi.uninstall()


@pytest.mark.chaos
def test_pg_wait_rides_pg_state_channel(mt_cluster):
    """Satellite: ready()/wait() ride the pg_state pubsub channel — a
    quota-blocked PG's waiter wakes on the CREATED push well inside the
    2s fallback-poll period once the quota is raised."""
    ray = mt_cluster
    from ray_tpu.util import jobs
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    jobs.register_job("waitq", quota={"CPU": 0.5}, priority=0)
    pg = placement_group([{"CPU": 1.0}], job="waitq")
    box = {}

    def wait_it():
        t0 = time.monotonic()
        box["ok"] = pg.wait(timeout_seconds=15.0)
        box["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=wait_it, daemon=True)
    t.start()
    time.sleep(1.2)       # waiter subscribed, PG quota-blocked
    assert "ok" not in box
    t_unblock = time.monotonic()
    jobs.update_job("waitq", quota={"CPU": 2.0})
    t.join(timeout=10)
    assert box.get("ok") is True
    woke_in = time.monotonic() - t_unblock
    assert woke_in < 1.5, \
        f"waiter took {woke_in:.2f}s after unblock (fallback is 2s)"
    remove_placement_group(pg)


# ------------------------------------------------------- sim-cluster soak

def _mt_soak_run(n_nodes: int, seed: int):
    """One deterministic multi-tenant soak: competing quota-capped
    jobs, seeded preempt storms, composed node kills."""
    from ray_tpu._private import fault_injection as fi
    from ray_tpu._private.sim_cluster import SimCluster

    os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"] = "0.2"
    fi.install(seed, "preempt_job:jt.job_tick:%2:200;"
                     "kill_node:*.mt_kill:p0.08")
    cluster = SimCluster(n_nodes=n_nodes, tick_interval=0.05,
                         poll_timeout=2.0).start()
    try:
        cpus = 4.0 * n_nodes
        cluster.register_job("bg", quota={"CPU": cpus * 0.5}, priority=0)
        cluster.register_job("jt", quota={"CPU": cpus * 0.4}, priority=5)
        cluster.run_ticks(2)
        for _ in range(3):
            cluster.create_job_pg("bg", n_bundles=3, cpu=2.0)
            cluster.create_job_pg("jt", n_bundles=2, cpu=2.0)
        cluster.run_ticks(4)
        for round_n in range(4):
            cluster.jobs_tick()
            if round_n == 1:
                cluster.mass_consult("mt_kill")
            cluster.run_ticks(3)
            cluster.sample_jobs()
        conv = cluster.wait_converged(timeout=30.0)
        st = cluster.gcs_call("debug_state")
        samples = cluster.metrics["job_samples"]
        return {
            "journal": cluster.journal_text(),
            "converged": conv["converged"],
            "killed": len(cluster.dead_ids()),
            "preemptions": st["preemptions_fired"],
            "violations": sum(len(s["violations"]) for s in samples),
        }
    finally:
        cluster.stop()
        fi.uninstall()
        del os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"]


@pytest.mark.soak
@pytest.mark.fault_injection
def test_multitenant_sim_soak_deterministic():
    """The 100-node scenario at smoke scale: preemption storms compose
    with node kills, quota stays inviolate in every sample, and the
    chaos journal is byte-identical across two runs of the same
    seed."""
    a = _mt_soak_run(14, seed=13)
    assert a["converged"]
    assert a["preemptions"] >= 1, "seeded storm never preempted"
    assert a["violations"] == 0, "quota violated under chaos"
    assert a["killed"] >= 1, "p0.08 kill schedule fired nothing at 14"
    b = _mt_soak_run(14, seed=13)
    assert a["journal"] == b["journal"], "chaos journal not reproducible"
