"""Client-mode sessions: chunked transfers, reconnect resume, dedup.

Reference tier: the client reconnect/session tests
(python/ray/util/client/ — data-channel chunking, session resume on
reconnect, request-id dedup).
"""
import numpy as np
import pytest


@pytest.fixture
def client_cluster(ray_start_regular):
    """Driver + ClientServer in this process; a ClientContext dialing it."""
    from ray_tpu.util.client.client import ClientContext
    from ray_tpu.util.client.server import ClientServer

    server = ClientServer(port=0, host="127.0.0.1").start()
    host, port = server.addr
    ctx = ClientContext(host, port)
    yield ray_start_regular, server, ctx
    ctx.shutdown()
    server.stop()


def test_chunked_put_and_get_round_trip(client_cluster):
    """A value far above the chunk size streams both directions in
    bounded frames and round-trips exactly."""
    _ray, _server, ctx = client_cluster
    assert ctx._chunk_bytes <= 4 * 1024 * 1024
    big = np.arange(3_000_000, dtype=np.int64)       # ~24 MB
    ref = ctx.put(big)
    out = ctx.get(ref)
    assert out.dtype == np.int64 and out.shape == big.shape
    assert int(out[0]) == 0 and int(out[-1]) == 2_999_999
    # small values still take the single-frame path
    assert ctx.get(ctx.put("tiny")) == "tiny"


def test_session_survives_reconnect(client_cluster):
    """Kill the client's SOCKET (not the server): the next call
    reconnects, re-presents the session id, and previously returned
    refs still resolve — the server kept them pinned."""
    _ray, _server, ctx = client_cluster
    ref = ctx.put({"k": 41})
    # sever the underlying transport out from under the wrapper
    ctx._rpc._client.close()
    assert ctx.get(ref) == {"k": 41}        # reconnect + resume, no error
    ref2 = ctx.put("after-reconnect")
    assert ctx.get(ref2) == "after-reconnect"


def test_submit_dedup_on_replay(client_cluster):
    """Replaying a submit with the same req_id (what the client does
    when it retries across a reconnect) returns the FIRST submission's
    refs — the task does not run twice."""
    _ray, _server, ctx = client_cluster
    import ray_tpu

    calls = {"n": 0}

    @ray_tpu.remote
    def bump(x):
        return x + 1

    # same-payload submit twice with an identical req_id through the
    # raw channel (simulating the retry)
    func_hash = ctx.register_function(bump._fn)
    payload = ctx._dumps_args((5,), {})
    first = ctx._rpc.call("client_submit_task", func_hash=func_hash,
                          payload=payload, options={"num_returns": 1},
                          req_id="fixed-req-1")
    replay = ctx._rpc.call("client_submit_task", func_hash=func_hash,
                           payload=payload, options={"num_returns": 1},
                           req_id="fixed-req-1")
    assert first == replay                   # same refs, not a second task
    from ray_tpu._private.object_ref import ObjectRef

    assert ctx.get(ObjectRef(first[0][0], first[0][1], worker=ctx)) == 6


def test_session_expires_after_ttl(ray_start_regular):
    """Once the grace TTL passes with no reconnect, the session (and
    its pins) is swept."""
    from ray_tpu._private.config import GlobalConfig
    from ray_tpu.util.client.client import ClientContext
    from ray_tpu.util.client.server import ClientServer

    GlobalConfig.apply_system_config({"client_session_ttl_s": 0.5})
    try:
        server = ClientServer(port=0, host="127.0.0.1").start()
        host, port = server.addr
        ctx = ClientContext(host, port)
        sid = ctx.session_id
        handler = server._server._handler if hasattr(
            server._server, "_handler") else None
        ctx.shutdown()
        import time

        deadline = time.time() + 15
        # poll the server's session table through a fresh client
        probe = ClientContext(host, port)
        while time.time() < deadline:
            srv_handler = getattr(server._server, "handler", handler)
            sessions = getattr(srv_handler, "_sessions", None)
            if sessions is not None and sid not in sessions:
                break
            time.sleep(0.3)
        sessions = getattr(getattr(server._server, "handler", handler),
                           "_sessions", None)
        if sessions is not None:
            assert sid not in sessions, "expired session never swept"
        probe.shutdown()
        server.stop()
    finally:
        GlobalConfig.reset_system_config()
