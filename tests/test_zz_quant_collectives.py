"""Block-quantized collective wire formats (late-alphabet; sequenced
after the tier-1 timeout horizon by design).

Covers the PR's tentpole: the bf16 / int8-with-block-scales wire
codecs (`util/collective/wire.py` + `src/quant/quant.cc`), their
per-segment eligibility fallback, the documented error bounds, the
`off` kill switch being bit-exact, rank-identical results under a
lossy wire, hierarchy/shm composition, wire telemetry, and chaos
parity (a dropped or duplicated quantized segment behaves exactly like
an exact one: timeout-not-hang, no double dequantize-accumulate).

Knob plumbing mirrors tests/test_zz_host_pipeline.py: members read the
collective config live from env, so actors get a `configure` method.
"""
import numpy as np
import pytest

SEG = 1024       # segment bytes under test: 256 f32 elements
BLOCK = 64       # int8 scale block (elements)

BASE_ENV = {
    "RAY_TPU_COLLECTIVE_SEGMENT_BYTES": SEG,
    "RAY_TPU_COLLECTIVE_QUANT_BLOCK": BLOCK,
    "RAY_TPU_COLLECTIVE_PIPELINE": "1",
}

# documented per-hop quantization step, relative to the running
# partial's absmax (see util/collective/wire.py docstring)
Q = {"bf16": 2.0 ** -8, "int8": 1.0 / 254.0}


def _bound(fmt: str, world: int, ins) -> float:
    """world quantized hops x q x (sum of per-rank input absmax) —
    the bound PERF.md documents and the bench records."""
    return world * Q[fmt] * sum(float(np.abs(x).max()) for x in ins)


def _rank_cls(ray):
    @ray.remote
    class Rank:
        def configure(self, env):
            import os

            os.environ.update({k: str(v) for k, v in env.items()})
            return True

        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def allreduce(self, arr, name, op="sum"):
            from ray_tpu.util import collective as col

            return col.allreduce(arr, name, op=op)

        def reducescatter(self, arr, name, op="sum"):
            from ray_tpu.util import collective as col

            return col.reducescatter(arr, name, op=op)

        def chaos(self, seed, schedule):
            from ray_tpu._private import fault_injection as fi

            fi.install(seed, schedule)
            return True

        def chaos_off(self):
            from ray_tpu._private import fault_injection as fi

            fi.uninstall()
            return True

        def destroy(self, name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(name)
            return True

    return Rank


def _make_world(ray, world, name, env=None):
    Rank = _rank_cls(ray)
    actors = [Rank.options(num_cpus=0).remote() for _ in range(world)]
    merged = dict(BASE_ENV)
    merged.update(env or {})
    ray.get([a.configure.remote(merged) for a in actors])
    ray.get([a.join.remote(world, i, name)
             for i, a in enumerate(actors)], timeout=120)
    return actors


def _teardown(ray, actors, name):
    try:
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)
    except Exception:
        pass
    for a in actors:
        try:
            ray.kill(a)
        except Exception:
            pass


def _mk(rank, size, dtype="float32", scale=3.0):
    rng = np.random.RandomState(1000 * rank + size)
    return (rng.standard_normal(size) * scale).astype(dtype)


# ------------------------------------------------------------ codec units

def test_codec_bounds_and_corners():
    """Encode/decode roundtrip honors the documented per-format bound,
    NaN/Inf corners, zero and subnormal blocks, sub-block tails — on
    the native kernels AND the numpy fallback."""
    from ray_tpu.util.collective import wire

    for force in (False, True):
        wire._force_numpy = force
        try:
            c8 = wire.WireCodec("int8", BLOCK)
            cb = wire.WireCodec("bf16", BLOCK)
            x = _mk(1, 1000) * 7
            e = c8.encode(x)
            assert wire.is_wire(e)
            d = c8.decode(e, out=np.empty(1000, np.float32))
            nq = 1000 // BLOCK * BLOCK
            bmax = np.abs(x[:nq].reshape(-1, BLOCK)).max(axis=1)
            err = np.abs(d[:nq] - x[:nq]).reshape(-1, BLOCK).max(axis=1)
            assert (err <= bmax / 254 + 1e-12).all()
            assert np.array_equal(d[nq:], x[nq:])   # tail exact
            eb = cb.encode(x)
            db = cb.decode(eb, out=np.empty(1000, np.float32))
            rel = np.abs(db - x) / np.maximum(np.abs(x), 1e-30)
            assert rel.max() <= 2 ** -8 + 1e-9
            # non-finite: int8 declines the whole segment; bf16 keeps
            # NaN as (quiet) NaN and Inf exact
            xn = x.copy()
            xn[3], xn[400], xn[500] = np.nan, np.inf, -np.inf
            assert c8.encode(xn) is None
            dn = cb.decode(cb.encode(xn),
                           out=np.empty(1000, np.float32))
            assert np.isnan(dn[3]) and dn[400] == np.inf \
                and dn[500] == -np.inf
            # zero/subnormal blocks flush to zero, bounded by 1.2e-36
            # (below the flush threshold 1/scale would overflow — the
            # deep-subnormal 3e-43 case was UB in the first native cut)
            xz = np.zeros(3 * BLOCK, np.float32)
            xz[BLOCK + 2] = 1e-38
            xz[2 * BLOCK + 5] = 3e-43
            dz = c8.decode(c8.encode(xz),
                           out=np.empty(3 * BLOCK, np.float32))
            assert np.abs(dz).max() <= 1.2e-36
            # all-tail / empty segments decline (exact fallback)
            assert c8.encode(np.ones(BLOCK - 1, np.float32)) is None
            assert c8.encode(np.empty(0, np.float32)) is None
        finally:
            wire._force_numpy = False


def test_codec_fused_paths_match_and_commute():
    """The fused kernels (reduce_into / add_both, native NT + scalar
    paths and the numpy fallback) produce bit-identical results from
    the same wire bytes, and add_both commutes — the property
    rank-identical pairwise results rest on."""
    from ray_tpu.util.collective import wire

    for fmt in ("int8", "bf16"):
        wire._force_numpy = False
        c = wire.WireCodec(fmt, BLOCK)
        n = 997
        x, y = _mk(1, n) * 9, _mk(2, n) * 9
        src = _mk(3, n)
        ea = tuple(v.copy() if isinstance(v, np.ndarray) else v
                   for v in c.encode(x, slot=0))
        eb = tuple(v.copy() if isinstance(v, np.ndarray) else v
                   for v in c.encode(y, slot=1))
        results = {}
        for force in (False, True):
            wire._force_numpy = force
            try:
                c2 = wire.WireCodec(fmt, BLOCK)
                acc = wire.aligned_empty(n, np.float32)      # NT path
                c2.add_both(ea, eb, acc)
                rev = wire.aligned_empty(n, np.float32)
                c2.add_both(eb, ea, rev)
                assert np.array_equal(acc, rev), (fmt, force)
                red = wire.aligned_empty(n, np.float32)
                c2.reduce_into(src, ea, red)
                dec = wire.aligned_empty(n, np.float32)
                c2.copy_into(ea, dec)
                unal = np.empty(n + 1, np.float32)[1:]       # scalar path
                c2.add_both(ea, eb, unal)
                results[force] = (acc.copy(), red.copy(), dec.copy(),
                                  unal.copy())
            finally:
                wire._force_numpy = False
        for a, b in zip(results[False], results[True]):
            assert np.array_equal(a, b), fmt
        # fused reduce == decode-then-add, and aligned == unaligned
        f = results[False]
        assert np.array_equal(f[1], src + f[2])
        assert np.array_equal(f[0], f[3])


def test_segment_elems_non_power_of_two_itemsize(ray_start_regular):
    """Satellite regression: _segment_elems floor-divides, so segments
    are always whole-element for itemsizes that don't divide
    collective_segment_bytes (the int8 block layout relies on both
    ends agreeing on element boundaries), and never drop below one
    element."""
    import os

    from ray_tpu.util.collective.host_backend import HostGroup

    g = HostGroup("segelems", 2, 0,
                  {0: ("h", 1), 1: ("h", 2)})
    os.environ["RAY_TPU_COLLECTIVE_SEGMENT_BYTES"] = "4096"
    try:
        for itemsize in (1, 2, 3, 4, 5, 8, 12, 16, 100):
            elems = g._segment_elems(itemsize)
            assert elems == max(1, 4096 // itemsize)
            assert elems * itemsize <= 4096 or elems == 1
        # an element larger than the whole budget still makes progress
        assert g._segment_elems(10_000) == 1
        assert g._segment_elems(0) >= 1   # guarded, not ZeroDivision
    finally:
        os.environ.pop("RAY_TPU_COLLECTIVE_SEGMENT_BYTES", None)
        g.close()


def test_unknown_wire_dtype_raises(ray_start_regular):
    import os

    from ray_tpu.util.collective.host_backend import HostGroup

    g = HostGroup("badfmt", 2, 0, {0: ("h", 1), 1: ("h", 2)})
    os.environ["RAY_TPU_COLLECTIVE_WIRE_DTYPE"] = "fp4"
    try:
        with pytest.raises(ValueError, match="fp4"):
            g._wire_ctx(np.float32, "sum")
    finally:
        os.environ.pop("RAY_TPU_COLLECTIVE_WIRE_DTYPE", None)
        g.close()


# --------------------------------------------------------------- oracles

def test_quantized_oracle_worlds_1_to_4(ray_start_regular):
    """float32 sum allreduce/reducescatter under bf16 and int8 across
    odd sizes and worlds 1-4: within the documented bound, and every
    rank returns BYTE-IDENTICAL results despite the lossy wire."""
    ray = ray_start_regular
    sizes = (1, 63, 64, 257, 1000)   # tail-only, block, odd, multi-seg
    for fmt in ("bf16", "int8"):
        for world in (1, 2, 3, 4):
            name = f"q_{fmt}_{world}"
            actors = _make_world(
                ray, world, name,
                env={"RAY_TPU_COLLECTIVE_WIRE_DTYPE": fmt})
            try:
                for size in sizes:
                    ins = [_mk(r, size) for r in range(world)]
                    exact = np.zeros(size, np.float64)
                    for x in ins:
                        exact += x
                    out = ray.get(
                        [a.allreduce.remote(ins[r], name)
                         for r, a in enumerate(actors)], timeout=60)
                    outs = [np.asarray(o) for o in out]
                    for o in outs[1:]:
                        assert o.tobytes() == outs[0].tobytes(), \
                            (fmt, world, size, "rank divergence")
                    got = outs[0].astype(np.float64)
                    assert got.dtype == np.float64
                    assert outs[0].shape == (size,)
                    err = np.abs(got - exact).max()
                    assert err <= _bound(fmt, world, ins) + 1e-6, \
                        (fmt, world, size, err)
                    rs = ray.get(
                        [a.reducescatter.remote(ins[r], name)
                         for r, a in enumerate(actors)], timeout=60)
                    shards = np.array_split(exact, world)
                    for r, got in enumerate(rs):
                        if shards[r].size == 0:
                            continue   # size < world: empty shard
                        rerr = np.abs(np.asarray(got).astype(np.float64)
                                      - shards[r]).max()
                        assert rerr <= _bound(fmt, world, ins) + 1e-6, \
                            (fmt, world, size, rerr)
            finally:
                _teardown(ray, actors, name)


def test_eligibility_fallback_matrix(ray_start_regular):
    """With a wire format armed, everything OUTSIDE float32-sum must be
    bit-exact: integer dtypes, float64, non-sum ops, and segments whose
    data is non-finite (int8 declines per segment)."""
    ray = ray_start_regular
    world, name = 2, "q_elig"
    actors = _make_world(ray, world, name,
                         env={"RAY_TPU_COLLECTIVE_WIRE_DTYPE": "int8"})
    try:
        size = 300
        cases = [
            ("int32", "sum"), ("int32", "max"),
            ("float64", "sum"), ("float32", "max"),
            ("float32", "product"), ("float32", "min"),
        ]
        for dtype, op in cases:
            ins = [_mk(r, size, dtype) if np.dtype(dtype).kind == "f"
                   else np.arange(size, dtype=dtype) + r
                   for r in range(world)]
            import functools

            fn = {"sum": np.add, "max": np.maximum, "min": np.minimum,
                  "product": np.multiply}[op]
            expect = functools.reduce(fn, ins[1:], ins[0])
            out = ray.get([a.allreduce.remote(ins[r], name, op)
                           for r, a in enumerate(actors)], timeout=60)
            for got in out:
                got = np.asarray(got)
                assert got.dtype == np.dtype(dtype), (dtype, op)
                assert got.tobytes() == expect.tobytes(), (dtype, op)
        # float32 sum with non-finite data: int8 declines every
        # poisoned segment; Inf/NaN propagate exactly like np.add
        bad = [_mk(r, size) for r in range(world)]
        bad[0][7] = np.inf
        bad[1][9] = np.nan
        expect = np.add(bad[0], bad[1])
        out = [np.asarray(o) for o in ray.get(
            [a.allreduce.remote(bad[r], name)
             for r, a in enumerate(actors)], timeout=60)]
        assert np.isinf(out[0][7]) and np.isnan(out[0][9])
        # the NaN/Inf-free remainder still reduces within bound (the
        # bound computed over the finite values only — absmax of data
        # containing Inf/NaN is not a number)
        mask = np.isfinite(expect)
        err = np.abs(out[0][mask] - expect[mask]).max()
        finite_bound = world * Q["int8"] * sum(
            float(np.abs(x[np.isfinite(x)]).max()) for x in bad)
        assert err <= finite_bound + 1e-6
    finally:
        _teardown(ray, actors, name)


def test_off_is_bit_identical_including_nan(ray_start_regular):
    """RAY_TPU_COLLECTIVE_WIRE_DTYPE=off (the default) is byte-for-byte
    the pre-quantization pipeline: pipelined-off results equal the
    legacy kill-switch ring bit-for-bit, NaN payload corners included,
    and `off` equals the knob being UNSET."""
    ray = ray_start_regular
    world, name = 3, "q_off"
    actors = _make_world(ray, world, name)
    try:
        rng = np.random.RandomState(11)
        ins = [rng.standard_normal(517).astype(np.float32)
               for _ in range(world)]
        for r in range(world):
            ins[r][r * 7] = np.nan    # NaN corners, distinct per rank
        results = {}
        for mode, env in (
                ("unset", {"RAY_TPU_COLLECTIVE_WIRE_DTYPE": "",
                           "RAY_TPU_COLLECTIVE_PIPELINE": "1"}),
                ("off", {"RAY_TPU_COLLECTIVE_WIRE_DTYPE": "off",
                         "RAY_TPU_COLLECTIVE_PIPELINE": "1"}),
                ("legacy", {"RAY_TPU_COLLECTIVE_WIRE_DTYPE": "off",
                            "RAY_TPU_COLLECTIVE_PIPELINE": "0"})):
            ray.get([a.configure.remote(env) for a in actors])
            ar = ray.get([a.allreduce.remote(ins[r], name)
                          for r, a in enumerate(actors)], timeout=60)
            rs = ray.get([a.reducescatter.remote(ins[r], name)
                          for r, a in enumerate(actors)], timeout=60)
            results[mode] = ([np.asarray(x).tobytes() for x in ar],
                             [np.asarray(x).tobytes() for x in rs])
        assert results["off"] == results["unset"]
        assert results["off"] == results["legacy"]
    finally:
        _teardown(ray, actors, name)


def test_hierarchy_and_shm_compose_with_quantization(ray_start_regular):
    """Forced intra-host hierarchy + the shm same-node transport with
    quantization armed: the inter-host (leader) ring quantizes, local
    hops stay exact, results land within bound and rank-identical.
    Large segments so the >=64KB shm gate engages for the quantized
    frames too."""
    ray = ray_start_regular
    world, name = 4, "q_hier"
    actors = _make_world(
        ray, world, name,
        env={"RAY_TPU_COLLECTIVE_WIRE_DTYPE": "int8",
             "RAY_TPU_COLLECTIVE_HIERARCHY": "1",
             "RAY_TPU_COLLECTIVE_SEGMENT_BYTES": 128 * 1024,
             "RAY_TPU_COLLECTIVE_QUANT_BLOCK": 1024})
    try:
        ins = [_mk(r, 100_000) for r in range(world)]
        exact = np.zeros(100_000, np.float64)
        for x in ins:
            exact += x
        out = [np.asarray(o) for o in ray.get(
            [a.allreduce.remote(ins[r], name)
             for r, a in enumerate(actors)], timeout=60)]
        for o in out[1:]:
            assert o.tobytes() == out[0].tobytes()
        err = np.abs(out[0].astype(np.float64) - exact).max()
        assert err <= _bound("int8", world, ins) + 1e-6
        # flat ring over shm too (hierarchy back to auto = off on one
        # host): same gate, forwarded quantized shm frames
        ray.get([a.configure.remote(
            {"RAY_TPU_COLLECTIVE_HIERARCHY": "0"}) for a in actors])
        out2 = [np.asarray(o) for o in ray.get(
            [a.allreduce.remote(ins[r], name)
             for r, a in enumerate(actors)], timeout=60)]
        for o in out2[1:]:
            assert o.tobytes() == out2[0].tobytes()
        err2 = np.abs(out2[0].astype(np.float64) - exact).max()
        assert err2 <= _bound("int8", world, ins) + 1e-6
    finally:
        _teardown(ray, actors, name)


def test_wire_telemetry_compression_ratio(ray_start_regular):
    """ray_tpu_collective_wire_bytes_total records the ACTUAL wire
    bytes by format: the int8 series for an op must be well under the
    payload bytes (compression visible), and the quant-error histogram
    records a sampled sub-bound ratio."""
    ray = ray_start_regular
    from ray_tpu.experimental.state.api import metrics_summary

    world, name = 2, "q_tm"
    actors = _make_world(ray, world, name,
                         env={"RAY_TPU_COLLECTIVE_WIRE_DTYPE": "int8",
                              "RAY_TPU_COLLECTIVE_QUANT_BLOCK": 256,
                              # realistic segments: with the tiny
                              # BASE_ENV segment size, per-segment
                              # framing would swamp the wire bytes
                              "RAY_TPU_COLLECTIVE_SEGMENT_BYTES":
                                  128 * 1024})
    try:
        size = 200_000   # 800KB payload per rank
        ins = [_mk(r, size) for r in range(world)]
        ray.get([a.allreduce.remote(ins[r], name)
                 for r, a in enumerate(actors)], timeout=60)
        import time as _time

        deadline = _time.time() + 30
        while True:
            snaps = {m["name"]: m for m in metrics_summary()}
            wb = snaps.get("ray_tpu_collective_wire_bytes_total")
            rows = [v for v in (wb or {}).get("values", ())
                    if v["tags"].get("group") == name
                    and v["tags"].get("format") == "int8"]
            if rows:
                break
            assert _time.time() < deadline, "wire bytes metric late"
            _time.sleep(0.5)
        wire_bytes = sum(v["value"] for v in rows)
        payload = size * 4 * world   # both ranks' full sends
        # int8 + scales + framing: must sit well under half the payload
        assert 0 < wire_bytes < payload / 2, (wire_bytes, payload)
        err = snaps.get("ray_tpu_collective_quant_error_ratio")
        samples = [r for r in (err or {}).get("counts", ())
                   if r["tags"].get("format") == "int8"]
        assert samples, "quant error histogram missing"
    finally:
        _teardown(ray, actors, name)


# ----------------------------------------------------------------- chaos

def test_dropped_quantized_segment_raises_timeout(ray_start_regular):
    """Chaos parity: a deterministically dropped QUANTIZED segment
    surfaces as the op timeout, never a hang (same failure detector as
    the exact path)."""
    ray = ray_start_regular
    world, name = 2, "q_chaos_drop"
    actors = _make_world(ray, world, name,
                         env={"RAY_TPU_COLLECTIVE_WIRE_DTYPE": "int8",
                              "RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "3"})
    try:
        ins = [_mk(r, 1000) for r in range(world)]
        ray.get([a.allreduce.remote(ins[r], name)
                 for r, a in enumerate(actors)], timeout=60)
        ray.get([a.chaos.remote(0, "drop:*.col_push_frame:#2")
                 for a in actors])
        refs = [a.allreduce.remote(ins[r], name)
                for r, a in enumerate(actors)]
        with pytest.raises(Exception) as ei:
            ray.get(refs, timeout=60)
        assert "timed out" in str(ei.value).lower()
        ray.get([a.chaos_off.remote() for a in actors])
    finally:
        _teardown(ray, actors, name)


def test_duplicated_quantized_segment_no_double_accumulate(
        ray_start_regular):
    """Chaos parity: a dup-delivered quantized segment must NOT be
    dequantize-accumulated twice — the mailbox overwrites the
    unconsumed duplicate, so results are identical to a clean run of
    the same inputs, repeatedly."""
    ray = ray_start_regular
    world, name = 2, "q_chaos_dup"
    actors = _make_world(ray, world, name,
                         env={"RAY_TPU_COLLECTIVE_WIRE_DTYPE": "int8"})
    try:
        ins = [_mk(r, 1000) for r in range(world)]
        clean = [np.asarray(o) for o in ray.get(
            [a.allreduce.remote(ins[r], name)
             for r, a in enumerate(actors)], timeout=60)]
        ray.get([a.chaos.remote(0, "dup:*.col_push_frame:p1")
                 for a in actors])
        for _ in range(2):
            out = [np.asarray(o) for o in ray.get(
                [a.allreduce.remote(ins[r], name)
                 for r, a in enumerate(actors)], timeout=60)]
            for got in out:
                # bit-identical to the clean quantized run: a double
                # accumulate would shift the sum by a whole
                # contribution, far outside equality
                assert got.tobytes() == clean[0].tobytes()
        ray.get([a.chaos_off.remote() for a in actors])
    finally:
        _teardown(ray, actors, name)
