"""Custom-VJP correctness: the memory-lean layer_norm / MLP backward rules
against plain autodiff of naive reference implementations.

These rules exist for HBM reasons (see models/layers.py docstrings); these
tests pin their math so perf work can't silently corrupt gradients.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import layers as L


def _naive_ln(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _naive_mlp(params, x):
    h = jnp.einsum("...d,df->...f", x, params["w1"]) + params["b1"]
    h = jax.nn.gelu(h)
    return jnp.einsum("...f,fd->...d", h, params["w2"]) + params["b2"]


def test_layer_norm_vjp_matches_autodiff():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (4, 16, 32), jnp.float32)
    scale = jax.random.normal(jax.random.fold_in(key, 1), (32,)) * 0.1 + 1.0
    bias = jax.random.normal(jax.random.fold_in(key, 2), (32,)) * 0.1
    ct = jax.random.normal(jax.random.fold_in(key, 3), (4, 16, 32))

    def loss(fn, x, s, b):
        return jnp.sum(fn(x, s, b) * ct)

    g1 = jax.grad(lambda *a: loss(L.layer_norm, *a), argnums=(0, 1, 2))(
        x, scale, bias)
    g2 = jax.grad(lambda *a: loss(_naive_ln, *a), argnums=(0, 1, 2))(
        x, scale, bias)
    for a, b, name in zip(g1, g2, ["dx", "dscale", "dbias"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4, err_msg=name)


def test_layer_norm_bf16_input():
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32)).astype(
        jnp.bfloat16)
    scale = jnp.ones((32,))
    bias = jnp.zeros((32,))
    y = L.layer_norm(x, scale, bias)
    assert y.dtype == jnp.bfloat16
    g = jax.grad(lambda x: jnp.sum(
        L.layer_norm(x, scale, bias).astype(jnp.float32)))(x)
    assert g.dtype == jnp.bfloat16


def test_mlp_vjp_matches_autodiff():
    key = jax.random.PRNGKey(42)
    D, F = 32, 64
    params = L.init_mlp(key, D, F)
    x = jax.random.normal(jax.random.fold_in(key, 9), (2, 8, D), jnp.float32)
    ct = jax.random.normal(jax.random.fold_in(key, 10), (2, 8, D))

    def loss_lean(params, x):
        return jnp.sum(
            L.apply_mlp(params, x, compute_dtype=jnp.float32) * ct)

    def loss_naive(params, x):
        return jnp.sum(_naive_mlp(params, x) * ct)

    g1 = jax.grad(loss_lean, argnums=(0, 1))(params, x)
    g2 = jax.grad(loss_naive, argnums=(0, 1))(params, x)
    flat1 = jax.tree_util.tree_leaves(g1)
    flat2 = jax.tree_util.tree_leaves(g2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3)


def test_mlp_vjp_under_scan_and_vmap():
    """The lean VJP must hold up inside the model's scan-over-layers."""
    key = jax.random.PRNGKey(7)
    D, F, N = 16, 32, 3
    stacked = jax.vmap(lambda k: L.init_mlp(k, D, F))(jax.random.split(key, N))
    x = jax.random.normal(jax.random.fold_in(key, 1), (2, 4, D))

    def run(stacked, x):
        def body(x, p):
            return L.apply_mlp(p, x, compute_dtype=jnp.float32), None

        y, _ = jax.lax.scan(body, x, stacked)
        return jnp.sum(y ** 2)

    def run_naive(stacked, x):
        def body(x, p):
            return _naive_mlp(p, x), None

        y, _ = jax.lax.scan(body, x, stacked)
        return jnp.sum(y ** 2)

    g1 = jax.grad(run)(stacked, x)
    g2 = jax.grad(run_naive)(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3)
