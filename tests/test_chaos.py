"""Chaos tests — work completes correctly while nodes die under it.

Reference tier: python/ray/tests/test_chaos.py:52-130
(_ray_start_chaos_cluster kills raylets on an interval; tasks/actors with
retries must still produce exact results).
"""
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.chaos


@pytest.fixture
def chaos_cluster(ray_start_cluster):
    """Head + 2 expendable worker nodes, plus a killer thread that
    terminates one worker node mid-run and replaces it."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)                       # head: driver only
    victims = [cluster.add_node(num_cpus=2, resources={"pool": 2})
               for _ in range(2)]
    cluster.connect()
    import ray_tpu

    yield cluster, ray_tpu, victims


def test_tasks_survive_node_death(chaos_cluster):
    cluster, ray_tpu, victims = chaos_cluster

    @ray_tpu.remote(num_cpus=0, resources={"pool": 0.5}, max_retries=5)
    def work(i):
        time.sleep(0.05)
        return i * i

    refs = [work.remote(i) for i in range(40)]

    killed = threading.Event()

    def killer():
        time.sleep(0.5)           # let work get in flight
        cluster.remove_node(victims[0])
        cluster.add_node(num_cpus=2, resources={"pool": 2})
        killed.set()

    t = threading.Thread(target=killer, daemon=True)
    t.start()
    out = ray_tpu.get(refs, timeout=180)
    t.join(timeout=30)
    assert killed.is_set()
    assert out == [i * i for i in range(40)]


def test_actor_restarts_under_churn(chaos_cluster):
    cluster, ray_tpu, victims = chaos_cluster

    @ray_tpu.remote(num_cpus=0, resources={"pool": 0.5}, max_restarts=5,
                    max_task_retries=5)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    counters = [Counter.remote() for _ in range(4)]
    # warm them up so they're placed before the kill
    assert ray_tpu.get([c.bump.remote() for c in counters], timeout=60) == \
        [1, 1, 1, 1]
    cluster.remove_node(victims[1])
    cluster.add_node(num_cpus=2, resources={"pool": 2})
    # survivors keep state; restarted ones restart from scratch — but every
    # call must SUCCEED (retries reroute through the restart)
    out = ray_tpu.get([c.bump.remote() for c in counters], timeout=120)
    assert all(v in (1, 2) for v in out)
    out2 = ray_tpu.get([c.bump.remote() for c in counters], timeout=120)
    assert [b - a for a, b in zip(out, out2)] == [1, 1, 1, 1]


def test_reconstruction_under_churn(chaos_cluster):
    """Objects produced before the kill are transparently rebuilt for
    consumers arriving after it."""
    cluster, ray_tpu, victims = chaos_cluster

    @ray_tpu.remote(num_cpus=0, resources={"pool": 0.5}, max_retries=3)
    def produce(i):
        return np.full(150_000, float(i))

    @ray_tpu.remote(num_cpus=0, resources={"pool": 0.5}, max_retries=3)
    def consume(arr):
        return float(arr[0]) + float(arr[-1])

    refs = [produce.remote(i) for i in range(6)]
    done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=120,
                           fetch_local=False)
    assert len(done) == len(refs)
    cluster.remove_node(victims[0])
    cluster.add_node(num_cpus=2, resources={"pool": 2})
    out = ray_tpu.get([consume.remote(r) for r in refs], timeout=180)
    assert out == [2.0 * i for i in range(6)]


def test_sigkill_os_node_process_recovery(tmp_path):
    """The hardest failure mode: SIGKILL a real node OS process (no
    graceful teardown at all) while tasks queue against its resources; a
    replacement node joins and every retried task completes. Exercises
    kernel-FIN connection failure, GCS death detection, transient lease
    retry, and queue re-spillback to the new node."""
    import json
    import signal
    import subprocess
    import sys

    cli = [sys.executable, "-m", "ray_tpu.scripts.cli"]
    out = subprocess.run(cli + ["start", "--head", "--num-cpus", "2"],
                         capture_output=True, text=True, timeout=90)
    assert out.returncode == 0, out.stderr
    address = [line for line in out.stdout.splitlines()
               if line.startswith("GCS address:")][0].split(": ")[1]
    out2 = subprocess.run(
        cli + ["start", "--address", address, "--num-cpus", "2",
               "--resources", json.dumps({"side": 2})],
        capture_output=True, text=True, timeout=90)
    assert out2.returncode == 0, out2.stderr
    try:
        import ray_tpu

        ray_tpu.init(address=address)

        @ray_tpu.remote(num_cpus=0, resources={"side": 0.5}, max_retries=5)
        def work(i):
            time.sleep(0.05)
            return i * 3

        refs = [work.remote(i) for i in range(20)]
        import os as _os

        pid_dir = "/tmp/ray_tpu/node_pids"
        victim = None
        for p in sorted(_os.listdir(pid_dir)):
            info = json.load(open(_os.path.join(pid_dir, p)))
            if not info.get("head"):
                victim = int(p)
        assert victim is not None
        time.sleep(0.3)
        _os.killpg(_os.getpgid(victim), signal.SIGKILL)
        out3 = subprocess.run(
            cli + ["start", "--address", address, "--num-cpus", "2",
                   "--resources", json.dumps({"side": 2})],
            capture_output=True, text=True, timeout=90)
        assert out3.returncode == 0, out3.stderr
        result = ray_tpu.get(refs, timeout=120)
        assert result == [i * 3 for i in range(20)]
        ray_tpu.shutdown()
    finally:
        subprocess.run(cli + ["stop"], capture_output=True, timeout=60)
