"""Dynamic/streaming task returns — ObjectRefGenerator.

Reference tier: python/ray/tests/test_generators.py (+ the
num_returns="dynamic" contract declared at python/ray/_raylet.pyx:168):
a task may yield a runtime-determined number of values, each stored as
its own object; streaming consumers start before the producer finishes;
closing the generator cancels the producer.
"""
import os
import tempfile
import time

import pytest


def test_dynamic_basic(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns="dynamic")
    def produce(n):
        for i in range(n):
            yield i * 10

    gen_ref = produce.remote(5)
    gen = ray.get(gen_ref)
    assert isinstance(gen, ray.ObjectRefGenerator)
    refs = list(gen)
    assert len(refs) == 5
    assert ray.get(refs) == [0, 10, 20, 30, 40]


def test_dynamic_len_and_repeat_get(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns="dynamic")
    def produce():
        yield "a"
        yield "b"

    gen = ray.get(produce.remote())
    assert len(gen) == 2
    refs = list(gen)
    # gets are repeatable
    assert ray.get(refs[0]) == "a"
    assert ray.get(refs[0]) == "a"
    # and the generator ref itself resolves again
    gen2 = ray.get(produce.remote())
    assert [ray.get(r) for r in gen2] == ["a", "b"]


def test_dynamic_zero_items(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns="dynamic")
    def produce():
        return iter(())

    gen = ray.get(produce.remote())
    assert list(gen) == []
    assert len(gen) == 0


def test_dynamic_large_items(ray_start_regular):
    """Items above the inline limit go through the shm store + object
    directory rather than the reply."""
    import numpy as np

    ray = ray_start_regular

    @ray.remote(num_returns="dynamic")
    def produce():
        for i in range(3):
            yield np.full((300_000,), i, dtype=np.int32)   # ~1.2 MB

    refs = list(ray.get(produce.remote()))
    assert len(refs) == 3
    for i, r in enumerate(refs):
        v = ray.get(r)
        assert v.shape == (300_000,) and int(v[0]) == i


def test_dynamic_non_iterable_errors(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns="dynamic")
    def produce():
        return 42

    with pytest.raises(ray.exceptions.TaskError, match="non-iterable"):
        ray.get(ray.get(produce.remote()))


def test_dynamic_error_mid_generation(ray_start_regular):
    """A producer that raises after k items: the stream yields the
    produced prefix, then surfaces the error (reference semantics)."""
    ray = ray_start_regular

    @ray.remote(num_returns="streaming")
    def produce():
        yield 1
        yield 2
        raise ValueError("boom at 2")

    gen = produce.remote()
    first = next(gen)
    assert ray.get(first) == 1
    assert ray.get(next(gen)) == 2
    with pytest.raises(ray.exceptions.TaskError, match="boom at 2"):
        next(gen)


def test_streaming_consume_while_producing(ray_start_regular):
    """The consumer reads item 0 BEFORE the producer finishes: the
    producer blocks after item 0 until the consumer (who has read it)
    drops a handshake file — progress proves streaming, not batching."""
    ray = ray_start_regular
    sync = tempfile.mktemp(prefix="gen_sync_")

    @ray.remote(num_returns="streaming")
    def produce(path):
        yield "first"
        deadline = time.time() + 30
        while not os.path.exists(path):   # wait for the consumer's ack
            if time.time() > deadline:
                raise TimeoutError("consumer never acked item 0")
            time.sleep(0.02)
        yield "second"

    gen = produce.remote(sync)
    assert ray.get(next(gen)) == "first"   # producer is still blocked
    with open(sync, "w") as f:
        f.write("ack")
    try:
        assert ray.get(next(gen)) == "second"
        with pytest.raises(StopIteration):
            next(gen)
    finally:
        os.unlink(sync)


def test_streaming_early_close_cancels_producer(ray_start_regular):
    """close() after the first item stops the producer: its progress
    file stops growing (reference: deleting a streaming generator
    cancels the task)."""
    ray = ray_start_regular
    progress = tempfile.mktemp(prefix="gen_prog_")

    @ray.remote(num_returns="streaming")
    def produce(path):
        for i in range(10_000):
            with open(path, "w") as f:
                f.write(str(i))
            yield i
            time.sleep(0.01)

    gen = produce.remote(progress)
    assert ray.get(next(gen)) == 0
    gen.close()
    # cancellation propagates between yields; give it a beat, then verify
    # progress has stopped
    time.sleep(1.0)
    with open(progress) as f:
        frozen = f.read()
    time.sleep(1.0)
    with open(progress) as f:
        assert f.read() == frozen, "producer kept running after close()"
    os.unlink(progress)
    with pytest.raises(StopIteration):
        next(gen)


def test_streaming_generator_not_serializable(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns="streaming")
    def produce():
        yield 1

    @ray.remote
    def consume(g):
        return 0

    gen = produce.remote()
    with pytest.raises(Exception, match="cannot be serialized"):
        ray.get(consume.remote(gen))
    gen.close()


def test_dynamic_refs_borrowable(ray_start_regular):
    """Item refs pass to other tasks like any ObjectRef."""
    ray = ray_start_regular

    @ray.remote(num_returns="dynamic")
    def produce():
        for i in range(4):
            yield i

    @ray.remote
    def add(a, b):
        return a + b

    refs = list(ray.get(produce.remote()))
    assert ray.get(add.remote(refs[1], refs[2])) == 3


def test_dynamic_on_actor_method(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Chunker:
        def __init__(self):
            self.calls = 0

        def chunks(self, n):
            self.calls += 1
            for i in range(n):
                yield (self.calls, i)

    c = Chunker.remote()
    gen = ray.get(c.chunks.options(num_returns="dynamic").remote(3))
    vals = ray.get(list(gen))
    assert vals == [(1, 0), (1, 1), (1, 2)]


def test_streaming_actor_early_close_cancels(ray_start_regular):
    """close() on a streaming ACTOR-method generator also stops the
    producer (the cancel routes through the actor connection)."""
    ray = ray_start_regular
    progress = tempfile.mktemp(prefix="gen_aprog_")

    @ray.remote
    class Producer:
        def produce(self, path):
            for i in range(10_000):
                with open(path, "w") as f:
                    f.write(str(i))
                yield i
                time.sleep(0.01)

    p = Producer.remote()
    gen = p.produce.options(num_returns="streaming").remote(progress)
    assert ray.get(next(gen)) == 0
    gen.close()
    time.sleep(1.0)
    with open(progress) as f:
        frozen = f.read()
    time.sleep(1.0)
    with open(progress) as f:
        assert f.read() == frozen, "actor generator kept running"
    os.unlink(progress)


def test_streaming_completed_ref(ray_start_regular):
    """completed() resolves once the producer finishes."""
    ray = ray_start_regular

    @ray.remote(num_returns="streaming")
    def produce():
        for i in range(3):
            yield i

    gen = produce.remote()
    done_ref = gen.completed()
    final = ray.get(done_ref)        # blocks until the task completes
    assert [ray.get(r) for r in final] == [0, 1, 2]
    # the live stream still iterates too
    assert [ray.get(r) for r in gen] == [0, 1, 2]
