"""Dashboard endpoints, GCS snapshot fault tolerance, distributed Train.

Reference tier: dashboard module tests, test_gcs_fault_tolerance.py, and
train's process-group setup tests.
"""
import json
import urllib.request

import numpy as np
import pytest


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=15) as r:
        return r.status, r.read()


def test_dashboard_endpoints(ray_start_regular):
    ray_tpu = ray_start_regular
    from ray_tpu.dashboard import DashboardServer

    @ray_tpu.remote
    def work(x):
        return x + 1

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    assert ray_tpu.get(work.remote(1)) == 2
    a = A.remote()
    assert ray_tpu.get(a.ping.remote()) == 1

    server = DashboardServer(address=None, port=0).start()
    try:
        status, body = _get(server.port, "/api/nodes")
        assert status == 200
        nodes = json.loads(body)
        assert sum(1 for n in nodes if n["Alive"]) == 1
        status, body = _get(server.port, "/api/actors")
        assert any(x["State"] == "ALIVE" for x in json.loads(body))
        status, body = _get(server.port, "/api/cluster_status")
        assert "Nodes: 1 alive" in json.loads(body)["summary"]
        status, body = _get(server.port, "/api/timeline")
        trace = json.loads(body)
        assert any(e["cat"] == "task" for e in trace)
        status, body = _get(server.port, "/metrics")
        assert status == 200
        status, body = _get(server.port, "/")
        assert b"/api/nodes" in body
        status, _ = _get(server.port, "/-/healthz")
        assert status == 200
    finally:
        server.stop()


def test_gcs_snapshot_restart(tmp_path):
    """Kill the GCS; a restart from its snapshot recovers the KV (function
    table, jobs), named-actor registry, and cluster identity — the
    reference's Redis-backed FT scope for metadata."""
    from ray_tpu._private.gcs import GcsServer

    snap = str(tmp_path / "gcs_snapshot")
    gcs = GcsServer(snapshot_path=snap).start()
    from ray_tpu._private.protocol import RpcClient

    c = RpcClient(gcs.addr)
    c.call("kv_put", ns="funcs", key=b"fn1", value=b"blob-1")
    c.call("kv_put", ns="jobs", key=b"job1",
           value=json.dumps({"status": "SUCCEEDED"}).encode())
    cluster_id = gcs.cluster_id
    gcs.rpc_save_snapshot()
    c.close()
    gcs.stop()

    gcs2 = GcsServer(snapshot_path=snap).start()
    try:
        c2 = RpcClient(gcs2.addr)
        assert c2.call("kv_get", ns="funcs", key=b"fn1") == b"blob-1"
        job = json.loads(c2.call("kv_get", ns="jobs", key=b"job1"))
        assert job["status"] == "SUCCEEDED"
        assert gcs2.cluster_id == cluster_id
        c2.close()
    finally:
        gcs2.stop()


def test_train_distributed_two_processes(ray_start_regular):
    """The Train stack through JaxConfig(distributed=True): two worker
    processes jointly initialize a jax.distributed world (single-device CPU
    each) and train data-parallel — the multi-host TPU pod path on the CI
    substrate (round-2 weak finding #6: this path was never tested)."""
    import ray_tpu
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.backend_executor import JaxConfig
    from ray_tpu.train.trainer import JaxTrainer

    def train_loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.air import session

        assert jax.process_count() == 2, \
            f"expected a 2-process jax world, got {jax.process_count()}"
        rank = jax.process_index()
        # data-parallel gradient agreement via the collective group
        from ray_tpu.util import collective as col

        w = np.zeros(4, np.float32)
        for step in range(2):
            local_grad = np.full(4, float(rank + 1), np.float32)
            total = col.allreduce(local_grad, group_name="train_dp")
            w = w - 0.1 * total / 2
            session.report({"step": step, "w0": float(w[0]),
                            "rank": rank})

    trainer = JaxTrainer(
        train_loop_per_worker=train_loop,
        scaling_config=ScalingConfig(num_workers=2),
        backend_config=JaxConfig(distributed=True,
                                 collective_backend="host"),
    )
    result = trainer.fit()
    # grad mean = (1+2)/2 = 1.5 → after 2 steps w0 = -0.3
    assert abs(result.metrics["w0"] - (-0.3)) < 1e-6


def test_dashboard_serve_route(ray_start_regular):
    from ray_tpu import serve
    from ray_tpu.dashboard import DashboardServer

    serve.start(http_options={"host": "127.0.0.1", "port": 0})

    @serve.deployment
    def hello(_req=None):
        return "hi"

    serve.run(hello.bind(), name="dashapp", route_prefix="/hello")
    server = DashboardServer(address=None, port=0).start()
    try:
        status, body = _get(server.port, "/api/serve")
        assert status == 200
        apps = json.loads(body)["applications"]
        assert apps["dashapp"]["status"] == "RUNNING"
        assert "hello" in apps["dashapp"]["deployments"]
    finally:
        server.stop()
        serve.shutdown()
