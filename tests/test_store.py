"""Native shared-memory object store tests.

Analog of the reference's plasma tests
(/root/reference/src/ray/object_manager/plasma/test/) — create/seal/get
lifecycle, eviction under pressure, pinning, multi-process access, spilling.
"""
import multiprocessing
import os

import numpy as np
import pytest

from ray_tpu._private.store_client import StoreClient, StoreError


@pytest.fixture
def store(tmp_path):
    name = f"/raystore_test_{os.getpid()}"
    c = StoreClient(name, create=True, size=8 * 1024 * 1024, n_slots=256,
                    spill_dir=str(tmp_path / "spill"))
    yield c
    c.close()


def oid(i: int) -> bytes:
    return i.to_bytes(16, "little")


def test_put_get_roundtrip(store):
    data = os.urandom(1000)
    assert store.put(oid(1), data)
    buf = store.get(oid(1))
    assert buf.to_bytes() == data
    buf.release()


def test_put_idempotent(store):
    assert store.put(oid(1), b"x")
    assert not store.put(oid(1), b"y")
    assert store.get(oid(1)).to_bytes() == b"x"


def test_get_missing(store):
    assert store.get(oid(99)) is None
    assert not store.contains(oid(99))


def test_numpy_zero_copy(store):
    arr = np.arange(1024, dtype=np.float32)
    store.put(oid(2), arr.tobytes())
    buf = store.get(oid(2))
    out = np.frombuffer(buf.memoryview(), dtype=np.float32)
    np.testing.assert_array_equal(out, arr)
    buf.release()


def test_delete(store):
    store.put(oid(3), b"abc")
    store.delete(oid(3))
    assert not store.contains(oid(3))


def test_delete_pinned_object_refused(store):
    store.put(oid(4), b"abc")
    buf = store.get(oid(4))
    store.delete(oid(4))  # best-effort; must NOT remove while pinned
    assert store.contains(oid(4))
    buf.release()
    store.delete(oid(4))
    assert not store.contains(oid(4))


def test_lru_eviction_under_pressure(store):
    # 8 MiB heap, 1 MiB objects: keep inserting; the store must evict old
    # unpinned objects rather than fail.
    blob = os.urandom(1024 * 1024)
    for i in range(20):
        assert store.put(oid(100 + i), blob)
    stats = store.stats()
    assert stats["evictions"] > 0
    # newest object still resident
    assert store.contains(oid(119))


def test_pinned_objects_survive_eviction(store):
    pinned = store.put(oid(5), b"keep me") and store.get(oid(5))
    blob = os.urandom(1024 * 1024)
    for i in range(20):
        store.put(oid(200 + i), blob)
    assert store.get(oid(5)).to_bytes() == b"keep me"
    pinned.release()


def test_spill_and_restore(tmp_path):
    name = f"/raystore_spill_{os.getpid()}"
    c = StoreClient(name, create=True, size=2 * 1024 * 1024, n_slots=64,
                    spill_dir=str(tmp_path))
    try:
        big = os.urandom(1024 * 1024)
        c.put(oid(1), big)
        pin = c.get(oid(1))  # pin so it can't evict
        # This can't fit next to the pinned 1MiB in a 2MiB heap → spills.
        big2 = os.urandom(1500 * 1024)
        c.put(oid(2), big2)
        assert c.contains(oid(2))
        pin.release()
        got = c.get(oid(2))
        assert got.to_bytes() == big2
    finally:
        c.close()


def _child_reader(name, result_q):
    c = StoreClient(name, create=False)
    buf = c.get((42).to_bytes(16, "little"))
    result_q.put(buf.to_bytes() if buf else None)
    c.close()


def test_multiprocess_access():
    name = f"/raystore_mp_{os.getpid()}"
    c = StoreClient(name, create=True, size=4 * 1024 * 1024, n_slots=64)
    try:
        data = os.urandom(5000)
        c.put(oid(42), data)
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_reader, args=(name, q))
        p.start()
        got = q.get(timeout=30)
        p.join(timeout=10)
        assert got == data
    finally:
        c.close()


def test_object_too_large_without_spill():
    name = f"/raystore_big_{os.getpid()}"
    c = StoreClient(name, create=True, size=1024 * 1024, n_slots=64)
    try:
        with pytest.raises(StoreError):
            c.put(oid(1), os.urandom(4 * 1024 * 1024))
    finally:
        c.close()


def test_zero_length_object(store):
    assert store.put(oid(7), b"")
    buf = store.get(oid(7))
    assert buf is not None and buf.to_bytes() == b""


def test_bad_id_rejected(store):
    with pytest.raises(ValueError):
        store.put(b"short", b"x")
    with pytest.raises(ValueError):
        store.get(b"short")


def test_many_small_objects(store):
    for i in range(150):
        store.put(oid(1000 + i), f"value-{i}".encode())
    for i in range(150):
        buf = store.get(oid(1000 + i))
        if buf is not None:  # some may be evicted under table pressure
            assert buf.to_bytes() == f"value-{i}".encode()
