"""Owner-based object directory.

Reference: src/ray/object_manager/ownership_based_object_directory.h —
object locations live with the OWNING worker; borrowers and the owner
resolve through it, and the GCS plays no per-object role on the pull
path. These tests pin the three load-bearing properties: zero GCS
directory traffic on the put/get hot path, owner-side location records
for remote task returns, and borrower resolution through the owner.
"""
import numpy as np


class _GcsSpy:
    """Wraps a CoreWorker's GCS client, recording call/push method names."""

    def __init__(self, inner):
        self._inner = inner
        self.methods: list[str] = []

    def call(self, method, *a, **kw):
        self.methods.append(method)
        return self._inner.call(method, *a, **kw)

    def call_async(self, method, **kw):
        self.methods.append(method)
        return self._inner.call_async(method, **kw)

    def push(self, method, **kw):
        self.methods.append(method)
        return self._inner.push(method, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


DIRECTORY_METHODS = {"add_object_location", "remove_object_location",
                     "get_object_locations"}


def test_zero_gcs_calls_on_object_hot_path(ray_start_regular):
    """put/get of many objects — including task returns big enough to ride
    the shm store — produces NO GCS object-directory RPCs, and the GCS
    call count stays flat in the object count (the round-5 done
    criterion)."""
    import ray_tpu
    from ray_tpu._private.worker_runtime import current_worker

    @ray_tpu.remote
    def produce(i):
        return np.full(50_000, float(i))   # 400 KB → stored, not inlined

    # warm up the submission path (function registration etc.)
    ray_tpu.get(produce.remote(0))

    w = current_worker()
    spy = _GcsSpy(w.gcs)
    w.gcs = spy
    try:
        refs = [ray_tpu.put(i) for i in range(50)]
        assert ray_tpu.get(refs) == list(range(50))
        big = [produce.remote(i) for i in range(8)]
        for i, arr in enumerate(ray_tpu.get(big)):
            assert arr[0] == float(i)
        hits = [m for m in spy.methods if m in DIRECTORY_METHODS]
        assert hits == [], f"GCS directory RPCs on the hot path: {hits}"
        # flatness: GCS traffic must not scale with the 58 objects moved
        assert len(spy.methods) < 30, (
            f"GCS call count scales with object count: {spy.methods}")
    finally:
        w.gcs = spy._inner


def test_owner_records_remote_task_return_locations(ray_start_cluster):
    """A big return stored on another node lands in the OWNER's directory
    via the task reply (no directory RPC), and the owner pulls it through
    that record."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.connect()
    import ray_tpu
    from ray_tpu._private.worker_runtime import current_worker

    @ray_tpu.remote(num_cpus=0, resources={"side": 0.5})
    def produce():
        return np.arange(100_000, dtype=np.float64)   # 800 KB

    ref = produce.remote()
    done, _ = ray_tpu.wait([ref], timeout=60, fetch_local=False)
    assert done
    w = current_worker()
    nodes, size = w._loc_snapshot(ref.id)
    assert nodes, "owner directory has no record of the stored return"
    assert nodes[0]["NodeID"] != w.node_id, "return should be remote"
    assert size == 0 or size > 100_000
    out = ray_tpu.get(ref, timeout=30)
    assert out.sum() == np.arange(100_000, dtype=np.float64).sum()


def test_borrower_resolves_big_value_through_owner(ray_start_cluster):
    """A borrower task on node B gets a driver-owned big object: the owner
    answers with holder locations ("at") and the bytes cross the data
    plane, not the owner's pickle channel."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.connect()
    import ray_tpu

    payload = np.random.default_rng(7).standard_normal(200_000)  # 1.6 MB
    ref = ray_tpu.put(payload)

    @ray_tpu.remote(num_cpus=0, resources={"side": 0.5})
    def consume(arr):
        return float(arr.sum())

    assert abs(ray_tpu.get(consume.remote(ref), timeout=60)
               - float(payload.sum())) < 1e-6


def test_locate_object_rpc_shapes(ray_start_regular):
    """locate_object: ready+nodes for a stored object, not-ready for an
    unknown id."""
    import os

    import ray_tpu
    from ray_tpu._private.worker_runtime import current_worker

    w = current_worker()
    ref = ray_tpu.put(np.zeros(64_000))
    reply = w.rpc_locate_object(None, ref.id)
    assert reply["ready"] and reply["nodes"]
    assert reply["nodes"][0]["NodeID"] == w.node_id
    missing = w.rpc_locate_object(None, os.urandom(16))
    assert not missing["ready"] and not missing["nodes"]
