"""OOM protection tests (reference: common/memory_monitor.h:88 +
raylet/worker_killing_policy.h:30 — a memory-hog worker is killed with a
retriable error instead of taking down the node)."""
import os
import time

import numpy as np
import pytest


def test_monitor_threshold_and_hysteresis():
    from ray_tpu._private.memory_monitor import MemoryMonitor

    usage = {"v": 0.5}
    fired = []
    mon = MemoryMonitor(lambda used, total: fired.append(used),
                        threshold=0.9, interval_s=3600, hysteresis=0.05,
                        usage_fn=lambda: (usage["v"] * 100, 100))
    mon.tick()
    assert fired == []
    usage["v"] = 0.95
    mon.tick()
    assert len(fired) == 1           # fires on crossing
    mon.tick()
    assert len(fired) == 1           # disarmed while above
    usage["v"] = 0.88                # within hysteresis band: stay disarmed
    mon.tick()
    usage["v"] = 0.95
    mon.tick()
    assert len(fired) == 1
    usage["v"] = 0.80                # below threshold - hysteresis: re-arm
    mon.tick()
    usage["v"] = 0.97
    mon.tick()
    assert len(fired) == 2


def test_pick_victim_newest_task_first():
    from ray_tpu._private.memory_monitor import pick_victim

    workers = [
        {"pid": 11, "task_started_at": 100.0, "id": "old"},
        {"pid": 22, "task_started_at": 200.0, "id": "new"},
        {"pid": 33, "task_started_at": None, "id": "idle"},
    ]
    assert pick_victim(workers)["id"] == "new"
    assert pick_victim([]) is None
    # only idle workers: falls back to largest RSS (own pid beats bogus)
    import os

    me = {"pid": os.getpid(), "task_started_at": None, "id": "me"}
    bogus = {"pid": 99999999, "task_started_at": None, "id": "gone"}
    assert pick_victim([bogus, me])["id"] == "me"


def test_node_memory_usage_sane():
    from ray_tpu._private.memory_monitor import node_memory_usage

    used, total = node_memory_usage()
    assert 0 < used <= total


def _wire_worker_rss_usage(threshold_gb: float = 2.0):
    """Point the running monitor at the sum of WORKER RSS (measured from
    /proc) instead of /proc/meminfo: this host's sandboxed kernel
    serves a SYNTHETIC meminfo that barely registers real allocations
    (a 3 GB subprocess moved MemTotal-MemAvailable by +0.6 GB), so the
    meminfo-driven E2E flaked on kernel accounting, not on the kill
    plumbing these tests exist to prove. The full pipeline still runs:
    tick -> pressure -> victim choice -> KV reason -> SIGKILL -> owner
    error mapping."""
    from ray_tpu._private import api
    from ray_tpu._private.memory_monitor import process_rss

    raylet = api._global_node.raylet
    # threshold crossed exactly when summed worker RSS exceeds
    # threshold_gb: total = 2*threshold_gb with the threshold at 50%
    total = int(threshold_gb * 2 * 2**30)

    def usage_fn():
        with raylet._lock:
            pids = [h.proc.pid for h in raylet._workers.values()
                    if h.proc is not None and h.proc.poll() is None]
        return sum(process_rss(p) for p in pids), total

    raylet._mem_monitor._usage_fn = usage_fn
    raylet._mem_monitor.threshold = 0.5   # scoped to this instance


def test_oom_kill_names_culprit_and_retry_succeeds():
    """A ballooning task is killed by the raylet with an error naming the
    culprit; a smaller retry succeeds; the node survives."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024,
                 system_config={"memory_monitor_refresh_ms": 100})
    _wire_worker_rss_usage(threshold_gb=2.0)   # hog's 3 GB crosses it
    try:
        state = {"attempt": 0}

        @ray_tpu.remote(max_retries=2)
        def maybe_hog(path):
            # first attempt balloons ~3 GB; the retry is modest. Attempt
            # count is tracked on disk because the retry may land in a
            # different worker process.
            import os

            with open(path, "a") as f:
                f.write("x")
            n = os.path.getsize(path)
            if n == 1:
                ballast = bytearray(3 * 2**30)   # ~3 GB
                # TOUCH the pages: an untouched bytearray is lazily
                # zero-mapped and never becomes RSS (whether it does
                # depends on allocator arena reuse — flaky kills)
                ballast[::4096] = b"x" * len(ballast[::4096])
                time.sleep(30)                   # hold until killed
                return ("survived", len(ballast))
            return ("retried-ok", n)

        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".attempts") as tf:
            result = ray_tpu.get(maybe_hog.remote(tf.name), timeout=120)
        assert result[0] == "retried-ok", result

        # the node survived: unrelated work still runs
        @ray_tpu.remote
        def ping():
            return "pong"

        assert ray_tpu.get(ping.remote(), timeout=60) == "pong"
    finally:
        ray_tpu.shutdown()


def test_oom_kill_error_is_named_when_retries_exhausted():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu.exceptions import OutOfMemoryError

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024,
                 system_config={"memory_monitor_refresh_ms": 100})
    _wire_worker_rss_usage(threshold_gb=2.0)
    try:
        @ray_tpu.remote(max_retries=0)
        def hog():
            ballast = bytearray(3 * 2**30)
            ballast[::4096] = b"x" * len(ballast[::4096])   # make it RSS
            time.sleep(30)
            return len(ballast)

        with pytest.raises(OutOfMemoryError) as ei:
            ray_tpu.get(hog.remote(), timeout=120)
        # the error names the culprit (rss + node context)
        msg = str(ei.value).lower()
        assert "memory" in msg and ("rss" in msg or "gb" in msg), msg
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
