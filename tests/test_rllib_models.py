"""Model zoo (CNN/LSTM) + multi-learner scaling + env throughput.

Reference tier: rllib/models tests (VisionNetwork/LSTM wrappers) and
core/learner/learner_group tests (N learners, grad all-reduce parity
with 1 learner).
"""
import numpy as np
import pytest


def test_cnn_policy_shapes():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import cnn_policy_apply, init_cnn_policy

    params = init_cnn_policy(jax.random.PRNGKey(0), (16, 16, 3), 4)
    obs = jnp.ones((7, 16, 16, 3))
    logits, value = jax.jit(cnn_policy_apply)(params, obs)
    assert logits.shape == (7, 4) and value.shape == (7,)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_lstm_policy_carries_state():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import (init_lstm_policy,
                                      lstm_policy_apply,
                                      lstm_policy_initial_state,
                                      lstm_policy_unroll)

    params = init_lstm_policy(jax.random.PRNGKey(0), 4, 2, hidden=16)
    state = lstm_policy_initial_state(16, batch=3)
    obs = jnp.ones((3, 4))
    logits1, _v, state1 = lstm_policy_apply(params, obs, state)
    logits2, _v, _state2 = lstm_policy_apply(params, obs, state1)
    assert logits1.shape == (3, 2)
    # state matters: same obs, different carry -> different logits
    assert not np.allclose(np.asarray(logits1), np.asarray(logits2))

    seq = jnp.ones((5, 3, 4))
    logits_seq, values_seq, final = lstm_policy_unroll(params, seq, state)
    assert logits_seq.shape == (5, 3, 2) and values_seq.shape == (5, 3)
    # scan step 0 == single step from the same carry
    assert np.allclose(np.asarray(logits_seq[0]), np.asarray(logits1),
                       atol=1e-5)


def test_learner_group_matches_single_learner():
    """The 8-way data-parallel step produces the SAME update as one
    learner on the full batch (pmean of shard grads == full-batch
    grad): the multi-learner scaling contract."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.learner_group import LearnerGroup

    def loss_fn(params, mb):
        pred = mb["x"] @ params["w"]
        loss = jnp.mean((pred - mb["y"]) ** 2)
        return loss, {"mse": loss}

    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(64, 8)).astype(np.float32),
             "y": rng.normal(size=(64,)).astype(np.float32)}
    w0 = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))

    group = LearnerGroup(loss_fn, {"w": w0}, lr=1e-2)
    assert group.num_learners == 8     # conftest forces 8 CPU devices
    out = group.update(batch)
    assert out["num_learners"] == 8 and np.isfinite(out["loss"])

    # single-learner reference update
    opt = optax.adam(1e-2)
    st = opt.init({"w": w0})
    (_l, _aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        {"w": w0}, {k: jnp.asarray(v) for k, v in batch.items()})
    upd, _ = opt.update(grads, st, {"w": w0})
    expect = optax.apply_updates({"w": w0}, upd)
    assert np.allclose(np.asarray(group.params["w"]),
                       np.asarray(expect["w"]), atol=1e-5), (
        "dp update diverged from single-learner update")


def test_learner_group_truncates_ragged_batch():
    import jax.numpy as jnp

    from ray_tpu.rllib.learner_group import LearnerGroup

    def loss_fn(params, mb):
        loss = jnp.mean((mb["x"] @ params["w"]) ** 2)
        return loss, {}

    group = LearnerGroup(loss_fn, {"w": jnp.ones((4,))}, lr=1e-3)
    out = group.update({"x": np.ones((67, 4), np.float32)})   # 67 % 8 != 0
    assert np.isfinite(out["loss"])


def test_vectorized_env_throughput_number(ray_start_regular):
    """Record a steps/s number for the sampling plane (weak #7 asked for
    a vectorized-env throughput measurement; the assertion is a sanity
    floor, the number itself prints for PERF.md)."""
    import time

    import jax

    from ray_tpu.rllib.models import init_policy
    from ray_tpu.rllib.rollout_worker import RolloutWorker

    w = RolloutWorker("CartPole-v1", num_envs=8, seed=0)
    params = init_policy(jax.random.PRNGKey(0), *w.spaces())
    w.sample(params, 16)                     # warm the jit
    t0 = time.time()
    batch = w.sample(params, 64)
    dt = time.time() - t0
    steps = len(batch["obs"])
    rate = steps / dt
    print(f"\nvectorized-env throughput: {rate:.0f} env-steps/s "
          f"({steps} steps in {dt:.2f}s, 8 envs)")
    assert steps == 8 * 64
    assert rate > 200, f"sampling plane unreasonably slow: {rate}/s"
