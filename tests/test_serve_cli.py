"""`ray-tpu serve` CLI (reference: `serve run/status/shutdown` CLI,
python/ray/serve/scripts.py)."""
import json
import subprocess
import sys
import time
import urllib.request

import pytest


APP_MODULE = '''
from ray_tpu import serve


@serve.deployment
def hello(request):
    return {"msg": "hi from cli"}


app = hello.bind()
'''


def test_serve_run_status_shutdown(ray_start_regular, tmp_path,
                                   monkeypatch):
    from ray_tpu._private.worker_runtime import current_worker

    (tmp_path / "cli_app.py").write_text(APP_MODULE)
    gcs = current_worker().gcs.addr
    address = f"{gcs[0]}:{gcs[1]}"
    env = dict(__import__("os").environ)
    env["PYTHONPATH"] = f"{tmp_path}:{env.get('PYTHONPATH', '')}"

    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "serve", "run",
         "cli_app:app", "--address", address, "--route-prefix", "/cli",
         "--non-blocking"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    out, _ = proc.communicate(timeout=180)
    assert proc.returncode == 0, out
    port = json.loads(out.strip().splitlines()[-1])["http_port"]

    # the app answers over HTTP
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/cli", timeout=30) as resp:
        assert json.loads(resp.read())["msg"] == "hi from cli"

    status = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "serve", "status",
         "--address", address],
        env=env, capture_output=True, text=True, timeout=120)
    assert status.returncode == 0, status.stderr
    payload = json.loads(status.stdout)
    assert payload["default"]["status"] == "RUNNING", payload
    assert "hello" in payload["default"]["deployments"], payload

    down = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "serve",
         "shutdown", "--address", address],
        env=env, capture_output=True, text=True, timeout=120)
    assert down.returncode == 0, down.stderr

    # the detached proxy must die with the instance even though shutdown
    # ran in a DIFFERENT process than the deploy (no local handle)
    deadline = time.monotonic() + 30
    dead = False
    while time.monotonic() < deadline and not dead:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/cli", timeout=3)
            time.sleep(0.5)
        except Exception:
            dead = True
    assert dead, "HTTP proxy still answering after serve shutdown"


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v", "-x"]))
