"""Tune tests: variant generation, trial execution, ASHA early stopping,
PBT exploit (the reference's tune/tests tier)."""
import numpy as np
import pytest


def test_variant_generator():
    from ray_tpu.tune.search import BasicVariantGenerator, choice, grid_search, uniform

    space = {"lr": grid_search([0.1, 0.01]),
             "wd": uniform(0, 1),
             "opt": choice(["adam", "sgd"]),
             "fixed": 7}
    configs = BasicVariantGenerator(space, num_samples=3, seed=0).generate()
    assert len(configs) == 6     # 2 grid x 3 samples
    assert {c["lr"] for c in configs} == {0.1, 0.01}
    assert all(0 <= c["wd"] <= 1 for c in configs)
    assert all(c["fixed"] == 7 for c in configs)


def test_tuner_basic(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu import tune

    def objective(config):
        from ray_tpu.air import session

        score = -(config["x"] - 3) ** 2
        session.report({"score": score})

    grid = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    ).fit()
    assert len(grid) == 5
    best = grid.get_best_result()
    assert best.metrics["score"] == 0


def test_tuner_with_checkpoint(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu import tune

    def objective(config):
        from ray_tpu.air import Checkpoint, session

        for i in range(3):
            session.report({"v": config["x"] * i},
                           checkpoint=Checkpoint.from_dict({"iter": i}))

    grid = tune.run(objective, config={"x": tune.grid_search([1, 2])},
                    metric="v", mode="max")
    best = grid.get_best_result()
    assert best.metrics["v"] == 4
    assert best.checkpoint.to_dict()["iter"] == 2


def test_asha_stops_bad_trials(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu import tune

    def objective(config):
        import time as _time

        from ray_tpu.air import session

        for step in range(20):
            # pace the steps: ASHA can only stop a trial it observes
            # RUNNING alongside its bracket peers — an instant 20-step
            # burst finishes before late-starting peers report (trial
            # starts serialize behind the worker-startup gate, ~0.5 s
            # per trial on this 1-core host, so each trial must span
            # several seconds to guarantee overlap)
            _time.sleep(0.2)
            session.report({"acc": config["quality"] * (step + 1)})

    sched = tune.AsyncHyperBandScheduler(metric="acc", mode="max",
                                         grace_period=2, max_t=20,
                                         reduction_factor=2)
    grid = tune.run(objective,
                    config={"quality": tune.grid_search(
                        [0.1, 0.2, 0.9, 1.0])},
                    metric="acc", mode="max", scheduler=sched)
    statuses = {t.config["quality"]: t.status for t in grid.trials}
    iters = {t.config["quality"]: len(t.results) for t in grid.trials}
    # the best trial must run further than the worst
    assert iters[1.0] > iters[0.1]
    assert grid.get_best_result().metrics["acc"] == pytest.approx(20.0)


def test_pbt_exploit(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu import tune

    def objective(config):
        from ray_tpu.air import Checkpoint, session

        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["score"]
        score = start
        for step in range(8):
            import time as _time

            # pace the steps (same rationale as the ASHA test): PBT can
            # only exploit trials it observes RUNNING together, and trial
            # starts serialize behind the worker-startup gate
            _time.sleep(0.3)
            score += config["lr"]
            session.report({"score": score},
                           checkpoint=Checkpoint.from_dict(
                               {"score": score}))

    sched = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"lr": [0.5, 1.0, 2.0]}, seed=1)
    grid = tune.run(objective,
                    config={"lr": tune.grid_search([0.01, 2.0])},
                    metric="score", mode="max", scheduler=sched)
    best = grid.get_best_result()
    # without exploit the 0.01 trial tops out at 0.08; exploit should lift
    # the population's floor well beyond it
    worst_final = min(t.last_result["score"] for t in grid.trials
                      if t.results)
    assert worst_final > 1.0, f"PBT exploit ineffective: {worst_final}"


def test_trainer_in_tuner(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu import tune
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import JaxTrainer

    def loop(config):
        from ray_tpu.air import session

        session.report({"final": config.get("boost", 0) + 1})

    trainer = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1))
    grid = tune.Tuner(
        trainer,
        param_space={"boost": tune.grid_search([10, 20])},
        tune_config=tune.TuneConfig(metric="final", mode="max"),
    ).fit()
    assert grid.get_best_result().metrics["final"] == 21


def test_tpe_searcher_unit():
    """TPE concentrates samples near the optimum after startup trials
    (pure searcher loop, no cluster)."""
    from ray_tpu.tune.search import TPESearcher, uniform

    searcher = TPESearcher(
        param_space={"x": uniform(-10, 10)},
        metric="score", mode="max", n_startup_trials=8, seed=0)
    late = []
    for i in range(60):
        tid = f"t{i}"
        config = searcher.suggest(tid)
        score = -(config["x"] - 3.0) ** 2
        searcher.on_trial_complete(tid, result={"score": score})
        if i >= 40:
            late.append(config["x"])
    # after exploration the sampler should hover near x=3
    assert abs(float(np.median(late)) - 3.0) < 2.0, np.median(late)


def test_concurrency_limiter_unit():
    from ray_tpu.tune.search import ConcurrencyLimiter, TPESearcher, uniform

    inner = TPESearcher(param_space={"x": uniform(0, 1)},
                        metric="m", mode="max", seed=1)
    limiter = ConcurrencyLimiter(inner, max_concurrent=2)
    limiter.set_search_properties("m", "max")
    assert limiter.suggest("a") is not None
    assert limiter.suggest("b") is not None
    assert limiter.suggest("c") is None          # saturated
    limiter.on_trial_complete("a", result={"m": 1.0})
    assert limiter.suggest("c") is not None       # slot freed


def test_tuner_with_search_alg(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu import tune

    def objective(config):
        from ray_tpu.air import session

        session.report({"score": -(config["x"] - 3) ** 2})

    grid = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(-10, 10)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=12,
            max_concurrent_trials=3,
            search_alg=tune.TPESearcher(n_startup_trials=4, seed=0)),
    ).fit()
    assert len(grid) == 12
    best = grid.get_best_result()
    assert best.metrics["score"] > -20   # found the neighborhood of x=3


def test_with_parameters(ray_start_regular):
    import numpy as _np

    from ray_tpu import tune
    from ray_tpu.tune.tuner import with_parameters

    big = _np.arange(1000, dtype=_np.float64)

    def objective(config, data=None):
        from ray_tpu.air import session

        session.report({"score": float(data.sum()) * config["scale"]})

    grid = tune.Tuner(
        with_parameters(objective, data=big),
        param_space={"scale": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    ).fit()
    assert len(grid) == 2
    assert grid.get_best_result().metrics["score"] == big.sum() * 2.0
