"""Crash-consistent sharded checkpointing with world-elastic restore
(late-alphabet; sequenced after the tier-1 timeout horizon by design).

Covers the sharded-checkpoint tentpole end to end:

- the sanctioned durability idiom (`_private/atomic_write.py`) under the
  fault DSL's disk primitives — `torn_write:` leaves exactly what a
  crash mid-write leaves (truncated temp, final path absent),
  `corrupt_file:` flips one byte that restore's digest check must catch,
  `kill_actor:` at the disk boundary dies mid-shard-write (subprocess
  pinned + the gang E2E);
- two-phase commit: a generation without MANIFEST.json is torn and
  invisible to restore; the groupless multi-rank directory-scan ack and
  the live-gang allgather ack both produce a manifest naming every
  shard;
- corruption detection + fallback: digest/size/missing-shard/plan
  mismatches quarantine the generation (CHECKPOINT_QUARANTINED naming
  shard + reason) and restore falls back to the newest complete one;
- world-elastic restore: saved at world 4, restored at 2/4/1 bit-exact
  vs the fixed-world oracle — params AND optimizer-state slots
  (reslice_spans index math), with the opt_state gauge proving no rank
  materialized full optimizer state;
- `num_to_keep` pruning across elastic restarts (4 -> 2 -> 4) that never
  deletes the last verified-complete generation;
- the `Checkpoint` tmpdir leak fix (satellite) and the RTD5xx durability
  lint pass (satellite).

Chaos tests are seeded + schedule-driven: the failure banner's
RAY_TPU_FAULT_SEED/RAY_TPU_FAULT_SCHEDULE pair replays them exactly.
"""
import gc
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

GROUP = "zzck"
STEPS = 5
BB = 2048          # bucket_bytes small enough for multi-bucket plans


def _params(seed=0, n=1500):
    rng = np.random.default_rng(seed)
    return {
        "w1": rng.standard_normal((n // 3, 3)).astype(np.float32),
        "b1": rng.standard_normal((7,)).astype(np.float32),
        "w2": rng.standard_normal((n // 2,)).astype(np.float32),
    }


def _leaves(params):
    from ray_tpu.parallel import sharding as sh

    leaves, _ = sh.flatten_tree(params)
    return [np.asarray(x) for x in leaves]


def _assert_tree_equal(a, b, msg=""):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(x, np.asarray(y)), msg


def _events_count(kind):
    from ray_tpu._private import events

    return sum(1 for e in events.snapshot() if e["kind"] == kind)


@pytest.fixture
def fault_plane():
    """In-process injector install/uninstall (the env pair drives
    spawned processes; this drives THIS process's disk boundaries)."""
    from ray_tpu._private import fault_injection as fi

    def _install(seed, schedule):
        return fi.install(seed, schedule)

    yield _install
    fi.uninstall()


# -------------------------------------------------- atomic_write + DSL


def test_disk_schedule_parsing():
    from ray_tpu._private.fault_injection import (_DISK_ACTIONS,
                                                  FaultInjector)

    assert {"torn_write", "corrupt_file", "kill_actor"} <= _DISK_ACTIONS
    inj = FaultInjector(
        3, "torn_write:ckpt.shard:#1;corrupt_file:ckpt.manifest:%2")
    assert len(inj._disk_rules) == 2
    actions = {r.action for r in inj._disk_rules}
    assert actions == {"torn_write", "corrupt_file"}
    # kill_actor is BOTH a reply action and a disk action; the disk
    # registration must not be lost to the reply bucket
    inj2 = FaultInjector(3, "kill_actor:rank1.shard:#2")
    assert [r.action for r in inj2._disk_rules] == ["kill_actor"]


def test_atomic_write_clean_then_torn_then_corrupt(tmp_path, fault_plane):
    from ray_tpu._private.atomic_write import TornWriteError, atomic_write

    path = str(tmp_path / "blob.bin")
    atomic_write(path, b"v1" * 100, tag="ckpt", name="shard")
    assert open(path, "rb").read() == b"v1" * 100
    assert os.listdir(tmp_path) == ["blob.bin"]   # no temp residue

    # torn: the final path keeps the OLD bytes, a truncated temp is the
    # only trace of the new write — exactly a crash between write+rename
    fault_plane(11, "torn_write:ckpt.shard:#1")
    with pytest.raises(TornWriteError):
        atomic_write(path, b"v2" * 100, tag="ckpt", name="shard")
    assert open(path, "rb").read() == b"v1" * 100
    residue = [n for n in os.listdir(tmp_path) if n != "blob.bin"]
    assert residue, "torn write must leave the truncated temp behind"
    assert os.path.getsize(str(tmp_path / residue[0])) < 200

    # corrupt: the write commits cleanly but exactly one byte differs
    fault_plane(11, "corrupt_file:ckpt.shard:#1")
    atomic_write(path, b"v3" * 100, tag="ckpt", name="shard")
    got = open(path, "rb").read()
    assert got != b"v3" * 100
    assert len(got) == 200
    assert sum(1 for a, b in zip(got, b"v3" * 100) if a != b) == 1


def test_kill_actor_at_disk_boundary_dies_mid_write(tmp_path):
    """The 'rank killed mid-shard-write' primitive, pinned in a real
    subprocess: os._exit(1) at the disk consult, final path never
    created — the generation stays torn."""
    target = str(tmp_path / "gen" / "shard.npz")
    code = (
        "import os\n"
        "os.makedirs(os.path.dirname(%r), exist_ok=True)\n"
        "from ray_tpu._private import fault_injection as fi\n"
        "fi.maybe_init_from_env()\n"
        "from ray_tpu._private.atomic_write import atomic_write\n"
        "atomic_write(%r, b'x' * 4096, tag='ckpt', name='shard')\n"
        "print('UNREACHABLE')\n" % (target, target))
    env = dict(os.environ, RAY_TPU_FAULT_SEED="3",
               RAY_TPU_FAULT_SCHEDULE="kill_actor:ckpt.shard:#1")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=120)
    assert proc.returncode == 1, proc.stderr.decode()
    assert b"UNREACHABLE" not in proc.stdout
    assert not os.path.exists(target)


# ------------------------------------------------ plan math (pure units)


def test_plan_fingerprint_world_independent_and_shape_sensitive():
    from ray_tpu.parallel import sharding as sh

    leaves = _leaves(_params())
    plan = sh.plan_buckets(leaves, BB)
    fp = sh.plan_fingerprint(leaves, plan)
    # same leaves + plan -> same fingerprint, no matter the world size
    # (the fingerprint is what lets a DIFFERENT world restore a save)
    assert fp == sh.plan_fingerprint(list(leaves), plan)
    for world in (1, 2, 4, 7):
        sh.plan_shard_map(leaves, plan, world)     # world never feeds fp
        assert sh.plan_fingerprint(leaves, plan) == fp
    # any shape/dtype/bucketing change is a different plan
    other = _leaves(_params(n=1503))
    assert sh.plan_fingerprint(
        other, sh.plan_buckets(other, BB)) != fp
    merged = sh.plan_buckets(leaves, BB * 100)   # one big bucket
    assert merged != plan
    assert sh.plan_fingerprint(leaves, merged) != fp


def test_reslice_spans_tile_exactly():
    """For every (elems, old, new) combo: the new ranks' spans tile the
    packed stream exactly once, and indexing an old-layout shard array
    with them reconstructs the new-layout slice bit-for-bit."""
    from ray_tpu.parallel import sharding as sh

    for elems in (1, 5, 64, 1000, 1001):
        stream = np.arange(elems, dtype=np.int64)
        for old_world in (1, 2, 3, 4):
            old_shards = [stream[lo:hi] for lo, hi in
                          sh.shard_bounds(elems, old_world)]
            for new_world in (1, 2, 3, 4, 5):
                covered = []
                for new_rank in range(new_world):
                    lo, hi = sh.shard_bounds(elems, new_world)[new_rank]
                    parts = [old_shards[r][a:b] for r, a, b in
                             sh.reslice_spans(elems, old_world,
                                              new_world, new_rank)]
                    got = (np.concatenate(parts) if parts
                           else np.empty(0, np.int64))
                    assert np.array_equal(got, stream[lo:hi]), \
                        (elems, old_world, new_world, new_rank)
                    covered.append(got)
                assert np.array_equal(np.concatenate(covered), stream)


# ------------------------------------------------- save/restore roundtrip


def test_save_restore_roundtrip_sync_and_async(tmp_path):
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params()
    p = sc.save_sharded(params, root=root, step=3, bucket_bytes=BB,
                        asynchronous=False, extra={"lr": 0.25})
    res = p.result()
    assert res["committed"] and res["error"] is None and res["step"] == 3
    assert os.path.isfile(os.path.join(res["path"], sc.MANIFEST))

    out = sc.restore_sharded(params, root=root, bucket_bytes=BB)
    assert out is not None
    restored, meta = out
    _assert_tree_equal(params, restored)
    assert meta["step"] == 3 and meta["world_saved"] == 1
    assert meta["resharded"] is False
    assert meta["extra"] == {"lr": 0.25}

    # async: write rides a background thread; result() harvests both
    # the write and the commit
    p2 = sc.save_sharded(params, root=root, step=7, bucket_bytes=BB,
                         asynchronous=True)
    res2 = p2.result(timeout=60)
    assert res2["committed"] and p2.done_writing()
    out2 = sc.restore_sharded(params, root=root, bucket_bytes=BB)
    assert out2 is not None and out2[1]["step"] == 7


def test_world4_save_elastic_restore_bit_exact(tmp_path):
    """Groupless multi-rank save (scan-ack commit): four ranks write,
    rank 0's result() writes a manifest naming all four shards; restore
    at world 2/4/1 is bit-exact vs the template for every rank, and
    only the genuinely-resharded restores record CHECKPOINT_RESHARDED."""
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params(seed=5)
    pendings = [sc.save_sharded(params, root=root, step=11, world=4,
                                rank=r, bucket_bytes=BB,
                                asynchronous=False) for r in range(4)]
    for r in (1, 2, 3):
        res = pendings[r].result()
        assert res["committed"] and res["manifest"] is None, res
    res0 = pendings[0].result()
    assert res0["committed"], res0
    assert sorted(res0["manifest"]["shards"]) == ["0", "1", "2", "3"]

    base = _events_count("CHECKPOINT_RESHARDED")
    resharded_restores = 0
    for new_world in (2, 4, 1):
        for new_rank in range(new_world):
            out = sc.restore_sharded(params, root=root, world=new_world,
                                     rank=new_rank, bucket_bytes=BB)
            restored, meta = out
            _assert_tree_equal(params, restored, (new_world, new_rank))
            assert meta["world_saved"] == 4
            assert meta["resharded"] == (new_world != 4)
            resharded_restores += int(new_world != 4)
    assert _events_count("CHECKPOINT_RESHARDED") - base == \
        resharded_restores


class _FakeZero:
    """Duck-typed stand-in for ddp.ZeroOptimizer: a deterministic
    optimizer-state shard per (world, rank) over the REAL plan/shard
    map, so save/restore's opt-state path runs without a live gang.
    Full slot vectors are pure functions of the packed bucket — every
    world slices the same streams, which is exactly the elastic-restore
    contract."""

    def __init__(self, params, world, rank, bucket_bytes=BB, step=9):
        from ray_tpu.parallel import sharding as sh

        leaves = _leaves(params)
        self._plan = sh.plan_buckets(leaves, bucket_bytes)
        self._shard_map = sh.plan_shard_map(leaves, self._plan, world)
        self.plan_fingerprint = sh.plan_fingerprint(leaves, self._plan)
        self._bucket_bytes = bucket_bytes
        self._group = None
        self._world, self._rank, self._step = world, rank, step
        self._full = []          # per bucket: slot -> FULL vector
        for b, indices in enumerate(self._plan):
            packed = np.asarray(sh.pack_bucket(leaves, indices),
                                dtype=np.float64)
            self._full.append({"m": packed * 0.5 + 1.0,
                               "v": packed * packed})
        self.loaded = None

    def _ensure_plan(self, leaves):
        pass

    def shard_state_dict(self):
        buckets = []
        for b in range(len(self._plan)):
            lo, hi = self._shard_map[b]["bounds"][self._rank]
            buckets.append({k: v[lo:hi]
                            for k, v in self._full[b].items()})
        return {"step": self._step,
                "plan_fingerprint": self.plan_fingerprint,
                "world": self._world, "rank": self._rank,
                "buckets": buckets}

    def load_shard_state_dict(self, state):
        self.loaded = state


def test_opt_state_elastic_restore_bit_exact(tmp_path):
    """Optimizer-state slots saved at world 4 restore at world 2 (and
    3, which shares no boundary with 4) bit-exact against the full-slot
    oracle — the reslice_spans path through restore_sharded itself."""
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params(seed=8)
    savers = [_FakeZero(params, 4, r) for r in range(4)]
    pendings = [sc.save_sharded(params, savers[r], root=root,
                                asynchronous=False) for r in range(4)]
    for r in (1, 2, 3):
        assert pendings[r].result()["committed"]
    res0 = pendings[0].result()
    assert res0["committed"], res0
    assert res0["step"] == 9                 # from the optimizer's step
    assert sorted(res0["manifest"]["slots"]) == ["m", "v"]

    for new_world in (2, 3, 4, 1):
        for new_rank in range(new_world):
            loader = _FakeZero(params, new_world, new_rank)
            out = sc.restore_sharded(params, loader, root=root,
                                     world=new_world, rank=new_rank)
            restored, meta = out
            _assert_tree_equal(params, restored)
            st = loader.loaded
            assert st["step"] == 9
            assert st["plan_fingerprint"] == loader.plan_fingerprint
            for b in range(len(loader._plan)):
                lo, hi = loader._shard_map[b]["bounds"][new_rank]
                for slot in ("m", "v"):
                    assert np.array_equal(
                        st["buckets"][b][slot],
                        loader._full[b][slot][lo:hi]), \
                        (new_world, new_rank, b, slot)


# ---------------------------------------- quarantine / fallback / verify


def test_restore_skips_torn_generation(tmp_path):
    """A generation without a manifest (the on-disk state a mid-write
    crash leaves) is invisible: restore quarantines it and falls back
    to the newest committed one."""
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params()
    assert sc.save_sharded(params, root=root, step=1, bucket_bytes=BB,
                           asynchronous=False).result()["committed"]
    # hand-build the torn newer generation: a shard, no manifest
    torn = sc.generation_dir(root, 2)
    os.makedirs(torn)
    open(os.path.join(torn, sc.shard_filename(0, 1)), "wb").write(b"x")

    base = _events_count("CHECKPOINT_QUARANTINED")
    out = sc.restore_sharded(params, root=root, bucket_bytes=BB)
    assert out is not None and out[1]["step"] == 1
    assert _events_count("CHECKPOINT_QUARANTINED") - base == 1
    assert not os.path.isdir(torn)
    assert os.path.isdir(torn + sc.QUARANTINE_SUFFIX)


def test_corrupt_file_chaos_quarantine_and_fallback(tmp_path, fault_plane):
    """The seeded byte-flip E2E: the second save's shard is corrupted
    in flight (corrupt_file:ckpt.shard:#2), the WRITER still commits
    (a latent media error is invisible to it) — restore's digest check
    catches it, quarantines with reason=digest_mismatch naming the
    shard, and falls back to the older clean generation."""
    from ray_tpu._private import telemetry as tm
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params(seed=2)
    fault_plane(19, "corrupt_file:ckpt.shard:#2")
    assert sc.save_sharded(params, root=root, step=1, bucket_bytes=BB,
                           asynchronous=False).result()["committed"]
    res2 = sc.save_sharded(params, root=root, step=2, bucket_bytes=BB,
                           asynchronous=False).result()
    assert res2["committed"]      # the flip is silent at write time

    verdict = sc.verify_generation(res2["path"])
    assert not verdict["ok"] and verdict["reason"] == "digest_mismatch"
    assert verdict["shard"] == sc.shard_filename(0, 1)

    base = _events_count("CHECKPOINT_QUARANTINED")
    out = sc.restore_sharded(params, root=root, bucket_bytes=BB)
    assert out is not None
    restored, meta = out
    assert meta["step"] == 1
    _assert_tree_equal(params, restored)
    assert _events_count("CHECKPOINT_QUARANTINED") - base == 1
    from ray_tpu._private import events

    ev = [e for e in events.snapshot()
          if e["kind"] == "CHECKPOINT_QUARANTINED"][-1]
    assert ev["reason"] == "digest_mismatch"
    assert ev["shard"] == sc.shard_filename(0, 1)
    if tm.ENABLED:
        fam = tm._metrics.get("ray_tpu_checkpoint_quarantined_total")
        assert fam is not None
        assert sum(v["value"] for v in fam.snapshot()["values"]
                   if v["tags"].get("reason") == "digest_mismatch") >= 1


def test_torn_manifest_write_never_commits(tmp_path, fault_plane):
    """torn_write on the MANIFEST: both phases of the two-phase commit
    fail atomically — the shard is durable but the generation does not
    exist as far as restore is concerned."""
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params()
    fault_plane(23, "torn_write:ckpt.manifest:#1")
    res = sc.save_sharded(params, root=root, step=4, bucket_bytes=BB,
                          asynchronous=False).result()
    assert res["committed"] is False
    assert "TornWriteError" in res["error"]
    assert not os.path.exists(os.path.join(res["path"], sc.MANIFEST))
    assert sc.restore_sharded(params, root=root, bucket_bytes=BB,
                              quarantine=False) is None


def test_verify_generation_reasons(tmp_path):
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params()
    res = sc.save_sharded(params, root=root, step=6, bucket_bytes=BB,
                          asynchronous=False).result()
    gen = res["path"]
    assert sc.verify_generation(gen)["ok"]
    assert sc.verify_generation(gen, fingerprint="nope")["reason"] == \
        "plan_mismatch"
    shard = os.path.join(gen, sc.shard_filename(0, 1))
    blob = open(shard, "rb").read()
    open(shard, "wb").write(blob[:-10])
    assert sc.verify_generation(gen)["reason"] == "size_mismatch"
    open(shard, "wb").write(blob[:-1] + bytes([blob[-1] ^ 0xFF]))
    assert sc.verify_generation(gen)["reason"] == "digest_mismatch"
    os.unlink(shard)
    assert sc.verify_generation(gen)["reason"] == "shard_missing"
    os.unlink(os.path.join(gen, sc.MANIFEST))
    assert sc.verify_generation(gen)["reason"] == "torn"


# ----------------------------------------------------------- pruning


def test_prune_never_deletes_last_complete(tmp_path):
    """num_to_keep=1 with the newest committed generation corrupted:
    the newest COMPLETE one survives the prune no matter the budget."""
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params()
    for step in (1, 2, 3):
        assert sc.save_sharded(params, root=root, step=step,
                               bucket_bytes=BB,
                               asynchronous=False).result()["committed"]
    # newest generation loses a shard AFTER commit
    os.unlink(os.path.join(sc.generation_dir(root, 3),
                           sc.shard_filename(0, 1)))
    removed = sc.prune_generations(root, keep=1)
    left = {s for s, _ in sc._list_generations(root)}
    assert 3 in left          # newest committed (budget)
    assert 2 in left          # newest verified-COMPLETE (unconditional)
    assert 1 not in left
    assert any(p.endswith("gen_00000001") for p in removed)

    # and an in-flight (torn, newer-than-committed) generation is not
    # pruning's to judge
    os.makedirs(sc.generation_dir(root, 4))
    sc.prune_generations(root, keep=1)
    assert os.path.isdir(sc.generation_dir(root, 4))


def test_prune_num_to_keep_across_elastic_restarts(tmp_path):
    """Satellite: a run checkpointing through world 4 -> 2 -> 4
    restarts with keep=2 stays bounded on disk, every restart restores
    bit-exact at its new world size, and the final state of the root is
    exactly the keep-window."""
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params(seed=4)
    step = 0
    for world in (4, 2, 4):
        # elastic restart: the new gang restores at ITS world size
        for rank in range(world):
            out = sc.restore_sharded(params, root=root, world=world,
                                     rank=rank, bucket_bytes=BB)
            if step:
                restored, meta = out
                _assert_tree_equal(params, restored, (world, rank))
                assert meta["step"] == step - 1
                assert meta["resharded"] == \
                    (meta["world_saved"] != world)
            else:
                assert out is None
        for _ in range(2):
            pendings = [sc.save_sharded(params, root=root, step=step,
                                        world=world, rank=r,
                                        bucket_bytes=BB, keep=2,
                                        asynchronous=False)
                        for r in range(world)]
            for r in range(world - 1, -1, -1):   # rank 0 commits last
                assert pendings[r].result()["committed"]
            step += 1
    entries = sc.summarize_checkpoints(root)
    committed = [e for e in entries if e["status"] == "committed"]
    assert [e["step"] for e in committed] == [5, 4]
    assert all(e["world"] == 4 for e in committed)
    assert len(os.listdir(root)) == 2        # the keep-window, nothing else


# ------------------------------------------------- summary + CLI + leak


def test_summarize_checkpoints_statuses(tmp_path, fault_plane):
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    params = _params()
    fault_plane(19, "corrupt_file:ckpt.shard:#2")
    sc.save_sharded(params, root=root, step=1, bucket_bytes=BB,
                    asynchronous=False).result()
    sc.save_sharded(params, root=root, step=2, bucket_bytes=BB,
                    asynchronous=False).result()     # corrupted shard
    os.makedirs(sc.generation_dir(root, 3))          # torn
    os.makedirs(sc.generation_dir(root, 0) + sc.QUARANTINE_SUFFIX)

    entries = sc.summarize_checkpoints(root)
    by_step = {e["step"]: e for e in entries}
    assert [e["step"] for e in entries] == [3, 2, 1, 0]
    assert by_step[3]["status"] == "torn"
    assert by_step[2]["status"] == "corrupt"
    assert by_step[2]["reason"] == "digest_mismatch"
    assert by_step[1]["status"] == "committed"
    assert by_step[1]["shards"] == 1 and by_step[1]["bytes"] > 0
    assert by_step[0]["status"] == "quarantined"
    # the cheap (digest-less) form calls the flipped byte committed —
    # documented: digests are restore's job, the summary's fast path
    # only proves structure
    cheap = {e["step"]: e for e in sc.summarize_checkpoints(
        root, digests=False)}
    assert cheap[2]["status"] == "committed"


def test_cli_checkpoints_summary(tmp_path, capsys):
    import argparse

    from ray_tpu.scripts import cli
    from ray_tpu.train import sharded_checkpoint as sc

    root = str(tmp_path)
    sc.save_sharded(_params(), root=root, step=12, bucket_bytes=BB,
                    asynchronous=False).result()
    rc = cli.cmd_checkpoints(argparse.Namespace(root=root,
                                                no_digests=False))
    assert rc in (None, 0)
    out = json.loads(capsys.readouterr().out)
    assert out["root"] == root
    assert out["generations"][0]["step"] == 12
    assert out["generations"][0]["status"] == "committed"


def test_checkpoint_tmpdir_leak_fixed():
    """Satellite: Checkpoint.from_bytes/to_directory scratch dirs are
    tied to the object's lifetime — dropping the last reference reaps
    them (the old code leaked one mkdtemp per call, forever)."""
    from ray_tpu.air.checkpoint import Checkpoint

    def _count():
        base = tempfile.gettempdir()
        return sum(1 for n in os.listdir(base)
                   if n.startswith("rtpu_ckpt_"))

    gc.collect()
    base = _count()
    ckpts = []
    for i in range(4):
        c = Checkpoint.from_dict({"i": i, "blob": os.urandom(256)})
        d1 = c.to_directory()
        # repeated materialization reuses the SAME scratch dir instead
        # of minting (and leaking) a fresh one per call
        assert c.to_directory() == d1
        ckpts.append(c)
        ckpts.append(Checkpoint.from_bytes(ckpts[0].to_bytes()))
    assert _count() > base            # scratch dirs exist while alive...
    del c, ckpts
    gc.collect()
    assert _count() == base           # ...and die with their owners


# ------------------------------------------------- durability lint pass


def test_durability_pass_flags_bare_writes():
    from ray_tpu._private.analysis import core as acore
    from ray_tpu._private.analysis.durability import durability_pass

    bad = (
        "import os\n"
        "def save(path, blob):\n"
        "    with open(path + '.tmp', 'wb') as f:\n"
        "        f.write(blob)\n"
        "    os.rename(path + '.tmp', path)\n"
        "def read(path):\n"
        "    return open(path, 'rb').read()\n")
    ctx = acore.AnalysisContext(overrides={
        "ray_tpu/_private/zz_fake_checkpoint_store.py": bad})
    found = [f for f in durability_pass(ctx)
             if "zz_fake_checkpoint_store" in f.path]
    codes = sorted(f.code for f in found)
    assert codes == ["RTD501", "RTD502"], found
    assert all(f.context == "save" for f in found)

    # the sanctioned spelling is clean — and so is a hand-rolled full
    # idiom (write + fsync + rename + dir fsync in one function)
    good = (
        "import os\n"
        "from ray_tpu._private.atomic_write import atomic_write\n"
        "def save(path, blob):\n"
        "    atomic_write(path, blob, tag='ckpt')\n"
        "def save_stream(path, rows):\n"
        "    with open(path + '.tmp', 'wb') as f:\n"      # noqa: RTD501
        "        for r in rows: f.write(r)\n"
        "        f.flush(); os.fsync(f.fileno())\n"
        "    os.rename(path + '.tmp', path)\n")
    ctx2 = acore.AnalysisContext(overrides={
        "ray_tpu/_private/zz_fake_checkpoint_store.py": good})
    found2 = [f for f in durability_pass(ctx2)
              if "zz_fake_checkpoint_store" in f.path]
    # the streaming writer still carries the bare-open finding (RTD501
    # is a policy gate routed through the baseline) but NOT the
    # rename-without-fsync one
    assert [f.code for f in found2] == ["RTD501"]
    # non-persistence modules are out of scope entirely
    ctx3 = acore.AnalysisContext(overrides={
        "ray_tpu/_private/zz_fake_scratch.py": bad})
    assert not [f for f in durability_pass(ctx3)
                if "zz_fake_scratch" in f.path]


def test_durability_pass_real_tree_is_baselined():
    """Every RTD finding on the actual tree is either fixed or a
    justified baseline entry — new bare writes in persistence modules
    fail here."""
    from ray_tpu._private.analysis import core as acore
    from ray_tpu._private.analysis.durability import durability_pass

    baseline = acore.load_baseline()
    new = [f for f in durability_pass(acore.AnalysisContext())
           if f.key not in baseline]
    assert not new, "un-baselined durability findings:\n" + \
        "\n".join(str(f) for f in new)


# --------------------------------------------------------------- chaos E2E


@pytest.fixture
def ray_chaos_env():
    """ray_start_regular, plus a seeded fault schedule exported BEFORE
    init so every spawned cluster process inherits the fault plane."""
    import ray_tpu

    started = []

    def _start(seed, schedule):
        os.environ["RAY_TPU_FAULT_SEED"] = str(seed)
        os.environ["RAY_TPU_FAULT_SCHEDULE"] = schedule
        ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
        started.append(True)
        return ray_tpu

    yield _start
    if started:
        ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_FAULT_SEED", None)
    os.environ.pop("RAY_TPU_FAULT_SCHEDULE", None)


def _sharded_loop(config):
    """Deterministic 2-rank loop checkpointing through the sharded
    plane each step (async write, harvested at the step's collective
    point) and restoring through it at start — the root rides the
    trainer's storage_path plumbing (session.checkpoint_dir), not
    config."""
    from ray_tpu._private import events
    from ray_tpu.air import session
    from ray_tpu.train import sharded_checkpoint as sc
    from ray_tpu.util import collective as col

    rank = session.get_world_rank()
    params = {"w": np.zeros(256, np.float32)}
    start = 0
    out = sc.restore_sharded(params, group_name=GROUP + "_gang",
                             bucket_bytes=BB)
    if out is not None:
        params, meta = out
        start = int(meta["step"]) + 1
    for step in range(start, STEPS):
        g = np.full(256, float((step + 1) * (rank + 1)), np.float32)
        s = np.asarray(col.allreduce(g, GROUP + "_gang"))
        params = {"w": params["w"] + s}
        pending = sc.save_sharded(params, step=step,
                                  group_name=GROUP + "_gang",
                                  bucket_bytes=BB, keep=2,
                                  asynchronous=True)
        res = pending.result(timeout=120)
        assert res["committed"], res
        session.report({"step": step})
    # whichever rank lists the root first performs the quarantine and
    # records the event locally — sum across the gang so rank 0's
    # report carries it regardless of who won the rename
    q = sum(1 for e in events.snapshot()
            if e["kind"] == "CHECKPOINT_QUARANTINED")
    q_sum = np.asarray(col.allreduce(
        np.full(1, float(q), np.float32), GROUP + "_gang"))
    session.report({"step": STEPS - 1, "final": float(params["w"][0]),
                    "spread": float(np.ptp(params["w"])),
                    "start": start, "q_events": int(q_sum[0])})


@pytest.mark.chaos
@pytest.mark.fault_injection
def test_chaos_kill_rank_mid_shard_write(ray_chaos_env, tmp_path):
    """The flagship chaos E2E, fully seeded: rank 1 dies (os._exit at
    the disk boundary) during its FIFTH shard write — i.e. step 4's
    checkpoint, after steps 0-3 committed. (Write counters are
    per-process, so #5 is reachable only by an attempt that started
    from step 0 — the kill fires exactly once across incarnations.)
    The generation it was contributing to never gets a manifest; the
    restarted gang's restore skips it (quarantine + fallback to step
    3's generation) and the run completes to the bit-correct oracle
    with exactly one max_failures token spent and no hung window."""
    from ray_tpu._private import events
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.backend_executor import JaxConfig
    from ray_tpu.train.sharded_checkpoint import summarize_checkpoints

    ray_chaos_env(7, "kill_actor:rank1.shard:#5")
    base_failed = sum(1 for e in events.snapshot()
                      if e["kind"] == "GANG_FAILED")
    t0 = time.monotonic()
    result = JaxTrainer(
        _sharded_loop,
        backend_config=JaxConfig(group_name=GROUP + "_gang"),
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name="zzck_run", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2)),
    ).fit()
    elapsed = time.monotonic() - t0
    assert elapsed < 180, f"gang restart took {elapsed:.0f}s (hang?)"
    assert result.error is None, result.error
    # oracle: step s adds (s+1)*(1+2) to every element
    oracle = 3.0 * STEPS * (STEPS + 1) / 2
    assert result.metrics["final"] == oracle
    assert result.metrics["spread"] == 0.0
    assert result.metrics["step"] == STEPS - 1
    # the surviving attempt resumed from step 3's generation (the
    # newest COMMITTED one), not from scratch, and saw the torn step-4
    # generation quarantined on the way
    assert result.metrics["start"] == STEPS - 1
    assert result.metrics["q_events"] >= 1
    # exactly the one injected death — no cascading failure tokens,
    # and the failure event advertises the generation the restart
    # actually resumed from (step 3, the newest COMMITTED at kill time)
    failed = [e for e in events.snapshot()
              if e["kind"] == "GANG_FAILED"][base_failed:]
    assert len(failed) == 1
    assert failed[0]["resume_step"] == STEPS - 2
    # on-disk end state: the keep-window of committed generations, the
    # newest being the final step, plus the torn wreckage preserved as
    # quarantined evidence
    root = os.path.join(str(tmp_path), "zzck_run", "sharded")
    entries = summarize_checkpoints(root)
    committed = [e for e in entries if e["status"] == "committed"]
    assert committed and committed[0]["step"] == STEPS - 1
    assert len(committed) <= 2
    assert all(e["world"] == 2 for e in committed)
    assert any(e["status"] == "quarantined" for e in entries)


def _ckpt_rank_cls(ray):
    @ray.remote
    class CkptRank:
        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def train_save(self, rank, name, root, steps=3):
            """A few real ZeroOptimizer steps, then a sharded save
            whose two-phase commit rides the LIVE collective plane
            (allgather ack). Returns the commit verdict + this rank's
            shard state and state-accounting triple for the driver's
            elastic-restore oracle."""
            from ray_tpu.train import ddp
            from ray_tpu.train import sharded_checkpoint as sc
            from ray_tpu.util.metrics import registry_snapshot

            def init_params():
                rng = np.random.RandomState(21)
                return {"wa": rng.standard_normal(1200)
                        .astype(np.float32),
                        "wb": rng.standard_normal((40, 11))
                        .astype(np.float32)}

            params = init_params()
            zopt = ddp.ZeroOptimizer(ddp.zero_adam(0.01), name,
                                     bucket_bytes=BB)
            for step in range(steps):
                grng = np.random.RandomState(50 * step + rank)
                grads = {k: grng.standard_normal(v.shape)
                         .astype(np.float32)
                         for k, v in sorted(params.items())}
                params = zopt.step(params, grads)
            res = sc.save_sharded(params, zopt, root=root,
                                  asynchronous=False).result(timeout=120)
            gauge = None
            for fam in registry_snapshot():
                if fam["name"] == "ray_tpu_train_state_bytes":
                    for v in fam["values"]:
                        if v["tags"].get("kind") == "opt_state" and \
                                v["tags"].get("rank") == str(rank):
                            gauge = v["value"]
            shard = zopt.shard_state_dict()
            return {"res": {k: res[k] for k in
                            ("committed", "step", "error")},
                    "manifest": res["manifest"] is not None,
                    "params": {k: np.asarray(v)
                               for k, v in params.items()},
                    "buckets": [{k: np.asarray(v)
                                 for k, v in st.items()}
                                for st in shard["buckets"]],
                    "gauge": gauge,
                    "state_bytes": zopt.state_bytes(),
                    "replicated": zopt.replicated_state_bytes()}

        def destroy(self, name):
            from ray_tpu.util import collective as col

            try:
                col.destroy_collective_group(name)
            except Exception:
                pass
            return True

    return CkptRank


@pytest.mark.chaos
def test_live_gang_allgather_commit_and_elastic_shrink(ray_start_regular,
                                                       tmp_path):
    """World-2 gang trains a real ZeroOptimizer, saves through the
    allgather two-phase commit (both ranks harvest; rank 0's manifest
    names both shards), the opt_state gauge proves each rank held ~half
    the replicated state — then the save restores at world 1 with the
    optimizer shards re-sliced 2->1 bit-exact against the ranks' own
    shard dicts."""
    ray = ray_start_regular
    name = GROUP + "_live"
    root = str(tmp_path / "live")
    Rank = _ckpt_rank_cls(ray)
    actors = [Rank.options(num_cpus=0).remote() for _ in range(2)]
    try:
        ray.get([a.join.remote(2, i, name)
                 for i, a in enumerate(actors)], timeout=120)
        got = ray.get([a.train_save.remote(i, name, root)
                       for i, a in enumerate(actors)], timeout=240)
    finally:
        try:
            ray.get([a.destroy.remote(name) for a in actors],
                    timeout=30)
        except Exception:
            pass
    for rank, g in enumerate(got):
        assert g["res"]["committed"], g["res"]
        assert g["res"]["error"] is None
        # no rank materialized full optimizer state, gauge-proven
        assert g["gauge"] == pytest.approx(g["state_bytes"])
        assert g["state_bytes"] < g["replicated"]
    assert got[0]["manifest"] and not got[1]["manifest"]
    assert got[0]["state_bytes"] + got[1]["state_bytes"] == \
        pytest.approx(got[0]["replicated"])
    # params replicated: both ranks ended identical
    for k in got[0]["params"]:
        assert np.array_equal(got[0]["params"][k], got[1]["params"][k])

    # ---- elastic 2 -> 1: driver-side restore sees the full state
    from ray_tpu.train import sharded_checkpoint as sc

    template = {k: np.zeros_like(v) for k, v in got[0]["params"].items()}
    loader = _FakeZero(template, 1, 0, bucket_bytes=BB)
    out = sc.restore_sharded(template, loader, root=root, world=1,
                             rank=0)
    assert out is not None
    restored, meta = out
    assert meta["world_saved"] == 2 and meta["resharded"]
    for k in template:
        assert np.array_equal(np.asarray(restored[k]),
                              got[0]["params"][k])
    # oracle: world-1's slot vectors are the rank-ordered concatenation
    # of the gang's saved shard slots
    st = loader.loaded
    assert st["step"] == 3
    for b in range(len(st["buckets"])):
        for slot in st["buckets"][b]:
            oracle = np.concatenate([got[0]["buckets"][b][slot],
                                     got[1]["buckets"][b][slot]])
            assert np.array_equal(st["buckets"][b][slot], oracle), \
                (b, slot)
