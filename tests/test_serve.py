"""Serve tests — deploy/route/scale/heal.

Models the reference's serve test surface (python/ray/serve/tests/):
handle calls, HTTP ingress, composition graphs, reconfigure, replica
failure recovery, autoscaling.
"""
import json
import threading
import time
import urllib.request

import pytest


@pytest.fixture
def serve_instance():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, object_store_memory=64 * 1024 * 1024)
    serve.start(http_options={"host": "127.0.0.1", "port": 0})
    yield serve
    serve.shutdown()
    ray_tpu.shutdown()


def _http_get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read()


def _http_post(port, path, data: bytes):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, r.read()


def test_function_deployment_handle(serve_instance):
    serve = serve_instance

    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), name="fn_app", route_prefix=None)
    assert handle.remote(21).result() == 42


def test_class_deployment_http(serve_instance):
    serve = serve_instance

    @serve.deployment(num_replicas=2)
    class Greeter:
        def __init__(self, greeting):
            self.greeting = greeting

        def __call__(self, request):
            name = request.query_params.get("name", "world")
            return {"greeting": f"{self.greeting}, {name}!"}

    serve.run(Greeter.bind("hello"), name="greet", route_prefix="/greet")
    port = serve.http_port()
    status, body = _http_get(port, "/greet?name=tpu")
    assert status == 200
    assert json.loads(body) == {"greeting": "hello, tpu!"}
    # routes endpoint lists the app
    status, body = _http_get(port, "/-/routes")
    assert json.loads(body) == {"/greet": "greet"}
    # unknown path 404s
    with pytest.raises(urllib.error.HTTPError) as err:
        _http_get(port, "/nope")
    assert err.value.code == 404


def test_http_post_json_and_error(serve_instance):
    serve = serve_instance

    @serve.deployment
    class Echo:
        def __call__(self, request):
            data = request.json()
            if data.get("boom"):
                raise ValueError("boom requested")
            return {"echo": data}

    serve.run(Echo.bind(), name="echo", route_prefix="/echo")
    port = serve.http_port()
    status, body = _http_post(port, "/echo", json.dumps({"a": 1}).encode())
    assert json.loads(body) == {"echo": {"a": 1}}
    with pytest.raises(urllib.error.HTTPError) as err:
        _http_post(port, "/echo", json.dumps({"boom": True}).encode())
    assert err.value.code == 500
    assert "boom requested" in err.value.read().decode()


def test_composition_graph(serve_instance):
    serve = serve_instance

    @serve.deployment
    class Adder:
        def __init__(self, increment):
            self.increment = increment

        def add(self, x):
            return x + self.increment

    @serve.deployment
    class Ingress:
        def __init__(self, adder):
            self.adder = adder   # DeploymentHandle injected by the graph

        def __call__(self, x):
            resp = self.adder.add.remote(x)
            return resp.result() * 10

    handle = serve.run(Ingress.bind(Adder.bind(5)), name="graph",
                       route_prefix=None)
    assert handle.remote(1).result() == 60


def test_reconfigure_user_config(serve_instance):
    serve = serve_instance

    @serve.deployment(user_config={"threshold": 1})
    class Thresholder:
        def __init__(self):
            self.threshold = None

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self, x):
            return x > self.threshold

    dep = Thresholder.bind()
    handle = serve.run(dep, name="cfg", route_prefix=None)
    assert handle.remote(2).result() is True
    # redeploy with a new user_config — replicas reconfigure in place
    serve.run(Thresholder.options(user_config={"threshold": 10}).bind(),
              name="cfg", route_prefix=None)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if handle.remote(2).result() is False:
            break
        time.sleep(0.1)
    assert handle.remote(2).result() is False
    assert handle.remote(11).result() is True


def test_replica_death_recovery(serve_instance):
    serve = serve_instance
    import ray_tpu

    @serve.deployment(num_replicas=2, health_check_period_s=0.2)
    class Worker:
        def pid(self):
            import os

            return os.getpid()

    handle = serve.run(Worker.bind(), name="heal", route_prefix=None)
    pids = {handle.pid.remote().result() for _ in range(10)}
    assert len(pids) >= 1
    # kill one replica actor out from under the controller
    status = serve.status()
    assert status["heal"]["status"] == "RUNNING"
    victims = [a for a in ray_tpu.nodes()]  # noqa: F841 (cluster sanity)
    # find a replica actor by name through the controller's routing table
    from ray_tpu.serve.handle import _get_router

    router = _get_router("heal#Worker")
    deadline = time.monotonic() + 10
    while router.num_replicas() < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert router.num_replicas() == 2
    with router._lock:
        victim = next(iter(router._replicas.values())).handle
    ray_tpu.kill(victim)
    # requests keep succeeding throughout recovery
    for _ in range(20):
        assert isinstance(handle.pid.remote().result(timeout_s=15), int)
        time.sleep(0.05)
    # controller replaces the dead replica
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        st = serve.status()["heal"]
        if st["deployments"]["Worker"]["replica_states"]["RUNNING"] == 2:
            break
        time.sleep(0.1)
    assert serve.status()["heal"]["deployments"]["Worker"][
        "replica_states"]["RUNNING"] == 2


def test_autoscaling_up_and_down(serve_instance):
    serve = serve_instance

    @serve.deployment(
        max_ongoing_requests=1,
        autoscaling_config=dict(min_replicas=1, max_replicas=3,
                                target_ongoing_requests=1.0,
                                upscale_delay_s=0.2, downscale_delay_s=0.5,
                                metrics_interval_s=0.1),
        graceful_shutdown_timeout_s=1.0,
    )
    class Slow:
        def __call__(self, t):
            time.sleep(t)
            return True

    handle = serve.run(Slow.bind(), name="auto", route_prefix=None)

    def peak_replicas():
        return serve.status()["auto"]["deployments"]["Slow"][
            "replica_states"]["RUNNING"]

    assert peak_replicas() == 1
    # sustained concurrent load → scale up
    results = []

    def fire():
        results.append(handle.remote(0.3).result(timeout_s=60))

    threads = [threading.Thread(target=fire) for _ in range(12)]
    for t in threads:
        t.start()
    peak = 1
    deadline = time.monotonic() + 20
    while any(t.is_alive() for t in threads) and time.monotonic() < deadline:
        peak = max(peak, peak_replicas())
        time.sleep(0.05)
    for t in threads:
        t.join(timeout=30)
    assert all(results) and len(results) == 12
    assert peak >= 2, f"expected scale-up beyond 1 replica, peak={peak}"
    # idle → back down to min_replicas
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if peak_replicas() == 1:
            break
        time.sleep(0.1)
    assert peak_replicas() == 1


def test_redeploy_scales_and_deletes(serve_instance):
    serve = serve_instance

    @serve.deployment(num_replicas=1)
    class S:
        def __call__(self, _=None):
            return "ok"

    serve.run(S.bind(), name="scale", route_prefix="/scale")
    serve.run(S.options(num_replicas=3).bind(), name="scale",
              route_prefix="/scale")
    st = serve.status()["scale"]["deployments"]["S"]
    assert st["target_num_replicas"] == 3
    serve.delete("scale")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if "scale" not in serve.status():
            break
        time.sleep(0.1)
    assert "scale" not in serve.status()


def test_batch_decorator_unit():
    """@serve.batch coalesces concurrent callers (no cluster needed)."""
    from ray_tpu.serve.batching import batch

    sizes = []

    @batch(max_batch_size=4, batch_wait_timeout_s=0.05)
    def square_all(items):
        sizes.append(len(items))
        return [x * x for x in items]

    results = [None] * 8
    threads = [threading.Thread(target=lambda i=i: results.__setitem__(
        i, square_all(i))) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [i * i for i in range(8)]
    assert max(sizes) > 1, f"no coalescing happened: {sizes}"
    assert all(s <= 4 for s in sizes)


def test_batch_decorator_method_and_errors():
    from ray_tpu.serve.batching import batch

    class Model:
        @batch(max_batch_size=8, batch_wait_timeout_s=0.02)
        def run(self, items):
            if any(x < 0 for x in items):
                raise ValueError("negative")
            return [x + 1 for x in items]

    m1, m2 = Model(), Model()
    assert m1.run(1) == 2
    assert m2.run(10) == 11  # separate instance, separate batcher
    with pytest.raises(ValueError):
        m1.run(-5)
    # batcher recovers after an error batch
    assert m1.run(3) == 4


def test_batch_in_deployment(serve_instance):
    serve = serve_instance

    @serve.deployment(max_ongoing_requests=16)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        def predict(self, items):
            self.batch_sizes.append(len(items))
            return [x * 10 for x in items]

        def __call__(self, x):
            return self.predict(x)

        def max_seen(self, _=None):
            return max(self.batch_sizes) if self.batch_sizes else 0

    handle = serve.run(Batched.bind(), name="batched", route_prefix=None)
    responses = [handle.remote(i) for i in range(12)]
    assert [r.result() for r in responses] == [i * 10 for i in range(12)]
    assert handle.max_seen.remote(None).result() > 1
