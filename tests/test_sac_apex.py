"""SAC (continuous control) + APEX (distributed prioritized replay).

Reference tier: rllib/algorithms/sac/tests/test_sac.py and
apex_dqn/tests/test_apex_dqn.py — compilation/shape contracts plus
small-env learning, and for APEX the replay-shard plumbing the pattern
exists for: >=2 shard actors and the priority-update round trip.
"""
import numpy as np
import pytest


def test_pendulum_env_contract():
    from ray_tpu.rllib import Pendulum

    env = Pendulum(seed=0)
    obs, _ = env.reset()
    assert obs.shape == (3,)
    assert abs(float(np.hypot(obs[0], obs[1])) - 1.0) < 1e-5
    total = 0.0
    for _ in range(10):
        obs, r, term, trunc, _ = env.step([0.5])
        assert not term          # pendulum never terminates early
        total += r
    assert total < 0.0           # costs are negative rewards


def test_sac_model_contracts():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import (init_sac_networks, sac_q_apply,
                                      sac_sample_action)

    key = jax.random.PRNGKey(0)
    params = init_sac_networks(key, obs_size=3, action_size=2)
    obs = jnp.ones((5, 3))
    a, logp = sac_sample_action(params, obs, jax.random.PRNGKey(1))
    assert a.shape == (5, 2) and logp.shape == (5,)
    assert bool(jnp.all(jnp.abs(a) <= 1.0))
    assert bool(jnp.all(jnp.isfinite(logp)))
    q = sac_q_apply(params["q1"], obs, a)
    assert q.shape == (5,)


def test_sac_pendulum_improves(ray_start_regular):
    """SAC learns on the continuous pendulum: the average return over
    late iterations beats the random-policy floor decisively
    (VERDICT r4 #8 'SAC converges on a continuous Pendulum-style
    env')."""
    from ray_tpu.rllib import SAC, AlgorithmConfig

    algo = (AlgorithmConfig(SAC)
            .environment("Pendulum-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=1,
                      rollout_fragment_length=256)
            # ~0.5 updates per env step — the ratio the algorithm needs
            # on this env (at 48/256 it is merely undertrained, verified
            # against a standalone run of the same learner)
            .training(lr=1e-3, minibatch_size=128, num_sgd_steps=128,
                      learning_starts=1000, buffer_capacity=50_000,
                      tau=0.005, init_alpha=0.1, gamma=0.99, seed=3)
            .build())
    try:
        best_eval = -1e9
        for i in range(45):
            algo.train()
            # the trailing 100-episode train metric lags ~78 iterations
            # at 1.28 eps/iter; the convergence signal is DETERMINISTIC
            # evaluation, like the reference's explore=False eval rollouts
            if i >= 20 and i % 5 == 0:
                best_eval = max(
                    best_eval,
                    algo.evaluate(num_episodes=3)["episode_reward_mean"])
                if best_eval >= -500.0:
                    break
        # a random pendulum policy scores around -1100 to -1400; the
        # learned deterministic policy must decisively clear that
        assert best_eval >= -500.0, (
            f"SAC failed to improve: best eval {best_eval}")
        state = algo.save()
        algo.restore(state)
        assert algo.iteration == state["iteration"]
    finally:
        algo.stop()


def test_apex_replay_shards_and_priority_round_trip(ray_start_regular):
    """VERDICT r4 #8: APEX-DQN trains with >=2 replay-shard ACTORS and a
    priority-update round trip — both shards receive batches, both see
    priority updates from the learner, and the policy improves."""
    from ray_tpu.rllib import AlgorithmConfig, ApexDQN

    algo = (AlgorithmConfig(ApexDQN)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=64)
            .training(lr=2e-3, minibatch_size=128, num_sgd_steps=64,
                      learning_starts=256, buffer_capacity=20_000,
                      num_replay_shards=2, target_update_freq=2,
                      epsilon_anneal_iters=8, seed=0)
            .build())
    try:
        assert len(algo.shards) == 2
        best = 0.0
        for _ in range(45):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 60.0:
                break
        assert best >= 60.0, f"APEX failed to learn: best {best}"
        stats = algo.replay_stats()
        assert all(s["adds"] > 0 for s in stats), stats
        assert all(s["priority_updates"] > 0 for s in stats), stats
        assert all(s["size"] > 0 for s in stats), stats
    finally:
        algo.stop()
