"""AIR preprocessors — distributed fit, vectorized transform.

Reference tier: python/ray/data/tests/test_preprocessors.py (scalers,
encoders, imputer, concatenator, chain; fit stats computed over the
distributed dataset, transform applied to datasets and raw batches).
"""
import numpy as np
import pytest


def _toy(ray, n=100, parallelism=4):
    from ray_tpu import data

    rows = [{"x": float(i), "y": float(i % 10), "cat": ["a", "b", "c"][i % 3]}
            for i in range(n)]
    return data.from_items(rows, parallelism=parallelism)


def test_standard_scaler(ray_start_regular):
    from ray_tpu.air import StandardScaler

    ds = _toy(ray_start_regular)
    sc = StandardScaler(columns=["x"]).fit(ds)
    out = sc.transform(ds).to_pandas()
    assert abs(out["x"].mean()) < 1e-9
    assert abs(out["x"].std(ddof=0) - 1.0) < 1e-6
    # raw-batch transform matches
    b = sc.transform_batch({"x": np.array([0.0, 99.0])})
    assert abs(b["x"][0] - out["x"].min()) < 1e-9


def test_minmax_scaler_and_not_fitted(ray_start_regular):
    from ray_tpu.air import MinMaxScaler, PreprocessorNotFittedError

    ds = _toy(ray_start_regular)
    sc = MinMaxScaler(columns=["x", "y"])
    with pytest.raises(PreprocessorNotFittedError):
        sc.transform_batch({"x": np.array([1.0])})
    out = sc.fit_transform(ds).to_pandas()
    assert out["x"].min() == 0.0 and out["x"].max() == 1.0
    assert out["y"].min() == 0.0 and out["y"].max() == 1.0


def test_ordinal_and_onehot_encoders(ray_start_regular):
    from ray_tpu.air import OneHotEncoder, OrdinalEncoder

    ds = _toy(ray_start_regular, n=30)
    enc = OrdinalEncoder(columns=["cat"]).fit(ds)
    out = enc.transform(ds).to_pandas()
    assert set(out["cat"].tolist()) == {0, 1, 2}
    # unseen category -> -1
    b = enc.transform_batch({"cat": np.array(["a", "zzz"])})
    assert b["cat"].tolist() == [0, -1]

    oh = OneHotEncoder(columns=["cat"]).fit(ds)
    out = oh.transform(ds).to_pandas()
    assert {"cat_a", "cat_b", "cat_c"} <= set(out.columns)
    assert (out[["cat_a", "cat_b", "cat_c"]].sum(axis=1) == 1).all()


def test_label_encoder_round_trip(ray_start_regular):
    from ray_tpu.air import LabelEncoder

    ds = _toy(ray_start_regular, n=30)
    le = LabelEncoder("cat").fit(ds)
    b = le.transform_batch({"cat": np.array(["b", "a", "c"])})
    back = le.inverse_transform_batch(b)
    assert back["cat"].tolist() == ["b", "a", "c"]


def test_simple_imputer(ray_start_regular):
    from ray_tpu import data
    from ray_tpu.air import SimpleImputer

    rows = [{"v": float(i)} for i in range(10)]
    rows[3]["v"] = float("nan")
    rows[7]["v"] = float("nan")
    ds = data.from_items(rows, parallelism=3)
    imp = SimpleImputer(columns=["v"], strategy="mean").fit(ds)
    out = imp.transform(ds).to_pandas()
    assert not out["v"].isna().any()
    clean_mean = np.mean([i for i in range(10) if i not in (3, 7)])
    assert abs(out["v"][3] - clean_mean) < 1e-9

    const = SimpleImputer(columns=["v"], strategy="constant",
                          fill_value=-1.0)
    b = const.transform_batch({"v": np.array([1.0, float("nan")])})
    assert b["v"].tolist() == [1.0, -1.0]


def test_concatenator_and_batch_mapper(ray_start_regular):
    from ray_tpu.air import BatchMapper, Concatenator

    ds = _toy(ray_start_regular, n=20)
    out = Concatenator(columns=["x", "y"]).transform(ds)
    batch = next(out.iter_batches(batch_size=20))
    assert batch["features"].shape == (20, 2)
    assert batch["features"].dtype == np.float32

    bm = BatchMapper(lambda b: {**b, "x2": np.asarray(b["x"]) * 2})
    out = bm.transform(ds).to_pandas()
    assert (out["x2"] == out["x"] * 2).all()


def test_chain_fits_on_prior_output(ray_start_regular):
    """Chain semantics: each stage fits on the PREVIOUS stage's output —
    the scaler here sees imputed values, not NaNs."""
    from ray_tpu import data
    from ray_tpu.air import Chain, Concatenator, SimpleImputer, StandardScaler

    rows = [{"v": float(i), "w": float(i * 2)} for i in range(20)]
    rows[5]["v"] = float("nan")
    ds = data.from_items(rows, parallelism=4)
    chain = Chain(
        SimpleImputer(columns=["v"], strategy="mean"),
        StandardScaler(columns=["v", "w"]),
        Concatenator(columns=["v", "w"]),
    ).fit(ds)
    out = chain.transform(ds)
    batch = next(out.iter_batches(batch_size=20))
    assert batch["features"].shape == (20, 2)
    assert np.isfinite(batch["features"]).all()
    # raw-batch path runs the same pipeline
    b = chain.transform_batch({"v": np.array([1.0]), "w": np.array([2.0])})
    assert b["features"].shape == (1, 2)
