"""Durable GCS storage + fault tolerance.

Reference tier: GCS FT tests over the Redis store client
(python/ray/tests/test_gcs_fault_tolerance.py): kill the GCS
mid-workload, restart it against the same store, and the control plane
comes back — raylets re-register (node_manager.cc:1179
HandleNotifyGCSRestart), live actors re-announce, lost ones restart.
"""
import os
import signal
import subprocess
import sys
import time

import pytest


# ------------------------------------------------------ store client tier

@pytest.mark.parametrize("kind", ["sqlite", "log"])
def test_store_client_roundtrip(tmp_path, kind):
    from ray_tpu._private.gcs_store import make_store

    path = str(tmp_path / f"store_{kind}")
    s = make_store(f"{kind}:{path}")
    s.put("actors", "a1", b"spec1")
    s.put("actors", "a2", b"spec2")
    s.put("kv", "k", b"v")
    s.delete("actors", "a1")
    assert s.get("actors", "a2") == b"spec2"
    assert s.get("actors", "a1") is None
    assert s.get_all("actors") == {"a2": b"spec2"}
    s.close()

    # durability: reopen sees the same state
    s2 = make_store(f"{kind}:{path}")
    assert s2.get_all("actors") == {"a2": b"spec2"}
    assert s2.get("kv", "k") == b"v"
    s2.close()


def test_filelog_torn_record_and_compaction(tmp_path):
    from ray_tpu._private.gcs_store import FileLogStoreClient

    path = str(tmp_path / "log")
    s = FileLogStoreClient(path, compact_bytes=4096)
    for i in range(200):                      # overwrites force compaction
        s.put("t", "key", b"x" * 64 + str(i).encode())
    s.close()
    assert os.path.getsize(path) < 4096 + 256, "log never compacted"

    # torn final record (crash mid-append) is dropped on replay AND
    # truncated away, so appending after it stays well-framed
    with open(path, "ab") as f:
        f.write(b"\x01\x02\x00\x00")          # garbage partial frame
    s2 = FileLogStoreClient(path)
    assert s2.get("t", "key") == b"x" * 64 + b"199"
    s2.put("t", "post_tear", b"alive")
    s2.close()
    s3 = FileLogStoreClient(path)
    assert s3.get("t", "key") == b"x" * 64 + b"199"
    assert s3.get("t", "post_tear") == b"alive"
    s3.close()


# --------------------------------------------------- write-through restore

def test_gcs_restart_restores_tables(tmp_path):
    """Actors, named actors, PGs, KV, and the job counter survive a stop
    + fresh-process-style restart with ZERO snapshot window (no
    save_snapshot call anywhere)."""
    from ray_tpu._private.gcs import GcsServer

    store = f"sqlite:{tmp_path}/gcs.db"
    gcs = GcsServer(store=store).start()
    try:
        gcs.rpc_register_actor(
            None, b"A" * 16,
            {"name": "keeper", "namespace": "ns1", "class_name": "K",
             "max_restarts": -1, "lifetime": "detached"})
        gcs.rpc_actor_started(None, b"A" * 16, ("127.0.0.1", 5), "node9")
        gcs.rpc_kv_put(None, ns="funcs", key=b"f1", value=b"blob")
        gcs.rpc_create_placement_group(
            None, b"P" * 16, [{"CPU": 1}], "PACK", name="gang")
        assert gcs.rpc_next_job_id(None) == 1
    finally:
        gcs.stop()

    gcs2 = GcsServer(store=store, recovery_grace_s=3600).start()
    try:
        info = gcs2.rpc_get_actor(None, name="keeper", namespace="ns1")
        assert info is not None and info["state"] == "ALIVE"
        assert gcs2.rpc_kv_get(None, ns="funcs", key=b"f1") == b"blob"
        pgs = gcs2.rpc_list_placement_groups(None)
        assert len(pgs) == 1 and pgs[0]["Name"] == "gang"
        assert gcs2.rpc_next_job_id(None) == 2   # counter continues
    finally:
        gcs2.stop()


# ----------------------------------------------------------- chaos tier

def _spawn_gcs(port: int, store: str, grace: float = 2.0):
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs", str(port),
         "--store", store, "--grace", str(grace)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline().strip()
    assert line.startswith("GCS_READY"), line
    addr = line.split()[1]
    host, p = addr.rsplit(":", 1)
    return proc, (host, int(p))


def test_sigkill_gcs_detached_actor_and_pg_survive(tmp_path):
    """VERDICT r4 #6 chaos: SIGKILL the GCS mid-workload, restart it on
    the same durable store, and (a) in-flight actor handles keep
    working THROUGH the outage, (b) named lookup works after restart
    without client errors, (c) the PG survives as CREATED on the
    re-registered node."""
    from ray_tpu._private.raylet import Raylet, detect_resources
    from ray_tpu._private.worker_runtime import (CoreWorker,
                                                 set_current_worker)

    store = f"sqlite:{tmp_path}/gcs.db"
    gcs_proc, gcs_addr = _spawn_gcs(0, store)
    raylet = None
    worker = None
    try:
        raylet = Raylet(gcs_addr, resources=detect_resources(4, 0),
                        store_size=64 * 1024 * 1024)
        worker = CoreWorker(gcs_addr, raylet.addr, mode="driver")
        set_current_worker(worker)
        import ray_tpu
        from ray_tpu.util.placement_group import placement_group

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        c = Counter.options(name="survivor", lifetime="detached",
                            max_restarts=-1).remote()
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 1

        pg = placement_group([{"CPU": 1}], strategy="PACK")
        assert pg.wait(30)

        # ---- SIGKILL the GCS mid-workload
        os.kill(gcs_proc.pid, signal.SIGKILL)
        gcs_proc.wait()

        # (a) the established actor channel needs no GCS: calls keep
        # flowing during the outage
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 2

        # ---- restart on the SAME port + store
        gcs_proc, _ = _spawn_gcs(gcs_addr[1], store)

        # (b) named resolution after restart — the driver's GCS channel
        # self-heals; the actor table was restored from the store and
        # the raylet re-announced the live actor
        deadline = time.time() + 30
        info = None
        while time.time() < deadline:
            try:
                h = ray_tpu.get_actor("survivor")
                info = h
                break
            except Exception:
                time.sleep(0.5)
        assert info is not None, "named actor not resolvable after restart"
        assert ray_tpu.get(info.incr.remote(), timeout=60) == 3

        # (c) the PG survived and its bundle node re-registered
        deadline = time.time() + 30
        state = None
        while time.time() < deadline:
            pgs = worker.gcs.call("list_placement_groups")
            if pgs and pgs[0]["State"] == "CREATED" and \
                    all(pgs[0]["BundleNodes"]):
                state = pgs[0]
                break
            time.sleep(0.5)
        assert state is not None, f"PG not CREATED after restart: {pgs}"

        # and new work schedules inside it
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        @ray_tpu.remote(num_cpus=1, max_retries=0)
        def inside():
            return "ok"

        assert ray_tpu.get(inside.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                pg)).remote(), timeout=60) == "ok"
    finally:
        try:
            gcs_proc.kill()
        except Exception:
            pass
        if worker is not None:
            worker.shutdown()
            set_current_worker(None)
        if raylet is not None:
            raylet.stop(kill_workers=True)


def test_gcs_restart_restarts_lost_detached_actor(tmp_path):
    """An actor whose HOST died during the GCS outage: after restart +
    grace, reconciliation restarts it on a surviving node (restored
    spec + durable KV actor_spec drive _push_recreate)."""
    from ray_tpu._private.raylet import Raylet, detect_resources
    from ray_tpu._private.worker_runtime import (CoreWorker,
                                                 set_current_worker)

    store = f"sqlite:{tmp_path}/gcs.db"
    gcs_proc, gcs_addr = _spawn_gcs(0, store, grace=2.0)
    raylets = []
    worker = None
    try:
        # node A hosts the actor; node B survives to restart it
        a = Raylet(gcs_addr, resources=detect_resources(2, 0),
                   store_size=64 * 1024 * 1024)
        raylets.append(a)
        worker = CoreWorker(gcs_addr, a.addr, mode="driver")
        set_current_worker(worker)
        import ray_tpu

        @ray_tpu.remote
        class Phoenix:
            def where(self):
                return os.getpid()

        p = Phoenix.options(name="phoenix", lifetime="detached",
                            max_restarts=-1).remote()
        pid1 = ray_tpu.get(p.where.remote(), timeout=60)

        b = Raylet(gcs_addr, resources=detect_resources(2, 0),
                   store_size=64 * 1024 * 1024)
        raylets.append(b)
        time.sleep(1.0)   # let B register + gossip

        os.kill(gcs_proc.pid, signal.SIGKILL)
        gcs_proc.wait()
        # the actor's host dies DURING the outage (stop() won't reach
        # the dead GCS; swallow the teardown noise)
        try:
            a.stop(kill_workers=True)
        except Exception:
            pass
        raylets.remove(a)

        gcs_proc, _ = _spawn_gcs(gcs_addr[1], store, grace=2.0)

        # after grace, reconciliation restarts the actor on node B
        deadline = time.time() + 60
        pid2 = None
        while time.time() < deadline:
            try:
                h = ray_tpu.get_actor("phoenix")
                pid2 = ray_tpu.get(h.where.remote(), timeout=10)
                break
            except Exception:
                time.sleep(0.5)
        assert pid2 is not None, "detached actor never restarted"
        assert pid2 != pid1
    finally:
        try:
            gcs_proc.kill()
        except Exception:
            pass
        if worker is not None:
            worker.shutdown()
            set_current_worker(None)
        for r in raylets:
            try:
                r.stop(kill_workers=True)
            except Exception:
                pass
