"""Multi-agent RL tests (reference: rllib/env/multi_agent_env.py +
multi-policy PPO over MultiAgentCartPole)."""
import numpy as np
import pytest


def test_multi_agent_env_contract():
    from ray_tpu.rllib.multi_agent import MultiAgentCartPole

    env = MultiAgentCartPole(num_agents=2, seed=0)
    obs, _ = env.reset()
    assert set(obs) == {"agent_0", "agent_1"}
    obs, rewards, terms, truncs, _ = env.step(
        {"agent_0": 0, "agent_1": 1})
    assert set(rewards) == {"agent_0", "agent_1"}
    assert terms["__all__"] is False
    # drive until everyone drops; __all__ must flip exactly then
    for _ in range(500):
        acts = {aid: 0 for aid in obs}
        obs, rewards, terms, truncs, _ = env.step(acts)
        if terms["__all__"]:
            break
    assert terms["__all__"] is True
    # reset revives every agent
    obs, _ = env.reset()
    assert set(obs) == {"agent_0", "agent_1"}


def test_shared_policy_learns(ray_start_regular):
    """Parameter sharing: both agents map to one policy, which must
    learn from their combined experience."""
    from ray_tpu.rllib.multi_agent import MultiAgentCartPole, MultiAgentPPO

    algo = MultiAgentPPO(
        lambda seed: MultiAgentCartPole(num_agents=2, seed=seed),
        policy_mapping_fn=lambda aid: "shared",
        num_rollout_workers=2, rollout_fragment_length=128,
        lr=3e-4, minibatch_size=128, seed=0)
    try:
        best = 0.0
        for _ in range(40):
            result = algo.train()
            assert result["policies_trained"] == ["shared"]
            best = max(best, result["episode_reward_mean"])
            if best >= 100.0:
                break
        assert best >= 80.0, f"shared policy failed to learn: {best}"
    finally:
        algo.stop()


def test_independent_policies_both_train(ray_start_regular):
    """Per-agent policies: each agent id gets its own parameters; one
    iteration must produce and update BOTH."""
    from ray_tpu.rllib.multi_agent import MultiAgentCartPole, MultiAgentPPO

    algo = MultiAgentPPO(
        lambda seed: MultiAgentCartPole(num_agents=2, seed=seed),
        policy_mapping_fn=lambda aid: aid,      # identity: own policy
        num_rollout_workers=1, rollout_fragment_length=64,
        minibatch_size=64, seed=0)
    try:
        before = {pid: algo.params[pid] for pid in algo.params}
        result = algo.train()
        assert result["policies_trained"] == ["agent_0", "agent_1"]
        import jax

        for pid in ("agent_0", "agent_1"):
            changed = jax.tree_util.tree_map(
                lambda a, b: bool(np.any(np.asarray(a) != np.asarray(b))),
                before[pid], algo.params[pid])
            assert any(jax.tree_util.tree_leaves(changed)), \
                f"{pid} params unchanged"
        # round-trips
        state = algo.save()
        algo.restore(state)
    finally:
        algo.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
