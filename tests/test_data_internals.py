"""Data internals: columnar blocks, push-based shuffle at scale across a
multi-node cluster, DatasetPipeline windows.

Reference tier: python/ray/data/tests/ (test_dataset_pipeline,
push-based-shuffle coverage).
"""
import numpy as np
import pytest


def test_million_row_shuffle_across_cluster(ray_start_cluster):
    """1M rows shuffled over a 3-node in-process cluster: the round-brief
    done-criterion for the data internals item."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    from ray_tpu import data

    n = 1_000_000
    ds = data.from_numpy(np.arange(n, dtype=np.int64), parallelism=12)
    shuffled = ds.random_shuffle(seed=7)
    # checksum: same multiset of values
    total = 0
    seen_order = []
    for batch in shuffled.iter_batches(batch_size=100_000):
        total += int(batch.sum())
        seen_order.append(int(batch[0]))
    assert total == n * (n - 1) // 2
    # actually shuffled: the first elements of batches aren't the sorted
    # prefix starts
    assert seen_order != sorted(seen_order)


def test_columnar_blocks_feed_batches_without_row_python(ray_start_regular):
    """Dict-rows datasets store columnar blocks; iter_batches slices
    arrays (never materializing Python row objects)."""
    import ray_tpu
    from ray_tpu import data
    from ray_tpu.data import block as B

    rows = [{"x": float(i), "y": i % 5} for i in range(1000)]
    ds = data.from_items(rows, parallelism=4)
    blk = ray_tpu.get(ds._block_refs[0])
    assert B.is_columnar(blk), f"expected columnar block, got {type(blk)}"
    batches = list(ds.iter_batches(batch_size=300))
    assert [len(b["x"]) for b in batches] == [300, 300, 300, 100]
    assert all(isinstance(b["x"], np.ndarray) for b in batches)
    np.testing.assert_array_equal(
        np.concatenate([b["x"] for b in batches]),
        np.arange(1000, dtype=float))


def test_batches_cross_block_boundaries(ray_start_regular):
    from ray_tpu import data

    ds = data.from_numpy(np.arange(100), parallelism=7)  # ragged blocks
    batches = list(ds.iter_batches(batch_size=17))
    assert sum(len(b) for b in batches) == 100
    np.testing.assert_array_equal(np.concatenate(batches), np.arange(100))
    assert all(len(b) == 17 for b in batches[:-1])


def test_dataset_pipeline_windows(ray_start_regular):
    from ray_tpu import data

    calls = []

    def stamp(block):
        return block * 10

    ds = data.from_numpy(np.arange(40), parallelism=8)
    pipe = ds.window(blocks_per_window=2).map_batches(stamp)
    assert pipe.num_windows() == 4
    out = np.concatenate(list(pipe.iter_batches(batch_size=10)))
    np.testing.assert_array_equal(out, np.arange(40) * 10)
    assert pipe.count() == 40
    del calls


def test_pipeline_repeat_epochs(ray_start_regular):
    from ray_tpu import data

    ds = data.from_numpy(np.arange(10), parallelism=2)
    pipe = ds.repeat(3)
    rows = [int(r) for r in pipe.iter_rows()]
    assert len(rows) == 30
    assert sorted(set(rows)) == list(range(10))
    # infinite repeat: take() terminates
    inf = ds.repeat()
    assert len(inf.take(25)) == 25


def test_pipeline_per_window_shuffle(ray_start_regular):
    from ray_tpu import data

    ds = data.from_numpy(np.arange(100), parallelism=4)
    pipe = ds.window(blocks_per_window=2).random_shuffle_each_window(seed=3)
    rows = [int(r) for r in pipe.iter_rows()]
    assert sorted(rows) == list(range(100))
    assert rows != list(range(100))


def test_distributed_groupby_large(ray_start_regular):
    from ray_tpu import data

    rows = [{"k": i % 17, "v": i} for i in range(5000)]
    out = data.from_items(rows, parallelism=8).groupby("k").aggregate(
        lambda g: sum(int(r["v"]) for r in g)).take_all()
    got = {int(r["key"]): int(r["value"]) for r in out}
    want = {}
    for i in range(5000):
        want[i % 17] = want.get(i % 17, 0) + i
    assert got == want
