"""Worker-log streaming tests (reference: _private/log_monitor.py tail →
pubsub → worker.py:1733 print_worker_logs on the driver)."""
import os
import time

import pytest


def test_collapse_repeats_dedup():
    from ray_tpu._private.log_monitor import _collapse_repeats

    assert _collapse_repeats([]) == []
    assert _collapse_repeats(["a", "b"]) == ["a", "b"]
    assert _collapse_repeats(["x"] * 50) == ["x [repeated 50 times]"]
    assert _collapse_repeats(["a", "a", "b", "a"]) == [
        "a [repeated 2 times]", "b", "a"]


def test_log_monitor_tails_batches_and_drains(tmp_path):
    from ray_tpu._private.log_monitor import LogMonitor

    out_path = tmp_path / "w1.out"
    err_path = tmp_path / "w1.err"
    out_path.write_text("")
    err_path.write_text("")
    batches = []
    mon = LogMonitor(lambda ch, msg: batches.append((ch, msg)),
                     node_id="node0123abcd")
    mon.track("w1", 4242, str(out_path), str(err_path))

    with open(out_path, "a") as f:
        f.write("first line\npartial")
    mon.tick()
    assert len(batches) == 1
    ch, msg = batches[0]
    assert ch == "worker_logs"
    assert msg["lines"] == ["first line"]      # partial line held back
    assert msg["pid"] == 4242 and msg["stream"] == "out"

    with open(out_path, "a") as f:
        f.write(" continued\nsecond\n")
    mon.tick()
    assert batches[-1][1]["lines"] == ["partial continued", "second"]

    # stderr goes out with stream="err"
    with open(err_path, "a") as f:
        f.write("oops\n")
    mon.tick()
    errs = [m for _, m in batches if m["stream"] == "err"]
    assert errs and errs[-1]["lines"] == ["oops"]

    # death: the unterminated tail is flushed, then the tail is dropped
    with open(out_path, "a") as f:
        f.write("last words")
    mon.mark_dead("w1")
    mon.tick()
    assert batches[-1][1]["lines"] == ["last words"]
    mon.tick()          # empty drain removes the tails
    n = len(batches)
    with open(out_path, "a") as f:
        f.write("ghost\n")
    mon.tick()
    assert len(batches) == n    # untracked after death


def test_format_log_batch_prefixes():
    from ray_tpu._private.log_monitor import format_log_batch

    lines = format_log_batch({
        "node_id": "deadbeefcafe0123", "worker_id": "w", "pid": 7,
        "actor_name": None, "stream": "out", "lines": ["hi", "there"]})
    assert lines == ["(pid=7, node=deadbeef) hi",
                     "(pid=7, node=deadbeef) there"]
    named = format_log_batch({
        "node_id": "deadbeefcafe0123", "worker_id": "w", "pid": 7,
        "actor_name": "Counter", "stream": "err", "lines": ["x"]})
    assert named == ["(Counter pid=7, node=deadbeef) x"]


def test_remote_print_streams_to_driver(capfd):
    """End to end: a remote print lands on the driver's console with the
    (pid=, node=) prefix."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def shout():
            print("hello-from-worker-xyz")
            import sys

            print("err-from-worker-xyz", file=sys.stderr)
            return 1

        assert ray_tpu.get(shout.remote(), timeout=60) == 1
        acc_out, acc_err = "", ""
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            out, err = capfd.readouterr()
            acc_out += out
            acc_err += err
            if ("hello-from-worker-xyz" in acc_out
                    and "err-from-worker-xyz" in acc_err):
                break
            time.sleep(0.2)
        assert "hello-from-worker-xyz" in acc_out, acc_out[-2000:]
        out_line = next(ln for ln in acc_out.splitlines()
                        if "hello-from-worker-xyz" in ln)
        assert out_line.startswith("(pid="), out_line
        assert "err-from-worker-xyz" in acc_err, acc_err[-2000:]
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
