"""Production Serve plane (late-alphabet; sequenced after the tier-1
timeout horizon by design — keep each test fast).

Covers the PR 6 tentpole at unit + E2E scale: config validation at
construction (named ``ServeConfigError``), shape-aware batching against
a recompile-count oracle (the compile_watch classification the batcher
shares with the training step), ``@serve.batch`` fan-out hardening
(per-caller exception clones, call-shape rejection), router
power-of-two-choices distribution + bounded-queue admission control
(typed ``ServeOverloadedError`` + ``REQUEST_SHED``), autoscale
hysteresis (a scale proposal must SUSTAIN for the configured delay),
drain semantics (``ReplicaDrainingError`` → transparent re-dispatch),
zero-copy same-node weight sharing over the shm store, and a seeded
``kill_actor`` replica death → sub-second failover with zero lost
accepted requests (the PR 5 fault DSL riding the ``serve-<dep>``
process tags replicas register at construction).
"""
import os
import threading
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.serve]


# ------------------------------------------------------------- pure units

def test_config_validation_named_errors():
    """Bad values fail at CONSTRUCTION with a named error, not as a deep
    controller-side failure three actors later."""
    from ray_tpu.exceptions import ServeConfigError
    from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig

    for bad in (dict(num_replicas=0), dict(num_replicas=-3),
                dict(max_ongoing_requests=0),
                dict(max_queued_requests=-1),
                dict(graceful_shutdown_timeout_s=-0.5),
                dict(health_check_period_s=-1),
                dict(health_check_timeout_s=-2)):
        with pytest.raises(ServeConfigError):
            DeploymentConfig(**bad)
    for bad in (dict(min_replicas=3, max_replicas=2),
                dict(min_replicas=-1),
                dict(max_replicas=0),
                dict(target_ongoing_requests=0),
                dict(target_ongoing_requests=-1.0),
                dict(upscale_delay_s=-0.1),
                dict(downscale_delay_s=-0.1),
                dict(metrics_interval_s=-1),
                dict(smoothing_factor=0)):
        with pytest.raises(ServeConfigError):
            AutoscalingConfig(**bad)
    # subclasses ValueError: generic config-validation handlers keep
    # working
    with pytest.raises(ValueError):
        DeploymentConfig(num_replicas=0)
    # defaults are valid
    DeploymentConfig()
    AutoscalingConfig()


def test_options_validates_at_call_site():
    """.options(...) round-trips through __post_init__, so the operator
    sees the error where they wrote the value, pre-deploy."""
    import ray_tpu.serve as serve
    from ray_tpu.exceptions import ServeConfigError

    @serve.deployment
    def f(x):
        return x

    with pytest.raises(ServeConfigError):
        f.options(num_replicas=0)
    with pytest.raises(ServeConfigError):
        f.options(max_ongoing_requests=-1)
    with pytest.raises(ServeConfigError):
        f.options(autoscaling_config={"min_replicas": 5, "max_replicas": 2})
    # valid options still produce an immutable copy
    g = f.options(num_replicas=3)
    assert g.config.num_replicas == 3 and f.config.num_replicas == 1

    # user_config is OPAQUE: .options() and to_dict() must ship the
    # operator's object itself, not an asdict()-mangled deep copy
    class MyCfg:
        lr = 0.1

    cfg_obj = MyCfg()
    h = f.options(user_config=cfg_obj)
    assert h.config.user_config is cfg_obj
    assert h.config.to_dict()["user_config"] is cfg_obj


def test_autoscale_desired_replicas_math():
    from ray_tpu.serve.config import AutoscalingConfig

    ac = AutoscalingConfig(min_replicas=1, max_replicas=8,
                           target_ongoing_requests=2.0)
    # per-replica load 4 = 2x target → double
    assert ac.desired_replicas(2, 8.0) == 4
    # at target: hold
    assert ac.desired_replicas(4, 8.0) == 4
    # clamp to bounds
    assert ac.desired_replicas(4, 1000.0) == 8
    assert ac.desired_replicas(4, 0.0) == 1
    # no running replicas: come up at the floor
    assert ac.desired_replicas(0, 0.0) == 1


def test_autoscale_hysteresis_sustain_before_scale():
    """A scale proposal only moves the target after it SUSTAINS for the
    configured up/downscale delay — blips don't scale."""
    from ray_tpu.serve._private.controller import RUNNING, _DeploymentState
    from ray_tpu.serve._private.long_poll import LongPollHost

    spec = {"name": "m", "user_callable": object, "config": {
        "autoscaling_config": {
            "min_replicas": 1, "max_replicas": 4,
            "target_ongoing_requests": 1.0,
            "upscale_delay_s": 0.15, "downscale_delay_s": 0.15,
            "metrics_interval_s": 3600.0}}}
    ds = _DeploymentState("app#m", spec, LongPollHost())

    class _R:
        state = RUNNING
        num_ongoing = 0.0
        warned = False
        drain_requested = False

    ds.replicas = [_R()]
    ds._last_metrics_poll = time.monotonic()   # suppress replica polling
    assert ds.target_num == 1

    # demand for 4 replicas appears (handle-side router metric)
    ds.handle_metrics["r1"] = (6.0, time.monotonic())
    ds._autoscale()
    assert ds.target_num == 1, "scaled on an unsustained proposal"
    time.sleep(0.2)
    ds.handle_metrics["r1"] = (6.0, time.monotonic())
    ds._autoscale()
    assert ds.target_num == 4, "sustained upscale proposal did not apply"

    # demand vanishes: downscale also waits out its delay
    ds.handle_metrics["r1"] = (0.0, time.monotonic())
    ds._autoscale()
    assert ds.target_num == 4
    time.sleep(0.2)
    ds.handle_metrics["r1"] = (0.0, time.monotonic())
    ds._autoscale()
    assert ds.target_num == 1

    # a proposal that CHANGES resets the clock (4 → idle blip → 4)
    ds.handle_metrics["r1"] = (6.0, time.monotonic())
    ds._autoscale()
    ds.handle_metrics["r1"] = (0.0, time.monotonic())
    ds._autoscale()                      # different proposal: clock resets
    ds.handle_metrics["r1"] = (6.0, time.monotonic())
    ds._autoscale()
    assert ds.target_num == 1, "flapping proposals must not scale"


def test_bucket_sizes_and_padding(monkeypatch):
    from ray_tpu.serve.batching import _Batcher, default_bucket_sizes

    assert default_bucket_sizes(8) == (1, 2, 4, 8)
    assert default_bucket_sizes(6) == (1, 2, 4, 6)   # max always included
    assert default_bucket_sizes(1) == (1,)

    b = _Batcher(lambda xs: xs, 6, 0.01)
    assert b.bucket_sizes == (1, 2, 4, 6)
    items, pad = b._pad_to_bucket([10, 20, 30])
    # padded by replicating the LAST REAL item, never a sentinel
    assert items == [10, 20, 30, 30] and pad == 1
    items, pad = b._pad_to_bucket([5])
    assert items == [5] and pad == 0
    items, pad = b._pad_to_bucket([1, 2, 3, 4, 5])
    assert len(items) == 6 and pad == 1

    # explicit buckets are honored (and max_batch_size appended if absent)
    b2 = _Batcher(lambda xs: xs, 8, 0.01, bucket_sizes=(3, 5))
    assert b2.bucket_sizes == (3, 5, 8)

    # a bucket above max_batch_size would pad batches past the bound the
    # wrapped function was sized for: rejected at decoration time
    from ray_tpu.serve.batching import batch

    with pytest.raises(ValueError, match="batch_size_buckets"):
        batch(max_batch_size=8, batch_size_buckets=[16])(lambda xs: xs)
    with pytest.raises(ValueError, match="batch_size_buckets"):
        batch(max_batch_size=8, batch_size_buckets=[0, 4])(lambda xs: xs)
    with pytest.raises(ValueError, match="max_batch_size"):
        batch(max_batch_size=0)(lambda xs: xs)

    # kill switch restores the legacy pad-free batcher
    monkeypatch.setenv("RAY_TPU_SERVE_SHAPE_BUCKETS", "0")
    b3 = _Batcher(lambda xs: xs, 8, 0.01)
    assert b3.bucket_sizes is None
    items, pad = b3._pad_to_bucket([1, 2, 3])
    assert items == [1, 2, 3] and pad == 0


def test_shape_bucketing_recompile_oracle(monkeypatch):
    """THE shape-aware acceptance proof at unit scale: a mixed
    batch-size traffic stream through the bucketing batcher converges to
    ZERO new pjit-cache misses once each bucket has compiled (4 buckets
    → 4 misses, flat afterwards), while the legacy
    ``RAY_TPU_SERVE_SHAPE_BUCKETS=0`` path keeps recompiling — one miss
    per distinct raw batch size, still climbing deep into the stream."""
    from ray_tpu.serve.batching import _Batcher
    from ray_tpu.util.metrics import registry_snapshot

    def misses(name):
        fam = next((m for m in registry_snapshot()
                    if m["name"] == "ray_tpu_pjit_cache_total"), None)
        if fam is None:
            return 0.0
        return sum(v["value"] for v in fam["values"]
                   if v["tags"].get("fn") == f"serve_batch::{name}"
                   and v["tags"].get("result") == "miss")

    traffic = [3, 1, 5, 2, 7, 4, 8, 6, 3, 5, 7, 1, 6, 2, 8, 4]

    def replay(name):
        b = _Batcher(lambda xs: [x.sum() for x in xs], 8, 0.01, name=name)
        assert misses(name) == 0.0
        history = []
        for n in traffic:
            items, _ = b._pad_to_bucket([np.zeros((4, 2))] * n)
            b._fn(items)           # classified exactly like the loop does
            history.append(misses(name))
        return history

    bucketed = replay("zz_oracle_bucketed")
    # warmup: sizes 3,1,5,2 touch buckets 4,1,8,2 — all four compiled
    assert bucketed[3] == 4.0
    # converged: no new compile for the rest of the stream
    assert bucketed[-1] == 4.0, f"bucketed batcher kept recompiling: " \
                                f"{bucketed}"

    monkeypatch.setenv("RAY_TPU_SERVE_SHAPE_BUCKETS", "0")
    legacy = replay("zz_oracle_legacy")
    # every distinct raw size is a fresh signature: 8 sizes → 8 misses,
    # the 8th landing at index 7 — recompiling long after the bucketed
    # path went flat
    assert legacy[-1] == 8.0
    assert legacy[7] > bucketed[7]


def test_batch_per_caller_exception_isolation():
    """Each caller of a failed batch gets ITS OWN exception object — one
    caller's handler mutating __cause__/__context__ must not corrupt
    what the batch's other callers observe."""
    from ray_tpu.serve.batching import batch

    @batch(max_batch_size=4, batch_wait_timeout_s=0.1)
    def boom(items):
        raise ValueError("batch exploded")

    errs = [None] * 3
    barrier = threading.Barrier(3)

    def call(i):
        barrier.wait()
        try:
            boom(i)
        except ValueError as e:
            errs[i] = e

    threads = [threading.Thread(target=call, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(isinstance(e, ValueError) for e in errs), errs
    assert len({id(e) for e in errs}) == 3, "callers shared one exception"
    # one caller re-raising `from` another error rewrites __cause__ —
    # the others must not see it
    cause = RuntimeError("caller 0's local context")
    errs[0].__cause__ = cause
    assert errs[1].__cause__ is not cause
    assert errs[2].__cause__ is not cause
    # the clones still agree on what failed
    assert {str(e) for e in errs} == {"batch exploded"}


def test_batch_call_shape_rejection():
    """kwargs / wrong arity get one clear message, not a bare TypeError
    arity mismatch from deep inside the batcher — on both the free-
    function and bound-method paths."""
    from ray_tpu.serve.batching import batch

    @batch
    def f(items):
        return items

    with pytest.raises(TypeError, match="keyword"):
        f(1, mode="fast")
    with pytest.raises(TypeError, match="exactly one request"):
        f(1, 2)
    with pytest.raises(TypeError, match="exactly one request"):
        f()

    class M:
        @batch
        def g(self, items):
            return [x + 1 for x in items]

    m = M()
    with pytest.raises(TypeError, match="keyword"):
        m.g(1, extra=2)
    with pytest.raises(TypeError, match="exactly one request"):
        m.g()
    assert m.g(41) == 42   # the good path still works after rejections


def test_batch_wrapper_pickle_roundtrip():
    """The wrapper ships inside deployment specs (a class attribute of
    the user class): it must cloudpickle with its live batcher thread
    and creation lock stripped, and rebuild them lazily on arrival."""
    import cloudpickle

    from ray_tpu.serve.batching import batch

    @batch(max_batch_size=4, batch_wait_timeout_s=0.01)
    def double_all(items):
        return [x * 2 for x in items]

    assert double_all(21) == 42      # live batcher thread now exists
    w2 = cloudpickle.loads(cloudpickle.dumps(double_all))
    assert w2(5) == 10
    assert w2._batch_size_buckets == double_all._batch_size_buckets


def test_replica_drain_refuses_new_work():
    """A draining replica rejects new requests with the typed error the
    handle layer re-dispatches on — scale-down must not lose accepted
    requests that raced the routing update."""
    from ray_tpu.exceptions import ReplicaDrainingError
    from ray_tpu.serve._private.replica import ReplicaActor

    class M:
        def __call__(self, x):
            return x + 1

    r = ReplicaActor("zzapp#m", "zzapp#m#abc", M, (), {})
    assert r.handle_request("__call__", (1,), {}) == 2
    assert r.prepare_for_shutdown(timeout_s=0.2) is True
    with pytest.raises(ReplicaDrainingError):
        r.handle_request("__call__", (1,), {})
    # draining replicas report their residual work to the autoscaler
    assert r.get_metrics()["num_ongoing_requests"] == 0


# ------------------------------------------------------------ runtime E2E

def test_router_distribution_admission_and_summary(ray_start_regular):
    """p2c routing spreads load across replicas; admission control sheds
    (typed error + retry-after + REQUEST_SHED event) instead of queueing
    without bound; the state API folds it all into one rollup."""
    import ray_tpu.serve as serve
    from ray_tpu._private import events
    from ray_tpu.exceptions import ServeOverloadedError

    @serve.deployment(num_replicas=2, max_ongoing_requests=2,
                      max_queued_requests=4)
    class Who:
        def __call__(self, _):
            import os as _os

            return _os.getpid()

    try:
        h = serve.run(Who.bind(), name="zzwho", route_prefix=None)
        pids = {h.remote(i).result(timeout_s=10) for i in range(16)}
        assert len(pids) == 2, f"p2c never reached one replica: {pids}"

        @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                          max_queued_requests=0)
        class Slow:
            def __call__(self, _):
                time.sleep(1.0)
                return "done"

        h2 = serve.run(Slow.bind(), name="zzslow", route_prefix=None)
        r1 = h2.remote(0)            # occupies the only slot
        time.sleep(0.2)
        with pytest.raises(ServeOverloadedError) as ei:
            h2.remote(1)             # saturated + zero queue → shed NOW
        assert ei.value.retry_after_s > 0
        assert "zzslow" in str(ei.value)
        assert any(e["kind"] == "REQUEST_SHED"
                   and e.get("deployment") == "zzslow#Slow"
                   for e in events.snapshot())
        assert r1.result(timeout_s=10) == "done"   # the accepted one runs

        from ray_tpu.experimental.state.api import summarize_serve

        s = summarize_serve()
        assert s["applications"]["zzwho"]["status"] == "RUNNING"
        row = s["requests"]["zzwho#Who"]
        assert row["ok"] >= 16 and row["mean_latency_s"] > 0
        assert s["requests"]["zzslow#Slow"]["shed"] >= 1
        assert any(e["kind"] == "REQUEST_SHED" for e in s["events"])
    finally:
        serve.shutdown()


def test_drain_redispatch_no_lost_requests(ray_start_regular):
    """A request that lands on a draining replica is transparently
    re-dispatched to a survivor. Regression: ReplicaDrainingError is a
    RayError, so serialize_error ships it UNWRAPPED and ray_tpu.get
    re-raises the raw type — a handler matching only the TaskError
    wrapper never fires and the caller sees the drain error (a lost
    accepted request)."""
    import ray_tpu
    import ray_tpu.serve as serve

    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Two:
        def __call__(self, x):
            return x + 1

    try:
        h = serve.run(Two.bind(), name="zzdrain", route_prefix=None)
        h.remote(0).result(timeout_s=10)   # force router creation
        from ray_tpu.serve.handle import _get_router

        router = _get_router("zzdrain#Two")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and router.num_replicas() < 2:
            time.sleep(0.05)
        # drain one replica BEHIND the controller's back: the router
        # keeps routing to it, so requests race the (never-coming)
        # broadcast — exactly the scale-down window
        rid = next(iter(router._replicas))
        victim = ray_tpu.get_actor(f"SERVE_REPLICA::{rid}",
                                   namespace="serve")
        assert ray_tpu.get(victim.prepare_for_shutdown.remote(0.1),
                           timeout=10)
        responses = [h.remote(i) for i in range(10)]
        results = [r.result(timeout_s=15) for r in responses]
        assert results == [i + 1 for i in range(10)], \
            "drain lost or corrupted accepted requests"
        failovers = sum(r.num_failovers for r in responses)
        assert failovers >= 1, "no request ever hit the drainer?"
        # the first rejection evicted the drainer from selection
        assert rid not in router._replicas
        # repeat result() replays the settled value without re-running
        # the request (metrics/retries are once per request)
        assert responses[0].result() == 1
    finally:
        serve.shutdown()


def test_shared_weights_zero_copy_same_node(ray_start_regular):
    """N same-node replicas of one model cost ONE host copy: the first
    loader publishes through the shm store's put_ephemeral path, later
    replicas map the sealed segment zero-copy (read-only views) and
    never run their loader."""
    ray = ray_start_regular

    class Replica:
        def load(self, marker):
            import numpy as _np

            import ray_tpu.serve as serve

            calls = []

            def loader():
                calls.append(1)
                return {"w": _np.arange(8, dtype=_np.float32) * marker,
                        "meta": f"from-{marker}"}

            v = serve.shared_weights("zzserve:wtest", loader)
            return {"loader_ran": len(calls), "w": v["w"].tolist(),
                    "writable": bool(v["w"].flags.writeable),
                    "meta": v["meta"]}

        def release(self):
            import ray_tpu.serve as serve

            return serve.release_shared_weights("zzserve:wtest",
                                                delete=True)

    a = ray.remote(Replica).options(num_cpus=0).remote()
    b = ray.remote(Replica).options(num_cpus=0).remote()
    first = ray.get(a.load.remote(1))
    second = ray.get(b.load.remote(999))   # poison loader: must not run
    assert first["loader_ran"] == 1
    assert second["loader_ran"] == 0, "second replica re-ran the loader"
    assert second["w"] == first["w"] == list(range(8))
    assert second["meta"] == "from-1"
    # zero-copy views over the shared segment are read-only
    assert first["writable"] is False and second["writable"] is False
    assert ray.get(a.release.remote()) is True


@pytest.mark.chaos
@pytest.mark.fault_injection
def test_seeded_replica_kill_subsecond_failover():
    """Deterministic chaos: every replica process of the deployment is
    killed (os._exit via the seeded ``kill_actor`` DSL) at its 3rd
    ``handle_request`` dispatch — so kills keep landing as the
    controller back-fills capacity. Every accepted request must still
    succeed (zero lost, all correct), recovery stays bounded even when
    BOTH replicas die back-to-back (full capacity rebuild), and the
    death feed's traffic-shed latency — the millisecond-failover claim
    — is then measured directly on a live replica kill."""
    import ray_tpu

    os.environ["RAY_TPU_FAULT_SEED"] = "11"
    os.environ["RAY_TPU_FAULT_SCHEDULE"] = \
        "kill_actor:serve-zzchaos-Victim.handle_request:#3"
    try:
        ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
        import ray_tpu.serve as serve
        from ray_tpu.util.metrics import registry_snapshot

        @serve.deployment(num_replicas=2, max_ongoing_requests=4)
        class Victim:
            def __call__(self, x):
                return x * 3

        try:
            h = serve.run(Victim.bind(), name="zzchaos", route_prefix=None)
            results, durations = [], []
            for i in range(12):
                t0 = time.monotonic()
                results.append(h.remote(i).result(timeout_s=20))
                durations.append(time.monotonic() - t0)
            # zero lost accepted requests, all correct
            assert results == [i * 3 for i in range(12)]
            # at least one request rode a killed replica and failed over
            fam = next((m for m in registry_snapshot()
                        if m["name"] == "ray_tpu_serve_failovers_total"),
                       None)
            failovers = sum(
                v["value"] for v in (fam["values"] if fam else [])
                if v["tags"].get("deployment") == "zzchaos#Victim")
            assert failovers >= 1, "schedule never landed a kill"
            # unaffected requests stay fast; even a request that rode a
            # kill cascade into a from-zero capacity rebuild (both
            # replicas dead → controller starts a replacement) recovers
            # within a bounded window, not an op-timeout
            durations.sort()
            # typical median ~60-120 ms; headroom for shared-cgroup
            # stalls (the precise numbers live in BENCH_r07.json)
            assert durations[len(durations) // 2] < 0.6, durations
            assert durations[-1] < 8.0, \
                f"recovery unbounded: {durations[-1]:.3f}s"

            # --- direct millisecond-failover measurement -------------
            # Kill a live replica and time the GCS-death-feed path:
            # the router must flag it (new traffic sheds, in-flight
            # re-dispatches) in well under a second — this, not the
            # capacity rebuild above, is the failover latency claim.
            from ray_tpu.serve.handle import _get_router

            router = _get_router("zzchaos#Victim")
            assert router.has_death_watch(), \
                "router degraded to long-poll-only updates"
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not router.num_replicas():
                time.sleep(0.05)       # wait out the rebuild from the loop
            rid = next(iter(router._replicas))
            victim = ray_tpu.get_actor(f"SERVE_REPLICA::{rid}",
                                       namespace="serve")
            t0 = time.monotonic()
            ray_tpu.kill(victim)
            while not router.replica_dead(rid):
                assert time.monotonic() - t0 < 5.0, \
                    "death feed never reached the router"
                time.sleep(0.002)
            shed_latency = time.monotonic() - t0
            # typically tens of ms (death feed publish latency); the
            # bound is generous for cgroup stalls but still 10x under
            # the health-check period this path exists to beat
            assert shed_latency < 1.5, \
                f"death→shed took {shed_latency:.3f}s"
            # traffic still flows (survivor + controller back-fill)
            assert h.remote(100).result(timeout_s=20) == 300
        finally:
            serve.shutdown()
    finally:
        os.environ.pop("RAY_TPU_FAULT_SEED", None)
        os.environ.pop("RAY_TPU_FAULT_SCHEDULE", None)
        ray_tpu.shutdown()
