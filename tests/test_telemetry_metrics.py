"""Internal metrics plane + Prometheus exposition (PR 2).

- golden-text Prometheus histogram family (cumulative _bucket/+Inf,
  _count, _sum, label escaping);
- cross-worker metrics_summary() aggregation (counters sum per tag set);
- registry re-instantiation keeps accumulated values (satellite fix);
- retry/fault counters consistent with an injected schedule;
- metric-catalog lint: every internal metric literal in the tree is
  declared in _private/telemetry.py with a ray_tpu_ prefix and a unit
  suffix.

Late-alphabet on purpose (tier-1 wall-clock budget); keep fast.
"""
import time

import pytest


# ------------------------------------------------------------ pure units


def test_prometheus_text_histogram_golden():
    from ray_tpu.util.metrics import Histogram, prometheus_text

    h = Histogram("golden_latency_seconds", description="golden help",
                  boundaries=[0.1, 1.0], tag_keys=("k",))
    tags = {"k": 'a"b\\c\nd'}
    h.observe(0.0625, tags=tags)
    h.observe(0.5, tags=tags)
    h.observe(2.0, tags=tags)
    text = prometheus_text([h.snapshot()])
    lbl = 'k="a\\"b\\\\c\\nd"'
    expected = "\n".join([
        "# HELP golden_latency_seconds golden help",
        "# TYPE golden_latency_seconds histogram",
        "golden_latency_seconds_bucket{%s,le=\"0.1\"} 1" % lbl,
        "golden_latency_seconds_bucket{%s,le=\"1.0\"} 2" % lbl,
        "golden_latency_seconds_bucket{%s,le=\"+Inf\"} 3" % lbl,
        "golden_latency_seconds_count{%s} 3" % lbl,
        "golden_latency_seconds_sum{%s} 2.5625" % lbl,
    ]) + "\n"
    assert text == expected, text


def test_metric_reregistration_keeps_values():
    """Satellite fix: re-instantiating a same-name/same-type metric must
    return the live instance, not silently drop accumulated values."""
    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    c1 = Counter("rereg_requests_total", description="first")
    c1.inc(3.0)
    c2 = Counter("rereg_requests_total")
    assert c2 is c1
    assert c2.snapshot()["values"] == [{"tags": {}, "value": 3.0}]
    with pytest.raises(ValueError):
        Gauge("rereg_requests_total")   # same name, different type
    h1 = Histogram("rereg_latency_seconds", boundaries=[0.1, 1.0])
    h1.observe(0.05)
    h2 = Histogram("rereg_latency_seconds", boundaries=[7.0])
    assert h2 is h1
    assert h2.boundaries == [0.1, 1.0]      # live layout kept
    assert h2.snapshot()["counts"][0]["counts"][0] == 1


def test_aggregate_snapshots_sums_and_dedups():
    from ray_tpu.util.metrics import Counter, aggregate_snapshots

    c = Counter("aggdedup_total", tag_keys=("t",))
    c.inc(2.0, tags={"t": "x"})
    a = c.snapshot()
    b = dict(a)
    b["pid"] = (a["pid"] or 0) + 1   # "another process"
    merged = aggregate_snapshots([a, a, b])   # a twice: deduped
    row = next(m for m in merged if m["name"] == "aggdedup_total")
    assert row["values"] == [{"tags": {"t": "x"}, "value": 4.0}]


def test_aggregate_snapshots_histogram_boundary_clash_drops_whole_snap():
    """A process with a different bucket layout must contribute NEITHER
    its sum NOR its counts — a summed _sum over excluded buckets would
    publish an internally inconsistent family."""
    from ray_tpu.util.metrics import Histogram, aggregate_snapshots

    h = Histogram("bclash_latency_seconds", boundaries=[0.1, 1.0])
    h.observe(0.05)
    a = h.snapshot()
    b = {**a, "pid": (a["pid"] or 0) + 1, "boundaries": [9.9],
         "values": [{"tags": {}, "value": 100.0}],
         "counts": [{"tags": {}, "counts": [1, 0]}]}
    merged = aggregate_snapshots([a, b])
    row = next(m for m in merged if m["name"] == "bclash_latency_seconds")
    assert row["values"] == a["values"]
    assert row["counts"][0]["counts"] == a["counts"][0]["counts"]


def test_retry_budget_exhaustion_counter_and_event():
    from ray_tpu._private import events
    from ray_tpu._private.retry import RetryBudget
    from ray_tpu.util.metrics import registry_snapshot

    def counter_value():
        for m in registry_snapshot():
            if m["name"] == "ray_tpu_retry_budget_exhausted_total":
                return sum(v["value"] for v in m["values"])
        return 0.0

    before = counter_value()
    budget = RetryBudget(capacity=1.0, refill_per_s=0.0)
    assert budget.take() is True
    assert budget.take() is False
    assert counter_value() == before + 1
    assert any(e["kind"] == "retry_budget_exhausted"
               for e in events.snapshot())


def test_profiling_timeline_events_carry_node():
    """Satellite: timeline pids collide across hosts — every span must
    name its producing host like tracing spans already do."""
    import os

    from ray_tpu._private import profiling

    with profiling.record_span("test", "node_tag_probe"):
        pass
    spans = [e for e in profiling.snapshot()
             if e["name"] == "node_tag_probe"]
    assert spans and all(e.get("node") == os.uname().nodename
                         for e in spans)


# ------------------------------------------------------- catalog lint


def test_metric_catalog_lint():
    """The catalog lint now LIVES in the analysis framework (PR 8:
    ray_tpu/_private/analysis/catalogs.py, codes RTC401/RTC402) — this
    test drives that pass, so the telemetry suite still gates it even
    when the full raylint gate (tests/test_zz_lint.py) is filtered out
    of a targeted run."""
    from ray_tpu._private.analysis.catalogs import metric_catalog_pass
    from ray_tpu._private.analysis.core import AnalysisContext

    findings = [f for f in metric_catalog_pass(AnalysisContext())
                if f.code in ("RTC401", "RTC402")]
    assert not findings, "\n".join(str(f) for f in findings)


def test_undeclared_collective_metric_fails_fast():
    """PR 3 satellite: an undeclared ray_tpu_collective_* name must
    raise at the instrumented call site (KeyError from the catalog
    lookup), not silently record an unlintable metric."""
    from ray_tpu._private import telemetry

    if not telemetry.ENABLED:
        pytest.skip("RAY_TPU_INTERNAL_TELEMETRY=0: the call-site lint "
                    "only fires with telemetry on")
    with pytest.raises(KeyError):
        telemetry.observe("ray_tpu_collective_bogus_seconds", 0.1)
    with pytest.raises(KeyError):
        telemetry.counter_inc("ray_tpu_collective_bogus_total")


def test_grafana_panels_reference_cataloged_metrics():
    """PR 3 satellite, PR 8 unified into the framework: the default
    Grafana dashboard may only chart metrics the runtime actually emits
    (analysis/catalogs.py, code RTC403)."""
    from ray_tpu._private.analysis.catalogs import metric_catalog_pass
    from ray_tpu._private.analysis.core import AnalysisContext

    findings = [f for f in metric_catalog_pass(AnalysisContext())
                if f.code == "RTC403"]
    assert not findings, "\n".join(str(f) for f in findings)


def test_event_kind_catalog_lint():
    """PR 8: the analogous event-name lint — every recorded kind is
    documented in events.py's docstring catalog and vice versa
    (analysis/catalogs.py, codes RTC404/RTC405)."""
    from ray_tpu._private.analysis.catalogs import event_catalog_pass
    from ray_tpu._private.analysis.core import AnalysisContext

    findings = list(event_catalog_pass(AnalysisContext()))
    assert not findings, "\n".join(str(f) for f in findings)


# ------------------------------------------------- cluster-level tests


def test_cross_worker_metrics_aggregation(ray_start_regular):
    """Satellite: metrics_summary() must SUM a same-named counter across
    worker processes (per tag set), not report per-process fragments."""
    ray_tpu = ray_start_regular
    from ray_tpu.experimental.state.api import metrics_summary

    @ray_tpu.remote
    class XwService:
        def __init__(self):
            from ray_tpu.util.metrics import Counter

            self.c = Counter("xw_requests_total", tag_keys=("who",))

        def bump(self, n):
            self.c.inc(n, tags={"who": "x"})
            import os

            return os.getpid()

    a, b = XwService.remote(), XwService.remote()
    pids = ray_tpu.get([a.bump.remote(2), b.bump.remote(3)], timeout=120)
    assert pids[0] != pids[1], "actors unexpectedly share a process"
    snaps = metrics_summary()
    row = next(m for m in snaps if m["name"] == "xw_requests_total")
    vals = {tuple(sorted(v["tags"].items())): v["value"]
            for v in row["values"]}
    assert vals[(("who", "x"),)] == 5.0, row


def test_internal_rpc_and_store_metrics_flow(ray_start_regular):
    ray_tpu = ray_start_regular
    import numpy as np

    from ray_tpu.experimental.state.api import metrics_summary

    @ray_tpu.remote
    def rpc_metric_probe():
        return 1

    assert ray_tpu.get(rpc_metric_probe.remote(), timeout=120) == 1
    # >100KB: forced through the shm store (inline results bypass it)
    ref = ray_tpu.put(np.zeros(300_000, np.uint8))
    assert ray_tpu.get(ref, timeout=120).nbytes == 300_000
    snaps = {m["name"]: m for m in metrics_summary()}
    lat = snaps["ray_tpu_rpc_latency_seconds"]
    methods = {r["tags"].get("method") for r in lat["values"]}
    assert methods & {"register_worker", "request_worker_lease",
                      "get_nodes", "kv_put"}, methods
    hits = sum(v["value"] for v in snaps[
        "ray_tpu_object_store_get_total"]["values"]
        if v["tags"].get("result") == "hit")
    assert hits >= 1
    assert sum(v["value"] for v in snaps[
        "ray_tpu_object_store_put_bytes_total"]["values"]) >= 300_000
    assert "ray_tpu_lease_grant_latency_seconds" in snaps


@pytest.mark.fault_injection
def test_injected_faults_and_retries_consistent_with_schedule(
        ray_start_regular):
    """Acceptance: /metrics retry and fault counters line up with the
    deterministic injected schedule."""
    ray_tpu = ray_start_regular
    from ray_tpu._private import fault_injection
    from ray_tpu._private.worker_runtime import current_worker
    from ray_tpu.experimental.state.api import metrics_summary

    def counter(snaps, name, **tags):
        row = next((m for m in snaps if m["name"] == name), None)
        if row is None:
            return 0.0
        return sum(v["value"] for v in row["values"]
                   if all(v["tags"].get(k) == tv
                          for k, tv in tags.items()))

    before = metrics_summary()
    inj = fault_injection.install(3, "disconnect:*.kv_put:#1")
    try:
        w = current_worker()
        # the disconnect kills the GCS channel mid-send; the unified
        # retry policy heals it and re-sends (kv_put is retry-safe)
        assert w.gcs.call("kv_put", ns="telemetry_test", key=b"k",
                          value=b"v") is True
        assert w.gcs.call("kv_get", ns="telemetry_test", key=b"k") == b"v"
    finally:
        fault_injection.uninstall()
    n_faults = sum(1 for a, _r, m, _n in inj.trace()
                   if a == "disconnect" and m == "kv_put")
    assert n_faults == 1, inj.trace()
    after = metrics_summary()
    d_faults = (counter(after, "ray_tpu_faults_injected_total",
                        action="disconnect", method="kv_put")
                - counter(before, "ray_tpu_faults_injected_total",
                          action="disconnect", method="kv_put"))
    assert d_faults == n_faults, (d_faults, n_faults)
    d_retries = (counter(after, "ray_tpu_retry_attempts_total",
                         method="kv_put")
                 - counter(before, "ray_tpu_retry_attempts_total",
                           method="kv_put"))
    assert d_retries >= 1, after
    # the healed channel means the user-visible call still succeeded —
    # and the transport error that triggered the retry was counted
    d_errors = (counter(after, "ray_tpu_rpc_errors_total",
                        method="kv_put", kind="connection_lost")
                - counter(before, "ray_tpu_rpc_errors_total",
                          method="kv_put", kind="connection_lost"))
    assert d_errors >= 1, after
