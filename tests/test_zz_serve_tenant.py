"""Serve as a first-class tenant (late-alphabet; past the tier-1
timeout horizon by design).

Covers PR 16 end to end: the controller's per-replica capacity gangs in
the PR 13 job plane (slot-tag-named, job-labeled, readiness gated on
CREATED), preemption warnings draining replicas inside the grace window,
scale-down riding the SAME warning machinery (self-preempt narrowed by
``pg_name``, gang removed pre-fire), the drain-aware shed contract
(``ServeOverloadedError.draining`` + the router broadcast's ``draining``
deadlines), the fault DSL's slot-tag composition
(``preempt_job:<app-job>.serve_tick``), and the capacity round trip: a
Serve demand spike preempts a training gang through the plane and hands
the capacity back when the spike passes.

Sim-level tests drive the REAL ``_DeploymentState`` FSM (reconcile /
autoscale / capacity poll run unmodified) against the harness GCS via
``sim_serve_deployment_cls``; the E2E runs a real single-node cluster
like tests/test_zz_multitenant.py.
"""
import os
import pickle
import time

import pytest

pytestmark = []


class _Conn:
    """Stub RpcServer connection for direct GCS handler calls."""

    _n = 0

    def __init__(self):
        _Conn._n += 1
        self.id = f"stubconn{_Conn._n}"
        self.meta = {}
        self.alive = True

    def push(self, *a, **k):
        pass


def _fresh(ev0: int, kind: str) -> list:
    """Events of ``kind`` recorded after sequence floor ``ev0`` (the
    ring is process-global — earlier tests leave events behind)."""
    from ray_tpu._private import events

    return [e for e in events.snapshot()
            if e["seq"] > ev0 and e["kind"] == kind]


def _wait(predicate, cluster, timeout_s=15.0, ticks=2):
    """Drive sim ticks until ``predicate()`` holds (gossip at the tick
    boundary is what re-drives the GCS's event-driven pending queue)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        cluster.run_ticks(ticks)
    return predicate()


# ----------------------------------------------------- capacity-gated start

def test_capacity_gated_start_and_slot_tagged_gang(monkeypatch):
    """A tenant replica only turns RUNNING once its capacity gang is
    CREATED, and the gang is slot-tag-named + job-labeled in the plane
    (the addressable identity chaos schedules and self-preemption use).
    """
    monkeypatch.setenv("RAY_TPU_GCS_PREEMPT_GRACE_S", "0.5")
    from ray_tpu._private import events
    from ray_tpu._private.sim_cluster import SimCluster
    from ray_tpu.serve._private.constants import slot_tag

    ev0 = events.stats()["recorded"]
    cluster = SimCluster(n_nodes=2, tick_interval=0.05).start()
    try:
        app = cluster.add_serve_app(
            "gate", "svc-gate", base_rate=200, service_rate=400,
            min_replicas=1, max_replicas=2, capacity_cpu=2.0)
        assert _wait(lambda: app.live_replicas() == 1, cluster), \
            "replica never turned RUNNING"
        (r,) = app.ds.replicas
        assert r.state == "RUNNING" and r.pg_created
        snap = cluster.gcs_call("get_placement_group",
                                pg_id=r.capacity_pg_id)
        assert snap["State"] == "CREATED"
        assert snap["Name"] == slot_tag(app.dep_id, r.slot)
        assert snap["Job"] == "svc-gate"
        placed = _fresh(ev0, "SERVE_CAPACITY_PLACED")
        assert placed and placed[0]["job"] == "svc-gate"
        assert placed[0]["wait_s"] >= 0.0
    finally:
        cluster.stop()


# ------------------------------------------- scale-down through the warning

def test_scale_down_drains_through_warning(monkeypatch):
    """Autoscaled scale-down self-preempts the victim slot's gang: the
    drain rides the preemption-warning machinery (SERVE_REPLICA_WARNED
    reason=scale_down), completes inside the grace window, and the gang
    is removed PRE-fire — zero PREEMPTION_FIRED for the whole cycle."""
    monkeypatch.setenv("RAY_TPU_GCS_PREEMPT_GRACE_S", "1.0")
    from ray_tpu._private import events
    from ray_tpu._private.sim_cluster import SimCluster

    ev0 = events.stats()["recorded"]
    cluster = SimCluster(n_nodes=3, tick_interval=0.05).start()
    try:
        app = cluster.add_serve_app(
            "sd", "svc-sd", base_rate=900, service_rate=400,
            min_replicas=1, max_replicas=2, capacity_cpu=2.0)
        # demand ~900/tick vs target 400/replica → autoscale to 2
        assert _wait(lambda: app.live_replicas() == 2, cluster), \
            "never scaled up to 2 replicas"
        up_gangs = {r.capacity_pg_id for r in app.ds.replicas}
        assert len(up_gangs) == 2
        # the spike passes: backlog drains, desired falls to 1, and the
        # downscale-delay hysteresis hands one replica to the drain path
        app.base_rate = 50
        assert _wait(lambda: (app.live_replicas() == 1
                              and len(app.ds.replicas) == 1), cluster,
                     timeout_s=20.0), "never scaled back down to 1"
        warned = _fresh(ev0, "SERVE_REPLICA_WARNED")
        assert any(e["reason"] == "scale_down" for e in warned), warned
        assert _fresh(ev0, "PREEMPTION_FIRED") == [], \
            "scale-down drain outlived the grace window"
        kept = {r.capacity_pg_id for r in app.ds.replicas}
        (removed,) = up_gangs - kept
        gone = cluster.gcs_call("get_placement_group", pg_id=removed)
        assert gone is None or gone["State"] == "REMOVED", gone
        jobs = {r["Job"]: r for r in cluster.gcs_call("list_jobs")}
        assert jobs["svc-sd"]["Preemptions"] == 0
        # the accepted backlog was fully served through the drain
        assert app.accepted - app.served - app._queued == 0
    finally:
        cluster.stop()


# ------------------------------------------------- the capacity round trip

def test_capacity_round_trip_spike_preempts_training_then_returns(
        monkeypatch):
    """The tentpole acceptance at sim scale: a demand spike on a
    high-priority Serve tenant claims capacity THROUGH the job plane —
    exactly one training gang is preempted (warning → grace → fire) —
    and when the spike drains, scale-down rides the warning machinery,
    the slot gang is removed pre-fire, and the fired training gang
    resumes CREATED. No flight-recorder dump anywhere in the cycle."""
    monkeypatch.setenv("RAY_TPU_GCS_PREEMPT_GRACE_S", "0.5")
    from ray_tpu._private import events
    from ray_tpu._private.sim_cluster import SimCluster

    ev0 = events.stats()["recorded"]
    cluster = SimCluster(n_nodes=2, tick_interval=0.05).start()
    try:
        def _state(pg_id):
            snap = cluster.gcs_call("get_placement_group", pg_id=pg_id)
            return snap["State"] if snap else "GONE"

        # the app first, on a free cluster: the startup backlog (nothing
        # serves until slot0 places) transiently over-scales, so let it
        # settle to 1 steady replica before packing the training tenants
        app = cluster.add_serve_app(
            "rt", "svc-rt", priority=10, base_rate=100, service_rate=400,
            min_replicas=1, max_replicas=2, capacity_cpu=2.0)
        assert _wait(lambda: (app.live_replicas() == 1
                              and len(app.ds.replicas) == 1
                              and app._queued == 0), cluster,
                     timeout_s=20.0), "app never settled at 1 replica"
        # 8 CPUs total: serve slot0 (2) + 3 training gangs x 2 = full.
        # The spike's second slot MUST claim capacity through the plane.
        cluster.register_job("rt-train", priority=0)
        train = [cluster.create_job_pg("rt-train", n_bundles=1, cpu=2.0)
                 for _ in range(3)]
        assert _wait(lambda: all(_state(p) == "CREATED" for p in train),
                     cluster), "training gangs never placed"
        # age the commits past the GCS's commit-reflection grace (fresh
        # bundles are conservatively double-counted against gossiped
        # availability for ~1.5s, which would over-warn victims)
        cluster.run_ticks(44)
        ev1 = events.stats()["recorded"]

        app.base_rate = 1100          # the spike: desired replicas → 2
        assert _wait(lambda: app.live_replicas() == 2, cluster,
                     timeout_s=20.0), "spike capacity never placed"
        fired = _fresh(ev1, "PREEMPTION_FIRED")
        assert len(fired) == 1 and fired[0]["job"] == "rt-train", fired
        assert sum(_state(p) == "PENDING" for p in train) == 1

        app.base_rate = 50            # the spike passes
        assert _wait(lambda: (app.live_replicas() == 1
                              and all(_state(p) == "CREATED"
                                      for p in train)), cluster,
                     timeout_s=25.0), "training gang never resumed"
        assert any(e["reason"] == "scale_down"
                   for e in _fresh(ev1, "SERVE_REPLICA_WARNED"))
        assert len(_fresh(ev1, "PREEMPTION_FIRED")) == 1, \
            "scale-down fired instead of draining"
        assert _fresh(ev0, "FLIGHT_RECORDER_DUMP") == []
        jobs = {r["Job"]: r for r in cluster.gcs_call("list_jobs")}
        assert jobs["svc-rt"]["Preemptions"] == 0
        assert jobs["rt-train"]["Preemptions"] == 1
        assert app.accepted - app.served - app._queued == 0
    finally:
        cluster.stop()


# --------------------------------------------- fault DSL slot composition

def _chaos_run(seed: int) -> dict:
    """One seeded storm against a tenant app: an app-job-scoped
    ``preempt_job`` rule fans out over the fixed slot range, warning
    every slot's gang simultaneously on the %7 ticks."""
    from ray_tpu._private import fault_injection as fi
    from ray_tpu._private.sim_cluster import SimCluster

    os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"] = "0.5"
    # 600ms grace: the controller's worst-case reaction is the 0.25s
    # capacity-poll cadence plus two reconcile ticks, so graces under
    # ~0.4s fire before any controller could have drained
    fi.install(seed, "preempt_job:svc-chaos.serve_tick:%7:600")
    cluster = SimCluster(n_nodes=3, tick_interval=0.05).start()
    try:
        app = cluster.add_serve_app(
            "cz", "svc-chaos", base_rate=700, service_rate=400,
            min_replicas=2, max_replicas=3, capacity_cpu=2.0)
        cluster.run_ticks(80)
        out = app.finalize()
        jobs = {r["Job"]: r for r in cluster.gcs_call("list_jobs")}
        return {
            "journal": cluster.journal_text(),
            "lost": out["lost"],
            "served": out["served"],
            "slot_firings": sum("preempt_slot" in ln
                                for ln in cluster.journal),
            "serve_fires": jobs["svc-chaos"]["Preemptions"],
        }
    finally:
        cluster.stop()
        fi.uninstall()
        del os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"]


@pytest.mark.fault_injection
def test_slot_tag_chaos_composition_deterministic():
    """Satellite: the `preempt_job:<app-job>` schedule composes through
    slot tags — per-(slot, method) counters fire all slots on the same
    tick, warned replicas drain with ZERO lost accepted requests and
    zero serve-side fires, and the journal is byte-identical across two
    runs of the same seed."""
    a = _chaos_run(7)
    assert a["slot_firings"] > 0, "%7 schedule never fired a slot"
    assert a["lost"] == 0, "storm drains lost accepted requests"
    assert a["served"] > 0
    assert a["serve_fires"] == 0, "a warned slot outlived its grace"
    b = _chaos_run(7)
    assert a["journal"] == b["journal"], "chaos journal not reproducible"


# ------------------------------------------------- drain-aware shed contract

def test_shed_error_carries_drain_hint():
    """Satellite: ``ServeOverloadedError`` distinguishes a capacity
    storm (draining=True, retry-after = grace remaining) from a load
    blip, and the distinction survives the pickle boundary replicas
    ship errors across."""
    from ray_tpu.exceptions import ServeOverloadedError

    e = ServeOverloadedError("app#main", queued=7, retry_after_s=2.5,
                             draining=True)
    assert e.draining is True and e.retry_after_s == 2.5 and e.queued == 7
    assert "draining" in str(e)
    e2 = pickle.loads(pickle.dumps(e))
    assert (e2.deployment_id, e2.queued, e2.retry_after_s, e2.draining) \
        == ("app#main", 7, 2.5, True)
    blip = ServeOverloadedError("app#main", queued=3)
    assert blip.draining is False and "draining" not in str(blip)


class _RecordingHost:
    """LongPollHost stand-in capturing the latest broadcast per key."""

    def __init__(self):
        self.values = {}

    def notify_changed(self, key, value):
        self.values[key] = value

    def drop_key(self, key):
        self.values.pop(key, None)


def test_warning_reaches_router_broadcast():
    """An external preempt warning on a replica's gang leaves the
    replica set and lands in the broadcast's ``draining`` list with the
    grace deadline (the router's proactive-drop + retry-after source);
    the drain completes pre-fire so the warning never becomes a fire."""
    from ray_tpu._private import events
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.sim_cluster import sim_serve_deployment_cls
    from ray_tpu.serve._private.constants import (deployment_id,
                                                  replicas_key, slot_tag)

    server = GcsServer(port=0).start()
    try:
        def gcs_call(method, **kw):
            return getattr(server, "rpc_" + method)(_Conn(), **kw)

        server.rpc_register_node(_Conn(), node_id="n1",
                                 addr=("127.0.0.1", 1),
                                 resources={"CPU": 4.0}, meta={})
        gcs_call("register_job", name="bh", quota=None, priority=5)
        dep_id = deployment_id("bh", "main")
        host = _RecordingHost()
        spec = {"name": "main", "user_callable": None, "version": "1",
                "config": {"num_replicas": 1, "max_ongoing_requests": 8,
                           "max_queued_requests": 100,
                           "graceful_shutdown_timeout_s": 1.0,
                           "health_check_period_s": 3600.0,
                           "ray_actor_options": {"num_cpus": 1.0}}}
        # Hold drains open until the test releases them: the sim stub
        # drains instantly, which collapses detect → drain → reap into
        # one reconcile and makes the draining broadcast zero-width.
        drain_gate = {"open": False}

        class _GatedDrain(sim_serve_deployment_cls()):
            def _check_drained(self, r):
                return drain_gate["open"]

            def _begin_stop(self, r, deadline_s=None):
                # the sim stub expires the drain deadline instantly;
                # honor the grace window so the gate actually holds
                super()._begin_stop(r, deadline_s)
                r.drain_deadline = time.monotonic() + (deadline_s or 1.0)

        ds = _GatedDrain(dep_id, spec, host, job="bh", gcs_call=gcs_call)

        def spin(pred, timeout_s=5.0):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                ds.reconcile()
                if pred():
                    return True
                time.sleep(0.05)
            return pred()

        assert spin(lambda: any(r.state == "RUNNING"
                                for r in ds.replicas))
        rkey = replicas_key(dep_id)
        assert len(host.values[rkey]["replicas"]) == 1
        assert host.values[rkey]["draining"] == []
        old_pg = ds.replicas[0].capacity_pg_id
        ev0 = events.stats()["recorded"]
        victim = gcs_call("preempt_job", name="bh", grace_s=1.0,
                          pg_name=slot_tag(dep_id, 0))
        assert victim is not None
        assert spin(lambda: bool(_fresh(ev0, "SERVE_REPLICA_WARNED")))
        warned = _fresh(ev0, "SERVE_REPLICA_WARNED")
        assert warned[0]["reason"] == "preempted"
        b = host.values[rkey]
        assert b["replicas"] == [], "warned replica still in rotation"
        assert len(b["draining"]) == 1
        assert b["draining"][0]["deadline_ts"] > time.time()
        # release the drain: the reap removes the gang pre-fire; the
        # replacement comes up on a FRESH gang; sleeping past the
        # grace window proves the removed gang's fire was no-opped
        drain_gate["open"] = True
        assert spin(lambda: any(r.state == "RUNNING" and not r.warned
                                for r in ds.replicas))
        gone = gcs_call("get_placement_group", pg_id=old_pg)
        assert gone is None or gone["State"] == "REMOVED", gone
        time.sleep(1.1)
        assert _fresh(ev0, "PREEMPTION_FIRED") == [], \
            "pre-fire gang removal did not cancel the fire"
    finally:
        server.stop()


def test_preemption_reprieve_when_preemptor_leaves(monkeypatch):
    """Tentpole hardening: a warned victim whose preemptor stops
    needing the capacity inside the grace window (here the pending
    gang is removed — the spike evaporated) is reprieved at fire
    time: PREEMPTION_CANCELED, the victim keeps its bundles, and no
    fire is recorded."""
    from ray_tpu._private import events
    from ray_tpu._private.gcs import GcsServer

    monkeypatch.setenv("RAY_TPU_GCS_PREEMPT_GRACE_S", "0.6")
    server = GcsServer(port=0).start()
    try:
        def gcs_call(method, **kw):
            return getattr(server, "rpc_" + method)(_Conn(), **kw)

        server.rpc_register_node(_Conn(), node_id="n1",
                                 addr=("127.0.0.1", 1),
                                 resources={"CPU": 4.0}, meta={})
        gcs_call("register_job", name="lo", quota=None, priority=0)
        gcs_call("register_job", name="hi", quota=None, priority=10)
        lo_id, hi_id = b"\x01" * 16, b"\x02" * 16
        gcs_call("create_placement_group", pg_id=lo_id,
                 bundles=[{"CPU": 4.0}], strategy="PACK", name="lo-g",
                 job="lo")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if gcs_call("get_placement_group",
                        pg_id=lo_id)["State"] == "CREATED":
                break
            time.sleep(0.02)
        ev0 = events.stats()["recorded"]
        gcs_call("create_placement_group", pg_id=hi_id,
                 bundles=[{"CPU": 4.0}], strategy="PACK", name="hi-g",
                 job="hi")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            gcs_call("get_placement_group", pg_id=hi_id)  # re-drives queue
            if _fresh(ev0, "PREEMPTION_WARNED"):
                break
            time.sleep(0.02)
        warned = _fresh(ev0, "PREEMPTION_WARNED")
        assert warned and warned[0]["job"] == "lo"
        # the demand evaporates inside the grace window
        gcs_call("remove_placement_group", pg_id=hi_id)
        time.sleep(0.8)   # past the grace: the armed fire must cancel
        canceled = _fresh(ev0, "PREEMPTION_CANCELED")
        assert len(canceled) == 1 and canceled[0]["job"] == "lo"
        assert _fresh(ev0, "PREEMPTION_FIRED") == [], \
            "victim fired for a preemptor that no longer exists"
        snap = gcs_call("get_placement_group", pg_id=lo_id)
        assert snap["State"] == "CREATED"
        assert snap["PreemptDeadline"] is None
        jobs = {r["Job"]: r for r in gcs_call("list_jobs")}
        assert jobs["lo"]["Preemptions"] == 0
    finally:
        server.stop()


# ----------------------------------------------------------- runtime E2E

@pytest.fixture
def serve_rt(monkeypatch):
    """Single-node runtime with a short preemption grace window; tears
    the Serve instance down after (detached actors outlive tests)."""
    monkeypatch.setenv("RAY_TPU_GCS_PREEMPT_GRACE_S", "1.0")
    try:
        import ray_tpu

        ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    except (ImportError, ModuleNotFoundError) as e:
        pytest.skip(f"runtime not built yet: {e}")
    yield ray_tpu
    from ray_tpu import serve

    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


class _EchoTenant:
    def __call__(self, x):
        return f"echo:{x}"


@pytest.mark.chaos
def test_serve_tenant_preempts_training_and_returns_capacity_e2e(serve_rt):
    """The tentpole E2E on the real runtime: a tenant app whose replica
    capacity cannot place preempts a lower-priority training gang
    through the job plane (exactly one fire, no GANG_FAILED, no
    flight-recorder dump), serves traffic while holding the capacity,
    shows up on both sides of the jobs↔serve state-API cross-link, and
    hands the capacity back on delete — the training gang resumes."""
    ray = serve_rt
    from ray_tpu import serve
    from ray_tpu._private import events
    from ray_tpu.experimental.state.api import summarize_jobs, summarize_serve
    from ray_tpu.util import jobs
    from ray_tpu.util.placement_group import placement_group

    ev0 = events.stats()["recorded"]
    jobs.register_job("svcE2E-train", priority=0)
    pg = placement_group([{"CPU": 4.0}], strategy="PACK",
                         job="svcE2E-train")
    assert pg.wait(timeout_seconds=15.0), "training gang never placed"

    dep = serve.deployment(_EchoTenant)
    handle = serve.run(dep.bind(), name="echo_app", route_prefix=None,
                       job="svcE2E", job_priority=10, _timeout_s=90.0)
    # the replica's capacity gang could not place on the full node: it
    # preempted the training gang (grace → fire) through the plane
    fired = _fresh(ev0, "PREEMPTION_FIRED")
    assert len(fired) == 1 and fired[0]["job"] == "svcE2E-train", fired
    assert _fresh(ev0, "GANG_FAILED") == []
    assert _fresh(ev0, "FLIGHT_RECORDER_DUMP") == []
    # the app actually serves while holding tenant capacity
    assert handle.remote("hi").result(timeout_s=30.0) == "echo:hi"
    # cross-links: the jobs side names the app; the serve side carries
    # the tenancy block joined from the job row
    sj = summarize_jobs()
    assert "echo_app" in sj["serve_apps"].get("svcE2E", []), sj["serve_apps"]
    assert sj["quota_violations"] == []
    ten = summarize_serve()["applications"]["echo_app"].get("tenancy")
    assert ten and ten["priority"] == 10
    # the spike passes: deleting the app drains the replica, removes the
    # capacity gang, and the fired training gang re-places
    serve.delete("echo_app")
    assert pg.wait(timeout_seconds=30.0), "training gang never resumed"
    assert len(_fresh(ev0, "PREEMPTION_FIRED")) == 1
    rows = {r["Job"]: r for r in summarize_jobs()["jobs"]}
    assert rows["svcE2E-train"]["Preemptions"] == 1
    assert rows["svcE2E"]["Preemptions"] == 0


# --------------------------------------------------- death-feed capacity leak

def test_death_feed_releases_capacity_gang():
    """Review pin: a replica crash delivered via the GCS death feed must
    release the replica's capacity gang exactly like _kill/_drop — the
    fast path used to drop the replica from the list only, leaking a
    CREATED, job-labeled, quota-counted gang per crash (and the
    replacement's slot-tag name then collided with the zombie's)."""
    from ray_tpu.serve._private.controller import (
        RUNNING,
        _DeploymentState,
        _Replica,
    )
    from ray_tpu.serve._private.long_poll import LongPollHost

    calls = []
    ds = _DeploymentState(
        "app#d", {"name": "d", "user_callable": object, "config": {}},
        LongPollHost(), job="svc-leak",
        gcs_call=lambda method, **kw: calls.append((method, kw)))

    class _H:
        _actor_id = b"\xab" * 8

    r = _Replica("d#r0", "actor0", _H(), ready_ref=None, slot=0)
    r.state = RUNNING
    r.capacity_pg_id = b"\x01" * 16
    ds.replicas = [r]

    assert ds.on_actor_death(_H._actor_id.hex())
    assert ds.replicas == []
    assert ("remove_placement_group", {"pg_id": b"\x01" * 16}) in calls, \
        "death-feed drop leaked the replica's capacity gang"
    assert r.capacity_pg_id is None
