"""Smoke tests for the collective bus-bandwidth harness
(benchmarks/collective_bench.py — BASELINE.md north-star metric #2;
reference shape: python/ray/util/collective/examples/)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "benchmarks"))

import collective_bench as cb  # noqa: E402


def test_bus_factor_conventions():
    assert cb.bus_factor("allreduce", 8) == 2 * 7 / 8
    assert cb.bus_factor("allgather", 8) == 7 / 8
    assert cb.bus_factor("reducescatter", 4) == 3 / 4
    assert cb.bus_factor("allreduce", 1) == 1.0


def test_xla_local_bench_smoke():
    rows = cb.run_xla_local(sizes=[64 * 1024], repeats=1, force_cpu=True)
    assert {r["op"] for r in rows} == set(cb.OPS)
    for r in rows:
        assert r["busbw_GBps"] > 0
        assert r["world"] == 8          # conftest's virtual CPU mesh


def test_host_bench_smoke():
    rows = cb.run_host(world=2, sizes=[64 * 1024], repeats=1)
    assert {r["op"] for r in rows} == set(cb.OPS)
    for r in rows:
        assert r["busbw_GBps"] > 0 and r["world"] == 2
