"""PB2, BOHB, and the external-searcher adapter.

Reference tier: tune/schedulers/pb2.py, hb_bohb.py +
search/bohb/bohb_search.py, and the optuna/hyperopt adapter shape
(tune/search/optuna/optuna_search.py).
"""
import pytest


def test_pb2_gp_explore_prefers_better_region():
    """With synthetic observations where high lr yields high score
    deltas, the GP-UCB explore lands in the top region — not uniform.
    Bounds are always respected."""
    from ray_tpu.tune.schedulers import PB2

    pb2 = PB2(metric="score", hyperparam_bounds={"lr": (0.0, 1.0)},
              seed=0)
    # observations: delta grows with lr
    for i in range(40):
        lr = (i % 10) / 10.0
        pb2._X.append(pb2._featurize({"lr": lr}, i // 10))
        pb2._y.append(lr * 2.0)
    picks = [pb2._explore({"lr": 0.5})["lr"] for _ in range(10)]
    assert all(0.0 <= p <= 1.0 for p in picks)
    assert sum(p > 0.5 for p in picks) >= 8, (
        f"GP-UCB ignored the learned trend: {picks}")


def test_pb2_requires_bounds():
    from ray_tpu.tune.schedulers import PB2

    with pytest.raises(ValueError, match="hyperparam_bounds"):
        PB2(metric="score")


def test_pb2_end_to_end_exploits(ray_start_regular):
    """PB2 drives the population's floor up like PBT, but the explored
    configs come from the GP acquisition."""
    from ray_tpu import tune

    def objective(config):
        import time as _time

        from ray_tpu.air import Checkpoint, session

        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["score"]
        score = start
        for _ in range(8):
            _time.sleep(0.3)
            score += config["lr"]
            session.report({"score": score},
                           checkpoint=Checkpoint.from_dict(
                               {"score": score}))

    sched = tune.PB2(metric="score", mode="max",
                     perturbation_interval=2,
                     hyperparam_bounds={"lr": (0.01, 2.0)}, seed=1)
    grid = tune.run(objective,
                    config={"lr": tune.grid_search([0.01, 2.0])},
                    metric="score", mode="max", scheduler=sched)
    worst_final = min(t.last_result["score"] for t in grid.trials
                      if t.results)
    assert worst_final > 1.0, f"PB2 exploit ineffective: {worst_final}"


def test_bohb_scheduler_feeds_searcher(ray_start_regular):
    """HyperBandForBOHB + BOHBSearcher pairing: rung observations reach
    the searcher, the model phase samples from the deepest rung with
    enough data, and the run finds the good region."""
    from ray_tpu import tune

    def objective(config):
        from ray_tpu.air import session

        for step in range(4):
            session.report(
                {"score": -(config["x"] - 3) ** 2 - 0.1 * (3 - step)})

    searcher = tune.BOHBSearcher(
        param_space={"x": tune.uniform(-10, 10)},
        n_startup_trials=4, min_rung_points=4, seed=0)
    sched = tune.HyperBandForBOHB(metric="score", mode="max",
                                  grace_period=1, reduction_factor=2)
    grid = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(-10, 10)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=12,
            max_concurrent_trials=3, scheduler=sched,
            search_alg=searcher),
    ).fit()
    assert len(grid) == 12
    assert searcher._rungs, "scheduler never fed rung observations"
    assert grid.get_best_result().metrics["score"] > -20


def test_external_searcher_ask_tell_protocol(ray_start_regular):
    """The adapter drives any ask/tell backend: configs come from ask,
    mode-signed final metrics reach tell."""
    from ray_tpu import tune

    class Backend:
        def __init__(self):
            self.n = 0
            self.tells = []

        def ask(self):
            if self.n >= 6:
                return None           # exhausted -> FINISHED
            self.n += 1
            return (f"h{self.n}", {"x": float(self.n)})

        def tell(self, handle, value, error=False):
            self.tells.append((handle, value, error))

    backend = Backend()

    def objective(config):
        from ray_tpu.air import session

        session.report({"loss": config["x"] * 2})

    grid = tune.Tuner(
        objective,
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=6,
            search_alg=tune.ExternalSearcher(backend, metric="loss",
                                             mode="min")),
    ).fit()
    assert len(grid) == 6
    assert len(backend.tells) == 6
    # mode="min" -> adapter negates so the backend always maximizes
    values = sorted(v for _h, v, _e in backend.tells)
    assert values[0] == -12.0 and values[-1] == -2.0


def test_external_searcher_rejects_bad_backend():
    from ray_tpu.tune import ExternalSearcher

    with pytest.raises(TypeError, match="ask"):
        ExternalSearcher(object())


def test_optuna_adapter_gated_on_import():
    from ray_tpu import tune

    try:
        import optuna  # noqa: F401
        pytest.skip("optuna installed; gating path not reachable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="TPESearcher"):
        tune.OptunaSearch({"x": tune.uniform(0, 1)}, metric="score")
