"""Data-plane observability (PR 3): collective-op telemetry, straggler
detection, compile watch, device gauges, seq validation.

Late-alphabet on purpose (tier-1 wall-clock budget; the cluster tests
here cost a few seconds each). Structure:

- pure units: straggler detector on synthetic rank timings, the
  rendezvous-side aggregator, the compile-cache wrapper, device gauges
  from injected probe records;
- overhead guard: instrumented host-path allreduce vs telemetry-off on
  a fake in-process group (op body dominates; <5% budget);
- cluster acceptance: a 4-rank host-backend collective with one
  slow_reply-faulted rank yields correct latency/bytes samples in
  metrics_summary(), a COLLECTIVE_STRAGGLER event naming the slow rank
  in list_cluster_events(), and a collective span linked under the
  submitting task's trace (both tracing and chrome-timeline planes);
- seq desync: a rank with a skewed op counter raises
  CollectiveSeqMismatchError instead of hanging.
"""
import time

import numpy as np
import pytest

from ray_tpu._private import telemetry as _tm

# the whole file exercises the data-plane telemetry; with the plane
# killed there is nothing meaningful to assert (CI runs with the
# default, telemetry on)
pytestmark = pytest.mark.skipif(
    not _tm.ENABLED,
    reason="RAY_TPU_INTERNAL_TELEMETRY=0 disables the plane under test")


# ------------------------------------------------- straggler detector


def _timings(starts, **extra):
    return [{"rank": r, "start": s, "group": "g", "op": "allreduce",
             "seq": 1, **extra} for r, s in enumerate(starts)]


def test_detector_flags_late_rank():
    from ray_tpu.util.collective.telemetry import detect_stragglers

    stragglers, lags, median = detect_stragglers(
        _timings([0.0, 0.002, 0.001, 0.400]),
        multiple=3.0, min_lag_s=0.05)
    assert [r for r, _ in stragglers] == [3]
    assert lags[3] == pytest.approx(0.4)
    assert median == pytest.approx(0.0015)


def test_detector_uniform_group_is_quiet():
    from ray_tpu.util.collective.telemetry import detect_stragglers

    stragglers, _, _ = detect_stragglers(
        _timings([0.0, 0.001, 0.002, 0.0015]),
        multiple=3.0, min_lag_s=0.05)
    assert stragglers == []


def test_detector_multiple_of_median_threshold():
    """A wide-but-proportionate spread stays quiet; shrinking the
    multiple flags the tail — the threshold really is a multiple of the
    leave-one-out median, not an absolute cut."""
    from ray_tpu.util.collective.telemetry import detect_stragglers

    starts = [0.0, 0.1, 0.2, 0.3]    # rank 3: others' median lag = .1
    quiet, _, _ = detect_stragglers(_timings(starts),
                                    multiple=3.0, min_lag_s=0.01)
    assert quiet == []                # .3 == 3 * .1, strictly-greater
    flagged, _, _ = detect_stragglers(_timings(starts),
                                      multiple=2.0, min_lag_s=0.01)
    assert [r for r, _ in flagged] == [3]   # .3 > 2 * .1


def test_detector_two_rank_group_not_blind():
    """Leave-one-out median: with a plain group median a 2-rank group
    could NEVER flag (the laggard's own lag is half the median for any
    multiple >= 2) — the smallest real topology must still detect."""
    from ray_tpu.util.collective.telemetry import detect_stragglers

    flagged, lags, _ = detect_stragglers(_timings([0.0, 10.0]),
                                         multiple=3.0, min_lag_s=0.05)
    assert [r for r, _ in flagged] == [1]
    assert lags[1] == pytest.approx(10.0)
    quiet, _, _ = detect_stragglers(_timings([0.0, 0.01]),
                                    multiple=3.0, min_lag_s=0.05)
    assert quiet == []                # under the floor


def test_detector_floor_suppresses_microjitter():
    """Tight group (median ~ 0): µs-scale jitter must not flag without
    the floor, and must not flag WITH the default floor."""
    from ray_tpu.util.collective.telemetry import detect_stragglers

    starts = [0.0, 1e-6, 2e-6, 2e-4]
    flagged, _, _ = detect_stragglers(_timings(starts),
                                      multiple=3.0, min_lag_s=0.0)
    assert [r for r, _ in flagged] == [3]   # no floor: flagged
    quiet, _, _ = detect_stragglers(_timings(starts),
                                    multiple=3.0, min_lag_s=0.05)
    assert quiet == []                      # 50ms floor: quiet


def test_detector_degenerate_sizes():
    from ray_tpu.util.collective.telemetry import detect_stragglers

    assert detect_stragglers([], multiple=3.0, min_lag_s=0.0) == \
        ([], {}, 0.0)
    assert detect_stragglers(_timings([1.0]), multiple=3.0,
                             min_lag_s=0.0) == ([], {}, 0.0)


def test_aggregator_emits_event_when_all_ranks_reported():
    from ray_tpu._private import events
    from ray_tpu.util.collective.telemetry import GroupTimingAggregator

    events.clear()
    agg = GroupTimingAggregator(world_size=4)
    t0 = 1000.0
    recs = _timings([t0, t0 + 0.001, t0 + 0.002, t0 + 0.9])
    agg.ingest(recs[:2])              # partial: no event yet
    assert not [e for e in events.snapshot()
                if e["kind"] == "COLLECTIVE_STRAGGLER"]
    agg.ingest(recs[2:])              # completes seq 1
    evs = [e for e in events.snapshot()
           if e["kind"] == "COLLECTIVE_STRAGGLER"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["group"] == "g" and ev["op"] == "allreduce"
    assert ev["ranks"] == [3]
    assert ev["lags_s"]["3"] == pytest.approx(0.9, abs=1e-5)
    assert agg.stragglers_found == 1


def test_aggregator_duplicate_report_is_noop():
    """A duplicated/retried report for an already-evaluated seq must
    neither re-emit the event nor resurrect an unfinishable pending
    slot (which would squat in the bounded table and evict genuinely
    pending seqs)."""
    from ray_tpu._private import events
    from ray_tpu.util.collective.telemetry import GroupTimingAggregator

    events.clear()
    agg = GroupTimingAggregator(world_size=2)
    recs = _timings([0.0, 5.0])
    agg.ingest(recs)
    n_events = len([e for e in events.snapshot()
                    if e["kind"] == "COLLECTIVE_STRAGGLER"])
    assert n_events == 1
    agg.ingest([recs[1]])            # duplicate delivery of rank 1
    assert agg._pending == {}        # not resurrected
    assert len([e for e in events.snapshot()
                if e["kind"] == "COLLECTIVE_STRAGGLER"]) == n_events


def test_aggregator_pending_table_is_bounded():
    from ray_tpu.util.collective import telemetry as ct

    agg = ct.GroupTimingAggregator(world_size=2)
    # 1000 seqs that never complete (only rank 0 reports)
    agg.ingest([{"rank": 0, "start": 0.0, "seq": s, "group": "g",
                 "op": "allreduce"} for s in range(1000)])
    assert len(agg._pending) <= ct._MAX_PENDING_SEQS


# ------------------------------------------------- compile watch


def test_compile_watch_hit_miss_and_events():
    from ray_tpu._private import events
    from ray_tpu.parallel.compile_watch import CompiledFunction
    from ray_tpu.util.metrics import registry_snapshot

    events.clear()
    calls = []
    fn = CompiledFunction(lambda x: calls.append(1) or x.sum(), "cw_test")
    fn(np.zeros((4, 4)))                  # miss
    fn(np.ones((4, 4)))                   # same signature: hit
    fn(np.zeros((8, 4)))                  # new shape: miss
    assert len(calls) == 3
    kinds = [e["kind"] for e in events.snapshot()
             if e.get("fn") == "cw_test"]
    assert kinds == ["COMPILE_BEGIN", "COMPILE_END",
                     "COMPILE_BEGIN", "COMPILE_END"]
    fam = next(m for m in registry_snapshot()
               if m["name"] == "ray_tpu_pjit_cache_total")
    by_result = {v["tags"]["result"]: v["value"] for v in fam["values"]
                 if v["tags"].get("fn") == "cw_test"}
    assert by_result == {"miss": 2.0, "hit": 1.0}
    comp = next(m for m in registry_snapshot()
                if m["name"] == "ray_tpu_pjit_compile_seconds")
    n = sum(sum(row["counts"]) for row in comp["counts"]
            if row["tags"].get("fn") == "cw_test")
    assert n == 2


def test_compile_watch_failed_compile_not_cached():
    from ray_tpu.parallel.compile_watch import CompiledFunction

    boom = [True]

    def fn(x):
        if boom[0]:
            raise RuntimeError("compile exploded")
        return x

    wrapped = CompiledFunction(fn, "cw_fail")
    with pytest.raises(RuntimeError):
        wrapped(np.zeros(3))
    boom[0] = False
    # the retry must re-classify as a miss (key was not cached)
    assert wrapped._seen == set()
    wrapped(np.zeros(3))
    assert len(wrapped._seen) == 1


def test_compile_watch_survives_cloudpickle():
    """make_train_step's return value used to be a bare jax.jit result,
    which cloudpickles across task boundaries — the wrapper must too
    (lock dropped, cache reset: the receiving process recompiles, so a
    fresh cache keeps its hit/miss classification truthful)."""
    import cloudpickle

    from ray_tpu.parallel.compile_watch import CompiledFunction

    fn = CompiledFunction(lambda x: x * 2, "cw_pickle")
    fn(np.zeros(3))
    clone = cloudpickle.loads(cloudpickle.dumps(fn))
    assert clone._name == "cw_pickle"
    assert clone._seen == set()            # fresh cache on the far side
    assert float(clone(np.ones(2))[0]) == 2.0
    assert len(clone._seen) == 1


def test_compile_watch_kill_switch(monkeypatch):
    from ray_tpu._private import telemetry as tm
    from ray_tpu.parallel.compile_watch import CompiledFunction

    monkeypatch.setattr(tm, "ENABLED", False)
    fn = CompiledFunction(lambda x: x, "cw_off")
    fn(np.zeros(2))
    assert fn._seen == set()   # classification skipped entirely


def test_compile_watch_cache_size_path_with_real_jit():
    """Real jitted functions classify via jit's own _cache_size delta
    (O(1) on the hit path — no per-leaf signature rebuild per training
    step), with the same metrics/events as the fallback path."""
    import jax

    from ray_tpu._private import events
    from ray_tpu.parallel.compile_watch import CompiledFunction
    from ray_tpu.util.metrics import registry_snapshot

    events.clear()
    fn = CompiledFunction(jax.jit(lambda x: x + 1), "cw_jit")
    assert getattr(fn._fn, "_cache_size", None) is not None
    fn(np.zeros(4))                  # compile
    fn(np.ones(4))                   # hit
    fn(np.zeros(6))                  # new shape: compile
    # the signature set records only MISSES (error-path classifier:
    # compile failure vs runtime failure of a compiled program) — the
    # hit never touched it
    assert len(fn._seen) == 2
    fam = next(m for m in registry_snapshot()
               if m["name"] == "ray_tpu_pjit_cache_total")
    by_result = {v["tags"]["result"]: v["value"] for v in fam["values"]
                 if v["tags"].get("fn") == "cw_jit"}
    assert by_result == {"miss": 2.0, "hit": 1.0}
    kinds = [e["kind"] for e in events.snapshot()
             if e.get("fn") == "cw_jit"]
    assert kinds == ["COMPILE_BEGIN", "COMPILE_END",
                     "COMPILE_BEGIN", "COMPILE_END"]
    begin = next(e for e in events.snapshot()
                 if e.get("fn") == "cw_jit"
                 and e["kind"] == "COMPILE_BEGIN")
    assert begin["started_at"] <= begin["ts"]   # materialized post hoc
    from ray_tpu._private import profiling

    assert any(e["name"] == "compile::cw_jit"
               for e in profiling.snapshot())


def test_compile_watch_failed_compile_visible_on_cache_size_path():
    """A trace/compile-time failure on the _cache_size path must be as
    visible as on the fallback path: miss counted, COMPILE_END ok=False
    recorded (a crash-looping worker must not show zero compile
    activity)."""
    import jax

    from ray_tpu._private import events
    from ray_tpu.parallel.compile_watch import CompiledFunction

    events.clear()

    def bad(x):
        raise ValueError("explodes during trace")

    fn = CompiledFunction(jax.jit(bad), "cw_jitfail")
    with pytest.raises(ValueError):
        fn(np.zeros(3))
    evs = [e for e in events.snapshot() if e.get("fn") == "cw_jitfail"]
    assert [e["kind"] for e in evs] == ["COMPILE_BEGIN", "COMPILE_END"]
    assert evs[1]["ok"] is False


def test_publish_local_device_gauges_in_process():
    """Owner-side gauge publish: in-process memory_stats from an
    already-imported jax backend, never a subprocess (the path train
    workers use per step — the subprocess probe can't run while they
    own the chips). On backends without memory stats it's a clean 0."""
    import jax

    from ray_tpu._private.tpu_probe import publish_local_device_gauges

    jax.devices()
    n = publish_local_device_gauges()
    assert n >= 0
    try:
        has_stats = bool(jax.local_devices()[0].memory_stats())
    except Exception:
        has_stats = False
    if has_stats:
        assert n == len(jax.local_devices())


def test_device_gauge_poller_one_shot_by_default(monkeypatch):
    """Default RAY_TPU_DEVICE_GAUGE_POLL_S=0: the publisher thread
    probes once and EXITS — a recurring subprocess probe would contend
    with training workers for TPU ownership."""
    import time as _time

    from ray_tpu._private import tpu_probe as tp

    calls = []
    monkeypatch.setattr(tp, "publish_device_gauges",
                        lambda *a, **k: calls.append(1))
    monkeypatch.setattr(tp, "_poller_thread", None)
    assert tp.start_device_gauge_poller() is True
    deadline = _time.time() + 5
    while tp._poller_thread.is_alive() and _time.time() < deadline:
        _time.sleep(0.02)
    assert not tp._poller_thread.is_alive(), "poller should be one-shot"
    assert calls == [1]


def test_mesh_build_metric_recorded():
    import jax

    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.util.metrics import registry_snapshot

    create_mesh(devices=[jax.devices()[0]], axes={"dp": 1})
    fam = next(m for m in registry_snapshot()
               if m["name"] == "ray_tpu_mesh_build_seconds")
    assert any(row["tags"].get("kind") == "mesh" and sum(row["counts"])
               for row in fam["counts"])


# ------------------------------------------------- device telemetry


def test_publish_device_gauges_from_injected_snapshot():
    from ray_tpu._private.tpu_probe import publish_device_gauges
    from ray_tpu.util.metrics import registry_snapshot

    n = publish_device_gauges(devices=[
        {"id": 7, "platform": "tpu", "kind": "TPU v4",
         "hbm_bytes_in_use": 1 << 30, "hbm_bytes_limit": 32 << 30},
        {"id": 8, "platform": "cpu"},     # CPU fallback: no hbm stats
    ])
    assert n == 2
    fam = next(m for m in registry_snapshot()
               if m["name"] == "ray_tpu_device_hbm_bytes")
    vals = {(v["tags"]["device"], v["tags"]["stat"]): v["value"]
            for v in fam["values"]}
    assert vals[("7", "in_use")] == float(1 << 30)
    assert vals[("7", "limit")] == float(32 << 30)
    assert not any(d == "8" for d, _ in vals)
    # every series carries the producing host: local device ids restart
    # at 0 per host, so a multi-host cluster needs the node tag to not
    # collide last-write-wins
    import os

    assert all(v["tags"]["node"] == os.uname().nodename
               for v in fam["values"])


def test_local_device_identity_shape():
    """jax is already imported by this suite's other tests, so the
    identity must carry platform + device ids; host/pid always."""
    import jax

    from ray_tpu._private.tpu_probe import local_device_identity

    jax.devices()
    info = local_device_identity()
    assert info["host"] and info["pid"]
    assert info["platform"] in ("cpu", "tpu", "gpu")
    assert info["device_count"] >= 1
    assert len(info["device_ids"]) == info["device_count"]


# ------------------------------------------------- overhead guard


def _fake_group(name, impl, world_size=4):
    """An in-process _GroupState over a no-RPC impl. store=None: timing
    records are buffered then dropped by the flusher (no rendezvous
    actor)."""
    from ray_tpu.util.collective.collective import _GroupState, _manager

    state = _GroupState(name, world_size, 0, "host", impl, None)
    _manager._groups[name] = state
    return state


def test_overhead_guard_host_allreduce_under_5pct(monkeypatch):
    """CI satellite: instrumentation on the host-backend allreduce hot
    path stays <5% vs uninstrumented (telemetry off). A direct A/B
    wall-clock ratio on a multi-ms op drowns a ~10µs wrapper in ±5%
    machine noise, so the guard measures the two quantities that make
    up the ratio separately — each is individually stable:

    - the ABSOLUTE per-call instrumentation cost, from a no-op impl
      (on-minus-off isolates the wrapper itself);
    - the hot-path op cost, from an impl doing the deterministic numpy
      work of a small ring step (a LOWER bound on any real collective,
      which also pays peer RPCs).

    Shows up in --durations by design."""
    import statistics

    from ray_tpu._private import telemetry as tm
    from ray_tpu.util import collective as col

    class _Noop:
        def allreduce(self, arr, op, seq):
            return arr

    class _RingStep:
        def allreduce(self, arr, op, seq):
            out = arr
            for _ in range(4):
                out = out + out * 0.5
            return out

    _fake_group("ovh_noop", _Noop())
    _fake_group("ovh_ring", _RingStep())
    tiny = np.zeros(16)
    arr = np.zeros(200_000)

    def per_call(group, payload, n=60):
        samples = []
        for _ in range(n):
            t0 = time.perf_counter()
            col.allreduce(payload, group_name=group)
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples)

    try:
        for g, p in (("ovh_noop", tiny), ("ovh_ring", arr)):
            col.allreduce(p, group_name=g)        # warm both paths
        rounds_on, rounds_off, op_rounds = [], [], []
        for _ in range(5):
            monkeypatch.setattr(tm, "ENABLED", False)
            rounds_off.append(per_call("ovh_noop", tiny))
            op_rounds.append(per_call("ovh_ring", arr, n=20))
            monkeypatch.setattr(tm, "ENABLED", True)
            rounds_on.append(per_call("ovh_noop", tiny))
        overhead = max(0.0, min(rounds_on) - min(rounds_off))
        op_cost = min(op_rounds)
        assert overhead < 0.05 * op_cost, (
            f"instrumentation adds {overhead * 1e6:.1f}µs/op — "
            f"{overhead / op_cost * 100:.1f}% of a {op_cost * 1e3:.2f}ms "
            f"host ring step (budget: 5%)")
    finally:
        from ray_tpu.util.collective.collective import _manager

        _manager._groups.pop("ovh_noop", None)
        _manager._groups.pop("ovh_ring", None)
        from ray_tpu.util.collective.telemetry import flush_timings

        flush_timings()   # drop buffered records for the dead groups


# ------------------------------------------------- cluster acceptance


def test_collective_telemetry_end_to_end(ray_start_regular):
    """Acceptance: 4-rank host-backend collective with one
    slow_reply-faulted rank →
    - correct latency/bytes samples in metrics_summary(),
    - COLLECTIVE_STRAGGLER event naming the slow rank,
    - collective span linked under the submitting task's trace,
    - collective span on the chrome timeline (both clock planes),
    - summarize_collectives() folds all of it."""
    ray = ray_start_regular
    from ray_tpu._private import fault_injection
    from ray_tpu.experimental.state.api import (
        list_cluster_events,
        metrics_summary,
        summarize_collectives,
    )
    from ray_tpu.util import collective as col
    from ray_tpu.util import tracing

    tracing.enable()
    try:
        from ray_tpu.util.collective import CollectiveActorMixin

        @ray.remote
        class Rank(CollectiveActorMixin):
            def go(self, value, straggle=False):
                from ray_tpu.util import collective as c

                if straggle:
                    # arrival delayed by a slow_reply-faulted control
                    # RPC (the in-process GCS stalls kv_get replies)
                    from ray_tpu._private.worker_runtime import (
                        current_worker,
                    )

                    try:
                        current_worker().gcs.call(
                            "kv_get", ns="straggle", key=b"x")
                    except Exception:
                        pass
                arr = np.full(1024, float(value))      # 8192 bytes
                return float(c.allreduce(arr, group_name="zzg")[0])

        n = 4
        actors = [Rank.options(num_cpus=0).remote() for _ in range(n)]
        col.create_collective_group(actors, n, list(range(n)),
                                    backend="host", group_name="zzg")
        fault_injection.install(11, "slow_reply:*.kv_get:p1:600")
        try:
            out = ray.get(
                [a.go.remote(i + 1, straggle=(i == 3))
                 for i, a in enumerate(actors)], timeout=120)
        finally:
            fault_injection.uninstall()
        assert out == [10.0] * n

        # --- metrics: 4 latency samples + 4 * 8192 payload bytes
        deadline = time.time() + 30
        while True:
            snaps = {m["name"]: m for m in metrics_summary()}
            lat = snaps.get("ray_tpu_collective_latency_seconds")
            rows = [r for r in (lat or {}).get("counts", ())
                    if r["tags"] == {"op": "allreduce", "backend": "host",
                                     "group": "zzg"}]
            if rows and sum(sum(r["counts"]) for r in rows) >= n:
                break
            assert time.time() < deadline, (lat, "latency samples late")
            time.sleep(0.5)
        assert sum(sum(r["counts"]) for r in rows) == n
        byt = snaps["ray_tpu_collective_bytes_total"]
        moved = sum(v["value"] for v in byt["values"]
                    if v["tags"] == {"op": "allreduce", "backend": "host",
                                     "group": "zzg"})
        assert moved == n * 1024 * 8

        # --- straggler event names the faulted rank
        deadline = time.time() + 30
        while True:
            evs = [e for e in list_cluster_events(
                       filters=[("kind", "=", "COLLECTIVE_STRAGGLER")])
                   if e.get("group") == "zzg"]
            if any(3 in e.get("ranks", ()) for e in evs):
                break
            assert time.time() < deadline, (
                f"no COLLECTIVE_STRAGGLER naming rank 3 within budget: "
                f"{evs}")
            time.sleep(0.5)
        ev = next(e for e in evs if 3 in e.get("ranks", ()))
        assert ev["op"] == "allreduce"
        assert float(ev["lags_s"]["3"]) > 0.3     # ~600ms injected

        # --- tracing: collective span joins the submitting task trace
        spans = tracing.get_spans()
        col_spans = [s for s in spans if s["name"] == "collective "
                     "allreduce" and s["attributes"].get("group") == "zzg"]
        assert len(col_spans) >= n
        submit_traces = {s["traceId"] for s in spans
                         if s["name"].startswith("submit ")}
        for s in col_spans:
            assert s["parentSpanId"], s
            assert s["traceId"] in submit_traces, (
                "collective span not linked under a submitted task's "
                "trace")

        # --- chrome timeline carries the same op (µs clock plane)
        trace = ray.timeline()
        tl = [e for e in trace if e["name"] == "collective::allreduce"
              and e.get("args", {}).get("group") == "zzg"]
        assert len(tl) >= n
        assert all(e["ph"] == "X" and e["dur"] >= 0 for e in tl)

        # --- the rollup folds ops + stragglers + (any) compile rows
        summary = summarize_collectives()
        row = next(r for r in summary["ops"]
                   if r["group"] == "zzg" and r["op"] == "allreduce")
        assert row["backend"] == "host"
        assert row["count"] == n
        assert row["bytes"] == moved
        assert row["mean_s"] > 0
        assert any(3 in e.get("ranks", ()) for e in summary["stragglers"])
    finally:
        tracing.disable()
        tracing.clear()


def test_seq_desync_raises_mismatch_not_hang(ray_start_regular):
    """Satellite: a rank whose op counter desynced (here: one bumped
    seq) used to hang until the op timeout or mis-pair payloads; now
    the rank that observes the NEWER peer seq raises
    CollectiveSeqMismatchError fast (its peer, seeing only an older
    seq, falls back to the bounded watchdog timeout)."""
    import os as _os

    _os.environ["RAY_TPU_COLLECTIVE_OP_TIMEOUT_S"] = "5"
    ray = ray_start_regular
    from ray_tpu.util import collective as col
    from ray_tpu.util.collective import CollectiveActorMixin

    @ray.remote
    class Rank(CollectiveActorMixin):
        def desync(self):
            from ray_tpu.util.collective.collective import _manager

            _manager.get("zzseq").next_seq()    # counter now skewed
            return True

        def go(self, value):
            from ray_tpu.util import collective as c

            return float(c.allreduce(np.full(4, float(value)),
                                     group_name="zzseq")[0])

    try:
        actors = [Rank.options(num_cpus=0).remote() for _ in range(2)]
        col.create_collective_group(actors, 2, [0, 1], backend="host",
                                    group_name="zzseq")
        ray.get(actors[1].desync.remote(), timeout=30)
        t0 = time.time()
        refs = [a.go.remote(1) for a in actors]
        # rank 0 observes rank 1's NEWER seq: immediate mismatch error
        with pytest.raises(Exception) as ei:
            ray.get(refs[0], timeout=60)
        assert "sequence mismatch" in str(ei.value)
        assert time.time() - t0 < 4    # beat even the 5s watchdog
        # rank 1 only sees an OLDER seq (ambiguous): bounded timeout,
        # annotated with the desync hint
        with pytest.raises(Exception) as ei2:
            ray.get(refs[1], timeout=60)
        assert "timed out" in str(ei2.value)
        assert "older seq" in str(ei2.value)
    finally:
        _os.environ.pop("RAY_TPU_COLLECTIVE_OP_TIMEOUT_S", None)


def test_col_take_seq_validation_unit(ray_start_regular):
    """Direct mailbox-level check of the mismatch rule: exact key wins
    even next to a stale same-channel message; a lone different-seq
    message raises."""
    from ray_tpu import exceptions as exc
    from ray_tpu._private.worker_runtime import current_worker

    w = current_worker()
    chan = ("zzu", "ar")
    w.col_push_local(chan + (2, 0, 1), b"seq2")
    w.col_push_local(chan + (5, 0, 1), b"seq5")
    # exact key present: returned, the pipelined seq5 untouched
    assert w.col_take(chan + (2, 0, 1), timeout=5, seq_pos=2) == b"seq2"
    # a NEWER same-channel seq waiting proves desync (in-order
    # delivery: our seq-4 message would already have arrived)
    with pytest.raises(exc.CollectiveSeqMismatchError) as ei:
        w.col_take(chan + (4, 0, 1), timeout=5, seq_pos=2)
    assert "expects seq 4" in str(ei.value)
    # an OLDER same-channel seq is ambiguous (redelivered dup vs
    # restarted peer): no mismatch — timeout, annotated with the hint
    chan2 = ("zzu2", "ar")
    w.col_push_local(chan2 + (1, 0, 1), b"stale-dup")
    with pytest.raises(TimeoutError) as ti:
        w.col_take(chan2 + (6, 0, 1), timeout=0.3, seq_pos=2)
    assert "older seq [1]" in str(ti.value)
    # a message from a DIFFERENT src is a different channel — neither
    # mismatch nor hint
    chan3 = ("zzu3", "ar")
    w.col_push_local(chan3 + (9, 0, 7), b"other-src")
    with pytest.raises(TimeoutError) as ti:
        w.col_take(chan3 + (8, 0, 1), timeout=0.3, seq_pos=2)
    assert "older seq" not in str(ti.value)


def test_destroy_purges_mailbox_for_reincarnation(ray_start_regular):
    """A payload from a dead group incarnation (e.g. landed after an op
    timeout) must not masquerade as a NEWER seq to a re-created group
    under the same name — destroy purges this process's mailbox."""
    from ray_tpu._private.worker_runtime import current_worker
    from ray_tpu.util.collective.collective import _GroupState, _manager

    w = current_worker()
    w.col_push_local(("zzpurge", "ar", 7, 0, 1), b"old-incarnation")
    w.col_push_local(("zzother", "ar", 7, 0, 1), b"unrelated")

    class _Impl:
        def close(self):
            pass

    _manager._groups["zzpurge"] = _GroupState("zzpurge", 2, 0, "host",
                                              _Impl(), None)
    assert _manager.destroy("zzpurge") is True
    # the dead incarnation's message is gone: a fresh seq-1 wait times
    # out instead of raising a phantom mismatch...
    with pytest.raises(TimeoutError):
        w.col_take(("zzpurge", "ar", 1, 0, 1), timeout=0.3, seq_pos=2)
    # ...and other groups' mail is untouched
    assert w.col_take(("zzother", "ar", 7, 0, 1), timeout=1) == \
        b"unrelated"


def test_list_cluster_events_limit_zero(ray_start_regular):
    from ray_tpu._private import events
    from ray_tpu.experimental.state.api import list_cluster_events

    events.record("zz_limit_probe")
    assert list_cluster_events(limit=0) == []
    assert len(list_cluster_events(limit=1)) == 1


def test_cli_has_collectives_subcommand(monkeypatch):
    """Parse-level smoke: `ray-tpu collectives --address h:1` routes to
    cmd_collectives with the address wired through (main() builds its
    parser per call, so patching the module-level handler intercepts)."""
    from ray_tpu.scripts import cli

    called = {}
    monkeypatch.setattr(
        cli, "cmd_collectives",
        lambda args: called.update(address=args.address) or 0)
    assert cli.main(["collectives", "--address", "h:1"]) == 0
    assert called == {"address": "h:1"}
