"""Tune callback / logger / syncer tests (reference: tune/callback.py,
tune/logger/, tune/syncer.py)."""
import csv
import json
import os

import pytest


def _trainable(config):
    from ray_tpu import tune as _  # noqa: F401  (session import parity)
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint

    for i in range(3):
        session.report(
            {"score": config["x"] * (i + 1)},
            checkpoint=Checkpoint.from_dict({"iter": i}) if i == 2
            else None)


def test_callbacks_fire_in_order(ray_start_regular):
    from ray_tpu import tune
    from ray_tpu.air import RunConfig

    events = []

    class Recorder(tune.Callback):
        def setup(self, experiment_dir):
            events.append(("setup", experiment_dir))

        def on_trial_start(self, iteration, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, iteration, trial, result):
            events.append(("result", result["score"]))

        def on_checkpoint(self, iteration, trial, checkpoint_path):
            events.append(("checkpoint", os.path.basename(checkpoint_path)))

        def on_trial_complete(self, iteration, trial):
            events.append(("complete", trial.trial_id))

        def on_experiment_end(self, trials):
            events.append(("end", len(trials)))

    tuner = tune.Tuner(
        _trainable,
        param_space={"x": 2},
        run_config=RunConfig(name="cb_exp", callbacks=[Recorder()]),
    )
    tuner.fit()
    kinds = [e[0] for e in events]
    assert kinds[0] == "setup"
    assert kinds.index("start") < kinds.index("result")
    assert [e[1] for e in events if e[0] == "result"] == [2, 4, 6]
    assert "checkpoint" in kinds
    assert kinds.index("complete") < kinds.index("end")
    assert events[-1] == ("end", 1)


def test_json_csv_tbx_loggers_write_artifacts(ray_start_regular, tmp_path):
    from ray_tpu import tune
    from ray_tpu.air import RunConfig

    tuner = tune.Tuner(
        _trainable,
        param_space={"x": tune.grid_search([1, 3])},
        run_config=RunConfig(
            name="log_exp", storage_path=str(tmp_path),
            callbacks=[tune.JsonLoggerCallback(),
                       tune.CSVLoggerCallback(),
                       tune.TBXLoggerCallback()]),
    )
    results = tuner.fit()
    exp = tmp_path / "log_exp"
    trial_dirs = [d for d in exp.iterdir()
                  if d.is_dir() and (d / "result.json").exists()]
    assert len(trial_dirs) == 2
    for d in trial_dirs:
        lines = [json.loads(ln) for ln in
                 (d / "result.json").read_text().splitlines()]
        assert len(lines) == 3 and "score" in lines[0]
        params = json.loads((d / "params.json").read_text())
        assert params["x"] in (1, 3)
        with open(d / "progress.csv") as f:
            rows = list(csv.DictReader(f))
        assert len(rows) == 3 and "score" in rows[0]
        assert any(p.name.startswith("events.out.tfevents")
                   for p in d.iterdir()), "no tensorboard events file"
    assert len(results) == 2


def test_storage_uri_syncs_experiment(ray_start_regular, tmp_path):
    """storage_path with a scheme stages locally and mirrors everything
    (state, checkpoints, logger artifacts) to the destination."""
    from ray_tpu import tune
    from ray_tpu.air import RunConfig

    bucket = tmp_path / "bucket"
    tuner = tune.Tuner(
        _trainable,
        param_space={"x": 5},
        run_config=RunConfig(
            name="sync_exp", storage_path=f"file://{bucket}",
            callbacks=[tune.JsonLoggerCallback()]),
    )
    tuner.fit()
    exp = bucket / "sync_exp"
    assert (exp / "experiment_state.json").exists()
    trial_dirs = [d for d in exp.iterdir() if d.is_dir()]
    assert trial_dirs, "no trial artifacts synced"
    assert any((d / "result.json").exists() for d in trial_dirs)
    # a checkpoint directory made it across too
    found_ckpt = any(
        p.name.startswith("checkpoint") for d in trial_dirs
        for p in d.iterdir() if d.is_dir())
    assert found_ckpt, [list(d.iterdir()) for d in trial_dirs]


def test_unknown_scheme_fails_loudly(ray_start_regular, tmp_path):
    from ray_tpu import tune
    from ray_tpu.air import RunConfig

    with pytest.raises(ValueError, match="no syncer"):
        tune.Tuner(
            _trainable, param_space={"x": 1},
            run_config=RunConfig(name="bad", storage_path="s3://nope"),
        ).fit()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
