"""util.accelerators + util.rpdb tests (reference: ray/util/accelerators,
ray/util/rpdb — `ray debug`)."""
import socket
import threading
import time

import pytest


def test_accelerator_helpers(monkeypatch):
    from ray_tpu.util import accelerators as acc

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    monkeypatch.setenv("TPU_NAME", "my-slice")
    monkeypatch.setenv("TPU_TOPOLOGY", "2x4")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1,h2,h3")
    assert acc.get_current_accelerator_type() == acc.TPU_V5E
    assert acc.get_current_pod_name() == "my-slice"
    assert acc.get_current_topology() == "2x4"
    assert acc.get_current_pod_worker_count() == 4
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE")
    assert acc.get_current_accelerator_type() is None


def test_rpdb_breakpoint_drives_over_socket(ray_start_regular):
    """A task hits set_trace; the test attaches over TCP, inspects a
    local variable, and continues the task (the `ray debug` flow)."""
    import re

    import ray_tpu
    from ray_tpu.util import rpdb

    @ray_tpu.remote
    def buggy():
        secret = 41 + 1
        rpdb.set_trace()
        return secret

    ref = buggy.remote()
    # find the announced breakpoint
    session = None
    deadline = time.monotonic() + 60
    while session is None and time.monotonic() < deadline:
        sessions = rpdb.active_sessions()
        if sessions:
            session = sessions[-1]
        else:
            time.sleep(0.2)
    assert session, "breakpoint never announced"

    sock = socket.create_connection(
        (session["host"], session["port"]), timeout=30)
    f = sock.makefile("rw", buffering=1)

    def read_until(pattern, timeout=30):
        buf = ""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            ch = f.read(1)
            if not ch:
                break
            buf += ch
            if re.search(pattern, buf):
                return buf
        raise AssertionError(f"pattern {pattern!r} not seen in {buf!r}")

    read_until(r"\(rpdb\) ")
    f.write("p secret\n")
    f.flush()
    out = read_until(r"42")
    assert "42" in out
    f.write("c\n")
    f.flush()
    sock.close()
    assert ray_tpu.get(ref, timeout=60) == 42


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
