"""Multi-slice MPMD pipeline parallelism (late-alphabet; sequenced
after the tier-1 timeout horizon by design).

Covers the tentpole end to end on a simulated >=2-slice cluster:

- SPREAD_ACROSS_SLICES places each pipeline stage's sub-gang contiguous
  on a DISTINCT slice (asserted through ``summarize_topology``);
- a 2-stage ``PipelineTrainer`` run matches the single-gang
  ``reference_run`` loss oracle BIT FOR BIT per seed (GPipe, 1F1B, and
  the GPipe ack-window variant — same float op order by construction),
  final params included (via the full-pipeline checkpoint);
- step_anatomy's measured per-stage bubble fraction lands within
  tolerance of the (P-1)/(M+P-1) schedule theory (SleepStage pipeline:
  sleeps don't contend for CPU, so the number reproduces under load);
- inter-stage hops show bf16 ``ray_tpu_collective_wire_bytes_total``
  when ``PipelineConfig.wire_dtype="bf16"`` (polled live, mid-run);
- a seeded ``kill_actor:stage1-rank0...`` chaos schedule drives the
  PR 5 teardown -> checkpoint -> resume path without hanging the other
  stages' send/recv windows;
- the streaming data plane feeds stage 0 from a ``ray_tpu.data``
  Dataset shard.
"""
import os
import threading
import time

import numpy as np
import pytest

GROUP_SEED = 11


def _two_slice(cluster, hosts_per_slice=1, cpus=4):
    cluster.remove_node(cluster.head_node)
    cluster.head_node = cluster.add_node(num_cpus=4)   # driver-only
    nodes = {}
    for sid in ("s0", "s1"):
        for wid in range(hosts_per_slice):
            nodes[(sid, wid)] = cluster.add_node(
                num_cpus=cpus, num_tpus=4,
                tpu_topology={"slice_id": sid, "worker_id": wid,
                              "chips": 4})
    cluster.connect()
    import ray_tpu

    return ray_tpu, nodes


def _stages():
    from ray_tpu.train.pipeline import DenseStage

    return [DenseStage(6, 5, "tanh"), DenseStage(5, 3, "none")]


_KW = dict(num_steps=3, microbatch_size=4, learning_rate=0.05,
           seed=GROUP_SEED)


# ------------------------------------------------------------- pure units

def test_schedule_orders():
    from ray_tpu.train.pipeline import (build_schedule, gpipe_schedule,
                                        max_inflight,
                                        one_f_one_b_schedule)

    g = gpipe_schedule(0, 2, 4)
    assert g == [("fwd", i) for i in range(4)] + \
        [("bwd", i) for i in range(4)]
    assert max_inflight(g) == 4
    # 1F1B: stage 0 of 2 warms up 1 forward, then alternates
    f = one_f_one_b_schedule(0, 2, 4)
    assert f == [("fwd", 0), ("fwd", 1), ("bwd", 0), ("fwd", 2),
                 ("bwd", 1), ("fwd", 3), ("bwd", 2), ("bwd", 3)]
    assert max_inflight(f) == 2
    # last stage: strict alternation, in-flight 1
    last = one_f_one_b_schedule(1, 2, 4)
    assert max_inflight(last) == 1
    # every schedule issues each microbatch exactly once per phase and
    # backwards in 0..M-1 order (the oracle's accumulation order)
    for p in (2, 3, 4):
        for s in range(p):
            for m in (1, 2, 5, 8):
                for name in ("gpipe", "1f1b"):
                    acts = build_schedule(name, s, p, m)
                    fwds = [i for k, i in acts if k == "fwd"]
                    bwds = [i for k, i in acts if k == "bwd"]
                    assert fwds == list(range(m))
                    assert bwds == list(range(m))
                    # no bwd before its fwd
                    seen = set()
                    for k, i in acts:
                        if k == "fwd":
                            seen.add(i)
                        else:
                            assert i in seen
                    if name == "1f1b":
                        assert max_inflight(acts) <= min(m, p - s)
    with pytest.raises(ValueError):
        build_schedule("interleaved", 0, 2, 4)


def test_theoretical_bubble_fraction():
    from ray_tpu.train.pipeline import theoretical_bubble_fraction

    assert theoretical_bubble_fraction(1, 8) == 0.0
    assert theoretical_bubble_fraction(2, 4) == pytest.approx(1 / 5)
    assert theoretical_bubble_fraction(4, 12) == pytest.approx(3 / 15)
    # more microbatches -> smaller bubble, monotonically
    fr = [theoretical_bubble_fraction(4, m) for m in (1, 2, 4, 8, 16)]
    assert fr == sorted(fr, reverse=True)


def test_pipeline_config_validation():
    from ray_tpu.train.pipeline import PipelineConfig, PipelineTrainer

    with pytest.raises(ValueError, match="schedule"):
        PipelineConfig(schedule="zigzag")
    with pytest.raises(ValueError, match="num_microbatches"):
        PipelineConfig(num_microbatches=0)
    # a typo'd wire format fails at construction on the driver, not in
    # a remote worker's first send
    with pytest.raises(ValueError, match="wire"):
        PipelineConfig(wire_dtype="fp16")
    PipelineConfig(wire_dtype="off")     # off-aliases stay valid
    with pytest.raises(ValueError, match="stage"):
        PipelineTrainer([])


def test_reference_run_learns():
    """The oracle itself behaves like training: loss decreases over
    steps on its deterministic synthetic task."""
    from ray_tpu.train.pipeline import reference_run

    ref = reference_run(_stages(), num_microbatches=4, num_steps=6,
                        microbatch_size=8, learning_rate=0.1,
                        seed=GROUP_SEED)
    assert len(ref["losses"]) == 6
    assert ref["losses"][-1] < ref["losses"][0]


# --------------------------------------------------- placement + topology

def test_stage_subgangs_on_distinct_slices(ray_start_cluster):
    """ACCEPTANCE: with 2 slices x 2 hosts and ranks_per_stage=2, each
    stage's sub-gang lands contiguous on its own slice — asserted
    through the state API's topology rollup."""
    ray_tpu, nodes = _two_slice(ray_start_cluster, hosts_per_slice=2,
                                cpus=2)
    from ray_tpu.experimental.state.api import summarize_topology
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"TPU": 4}] * 4,
                         strategy="SPREAD_ACROSS_SLICES",
                         bundle_stages=[0, 0, 1, 1], name="mpmd-gang")
    assert pg.wait(10)
    worker = ray_tpu._private.api._require_worker()
    snap = worker.gcs.call("get_placement_group", pg_id=pg.id)
    by_node = {nodes[k].node_id: k for k in nodes}
    placed = [by_node[n] for n in snap["BundleNodes"]]
    slice_of_stage = {0: {s for s, _ in placed[:2]},
                      1: {s for s, _ in placed[2:]}}
    assert len(slice_of_stage[0]) == 1 and len(slice_of_stage[1]) == 1
    assert slice_of_stage[0] != slice_of_stage[1], placed
    for pair in (placed[:2], placed[2:]):
        wids = sorted(w for _, w in pair)
        assert wids[1] - wids[0] == 1, f"stage not contiguous: {pair}"
    topo = summarize_topology()
    assert topo["num_slices"] == 2
    row = next(r for r in topo["placement_groups"]
               if r["name"] == "mpmd-gang")
    assert set(row["stages"]) == {"0", "1"}
    assert row["stages"]["0"] != row["stages"]["1"]
    occupied = {sid for sids in row["stages"].values() for sid in sids}
    for sid in occupied:
        assert row["placement_group_id"] in topo["slices"][sid]["occupants"]


# ------------------------------------------------------ loss oracle E2Es

def test_gpipe_matches_reference_bit_for_bit(ray_start_cluster):
    """ACCEPTANCE: the 2-stage distributed pipeline reproduces the
    single-gang oracle's per-step losses AND final params bit for bit
    (exact wire, same float op order) — per seed."""
    _two_slice(ray_start_cluster)
    from ray_tpu.train.pipeline import (PipelineConfig, PipelineTrainer,
                                        reference_run)

    stages = _stages()
    ref = reference_run(stages, num_microbatches=4, **_KW)
    result = PipelineTrainer(
        stages, pipeline_config=PipelineConfig(num_microbatches=4,
                                               group_name="zzp_gpipe"),
        **_KW).fit()
    assert result.error is None, result.error
    assert [r["loss"] for r in result.metrics_history] == ref["losses"]
    # final checkpoint carries every stage's params — compare exactly
    state = result.checkpoint.to_dict()
    assert state["step"] == _KW["num_steps"] - 1
    for si, ps in enumerate(ref["params"]):
        got = state["stage_params"][si]
        assert len(got) == len(ps)
        for a, b in zip(got, ps):
            assert np.array_equal(np.asarray(a), b), f"stage {si} params"


def test_1f1b_and_ack_window_match_reference(ray_start_cluster):
    """1F1B and the GPipe in-flight ack window change the SCHEDULE, not
    the math: both stay bit-identical to the oracle."""
    _two_slice(ray_start_cluster)
    from ray_tpu.train.pipeline import (PipelineConfig, PipelineTrainer,
                                        reference_run)

    stages = _stages()
    ref = reference_run(stages, num_microbatches=4, **_KW)
    for pc in (PipelineConfig(num_microbatches=4, schedule="1f1b",
                              group_name="zzp_1f1b"),
               PipelineConfig(num_microbatches=4, inflight_window=1,
                              group_name="zzp_win")):
        result = PipelineTrainer(stages, pipeline_config=pc, **_KW).fit()
        assert result.error is None, result.error
        got = [r["loss"] for r in result.metrics_history]
        assert got == ref["losses"], (pc.schedule, pc.inflight_window)


def test_bf16_wire_on_interstage_hops(ray_start_cluster):
    """ACCEPTANCE: with wire_dtype="bf16" the inter-stage hops emit
    ray_tpu_collective_wire_bytes_total{op="send",format="bf16"}
    (observed LIVE, while the gang runs — worker registries die with
    the gang), and the loss trajectory is close to, but not bitwise
    equal to, the exact-wire oracle."""
    _two_slice(ray_start_cluster)
    from ray_tpu.train.pipeline import (PipelineConfig, PipelineTrainer,
                                        reference_run)

    stages = _stages()
    ref = reference_run(stages, num_microbatches=4, **_KW)
    seen: list = []
    stop = threading.Event()

    def _poll():
        from ray_tpu.experimental.state.api import metrics_summary

        while not stop.is_set():
            try:
                snaps = {m["name"]: m for m in metrics_summary()}
                wb = snaps.get("ray_tpu_collective_wire_bytes_total")
                rows = [v for v in (wb or {}).get("values", ())
                        if v["tags"].get("format") == "bf16"
                        and v["tags"].get("op") == "send"
                        and v["tags"].get("group") == "zzp_bf16"]
                if rows:
                    seen.append(rows)
                    return
            except Exception:
                pass
            time.sleep(0.2)

    t = threading.Thread(target=_poll, daemon=True)
    t.start()
    result = PipelineTrainer(
        stages, pipeline_config=PipelineConfig(num_microbatches=4,
                                               wire_dtype="bf16",
                                               group_name="zzp_bf16"),
        **_KW).fit()
    stop.set()
    t.join(timeout=5)
    assert result.error is None, result.error
    got = [r["loss"] for r in result.metrics_history]
    assert got != ref["losses"], "bf16 wire should not be bit-exact"
    for a, b in zip(got, ref["losses"]):
        assert abs(a - b) / abs(b) < 0.05, (a, b)
    assert seen, "no bf16 send wire bytes observed during the run"
    assert sum(v["value"] for v in seen[0]) > 0


# ------------------------------------------------------- bubble fraction

def test_bubble_fraction_matches_schedule_theory(ray_start_cluster):
    """ACCEPTANCE: measured per-stage bubble fraction ~ (P-1)/(M+P-1).
    SleepStage compute is contention-immune, so the measurement is
    stable under a loaded suite; tolerance is max(50% relative, 0.1
    absolute). The per-rank attribution is also visible through
    summarize_steps (step_anatomy `pipeline_bubble` activities)."""
    _two_slice(ray_start_cluster)
    from ray_tpu.train.pipeline import (PipelineConfig, PipelineTrainer,
                                        SleepStage,
                                        theoretical_bubble_fraction)

    P, M = 2, 4
    stages = [SleepStage(4, fwd_s=0.03) for _ in range(P)]
    fused: list = []
    stop = threading.Event()

    def _poll():
        from ray_tpu.experimental.state.api import summarize_steps

        while not stop.is_set():
            try:
                s = summarize_steps()
                good = [st for st in s.get("steps", [])
                        if st.get("complete") and len(st["ranks"]) == P
                        and all(r.get("bubble_s", 0) > 0
                                for r in st["ranks"].values())]
                if len(good) >= 2:
                    fused.append(good)
                    return
            except Exception:
                pass
            time.sleep(0.2)

    t = threading.Thread(target=_poll, daemon=True)
    t.start()
    result = PipelineTrainer(
        stages,
        pipeline_config=PipelineConfig(num_microbatches=M,
                                       group_name="zzp_bubble"),
        num_steps=6, microbatch_size=2, learning_rate=0.0, seed=1).fit()
    stop.set()
    t.join(timeout=5)
    assert result.error is None, result.error
    theory = theoretical_bubble_fraction(P, M)
    fracs = [r["bubble_fraction"] for r in result.metrics_history][1:]
    measured = sum(fracs) / len(fracs)
    assert abs(measured - theory) < max(0.5 * theory, 0.1), \
        (measured, theory)
    assert fused, "summarize_steps never showed per-rank bubble_s"
    step = fused[0][-1]
    for rank, br in step["ranks"].items():
        assert 0 < br["bubble_s"] < br["wall_s"], (rank, br)


# ------------------------------------------------------------- chaos E2E

@pytest.fixture
def chaos_cluster_env(ray_start_cluster):
    """2-slice cluster whose every process inherits a seeded fault
    schedule (env exported BEFORE any node starts)."""
    def _start(seed, schedule):
        os.environ["RAY_TPU_FAULT_SEED"] = str(seed)
        os.environ["RAY_TPU_FAULT_SCHEDULE"] = schedule
        return _two_slice(ray_start_cluster)

    yield _start
    os.environ.pop("RAY_TPU_FAULT_SEED", None)
    os.environ.pop("RAY_TPU_FAULT_SCHEDULE", None)


@pytest.mark.chaos
@pytest.mark.fault_injection
def test_stage_rank_death_checkpoint_resume(chaos_cluster_env):
    """ACCEPTANCE (CI/chaos satellite): a seeded kill_actor schedule
    shoots stage 1's rank while it serves its 3rd next_result —
    mid-training, after checkpointed steps. The death must poison the
    gang fast (stage 0's pending send/recv windows raise instead of
    wedging until the 300s op timeout), fit() tears down + rebuilds
    once, and the resumed pipeline finishes on the oracle trajectory."""
    from ray_tpu._private import events
    from ray_tpu.air.config import FailureConfig, RunConfig
    from ray_tpu.train.pipeline import (PipelineConfig, PipelineTrainer,
                                        reference_run)

    chaos_cluster_env(7, "kill_actor:stage1-rank0.next_result:#3")
    stages = _stages()
    kw = dict(_KW, num_steps=4)
    ref = reference_run(stages, num_microbatches=4, **kw)

    def count(kind):
        return sum(1 for e in events.snapshot() if e["kind"] == kind)

    base_restarted = count("GANG_RESTARTED")
    t0 = time.monotonic()
    result = PipelineTrainer(
        stages,
        pipeline_config=PipelineConfig(num_microbatches=4,
                                       checkpoint_every=1,
                                       group_name="zzp_chaos"),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)),
        **kw).fit()
    elapsed = time.monotonic() - t0
    # detection + teardown + rebuild + resume: nowhere near the 300s
    # collective op timeout a hung send/recv window would burn
    assert elapsed < 120, f"pipeline gang restart took {elapsed:.0f}s"
    assert result.error is None, result.error
    hist = [r["loss"] for r in result.metrics_history]
    assert hist[-1] == ref["losses"][-1], "resume diverged from oracle"
    # resumed from a checkpoint: the final attempt replayed only the
    # remaining step(s), not the whole run
    assert len(hist) < kw["num_steps"]
    assert count("GANG_RESTARTED") - base_restarted == 1
    # both gang incarnations announced their slice layout
    ev = [e for e in events.snapshot()
          if e["kind"] == "PIPELINE_GANG_STARTED"
          and e.get("group") == "zzp_chaos"]
    assert len(ev) == 2
    assert all(len(e["stage_slices"]) == 2 for e in ev)


# ------------------------------------------------------- data-plane feed

def test_streaming_dataset_feeds_stage_zero(ray_start_cluster):
    """Stage 0 pulls microbatches from a ray_tpu.data shard (the
    streaming executor path); later stages receive activations only.
    Loss must be finite and the run completes."""
    _two_slice(ray_start_cluster)
    import ray_tpu.data as rdata
    from ray_tpu.train.pipeline import (PipelineConfig, PipelineTrainer)

    rng = np.random.default_rng(5)
    items = [{"x": rng.standard_normal(6).astype(np.float32),
              "y": rng.standard_normal(3).astype(np.float32)}
             for _ in range(64)]
    ds = rdata.from_items(items, parallelism=4)
    result = PipelineTrainer(
        _stages(),
        pipeline_config=PipelineConfig(num_microbatches=2,
                                       group_name="zzp_data"),
        datasets={"train": ds}, num_steps=2, microbatch_size=4,
        learning_rate=0.05, seed=3).fit()
    assert result.error is None, result.error
    for r in result.metrics_history:
        assert np.isfinite(r["loss"])
