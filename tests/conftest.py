"""Global test configuration.

All tests run on a virtual 8-device CPU mesh (the TPU analog of the
reference's single-node gloo collective tests — see
/root/reference/python/ray/util/collective/tests/single_node_cpu_tests/):
sharding/collective code paths compile and execute exactly as they would on
an 8-chip slice, but on host CPU devices.
"""
import os

# Must be set before any jax backend initializes. The axon TPU plugin's
# sitecustomize overrides JAX_PLATFORMS programmatically, so the env var
# alone is not enough — we also force the config at import time.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("RAY_TPU_TESTING", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def _fault_banner() -> str | None:
    """The active fault-injection plane as one reproducible line (an
    in-process install() wins over the env pair it was derived from)."""
    from ray_tpu._private import fault_injection

    if fault_injection.ACTIVE is not None:
        return fault_injection.ACTIVE.banner()
    schedule = os.environ.get("RAY_TPU_FAULT_SCHEDULE")
    if schedule:
        seed = os.environ.get("RAY_TPU_FAULT_SEED", "0")
        return f"RAY_TPU_FAULT_SEED={seed} " \
               f"RAY_TPU_FAULT_SCHEDULE='{schedule}'"
    return None


def _raylint_banner() -> str:
    """The lint baseline size, printed in every run's header so drift
    is visible tier-1-wide: the number should only ever SHRINK (fixed
    findings get their baseline lines deleted) — a session that grew it
    added a documented-by-design exception and must justify it."""
    try:
        from ray_tpu._private.analysis import load_baseline

        entries = load_baseline()
        return (f"raylint: {len(entries)} baselined finding(s) "
                f"(ray_tpu/_private/analysis/baseline.txt; gate: "
                f"tests/test_zz_lint.py, `ray-tpu lint`)")
    except Exception as e:   # never block the suite on the lint plane
        return f"raylint: baseline unreadable ({e!r})"


def pytest_report_header(config):
    lines = [_raylint_banner()]
    banner = _fault_banner()
    if banner:
        lines.append(f"fault injection: ACTIVE — {banner}")
    else:
        lines.append("fault injection: disabled "
                     "(RAY_TPU_FAULT_SCHEDULE activates it; see "
                     "ray_tpu/_private/fault_injection.py)")
    return lines


def _memory_orphan_digest() -> str:
    """One-line leak digest for failed chaos tests: the local memory
    ledger's sweep verdict (orphan count/bytes, worst offender's
    category+group+reason, dropped-free stages) — points a post-mortem
    at `ray-tpu memory` / summarize_memory() without the full fan-out
    cost on every failure."""
    try:
        from ray_tpu._private import memory_anatomy as _ma

        snap = _ma.local_snapshot(top_k=1)
        if not snap.get("enabled", True):
            return "memory anatomy disabled (RAY_TPU_INTERNAL_TELEMETRY=0)"
        orphans = snap.get("orphans") or []
        dropped = snap.get("dropped_frees") or {}
        if not orphans and not dropped:
            return ("no orphans, no dropped frees "
                    "(state.api.summarize_memory() for the cluster view)")
        parts = []
        if orphans:
            worst = max(orphans, key=lambda r: r.get("nbytes") or 0)
            parts.append(
                f"{len(orphans)} orphan(s), "
                f"{sum(int(r.get('nbytes') or 0) for r in orphans)} bytes "
                f"(worst: {worst.get('category')} "
                f"group={worst.get('group')} reason={worst.get('reason')})")
        if dropped:
            parts.append("dropped frees: " + ", ".join(
                f"{k}={v}" for k, v in sorted(dropped.items())))
        return "; ".join(parts) + \
            " — summarize_memory() / `ray-tpu memory` for provenance"
    except Exception as e:
        return f"memory anatomy unavailable ({e!r})"


def _flight_recorder_hint() -> str:
    """Where this failure's black box is (or would be): the last dump
    this process wrote, else the base dir cluster processes dump into —
    post-mortems of seeded-kill tests start from the black box, not
    from scrollback."""
    try:
        from ray_tpu._private import flight_recorder as fr

        path = fr.last_dump_path() or fr.find_latest_dump()
        if path:
            return f"dump: {path}"
        return (f"no dump written yet; auto-dumps land under "
                f"{fr.base_dir()} (ray-tpu blackbox dump for a "
                f"manual one)")
    except Exception as e:
        return f"flight recorder unavailable ({e!r})"


def _checkpoint_hint() -> str:
    """Newest sharded-checkpoint generation + its manifest status for
    failed chaos tests: a restore that 'lost' progress usually means the
    newest generation is torn/quarantined — say so next to the black
    box instead of making the post-mortem rediscover it with the CLI."""
    try:
        import os as _os

        root = _os.environ.get("RAY_TPU_CHECKPOINT_DIR")
        if not root:
            return ("no checkpoint root in this process "
                    "(RAY_TPU_CHECKPOINT_DIR unset; `ray-tpu "
                    "checkpoints <root>` to inspect one)")
        from ray_tpu.train.sharded_checkpoint import summarize_checkpoints

        entries = summarize_checkpoints(root, digests=False)
        if not entries:
            return f"no generations under {root}"
        newest = entries[0]
        return (f"newest generation: {newest['path']} "
                f"status={newest['status']}"
                + (f" reason={newest['reason']}" if newest["reason"]
                   else "")
                + f" ({len(entries)} on disk; `ray-tpu checkpoints "
                  f"{root}` for digests)")
    except Exception as e:
        return f"checkpoint summary unavailable ({e!r})"


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Stamp failures with the seed+schedule that reproduces the exact
    injected-fault sequence (the injector is deterministic per call
    index, so this one line replays the failure), and — for chaos /
    fault_injection-marked tests — with the flight-recorder dump path,
    so the post-mortem starts from the black box."""
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed:
        banner = _fault_banner()
        if banner:
            rep.sections.append(
                ("fault injection", f"reproduce with: {banner}"))
        if item.get_closest_marker("chaos") is not None or \
                item.get_closest_marker("fault_injection") is not None:
            rep.sections.append(
                ("flight recorder", _flight_recorder_hint()))
            rep.sections.append(
                ("memory anatomy", _memory_orphan_digest()))
            rep.sections.append(
                ("checkpoints", _checkpoint_hint()))


# ---------------------------------------------------------------------------
# Tier-1 duration guard. The tier-1 budget is a hard 870 s wall-clock
# timeout over the alphabetical file order, so one slow EARLY file
# silently starves every file behind it out of the run (DOTS_PASSED is
# wall-clock sensitive). This guard turns that silent starvation into an
# attributable failure: any early-alphabet test file whose summed test
# durations (the same per-phase numbers --durations reports) exceed the
# per-file budget fails the session at the end. Late-alphabet files
# (test_z*) are exempt by design — they are sequenced last precisely so
# they spill past the timeout, not displace others. Override/disable via
# RAY_TPU_TEST_FILE_BUDGET_S (0 disables).

_FILE_BUDGET_DEFAULT_S = 120.0
_file_durations: dict = {}


def _file_budget_s() -> float:
    try:
        return float(os.environ.get("RAY_TPU_TEST_FILE_BUDGET_S",
                                    _FILE_BUDGET_DEFAULT_S))
    except ValueError:
        return _FILE_BUDGET_DEFAULT_S


def pytest_runtest_logreport(report):
    fname = report.nodeid.split("::", 1)[0]
    _file_durations[fname] = \
        _file_durations.get(fname, 0.0) + report.duration


def _early_alphabet(fname: str) -> bool:
    base = os.path.basename(fname)
    return base.startswith("test_") and not base.startswith("test_z")


def pytest_sessionfinish(session, exitstatus):
    budget = _file_budget_s()
    if budget <= 0:
        return
    if len(_file_durations) < 10:
        return   # targeted run (one file / a few tests), not the suite:
                 # a developer iterating on a slow file shouldn't fail
                 # their own focused run
    over = sorted(((f, d) for f, d in _file_durations.items()
                   if _early_alphabet(f) and d > budget),
                  key=lambda p: -p[1])
    if not over:
        return
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    lines = [f"  {f}: {d:.1f}s > {budget:.0f}s budget" for f, d in over]
    msg = ("tier-1 duration guard: early-alphabet test file(s) over the "
           "per-file wall-clock budget (slow early files starve the "
           "870s tier-1 run; mark tests `slow`, speed them up, or raise "
           "RAY_TPU_TEST_FILE_BUDGET_S):\n" + "\n".join(lines))
    if tr is not None:
        tr.write_sep("=", "tier-1 duration guard", red=True)
        tr.write_line(msg)
    if session.exitstatus in (0, 1):
        # escalate only from ok/tests-failed — an interrupted (2) or
        # internally-errored (3) session keeps its more-severe code
        session.exitstatus = 1


@pytest.fixture
def ray_start_regular():
    """Start a fresh single-node runtime for a test, shut down after.

    Mirrors the reference fixture of the same name
    (python/ray/tests/conftest.py:245-360).
    """
    try:
        import ray_tpu

        ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    except (ImportError, ModuleNotFoundError) as e:
        pytest.skip(f"runtime not built yet: {e}")
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """A multi-node in-process cluster, the reference's central test trick
    (python/ray/cluster_utils.py:99)."""
    try:
        from ray_tpu.cluster_utils import Cluster
    except (ImportError, ModuleNotFoundError) as e:
        pytest.skip(f"cluster_utils not built yet: {e}")
    cluster = Cluster()
    yield cluster
    cluster.shutdown()
