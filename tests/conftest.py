"""Global test configuration.

All tests run on a virtual 8-device CPU mesh (the TPU analog of the
reference's single-node gloo collective tests — see
/root/reference/python/ray/util/collective/tests/single_node_cpu_tests/):
sharding/collective code paths compile and execute exactly as they would on
an 8-chip slice, but on host CPU devices.
"""
import os

# Must be set before any jax backend initializes. The axon TPU plugin's
# sitecustomize overrides JAX_PLATFORMS programmatically, so the env var
# alone is not enough — we also force the config at import time.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("RAY_TPU_TESTING", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def _fault_banner() -> str | None:
    """The active fault-injection plane as one reproducible line (an
    in-process install() wins over the env pair it was derived from)."""
    from ray_tpu._private import fault_injection

    if fault_injection.ACTIVE is not None:
        return fault_injection.ACTIVE.banner()
    schedule = os.environ.get("RAY_TPU_FAULT_SCHEDULE")
    if schedule:
        seed = os.environ.get("RAY_TPU_FAULT_SEED", "0")
        return f"RAY_TPU_FAULT_SEED={seed} " \
               f"RAY_TPU_FAULT_SCHEDULE='{schedule}'"
    return None


def pytest_report_header(config):
    banner = _fault_banner()
    if banner:
        return [f"fault injection: ACTIVE — {banner}"]
    return ["fault injection: disabled "
            "(RAY_TPU_FAULT_SCHEDULE activates it; see "
            "ray_tpu/_private/fault_injection.py)"]


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Stamp failures with the seed+schedule that reproduces the exact
    injected-fault sequence (the injector is deterministic per call
    index, so this one line replays the failure)."""
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed:
        banner = _fault_banner()
        if banner:
            rep.sections.append(
                ("fault injection", f"reproduce with: {banner}"))


@pytest.fixture
def ray_start_regular():
    """Start a fresh single-node runtime for a test, shut down after.

    Mirrors the reference fixture of the same name
    (python/ray/tests/conftest.py:245-360).
    """
    try:
        import ray_tpu

        ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    except (ImportError, ModuleNotFoundError) as e:
        pytest.skip(f"runtime not built yet: {e}")
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """A multi-node in-process cluster, the reference's central test trick
    (python/ray/cluster_utils.py:99)."""
    try:
        from ray_tpu.cluster_utils import Cluster
    except (ImportError, ModuleNotFoundError) as e:
        pytest.skip(f"cluster_utils not built yet: {e}")
    cluster = Cluster()
    yield cluster
    cluster.shutdown()
