"""RLlib tests — PPO on CartPole converges using the framework's actors.

Reference tier: rllib smoke tests over tuned_examples (CartPole PPO is the
canonical one).
"""
import numpy as np
import pytest


def test_cartpole_env_contract():
    from ray_tpu.rllib import CartPole

    env = CartPole(seed=0)
    obs, _ = env.reset()
    assert obs.shape == (4,)
    total = 0.0
    for _ in range(10):
        obs, r, term, trunc, _ = env.step(1)
        total += r
        if term or trunc:
            break
    assert total >= 1.0


def test_rollout_worker_batch_shapes(ray_start_regular):
    import jax

    from ray_tpu.rllib import RolloutWorker, init_policy

    w = RolloutWorker("CartPole-v1", num_envs=2, seed=0)
    params = init_policy(jax.random.PRNGKey(0), *w.spaces())
    batch = w.sample(params, 16)
    assert batch["obs"].shape == (32, 4)
    assert batch["actions"].shape == (32,)
    assert batch["advantages"].shape == (32,)
    assert np.isfinite(batch["advantages"]).all()


def test_ppo_cartpole_converges(ray_start_regular):
    """The round-brief done-criterion: PPO on CartPole learns using the
    framework's own actors + object store. Random policy scores ~22;
    we require a 4x improvement within a bounded budget."""
    from ray_tpu.rllib import AlgorithmConfig, PPO

    algo = (AlgorithmConfig(PPO)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=128)
            .training(lr=3e-4, minibatch_size=128)
            .build())
    try:
        best = 0.0
        for _ in range(40):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 120.0:
                break
        assert best >= 100.0, f"PPO failed to learn: best reward {best}"
        # save/restore round-trips
        state = algo.save()
        algo.restore(state)
        assert algo.iteration == state["iteration"]
    finally:
        algo.stop()


def test_replay_buffer_ring_and_sampling():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=10, seed=0)
    buf.add_batch({"x": np.arange(6, dtype=np.float32)})
    assert len(buf) == 6
    buf.add_batch({"x": np.arange(6, 14, dtype=np.float32)})
    assert len(buf) == 10          # capacity-bounded
    sample = buf.sample(32)
    assert sample["x"].shape == (32,)
    # rows 0-3 were overwritten by the wrap-around (values 10-13)
    assert set(sample["x"].tolist()) <= set(range(4, 14))


def test_prioritized_replay_buffer():
    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=100, seed=0)
    buf.add_batch({"x": np.arange(50, dtype=np.float32)})
    s = buf.sample(16)
    assert "weights" in s and "batch_indexes" in s
    # boost one row's priority and confirm it dominates sampling
    buf.update_priorities(np.array([7]), np.array([100.0]))
    counts = 0
    for _ in range(20):
        counts += int((buf.sample(16)["batch_indexes"] == 7).sum())
    assert counts > 20, f"prioritized row rarely sampled ({counts})"


def test_dqn_cartpole_learns(ray_start_regular):
    """DQN on CartPole: epsilon-greedy transitions through the object
    store into a replay buffer; double-Q learner improves the policy
    well past the random-policy return (~22)."""
    from ray_tpu.rllib import DQN, AlgorithmConfig

    algo = (AlgorithmConfig(DQN)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=64)
            .training(lr=2e-3, minibatch_size=128, num_sgd_steps=64,
                      learning_starts=256, epsilon_anneal_iters=8,
                      target_update_freq=2)
            .build())
    try:
        best = 0.0
        for _ in range(45):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 80.0:
                break
        assert best >= 60.0, f"DQN failed to learn: best reward {best}"
        state = algo.save()
        algo.restore(state)
    finally:
        algo.stop()
