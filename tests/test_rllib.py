"""RLlib tests — PPO on CartPole converges using the framework's actors.

Reference tier: rllib smoke tests over tuned_examples (CartPole PPO is the
canonical one).
"""
import numpy as np
import pytest


def test_cartpole_env_contract():
    from ray_tpu.rllib import CartPole

    env = CartPole(seed=0)
    obs, _ = env.reset()
    assert obs.shape == (4,)
    total = 0.0
    for _ in range(10):
        obs, r, term, trunc, _ = env.step(1)
        total += r
        if term or trunc:
            break
    assert total >= 1.0


def test_rollout_worker_batch_shapes(ray_start_regular):
    import jax

    from ray_tpu.rllib import RolloutWorker, init_policy

    w = RolloutWorker("CartPole-v1", num_envs=2, seed=0)
    params = init_policy(jax.random.PRNGKey(0), *w.spaces())
    batch = w.sample(params, 16)
    assert batch["obs"].shape == (32, 4)
    assert batch["actions"].shape == (32,)
    assert batch["advantages"].shape == (32,)
    assert np.isfinite(batch["advantages"]).all()


def test_ppo_cartpole_converges(ray_start_regular):
    """The round-brief done-criterion: PPO on CartPole learns using the
    framework's own actors + object store. Random policy scores ~22;
    we require a 4x improvement within a bounded budget."""
    from ray_tpu.rllib import AlgorithmConfig, PPO

    algo = (AlgorithmConfig(PPO)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=128)
            .training(lr=3e-4, minibatch_size=128)
            .build())
    try:
        best = 0.0
        for _ in range(40):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 120.0:
                break
        assert best >= 100.0, f"PPO failed to learn: best reward {best}"
        # save/restore round-trips
        state = algo.save()
        algo.restore(state)
        assert algo.iteration == state["iteration"]
    finally:
        algo.stop()
