"""RLlib tests — PPO on CartPole converges using the framework's actors.

Reference tier: rllib smoke tests over tuned_examples (CartPole PPO is the
canonical one).
"""
import numpy as np
import pytest


def test_cartpole_env_contract():
    from ray_tpu.rllib import CartPole

    env = CartPole(seed=0)
    obs, _ = env.reset()
    assert obs.shape == (4,)
    total = 0.0
    for _ in range(10):
        obs, r, term, trunc, _ = env.step(1)
        total += r
        if term or trunc:
            break
    assert total >= 1.0


def test_rollout_worker_batch_shapes(ray_start_regular):
    import jax

    from ray_tpu.rllib import RolloutWorker, init_policy

    w = RolloutWorker("CartPole-v1", num_envs=2, seed=0)
    params = init_policy(jax.random.PRNGKey(0), *w.spaces())
    batch = w.sample(params, 16)
    assert batch["obs"].shape == (32, 4)
    assert batch["actions"].shape == (32,)
    assert batch["advantages"].shape == (32,)
    assert np.isfinite(batch["advantages"]).all()


def test_ppo_cartpole_converges(ray_start_regular):
    """The round-brief done-criterion: PPO on CartPole learns using the
    framework's own actors + object store. Random policy scores ~22;
    we require a 4x improvement within a bounded budget."""
    from ray_tpu.rllib import AlgorithmConfig, PPO

    algo = (AlgorithmConfig(PPO)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=128)
            .training(lr=3e-4, minibatch_size=128)
            .build())
    try:
        best = 0.0
        for _ in range(40):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 120.0:
                break
        assert best >= 100.0, f"PPO failed to learn: best reward {best}"
        # save/restore round-trips
        state = algo.save()
        algo.restore(state)
        assert algo.iteration == state["iteration"]
    finally:
        algo.stop()


def test_replay_buffer_ring_and_sampling():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=10, seed=0)
    buf.add_batch({"x": np.arange(6, dtype=np.float32)})
    assert len(buf) == 6
    buf.add_batch({"x": np.arange(6, 14, dtype=np.float32)})
    assert len(buf) == 10          # capacity-bounded
    sample = buf.sample(32)
    assert sample["x"].shape == (32,)
    # rows 0-3 were overwritten by the wrap-around (values 10-13)
    assert set(sample["x"].tolist()) <= set(range(4, 14))


def test_prioritized_replay_buffer():
    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=100, seed=0)
    buf.add_batch({"x": np.arange(50, dtype=np.float32)})
    s = buf.sample(16)
    assert "weights" in s and "batch_indexes" in s
    # boost one row's priority and confirm it dominates sampling
    buf.update_priorities(np.array([7]), np.array([100.0]))
    counts = 0
    for _ in range(20):
        counts += int((buf.sample(16)["batch_indexes"] == 7).sum())
    assert counts > 20, f"prioritized row rarely sampled ({counts})"


def test_dqn_cartpole_learns(ray_start_regular):
    """DQN on CartPole: epsilon-greedy transitions through the object
    store into a replay buffer; double-Q learner improves the policy
    well past the random-policy return (~22)."""
    from ray_tpu.rllib import DQN, AlgorithmConfig

    algo = (AlgorithmConfig(DQN)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=64)
            .training(lr=2e-3, minibatch_size=128, num_sgd_steps=64,
                      learning_starts=256, epsilon_anneal_iters=8,
                      target_update_freq=2)
            .build())
    try:
        best = 0.0
        for _ in range(45):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 80.0:
                break
        assert best >= 60.0, f"DQN failed to learn: best reward {best}"
        state = algo.save()
        algo.restore(state)
    finally:
        algo.stop()


def test_a2c_cartpole_converges(ray_start_regular):
    """A2C (PPO minus the surrogate/epochs) must also learn CartPole —
    its single-step on-policy update is the simplest learner shape."""
    from ray_tpu.rllib import A2C, AlgorithmConfig

    algo = (AlgorithmConfig(A2C)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=128)
            .training(lr=1e-3, entropy_coeff=0.01)
            .build())
    try:
        best = 0.0
        for _ in range(60):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 80.0:
                break
        assert best >= 60.0, f"A2C failed to learn: best reward {best}"
    finally:
        algo.stop()


def _expert_cartpole_data(n: int = 4000, seed: int = 0):
    """Roll a hand-coded balancing controller (act on pole angle +
    angular velocity) to produce imitation data; it scores far above
    random, so cloning it is measurable."""
    import numpy as np

    from ray_tpu.rllib.env import CartPole

    env = CartPole(seed=seed)
    obs_list, act_list = [], []
    obs, _ = env.reset()
    while len(obs_list) < n:
        action = int(obs[2] + 0.5 * obs[3] > 0)
        obs_list.append(obs.copy())
        act_list.append(action)
        obs, _r, terminated, truncated, _ = env.step(action)
        if terminated or truncated:
            obs, _ = env.reset()
    return {"obs": np.asarray(obs_list, np.float32),
            "actions": np.asarray(act_list, np.int64)}


def test_bc_offline_imitates_expert(ray_start_regular):
    """Offline RL: BC trains purely from a dataset (no env interaction)
    and the cloned policy scores like the expert when evaluated."""
    from ray_tpu.rllib import BC, AlgorithmConfig

    data = _expert_cartpole_data()
    algo = (AlgorithmConfig(BC)
            .environment("CartPole-v1")
            .rollouts(num_envs_per_worker=2, rollout_fragment_length=256)
            .training(lr=1e-3, minibatch_size=256, offline_data=data)
            .build())
    try:
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.rllib import policy_apply

        def full_accuracy():
            logits, _ = policy_apply(algo.params, jnp.asarray(data["obs"]))
            pred = np.asarray(jnp.argmax(logits, axis=-1))
            return float((pred == data["actions"]).mean())

        acc = 0.0
        for _ in range(40):
            algo.train()
            acc = full_accuracy()      # whole-dataset, not one minibatch
            if acc >= 0.97:
                break
        assert acc >= 0.9, f"BC failed to fit the expert: acc={acc}"
        ev = algo.evaluate()
        # hand-coded expert scores ~180+; random ~22. Cloning must land
        # decisively on the expert side.
        assert ev["episode_reward_mean"] >= 100.0, ev
    finally:
        algo.stop()


def test_bc_accepts_dataset_offline_data(ray_start_regular):
    """The documented Dataset form of offline_data (rows with
    'obs'/'actions') builds and trains."""
    import numpy as np

    import ray_tpu.data as rdata
    from ray_tpu.rllib import BC, AlgorithmConfig

    raw = _expert_cartpole_data(n=512)
    ds = rdata.from_items([
        {"obs": raw["obs"][i], "actions": int(raw["actions"][i])}
        for i in range(len(raw["actions"]))])
    algo = (AlgorithmConfig(BC)
            .environment("CartPole-v1")
            .training(lr=1e-3, minibatch_size=128, offline_data=ds)
            .build())
    try:
        result = algo.train()
        assert result["num_samples_trained"] == 512
        assert "bc_loss" in result
    finally:
        algo.stop()


def test_connectors_transform_pipeline():
    from ray_tpu.rllib.connectors import (
        ClipReward,
        ConnectorPipeline,
        FrameStack,
        MeanStdObsNormalizer,
    )

    pipe = ConnectorPipeline([MeanStdObsNormalizer(), FrameStack(k=3)])
    assert pipe.obs_size(4) == 12
    o1 = pipe.transform_obs(np.array([1.0, 2.0, 3.0, 4.0]), stream_key=0)
    assert o1.shape == (12,)
    # frame stack rolls: a second obs shifts the window
    o2 = pipe.transform_obs(np.array([5.0, 6.0, 7.0, 8.0]), stream_key=0)
    assert not np.allclose(o1, o2)
    # reset clears per-stream state
    pipe.reset(stream_key=0)
    clip = ClipReward(1.0)
    assert clip.transform_reward(7.3) == 1.0
    assert clip.transform_reward(-2.0) == -1.0
    # normalizer drives running stats toward zero-mean
    norm = MeanStdObsNormalizer()
    for i in range(200):
        out = norm.transform_obs(np.array([10.0 + (i % 3)]))
    assert abs(float(out[0])) < 3.0


def test_rollout_worker_with_connectors(ray_start_regular):
    """Connectors change the policy's observation space and the sampled
    batch shapes end-to-end (reference: connector pipelines run inside
    the rollout worker)."""
    import jax

    from ray_tpu.rllib import RolloutWorker, init_policy
    from ray_tpu.rllib.connectors import FrameStack, MeanStdObsNormalizer

    w = RolloutWorker("CartPole-v1", num_envs=2, seed=0,
                      connectors=[MeanStdObsNormalizer(), FrameStack(k=2)])
    obs_size, num_actions = w.spaces()
    assert obs_size == 8          # 4 raw x 2 stacked
    params = init_policy(jax.random.PRNGKey(0), obs_size, num_actions)
    batch = w.sample(params, 16)
    assert batch["obs"].shape == (32, 8)
    assert np.isfinite(batch["obs"]).all()


def test_ppo_with_connectors_still_learns(ray_start_regular):
    from ray_tpu.rllib import AlgorithmConfig, PPO
    from ray_tpu.rllib.connectors import MeanStdObsNormalizer

    algo = (AlgorithmConfig(PPO)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=128,
                      connectors=[MeanStdObsNormalizer])
            .training(lr=3e-4, minibatch_size=128)
            .build())
    try:
        best = 0.0
        for _ in range(40):
            best = max(best, algo.train()["episode_reward_mean"])
            if best >= 100.0:
                break
        assert best >= 80.0, f"PPO+normalizer failed to learn: {best}"
    finally:
        algo.stop()
