"""Multi-node tests over the in-process Cluster fixture — scheduling across
nodes, object transfer, placement groups, node failure (the reference's
test_multi_node / test_placement_group / test_failure tier)."""
import time

import numpy as np
import pytest


@pytest.fixture
def two_node_cluster(ray_start_cluster):
    cluster = ray_start_cluster
    # head has 2 CPUs, second node 2 CPUs
    cluster.remove_node(cluster.head_node)
    cluster.head_node = cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    import ray_tpu

    yield cluster, ray_tpu


def test_schedule_across_nodes(two_node_cluster):
    cluster, ray = two_node_cluster

    @ray.remote(num_cpus=2)
    def where():
        import ray_tpu

        time.sleep(0.2)
        return ray_tpu.get_runtime_context().get_node_id()

    # two 2-CPU tasks cannot fit on one 2-CPU node concurrently
    nodes = ray.get([where.remote(), where.remote()], timeout=60)
    assert len(set(nodes)) == 2, f"expected 2 distinct nodes, got {nodes}"


def test_object_transfer_between_nodes(two_node_cluster):
    cluster, ray = two_node_cluster

    @ray.remote(num_cpus=2)
    def produce():
        return np.full(300_000, 7.0)   # > inline limit → shm store

    @ray.remote(num_cpus=2)
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    _ = ray.get(ref)   # make sure it's done; lease freed
    # consume may land on the other node → remote fetch path
    total = ray.get(consume.remote(ref), timeout=60)
    assert total == 7.0 * 300_000


def test_actor_on_specific_node(two_node_cluster):
    cluster, ray = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    # ray.nodes() includes the dead original head node (reference parity:
    # dead nodes are listed with Alive=False) — only target alive ones.
    target = sorted(n["NodeID"] for n in ray.nodes() if n["Alive"])[-1]

    @ray.remote
    class Pin:
        def node(self):
            import ray_tpu

            return ray_tpu.get_runtime_context().get_node_id()

    a = Pin.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=target)
    ).remote()
    assert ray.get(a.node.remote(), timeout=60) == target


def test_placement_group_spread(two_node_cluster):
    cluster, ray = two_node_cluster
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(30), "placement group did not schedule"

    @ray.remote(num_cpus=1)
    class Member:
        def node(self):
            import ray_tpu

            return ray_tpu.get_runtime_context().get_node_id()

    members = [
        Member.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=i)).remote()
        for i in range(2)
    ]
    nodes = ray.get([m.node.remote() for m in members], timeout=60)
    assert len(set(nodes)) == 2
    remove_placement_group(pg)


def test_placement_group_pack(two_node_cluster):
    cluster, ray = two_node_cluster
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
    assert pg.wait(30)
    snap = None
    for s in ray.get_runtime_context()._worker.gcs.call(
            "list_placement_groups"):
        if s["PlacementGroupID"] == pg.id.hex():
            snap = s
    assert snap and len(set(snap["BundleNodes"])) == 1


def test_pg_infeasible_then_schedulable(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.connect()
    import ray_tpu as ray
    from ray_tpu.util.placement_group import placement_group

    # head node has 1 CPU; a 2-bundle strict-spread PG can't schedule yet
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert not pg.wait(1.0)
    cluster.add_node(num_cpus=2)
    assert pg.wait(30), "PG should schedule after node joins"


def test_node_death_kills_actor(two_node_cluster):
    cluster, ray = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    victim_raylet = [r for r in cluster._raylets.values()
                     if r is not cluster.head_node][0]

    @ray.remote(max_restarts=0)
    class Doomed:
        def node(self):
            import ray_tpu

            return ray_tpu.get_runtime_context().get_node_id()

    a = Doomed.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=victim_raylet.node_id)).remote()
    assert ray.get(a.node.remote(), timeout=60) == victim_raylet.node_id

    cluster.remove_node(victim_raylet)
    with pytest.raises((ray.exceptions.ActorDiedError,
                        ray.exceptions.ActorUnavailableError,
                        ray.exceptions.GetTimeoutError)):
        ray.get(a.node.remote(), timeout=15)


def test_node_death_actor_restarts_elsewhere(two_node_cluster):
    cluster, ray = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    victim = [r for r in cluster._raylets.values()
              if r is not cluster.head_node][0]

    @ray.remote(max_restarts=1)
    class Survivor:
        def node(self):
            import ray_tpu

            return ray_tpu.get_runtime_context().get_node_id()

    # soft affinity: prefers the victim but may restart elsewhere
    a = Survivor.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=victim.node_id, soft=True)).remote()
    first = ray.get(a.node.remote(), timeout=60)
    if first != victim.node_id:
        pytest.skip("actor did not land on victim node")
    cluster.remove_node(victim)

    deadline = time.time() + 40
    second = None
    while time.time() < deadline:
        try:
            second = ray.get(a.node.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.5)
    assert second == cluster.head_node.node_id, (
        f"actor should restart on surviving node, got {second}")


def test_get_raises_object_lost_on_node_death(ray_start_cluster):
    """When every copy of a created object dies with its node, get() raises
    ObjectLostError instead of polling forever (reference raises the same
    after reconstruction is exhausted; advisor finding on the hang)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)          # head: driver-only
    node2 = cluster.add_node(num_cpus=2)
    cluster.connect()
    import ray_tpu
    from ray_tpu.exceptions import ObjectLostError

    @ray_tpu.remote(num_cpus=2, max_retries=0)
    def produce():
        return np.full(300_000, 3.0)      # > inline limit -> node2's store

    ref = produce.remote()
    # wait for creation WITHOUT fetching (a get() would cache a copy on the
    # driver's node and the object would rightly not be lost)
    done, _ = ray_tpu.wait([ref], timeout=60, fetch_local=False)
    assert done, "produce task did not finish"
    cluster.remove_node(node2)

    with pytest.raises(ObjectLostError):
        ray_tpu.get(ref, timeout=20)
