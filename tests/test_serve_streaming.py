"""Serve streaming + ASGI tests (reference: http_proxy.py streaming
StreamingResponses through uvicorn; serve.ingress mounting FastAPI).

The incrementality assertion is the point: chunks must reach the client
WHILE the generator is still producing, not after it finishes.
"""
import http.client
import json
import time

import pytest


def _http(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path, body=body)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _read_chunks_timed(port, path):
    """Stream a response, recording arrival time per chunk batch."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    assert resp.status == 200
    arrivals = []
    while True:
        piece = resp.read1(65536)
        if not piece:
            break
        arrivals.append((time.monotonic(), piece))
    conn.close()
    return arrivals


@pytest.fixture
def serve_instance(ray_start_regular):
    from ray_tpu import serve

    serve.start()
    yield serve
    serve.shutdown()


def test_streaming_response_chunks_arrive_incrementally(serve_instance):
    serve = serve_instance

    @serve.deployment
    def ticker(request):
        def gen():
            for i in range(5):
                yield f"tick-{i};"
                time.sleep(0.3)
        return serve.StreamingResponse(gen(), content_type="text/plain")

    serve.run(ticker.bind(), route_prefix="/tick")
    port = serve.http_port()
    t0 = time.monotonic()
    arrivals = _read_chunks_timed(port, "/tick")
    total = time.monotonic() - t0
    body = b"".join(p for _, p in arrivals)
    assert body == b"".join(f"tick-{i};".encode() for i in range(5))
    # first chunk must land while later chunks are still being produced:
    # generation takes ~1.5s; an un-streamed response would deliver
    # everything at the end
    first_at = arrivals[0][0] - t0
    assert total >= 1.2, f"generator finished too fast ({total:.2f}s)"
    assert first_at < total / 2, (
        f"first chunk at {first_at:.2f}s of {total:.2f}s — not streamed")


def test_bare_generator_streams_and_handle_iterates(serve_instance):
    serve = serve_instance

    @serve.deployment
    class Tokens:
        def __call__(self, request):
            return self.tokens()

        def tokens(self):
            for t in ["alpha", "beta", "gamma"]:
                yield t + " "

    serve.run(Tokens.bind(), route_prefix="/tok")
    port = serve.http_port()
    status, data = _http(port, "GET", "/tok")
    assert status == 200 and data == b"alpha beta gamma "

    # handle-level: the caller gets a chunk iterator
    handle = serve.get_app_handle("default")
    out = b"".join(handle.tokens.remote().result(timeout_s=30))
    assert out == b"alpha beta gamma "


def test_asgi_app_full_and_streaming(serve_instance):
    """A hand-rolled ASGI 3.0 app (no FastAPI dependency) mounted via
    serve.ingress: JSON echo + a streaming endpoint."""
    serve = serve_instance

    async def asgi_app(scope, receive, send):
        assert scope["type"] == "http"
        if scope["path"].endswith("/stream"):
            await send({"type": "http.response.start", "status": 200,
                        "headers": [(b"content-type", b"text/plain")]})
            for i in range(3):
                await send({"type": "http.response.body",
                            "body": f"s{i}.".encode(), "more_body": True})
            await send({"type": "http.response.body", "body": b"end",
                        "more_body": False})
            return
        ev = await receive()
        body = ev.get("body", b"")
        payload = json.dumps({
            "method": scope["method"],
            "path": scope["path"],
            "echo": body.decode() if body else None,
        }).encode()
        await send({"type": "http.response.start", "status": 201,
                    "headers": [(b"content-type", b"application/json"),
                                (b"x-app", b"asgi")]})
        await send({"type": "http.response.body", "body": payload,
                    "more_body": False})

    @serve.deployment
    @serve.ingress(asgi_app)
    class Api:
        pass

    serve.run(Api.bind(), route_prefix="/api")
    port = serve.http_port()

    status, data = _http(port, "POST", "/api/echo", body=b"hello")
    assert status == 201
    reply = json.loads(data)
    assert reply == {"method": "POST", "path": "/api/echo",
                     "echo": "hello"}

    status, data = _http(port, "GET", "/api/stream")
    assert status == 200 and data == b"s0.s1.s2.end"


def test_fastapi_app_if_available(serve_instance):
    fastapi = pytest.importorskip("fastapi")
    serve = serve_instance
    app = fastapi.FastAPI()

    @app.get("/hello")
    def hello():
        return {"msg": "hi"}

    @serve.deployment
    @serve.ingress(app)
    class Api:
        pass

    serve.run(Api.bind(), route_prefix="/f")
    status, data = _http(serve.http_port(), "GET", "/f/hello")
    assert status == 200 and json.loads(data) == {"msg": "hi"}


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
