"""Tests for the parallelism stack: mesh, ring attention, pipeline, MoE,
flash attention, and the sharded GPT-2 train step — all on the virtual
8-device CPU mesh (conftest.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import gpt2
from ray_tpu.models.layers import MoEConfig, apply_moe, init_moe
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.parallel.mesh import MeshConfig, create_mesh, balanced_factorization
from ray_tpu.parallel.pipeline import (
    gpipe,
    microbatch,
    stack_stage_params,
    unmicrobatch,
)
from ray_tpu.parallel.ring_attention import reference_attention, ring_attention
from ray_tpu.parallel.train_step import (
    default_optimizer,
    make_train_state,
    make_train_step,
)


def test_mesh_construction():
    mesh = create_mesh(MeshConfig(dp=2, sp=2, tp=2))
    assert dict(mesh.shape) == {"dp": 2, "pp": 1, "ep": 1, "sp": 2, "tp": 2}
    mesh = create_mesh(MeshConfig(dp=-1, tp=2))
    assert dict(mesh.shape)["dp"] == 4


def test_balanced_factorization():
    sizes = balanced_factorization(8, ["dp", "pp", "tp"])
    assert np.prod(list(sizes.values())) == 8
    assert all(v >= 2 for v in sizes.values())


def test_ring_attention_matches_reference():
    mesh = create_mesh(MeshConfig(dp=2, sp=2, tp=2))
    k = jax.random.PRNGKey(0)
    B, S, H, D = 4, 32, 4, 16
    q, kk, v = [jax.random.normal(kq, (B, S, H, D)) for kq in jax.random.split(k, 3)]
    spec = NamedSharding(mesh, P("dp", "sp", "tp", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, kk, v))
    with jax.set_mesh(mesh):
        for causal in (True, False):
            out = ring_attention(qs, ks, vs, mesh, causal=causal)
            ref = reference_attention(q, kk, v, causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_grad():
    mesh = create_mesh(MeshConfig(sp=4, tp=2))
    k = jax.random.PRNGKey(1)
    B, S, H, D = 2, 32, 2, 8
    q, kk, v = [jax.random.normal(kq, (B, S, H, D)) for kq in jax.random.split(k, 3)]
    with jax.set_mesh(mesh):
        g = jax.grad(lambda q: jnp.sum(ring_attention(q, kk, v, mesh) ** 2))(q)
    gref = jax.grad(lambda q: jnp.sum(reference_attention(q, kk, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref), atol=5e-5)


def test_flash_attention_interpret():
    k = jax.random.PRNGKey(2)
    B, S, H, D = 2, 256, 2, 32
    q, kk, v = [jax.random.normal(kq, (B, S, H, D)) for kq in jax.random.split(k, 3)]
    o = flash_attention(q, kk, v, causal=True, block_q=128, block_k=128)
    ref = reference_attention(q, kk, v, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)
    g = jax.grad(
        lambda q: jnp.sum(flash_attention(q, kk, v, block_q=128, block_k=128) ** 2)
    )(q)
    gref = jax.grad(lambda q: jnp.sum(reference_attention(q, kk, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref), atol=5e-5)


def test_flash_attention_backward_all_grads():
    """The Pallas backward kernels (dq + dk/dv) against the reference VJP,
    causal and non-causal, including a seq length that doesn't divide the
    block size (exercises the padding/masking paths)."""
    key = jax.random.PRNGKey(3)
    for S, causal in [(256, True), (256, False), (192, True)]:
        B, H, D = 2, 2, 32
        q, kk, v = [jax.random.normal(kq, (B, S, H, D))
                    for kq in jax.random.split(jax.random.fold_in(key, S), 3)]

        def loss_flash(q, kk, v):
            o = flash_attention(q, kk, v, causal=causal,
                                block_q=128, block_k=128)
            return jnp.sum(o * jnp.cos(o))   # non-symmetric cotangents

        def loss_ref(q, kk, v):
            o = reference_attention(q, kk, v, causal=causal)
            return jnp.sum(o * jnp.cos(o))

        grads = jax.grad(loss_flash, argnums=(0, 1, 2))(q, kk, v)
        grefs = jax.grad(loss_ref, argnums=(0, 1, 2))(q, kk, v)
        for g, gref, name in zip(grads, grefs, "q k v".split()):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(gref), atol=1e-4,
                err_msg=f"d{name} mismatch (S={S}, causal={causal})")


def test_moe_matches_per_token_oracle():
    cfg = MoEConfig(n_experts=4, top_k=2, capacity_factor=8.0)
    k = jax.random.PRNGKey(3)
    p = init_moe(k, 16, 32, cfg)
    x = jax.random.normal(k, (2, 8, 16))
    out, aux = apply_moe(p, x, cfg, compute_dtype=jnp.float32)
    probs = jax.nn.softmax(jnp.einsum("bsd,de->bse", x, p["wg"]), -1)
    gv, gi = jax.lax.top_k(probs, 2)
    gv = gv / gv.sum(-1, keepdims=True)
    ref = jnp.zeros_like(x)
    for b in range(2):
        for s in range(8):
            acc = sum(
                gv[b, s, j]
                * (jax.nn.gelu(x[b, s] @ p["w1"][gi[b, s, j]]) @ p["w2"][gi[b, s, j]])
                for j in range(2)
            )
            ref = ref.at[b, s].set(acc)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    assert float(aux) > 0


def test_moe_ep_sharded():
    cfg = MoEConfig(n_experts=4, top_k=2, capacity_factor=8.0)
    k = jax.random.PRNGKey(4)
    p = init_moe(k, 16, 32, cfg)
    x = jax.random.normal(k, (4, 8, 16))
    dense_out, _ = apply_moe(p, x, cfg, compute_dtype=jnp.float32)
    mesh = create_mesh(MeshConfig(dp=2, ep=4))
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
    ps = {
        "wg": jax.device_put(p["wg"], NamedSharding(mesh, P())),
        "w1": jax.device_put(p["w1"], NamedSharding(mesh, P("ep"))),
        "w2": jax.device_put(p["w2"], NamedSharding(mesh, P("ep"))),
    }
    out, _ = jax.jit(lambda p, x: apply_moe(p, x, cfg, compute_dtype=jnp.float32))(
        ps, xs
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_out), atol=1e-5)


def test_gpipe_matches_sequential():
    mesh = create_mesh(MeshConfig(dp=2, pp=2, tp=2))
    k = jax.random.PRNGKey(5)
    Ws = [jax.random.normal(kq, (8, 8)) * 0.1 for kq in jax.random.split(k, 2)]
    stacked = stack_stage_params([{"w": Ws[0]}, {"w": Ws[1]}])
    x = jax.random.normal(k, (16, 8))

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    with jax.set_mesh(mesh):
        st = jax.device_put(stacked, NamedSharding(mesh, P("pp")))
        y = gpipe(stage_fn, st, microbatch(x, 4), mesh)
        ref = jnp.tanh(jnp.tanh(x @ Ws[0]) @ Ws[1])
        np.testing.assert_allclose(np.asarray(unmicrobatch(y)), np.asarray(ref), atol=1e-5)
        # gradients flow through the schedule
        g = jax.grad(lambda s: jnp.sum(gpipe(stage_fn, s, microbatch(x, 4), mesh) ** 2))(
            st
        )
    assert jax.tree_util.tree_map(lambda a: a.shape, g)["w"] == (2, 8, 8)


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = gpt2.gpt2_tiny()
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, cfg.vocab_size)
    return cfg, params, tokens


def test_gpt2_forward_shapes(tiny_setup):
    cfg, params, tokens = tiny_setup
    logits, aux = gpt2.forward(params, tokens[:, :-1], cfg)
    assert logits.shape == (8, 32, cfg.vocab_size)
    assert jnp.isfinite(logits).all()


def test_gpt2_sharded_forward_matches_unsharded(tiny_setup):
    cfg, params, tokens = tiny_setup
    dense_logits, _ = gpt2.forward(params, tokens[:, :-1], cfg)
    mesh = create_mesh(MeshConfig(dp=2, sp=2, tp=2))
    specs = gpt2.partition_specs(cfg)
    with jax.set_mesh(mesh):
        sharded = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
        )
        logits, _ = jax.jit(
            lambda p, t: gpt2.forward(p, t, cfg, mesh)
        )(sharded, tokens[:, :-1])
    # ring attention (sp=2) vs dense attention: same math, but bf16 compute
    # with different accumulation order — tolerance sized for bf16.
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(dense_logits), atol=2e-2
    )


def test_gpt2_pipelined_matches_dense(tiny_setup):
    cfg, params, tokens = tiny_setup
    dense_logits, _ = gpt2.forward(params, tokens[:, :-1], cfg)
    mesh = create_mesh(MeshConfig(dp=2, pp=2, tp=2))
    with jax.set_mesh(mesh):
        logits, _ = jax.jit(
            lambda p, t: gpt2.forward_pipelined(p, t, cfg, mesh, n_microbatches=4)
        )(params, tokens[:, :-1])
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(dense_logits), atol=2e-2
    )


def test_gpt2_pipelined_pp_sp_joint_training(tiny_setup):
    """pp×sp composition (round-3 fix): the pipelined forward with sp>1
    uses ring_local attention inside ONE flat {pp, sp} manual region, and
    — the part that used to DuplicateSpecError — it differentiates.
    Forward AND gradients match the dense single-device oracle."""
    cfg, params, tokens = tiny_setup
    mesh = create_mesh(MeshConfig(dp=2, pp=2, sp=2))

    def oracle_loss(p, t):
        logits, _ = gpt2.forward(p, t, cfg)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    def pp_sp_loss(p, t):
        logits, _ = gpt2.forward_pipelined(p, t, cfg, mesh,
                                           n_microbatches=4)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    toks = tokens[:, :-1]
    with jax.set_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(pp_sp_loss))(params, toks)
    oracle, ograds = jax.value_and_grad(oracle_loss)(params, toks)
    np.testing.assert_allclose(float(loss), float(oracle), atol=2e-3)
    flat = jax.tree_util.tree_leaves(grads)
    oflat = jax.tree_util.tree_leaves(ograds)
    for g, og in zip(flat, oflat):
        np.testing.assert_allclose(np.asarray(g), np.asarray(og),
                                   atol=5e-2, rtol=5e-2)


def test_gpt2_moe_forward():
    cfg = gpt2.GPT2Config(
        vocab_size=128,
        max_seq=64,
        n_layer=2,
        n_head=2,
        d_model=32,
        remat=False,
        moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=2.0),
    )
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, cfg.vocab_size)
    loss, metrics = gpt2.loss_fn(params, {"tokens": tokens}, cfg)
    assert jnp.isfinite(loss)
    assert float(metrics["aux_loss"]) > 0


def test_train_step_loss_decreases(tiny_setup):
    cfg, _, tokens = tiny_setup
    mesh = create_mesh(MeshConfig(dp=2, sp=2, tp=2))
    opt = default_optimizer(1e-2, warmup_steps=1, total_steps=50)
    specs = gpt2.partition_specs(cfg)
    with jax.set_mesh(mesh):
        state = make_train_state(
            lambda rng: gpt2.init(rng, cfg), jax.random.PRNGKey(0), opt, mesh, specs
        )
        step = make_train_step(
            lambda p, b: gpt2.loss_fn(p, b, cfg, mesh), opt, mesh
        )
        batch = {"tokens": tokens}
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 5


def test_hybrid_mesh_slice_major_dp():
    """Multi-slice hybrid mesh: dp spans the (simulated) slices, inner axes
    stay within a slice; a dp-psum executes correctly over the layout."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import MeshConfig, create_hybrid_mesh

    devices = jax.devices()[:8]
    # simulate 2 slices of 4 chips each
    assignments = [0] * 4 + [1] * 4
    mesh = create_hybrid_mesh(MeshConfig(dp=1, tp=4), dcn_dp=2,
                              devices=devices,
                              slice_assignments=assignments)
    assert dict(mesh.shape) == {"dp": 2, "pp": 1, "ep": 1, "sp": 1, "tp": 4}
    # dp must be slice-major: each dp row holds exactly one slice's devices
    dev_array = np.asarray(mesh.devices)
    row0 = set(d.id for d in dev_array[0].ravel())
    assert row0 == {d.id for d in devices[:4]}, "dp row 0 != slice 0"

    @jax.jit
    def summed(x):
        return shard_map(
            lambda s: jax.lax.psum(s, "dp"),
            mesh=mesh, in_specs=P("dp"), out_specs=P(),
        )(x)

    x = jnp.arange(8.0)
    out = summed(x)
    assert np.allclose(out, np.arange(8.0).reshape(2, 4).sum(0))


def test_hybrid_mesh_rejects_uneven_slices():
    import jax
    import pytest as _pytest

    from ray_tpu.parallel.mesh import create_hybrid_mesh

    devices = jax.devices()[:7]
    with _pytest.raises(ValueError, match="uneven"):
        create_hybrid_mesh(devices=devices,
                           slice_assignments=[0, 0, 0, 0, 1, 1, 1])
