"""Tune class Trainable, experiment persistence + resume, top-K
checkpoints, orbax checkpoint form.

Reference tier: tune/tests/test_trainable.py, test_tuner_restore.py,
execution/checkpoint_manager tests.
"""
import json
import os

import numpy as np
import pytest


def test_class_trainable_runs_and_checkpoints(ray_start_regular, tmp_path):
    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.air.config import RunConfig
    from ray_tpu.tune.trainable import Trainable
    from ray_tpu.tune.tuner import Tuner, TuneConfig

    class Quadratic(Trainable):
        def setup(self, config):
            self.x = 0.0
            self.lr = config["lr"]

        def step(self):
            self.x += self.lr
            return {"score": -(self.x - 2.0) ** 2}

        def save_checkpoint(self):
            return {"x": self.x}

        def load_checkpoint(self, state):
            self.x = state["x"]

    tuner = Tuner(
        Quadratic,
        param_space={"lr": tune.grid_search([0.5, 1.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="quad", storage_path=str(tmp_path),
                             stop={"training_iteration": 4}),
    )
    results = tuner.fit()
    assert len(results) == 2
    best = results.get_best_result("score")
    assert best.metrics["score"] == 0.0     # lr=0.5 hits x=2 at iter 4
    # experiment state + checkpoints persisted
    state_file = tmp_path / "quad" / "experiment_state.json"
    assert state_file.exists()
    state = json.loads(state_file.read_text())
    assert len(state["trials"]) == 2
    assert all(t["status"] == "TERMINATED" for t in state["trials"])
    assert all(t["checkpoint_dir"] for t in state["trials"])


def test_experiment_resume_skips_finished(ray_start_regular, tmp_path):
    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.air.config import RunConfig
    from ray_tpu.air import session
    from ray_tpu.tune.tuner import Tuner, TuneConfig

    marker = str(tmp_path / "ran")

    def trainable(config):
        with open(marker, "a") as f:
            f.write(f"{config['i']}\n")
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["it"] if ckpt else 0
        for it in range(start, 3):
            from ray_tpu.air.checkpoint import Checkpoint

            session.report({"v": config["i"] * 10 + it},
                           checkpoint=Checkpoint.from_dict({"it": it + 1}))

    run_cfg = RunConfig(name="resume_exp", storage_path=str(tmp_path))
    tuner = Tuner(trainable, param_space={"i": tune.grid_search([1, 2])},
                  tune_config=TuneConfig(metric="v", mode="max"),
                  run_config=run_cfg)
    results = tuner.fit()
    assert len(results) == 2
    first_runs = open(marker).read().count("\n")
    assert first_runs == 2

    # doctor the state file: pretend trial for i=2 died mid-run with only
    # its second checkpoint persisted
    state_path = tmp_path / "resume_exp" / "experiment_state.json"
    state = json.loads(state_path.read_text())
    for t in state["trials"]:
        if t["config"]["i"] == 2:
            t["status"] = "RUNNING"
            t["checkpoint_dir"] = os.path.join(
                os.path.dirname(t["checkpoint_dir"]), "checkpoint_000002")
            assert os.path.isdir(t["checkpoint_dir"])
    state_path.write_text(json.dumps(state))

    restored = Tuner.restore(str(tmp_path / "resume_exp"), trainable,
                             tune_config=TuneConfig(metric="v", mode="max"))
    results2 = restored.fit()
    assert len(results2) == 2
    # only the unfinished trial re-ran
    assert open(marker).read().count("\n") == first_runs + 1
    assert results2.get_best_result("v").metrics["v"] == 22


def test_checkpoint_manager_keeps_top_k(tmp_path):
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.air.config import CheckpointConfig
    from ray_tpu.tune.checkpoint_manager import CheckpointManager

    cm = CheckpointManager(str(tmp_path), CheckpointConfig(
        num_to_keep=2, checkpoint_score_attribute="acc"))
    for it, acc in [(1, 0.2), (2, 0.9), (3, 0.5), (4, 0.1)]:
        cm.on_checkpoint(Checkpoint.from_dict({"it": it}), {"acc": acc}, it)
    kept = sorted(os.listdir(tmp_path))
    # best-scored (it=2, acc=.9) survives; latest (it=4) is never evicted
    assert "checkpoint_000002" in kept
    assert "checkpoint_000004" in kept
    assert len(kept) == 2
    assert cm.best_checkpoint().to_dict()["it"] == 2


def test_orbax_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from ray_tpu.air.checkpoint import Checkpoint

    tree = {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
            "step": jnp.int32(7)}
    ckpt = Checkpoint.from_jax(tree, path=str(tmp_path / "ck"))
    restored = ckpt.to_jax()
    assert int(restored["step"]) == 7
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.arange(6.0).reshape(2, 3))
    # survives the bytes round trip (how checkpoints cross nodes)
    blob = ckpt.to_bytes()
    back = Checkpoint.from_bytes(blob).to_jax()
    assert int(back["step"]) == 7
