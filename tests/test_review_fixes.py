"""Regression tests for review findings: cancel, zero-cpu tasks, option
immutability, re-init function registration, kill-then-call, DAG binding."""
import time

import pytest


def test_cancel_running_task(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def hang():
        time.sleep(60)
        return "finished"

    ref = hang.remote()
    time.sleep(1.0)   # let it start
    ray.cancel(ref, force=True)
    with pytest.raises((ray.exceptions.TaskCancelledError,
                        ray.exceptions.TaskError)):
        ray.get(ref, timeout=20)


def test_cancel_queued_task(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_cpus=4)
    def block():
        time.sleep(5)

    @ray.remote(num_cpus=4)
    def queued():
        return "ran"

    b = block.remote()
    time.sleep(0.5)
    q = queued.remote()   # can't start while block holds all CPUs
    ray.cancel(q)
    with pytest.raises(ray.exceptions.TaskCancelledError):
        ray.get(q, timeout=30)
    ray.get(b)  # drain


def test_zero_cpu_task(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_cpus=4)
    def hog():
        time.sleep(6)
        return "hog"

    @ray.remote(num_cpus=0)
    def featherweight():
        return "light"

    h = hog.remote()
    time.sleep(0.3)
    # zero-cpu task must run even with all CPUs held (finishing while the
    # hog still sleeps proves it didn't wait for CPU resources)
    t0 = time.time()
    assert ray.get(featherweight.remote(), timeout=10) == "light"
    assert time.time() - t0 < 4.0, "zero-cpu task waited for CPU resources"
    ray.get(h, timeout=30)


def test_num_gpus_alias_stable_across_calls(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu._private.api import _build_resources

    opts = {"num_cpus": 1, "num_gpus": 2}
    first = _build_resources(opts)
    second = _build_resources(opts)
    assert first == second == {"CPU": 1.0, "TPU": 2.0}
    assert opts.get("num_gpus") == 2   # not mutated


def test_function_reregistered_after_reinit():
    import ray_tpu as ray

    @ray.remote
    def f():
        return 42

    ray.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        assert ray.get(f.remote(), timeout=30) == 42
    finally:
        ray.shutdown()
    ray.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        # second runtime has a fresh GCS function table
        assert ray.get(f.remote(), timeout=30) == 42
    finally:
        ray.shutdown()


def test_call_after_kill_fails_fast(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray.get(v.ping.remote()) == "pong"
    ray.kill(v)
    time.sleep(1.0)
    # must resolve to an error, not hang forever
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(v.ping.remote(), timeout=30)


def test_system_error_is_narrow():
    from ray_tpu import exceptions as exc

    assert not issubclass(exc.TaskError, exc.RaySystemError)
    assert issubclass(exc.RaySystemError, exc.RayTpuError)
    err = exc.TaskError("ValueError", "tb", cause=ValueError("x"))
    assert err.__cause__ is err.cause


def test_dag_bind_execute(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.dag import InputNode

    @ray.remote
    def double(x):
        return 2 * x

    @ray.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        d = double.bind(inp)
        out = add.bind(d, d)   # shared sub-node executes once

    ref = out.execute(5)
    assert ray.get(ref, timeout=30) == 20


def test_dag_actor_bind(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.dag import InputNode

    @ray.remote
    class Acc:
        def __init__(self, base):
            self.base = base

        def add(self, x):
            return self.base + x

    with InputNode() as inp:
        node = Acc.bind(100)
        out = node.add.bind(inp)

    assert ray.get(out.execute(5), timeout=30) == 105
