"""TPU-pod node provider + cluster launcher tests.

Reference tier: autoscaler fake-multinode E2E tests
(python/ray/tests/test_autoscaler_fake_multinode.py) + the GCP provider
unit tests (test_gcp_node_provider.py), re-shaped around slice-atomic
queued-resources semantics.
"""
import json
import os
import subprocess
import time

import pytest


# ------------------------------------------------------------- unit tier

def test_slice_atomic_create_and_terminate():
    """Creating a pod creates every host in ONE request; terminating any
    host releases the whole slice."""
    from ray_tpu.autoscaler import MockTpuApi, TPUPodNodeProvider

    api = MockTpuApi()
    prov = TPUPodNodeProvider(api, "t")
    ids = prov.create_slice(
        "v5e_pod", {"tpu_slice": {"hosts": 4, "topology": "4x4",
                                  "accelerator_type": "v5litepod-16"}},
        "4x4")
    assert len(ids) == 4
    assert len(api.requests) == 1 and api.requests[0]["hosts"] == 4
    assert api.requests[0]["topology"] == "4x4"
    nodes = prov.non_terminated_nodes()
    assert len(nodes) == 4
    assert len({n["slice_id"] for n in nodes}) == 1

    prov.terminate_node(ids[2])          # any host → whole slice
    assert prov.non_terminated_nodes() == []
    deletes = [r for r in api.requests if r["op"] == "delete"]
    assert len(deletes) == 1
    prov.terminate_node(ids[0])          # second ask: no-op
    assert len([r for r in api.requests if r["op"] == "delete"]) == 1


def test_provisioning_slice_is_not_capacity():
    """A slice still WAITING/PROVISIONING is invisible to binpacking —
    QR grants are all-or-nothing."""
    from ray_tpu.autoscaler import MockTpuApi, TPUPodNodeProvider

    api = MockTpuApi(provision_delay_s=0.5)
    prov = TPUPodNodeProvider(api, "t")
    prov.create_slice("pod", {"tpu_slice": {"hosts": 2}}, "")
    assert prov.non_terminated_nodes() == []
    assert len(prov.pending_slices()) == 1
    deadline = time.time() + 5
    while not prov.non_terminated_nodes() and time.time() < deadline:
        time.sleep(0.05)
    assert len(prov.non_terminated_nodes()) == 2
    prov.shutdown()


def test_quota_exhaustion_raises():
    from ray_tpu.autoscaler import MockTpuApi, TPUPodNodeProvider

    api = MockTpuApi(capacity_hosts=4)
    prov = TPUPodNodeProvider(api, "t")
    prov.create_slice("pod", {"tpu_slice": {"hosts": 4}}, "")
    with pytest.raises(RuntimeError, match="QUOTA_EXHAUSTED"):
        prov.create_slice("pod", {"tpu_slice": {"hosts": 4}}, "")
    prov.shutdown()


def test_gce_api_request_shapes():
    """GceTpuApi builds the queued-resources REST calls; _execute is the
    recorded seam."""
    from ray_tpu.autoscaler.tpu_provider import ACTIVE, GceTpuApi

    calls = []

    class Recorder(GceTpuApi):
        def _execute(self, method, path, body):
            calls.append((method, path, body))
            if method == "GET":
                return {"queuedResources": [{
                    "name": f"{self._parent}/queuedResources/qr1",
                    "state": {"state": "ACTIVE"},
                    "tpu": {"nodeSpec": [{
                        "nodeId": "qr1",
                        "node": {"accelerator_type": "v5litepod-16",
                                 "accelerator_config": {
                                     "type": "V5LITE_POD",
                                     "topology": "4x4"}}}]},
                }]}
            return {}

    api = Recorder("proj", "us-central2-b")
    sid = api.create_slice("qr1", "v5litepod-16", "4x4", 4,
                           {"schedulingConfig": {"preemptible": True}})
    assert sid == "qr1"
    method, path, body = calls[0]
    assert method == "POST"
    assert "projects/proj/locations/us-central2-b/queuedResources" in path
    assert "queued_resource_id=qr1" in path
    spec = body["tpu"]["node_spec"][0]
    assert spec["node"]["accelerator_type"] == "v5litepod-16"
    assert spec["node"]["accelerator_config"]["topology"] == "4x4"
    assert "best_effort" in body            # preemptible → best-effort QR

    slices = api.list_slices()
    assert slices[0]["state"] == ACTIVE
    # 4x4 topology = 16 chips = 4 hosts
    assert len(slices[0]["hosts"]) == 4

    api.delete_slice("qr1")
    method, path, _ = calls[-1]
    assert method == "DELETE" and "queuedResources/qr1" in path


# ------------------------------------------------ GceTpuApi HTTP replay
#
# Replay/fixture tier (VERDICT r5 weak #4): the REAL _execute layer —
# auth header, retry-on-429/503 under the unified RetryPolicy, and
# error mapping — exercised against canned GCE REST responses through
# the injectable `http` seam. No network, no credentials.


class _ReplayHttp:
    """Canned (status, payload) script; records every request it saw."""

    def __init__(self, script):
        self.script = list(script)
        self.requests: list[dict] = []

    def __call__(self, method, url, body, headers):
        self.requests.append({"method": method, "url": url,
                              "body": body, "headers": dict(headers)})
        status, payload = self.script.pop(0)
        if isinstance(payload, (bytes, bytearray)):
            return status, bytes(payload)
        return status, json.dumps(payload).encode()


@pytest.fixture
def fast_retries(monkeypatch):
    from ray_tpu._private import retry

    monkeypatch.setenv("RAY_TPU_RPC_RETRY_MAX_ATTEMPTS", "3")
    monkeypatch.setenv("RAY_TPU_RPC_RETRY_BASE_BACKOFF_S", "0.001")
    monkeypatch.setenv("RAY_TPU_RPC_RETRY_MAX_BACKOFF_S", "0.002")
    # exact-count assertions below must not depend on how much of the
    # process-wide budget earlier tests consumed
    monkeypatch.setattr(retry, "_default_budget",
                        retry.RetryBudget(capacity=1000,
                                          refill_per_s=1000))


def test_gce_replay_auth_header_and_url(fast_retries):
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi

    http = _ReplayHttp([(200, {})])
    api = GceTpuApi("proj", "us-central2-b",
                    token_provider=lambda: "tok-123", http=http)
    api.create_slice("qr1", "v5litepod-16", "4x4", 4, {})
    req = http.requests[0]
    assert req["headers"]["Authorization"] == "Bearer tok-123"
    assert req["headers"]["Content-Type"] == "application/json"
    assert req["url"].startswith(
        "https://tpu.googleapis.com/v2alpha1/projects/proj/locations/"
        "us-central2-b/queuedResources")
    assert b"node_spec" in req["body"]


def test_gce_replay_metadata_token_fallback(fast_retries):
    """No token_provider → the GCE metadata server is consulted with the
    Metadata-Flavor header, and its token rides the API call."""
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi

    http = _ReplayHttp([
        (200, {"access_token": "meta-tok", "expires_in": 3599}),
        (200, {"queuedResources": []}),
    ])
    api = GceTpuApi("proj", "us-central2-b", http=http)
    assert api.list_slices() == []
    meta_req, api_req = http.requests
    assert "metadata.google.internal" in meta_req["url"]
    assert meta_req["headers"]["Metadata-Flavor"] == "Google"
    assert api_req["headers"]["Authorization"] == "Bearer meta-tok"


def test_gce_replay_retry_on_429_then_503_then_success(fast_retries):
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi

    err = {"error": {"message": "rate limited", "status": "RESOURCE_"
                     "EXHAUSTED"}}
    http = _ReplayHttp([
        (429, err),
        (503, {"error": {"message": "backend unavailable"}}),
        (200, {"queuedResources": [{
            "name": "projects/p/locations/z/queuedResources/qr9",
            "state": {"state": "ACTIVE"},
            "tpu": {"nodeSpec": [{
                "nodeId": "qr9",
                "node": {"accelerator_type": "v5litepod-8"}}]},
        }]}),
    ])
    api = GceTpuApi("proj", "us-central2-b",
                    token_provider=lambda: "t", http=http)
    slices = api.list_slices()
    assert len(http.requests) == 3            # two retries, then success
    assert slices[0]["slice_id"] == "qr9"
    # v5litepod-8 → 8 chips → one host
    assert len(slices[0]["hosts"]) == 1


def test_gce_replay_quota_exhaustion_maps_to_named_error(fast_retries):
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi, TpuQuotaError

    err = {"error": {"message": "Quota exceeded for QR",
                     "status": "RESOURCE_EXHAUSTED"}}
    http = _ReplayHttp([(429, err)] * 3)
    api = GceTpuApi("proj", "us-central2-b",
                    token_provider=lambda: "t", http=http)
    with pytest.raises(TpuQuotaError, match="QUOTA_EXHAUSTED"):
        api.create_slice("qr1", "v5litepod-16", "4x4", 4, {})
    assert len(http.requests) == 3            # bounded by the policy cap


def test_gce_replay_auth_errors_never_retry(fast_retries):
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi, TpuAuthError

    for status in (401, 403):
        http = _ReplayHttp([
            (status, {"error": {"message": "bad credentials"}})])
        api = GceTpuApi("proj", "us-central2-b",
                        token_provider=lambda: "t", http=http)
        with pytest.raises(TpuAuthError, match="bad credentials"):
            api.list_slices()
        # re-sending bad credentials just burns quota: exactly one try
        assert len(http.requests) == 1


def test_gce_replay_delete_404_is_idempotent_noop(fast_retries):
    """terminate_node double-asks per slice by design; releasing an
    already-released slice must not raise."""
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi

    http = _ReplayHttp([
        (404, {"error": {"message": "queued resource not found"}})])
    api = GceTpuApi("proj", "us-central2-b",
                    token_provider=lambda: "t", http=http)
    api.delete_slice("qr-gone")               # no raise


def test_gce_replay_metadata_hiccup_retries_not_auth_error(fast_retries):
    """A transient 503 from the metadata server is retryable, not a
    credentials failure steering the operator at a nonexistent
    misconfiguration."""
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi

    http = _ReplayHttp([
        (503, {"error": {"message": "metadata blip"}}),     # token try 1
        (200, {"access_token": "tok2"}),                    # token try 2
        (200, {"queuedResources": []}),                     # API call
    ])
    api = GceTpuApi("proj", "us-central2-b", http=http)
    assert api.list_slices() == []
    assert len(http.requests) == 3


def test_gce_replay_network_error_is_retried_then_mapped(fast_retries):
    """URLError-class transport failures (refused/reset/DNS) retry under
    the policy; exhaustion maps to TpuApiError, not a raw OSError."""
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi, TpuApiError

    calls = []

    def flaky_http(method, url, body, headers):
        calls.append(url)
        if len(calls) < 3:
            raise ConnectionResetError("peer reset")
        return 200, b'{"queuedResources": []}'

    api = GceTpuApi("proj", "us-central2-b",
                    token_provider=lambda: "t", http=flaky_http)
    assert api.list_slices() == []
    assert len(calls) == 3

    def dead_http(method, url, body, headers):
        raise ConnectionRefusedError("refused")

    api2 = GceTpuApi("proj", "us-central2-b",
                     token_provider=lambda: "t", http=dead_http)
    with pytest.raises(TpuApiError, match="transport failure"):
        api2.list_slices()


def test_gce_replay_error_mapping_carries_server_message(fast_retries):
    from ray_tpu.autoscaler.tpu_provider import GceTpuApi, TpuApiError

    http = _ReplayHttp([
        (400, {"error": {"message": "Invalid topology 9x9",
                         "status": "INVALID_ARGUMENT"}})])
    api = GceTpuApi("proj", "us-central2-b",
                    token_provider=lambda: "t", http=http)
    with pytest.raises(TpuApiError, match="Invalid topology 9x9") as ei:
        api.create_slice("qr1", "v5litepod-16", "9x9", 4, {})
    assert ei.value.status == 400
    # a non-JSON error body degrades to a readable snippet, not a crash
    http2 = _ReplayHttp([(500, b"<html>boom</html>")] * 3)
    api2 = GceTpuApi("proj", "us-central2-b",
                     token_provider=lambda: "t", http=http2)
    with pytest.raises(TpuApiError, match="boom"):
        api2.list_slices()


# -------------------------------------------------------- autoscaler E2E

def test_autoscaler_pod_demand_to_scale_down():
    """VERDICT r4 #5 E2E: pending PG demand → ONE slice-atomic launch
    (all hosts join as real nodes) → PG schedules → idle → the slice
    scales down as a unit."""
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.raylet import Raylet, detect_resources
    from ray_tpu._private.worker_runtime import (CoreWorker,
                                                 set_current_worker)
    from ray_tpu.autoscaler import (MockTpuApi, StandardAutoscaler,
                                    TPUPodNodeProvider)

    gcs = GcsServer().start()
    head = Raylet(gcs.addr, resources=detect_resources(1, 0),
                  store_size=64 * 1024 * 1024)
    address = f"{gcs.addr[0]}:{gcs.addr[1]}"
    api = MockTpuApi(address)
    provider = TPUPodNodeProvider(api, "e2e")
    autoscaler = StandardAutoscaler(
        address,
        {"max_workers": 4, "min_workers": 0, "idle_timeout_s": 1.0,
         "available_node_types": {
             "v5e_pod": {"resources": {"CPU": 2, "TPU": 4},
                         "max_workers": 4,
                         "object_store_memory": 64 * 1024 * 1024,
                         "tpu_slice": {"hosts": 2, "topology": "2x4",
                                       "accelerator_type":
                                           "v5litepod-8"}}}},
        provider)
    worker = CoreWorker(gcs.addr, head.addr, mode="driver")
    set_current_worker(worker)
    try:
        import ray_tpu
        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)

        pg = placement_group([{"TPU": 4}, {"TPU": 4}], strategy="SPREAD")
        assert not pg.wait(1)

        report = autoscaler.update()
        assert report["launched"], "no slice launched for TPU PG demand"
        creates = [r for r in api.requests if r["op"] == "create"]
        assert len(creates) == 1 and creates[0]["hosts"] == 2
        assert pg.wait(60), "PG never scheduled on the slice"
        nodes = provider.non_terminated_nodes()
        assert len(nodes) == 2
        assert all(n["node_id"] for n in nodes), "hosts didn't join GCS"

        remove_placement_group(pg)
        deadline = time.time() + 30
        terminated = []
        while time.time() < deadline:
            terminated = autoscaler.update()["terminated"]
            if terminated:
                break
            time.sleep(0.5)
        assert terminated, "idle slice never scaled down"
        assert provider.non_terminated_nodes() == []
        assert [r for r in api.requests if r["op"] == "delete"]
    finally:
        autoscaler.stop()
        provider.shutdown()
        worker.shutdown()
        set_current_worker(None)
        head.stop(kill_workers=True)
        gcs.stop()


# ---------------------------------------------------------- launcher E2E

def test_up_down_cli(tmp_path):
    """`ray-tpu up` brings up head + monitor + min_workers on the mock
    provider; a driver connects and runs work on a scaled node;
    `ray-tpu down` releases everything."""
    cfg = {
        "cluster_name": f"lnch{os.getpid()}",
        "max_workers": 2,
        "min_workers": 1,
        "idle_timeout_s": 300,
        "provider": {"type": "mock"},
        "head_node_type": "head",
        "available_node_types": {
            "head": {"resources": {"CPU": 1}},
            "worker": {"resources": {"CPU": 2, "lava": 2},
                       "max_workers": 2,
                       "object_store_memory": 64 * 1024 * 1024,
                       "tpu_slice": {"hosts": 1}},
        },
    }
    import yaml

    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(cfg))

    from ray_tpu.scripts import cli

    assert cli.main(["up", str(path)]) == 0
    state_file = f"/tmp/ray_tpu/clusters/{cfg['cluster_name']}.json"
    assert os.path.exists(state_file)
    with open(state_file) as f:
        state = json.load(f)
    try:
        import ray_tpu

        ray_tpu.init(address=state["gcs_address"])
        try:
            # min_workers=1 slice carries the "lava" resource; wait for
            # the monitor to bring it up, then run on it
            @ray_tpu.remote(num_cpus=0, resources={"lava": 1},
                            max_retries=0)
            def on_worker():
                return os.getpid()

            pid = ray_tpu.get(on_worker.remote(), timeout=90)
            assert pid != os.getpid()
        finally:
            ray_tpu.shutdown()

        from ray_tpu.autoscaler.launcher import _alive

        head_pid, mon_pid = state["head_pid"], state["monitor_pid"]
        assert cli.main(["down", str(path)]) == 0
        assert not os.path.exists(state_file)
        for pid in (head_pid, mon_pid):
            deadline = time.time() + 15
            while time.time() < deadline and _alive(pid):
                time.sleep(0.2)
            assert not _alive(pid), f"pid {pid} still alive after down"
        # idempotent: down again reports nothing to do
        assert cli.main(["down", str(path)]) == 1
    finally:
        subprocess.run([__import__("sys").executable, "-c", f"""
import json, os, signal
try:
    with open({state_file!r}) as f:
        st = json.load(f)
    for k in ("monitor_pid", "head_pid"):
        try: os.kill(st[k], signal.SIGKILL)
        except Exception: pass
    os.unlink({state_file!r})
except FileNotFoundError:
    pass
"""], check=False)
