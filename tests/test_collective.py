"""Collective layer tests — the analog of the reference's
python/ray/util/collective/tests/single_node_cpu_tests/ (gloo backend):
N actors on one machine exercising each op."""
import numpy as np
import pytest


@pytest.fixture
def collective_world(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.util.collective import CollectiveActorMixin

    @ray.remote
    class Rank(CollectiveActorMixin):
        def allreduce(self, value):
            from ray_tpu.util import collective as col

            arr = np.full(4, float(value))
            return col.allreduce(arr)

        def allgather(self, value):
            from ray_tpu.util import collective as col

            return col.allgather(np.array([float(value)]))

        def broadcast(self, value):
            from ray_tpu.util import collective as col

            return col.broadcast(np.array([float(value)]), src_rank=0)

        def reducescatter(self, value):
            from ray_tpu.util import collective as col

            return col.reducescatter(np.arange(4.0) + value, op="sum")

        def sendrecv(self, peer, value):
            from ray_tpu.util import collective as col

            rank = col.get_rank()
            if rank < peer:
                col.send(np.array([float(value)]), peer)
                return None
            return col.recv(peer if rank > peer else 0)

        def p2p(self, value):
            from ray_tpu.util import collective as col

            rank = col.get_rank()
            if rank == 0:
                col.send(np.array([float(value)]), 1)
                return None
            return col.recv(0)

        def barrier_then(self, value):
            from ray_tpu.util import collective as col

            col.barrier()
            return value

    world_size = 2
    actors = [Rank.remote() for _ in range(world_size)]
    from ray_tpu.util import collective as col

    col.create_collective_group(actors, world_size, list(range(world_size)))
    yield ray, actors


def test_allreduce(collective_world):
    ray, actors = collective_world
    out = ray.get([a.allreduce.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=60)
    for arr in out:
        assert (arr == 3.0).all()     # 1 + 2


def test_allgather(collective_world):
    ray, actors = collective_world
    out = ray.get([a.allgather.remote(i * 10) for i, a in enumerate(actors)],
                  timeout=60)
    for gathered in out:
        assert [g[0] for g in gathered] == [0.0, 10.0]


def test_broadcast(collective_world):
    ray, actors = collective_world
    out = ray.get([a.broadcast.remote(i + 5) for i, a in enumerate(actors)],
                  timeout=60)
    for arr in out:
        assert arr[0] == 5.0          # rank 0's value


def test_reducescatter(collective_world):
    ray, actors = collective_world
    out = ray.get([a.reducescatter.remote(i) for i, a in enumerate(actors)],
                  timeout=60)
    # sum over ranks of arange(4)+rank = [1,3,5,7]; rank0 gets [1,3], rank1 [5,7]
    assert list(out[0]) == [1.0, 3.0]
    assert list(out[1]) == [5.0, 7.0]


def test_send_recv(collective_world):
    ray, actors = collective_world
    out = ray.get([a.p2p.remote(99) for a in actors], timeout=60)
    assert out[0] is None
    assert out[1][0] == 99.0


def test_barrier(collective_world):
    ray, actors = collective_world
    out = ray.get([a.barrier_then.remote(i) for i, a in enumerate(actors)],
                  timeout=60)
    assert out == [0, 1]
