"""Collective layer tests — the analog of the reference's
python/ray/util/collective/tests/single_node_cpu_tests/ (gloo backend):
N actors on one machine exercising each op."""
import numpy as np
import pytest


# Matrix: host backend at 2/4/8 ranks, xla (jax.distributed CPU world) at
# 2/4 — the reference's per-op multi-worker suite shape
# (python/ray/util/collective/tests/single_node_cpu_tests/).
@pytest.fixture(params=[("host", 2), ("host", 4), ("host", 8),
                        ("xla", 2), ("xla", 4)],
                ids=lambda p: f"{p[0]}-n{p[1]}")
def collective_world(request, ray_start_regular):
    ray = ray_start_regular
    backend = request.param[0]
    from ray_tpu.util.collective import CollectiveActorMixin

    @ray.remote
    class Rank(CollectiveActorMixin):
        def allreduce(self, value):
            from ray_tpu.util import collective as col

            arr = np.full(4, float(value))
            return col.allreduce(arr)

        def allgather(self, value):
            from ray_tpu.util import collective as col

            return col.allgather(np.array([float(value)]))

        def broadcast(self, value):
            from ray_tpu.util import collective as col

            return col.broadcast(np.array([float(value)]), src_rank=0)

        def reducescatter(self, value):
            from ray_tpu.util import collective as col

            n = col.get_collective_group_size()
            return col.reducescatter(np.arange(2.0 * n) + value, op="sum")

        def sendrecv(self, peer, value):
            from ray_tpu.util import collective as col

            rank = col.get_rank()
            if rank < peer:
                col.send(np.array([float(value)]), peer)
                return None
            return col.recv(peer if rank > peer else 0)

        def p2p(self, value):
            from ray_tpu.util import collective as col

            rank = col.get_rank()
            if rank == 0:
                col.send(np.array([float(value)]), 1)
                return None
            if rank == 1:
                return col.recv(0)
            return None

        def barrier_then(self, value):
            from ray_tpu.util import collective as col

            col.barrier()
            return value

        def reduce_to0(self, value):
            from ray_tpu.util import collective as col

            return col.reduce(np.full(3, float(value)), dst_rank=0)

        def destroy(self):
            from ray_tpu.util import collective as col

            col.destroy_collective_group()

    world_size = request.param[1]
    actors = [Rank.options(num_cpus=0).remote() for _ in range(world_size)]
    from ray_tpu.util import collective as col

    col.create_collective_group(actors, world_size, list(range(world_size)),
                                backend=backend)
    yield ray, actors
    for a in actors:
        try:
            a.destroy.remote()
        except Exception:
            pass


def test_allreduce(collective_world):
    ray, actors = collective_world
    n = len(actors)
    out = ray.get([a.allreduce.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=120)
    expect = n * (n + 1) / 2
    for arr in out:
        assert (np.asarray(arr) == expect).all()


def test_allgather(collective_world):
    ray, actors = collective_world
    n = len(actors)
    out = ray.get([a.allgather.remote(i * 10) for i, a in enumerate(actors)],
                  timeout=120)
    for gathered in out:
        assert [float(np.asarray(g)[0]) for g in gathered] == \
            [10.0 * i for i in range(n)]


def test_broadcast(collective_world):
    ray, actors = collective_world
    out = ray.get([a.broadcast.remote(i + 5) for i, a in enumerate(actors)],
                  timeout=120)
    for arr in out:
        assert np.asarray(arr)[0] == 5.0          # rank 0's value


def test_reducescatter(collective_world):
    ray, actors = collective_world
    n = len(actors)
    out = ray.get([a.reducescatter.remote(i) for i, a in enumerate(actors)],
                  timeout=120)
    # sum over ranks of (arange(2n)+rank): chunk r of size 2 goes to rank r
    total = sum(np.arange(2.0 * n) + r for r in range(n))
    for r, chunk in enumerate(out):
        assert list(np.asarray(chunk)) == list(total[2 * r:2 * r + 2])


def test_send_recv(collective_world):
    ray, actors = collective_world
    out = ray.get([a.p2p.remote(99) for a in actors[:2]], timeout=120)
    assert out[0] is None
    assert np.asarray(out[1])[0] == 99.0


def test_barrier(collective_world):
    ray, actors = collective_world
    out = ray.get([a.barrier_then.remote(i) for i, a in enumerate(actors)],
                  timeout=120)
    assert out == list(range(len(actors)))


def test_reduce(collective_world):
    ray, actors = collective_world
    n = len(actors)
    out = ray.get([a.reduce_to0.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=120)
    assert (np.asarray(out[0]) == n * (n + 1) / 2).all()


def test_host_ring_four_ranks(ray_start_regular):
    """4-rank ring with a larger tensor: data crosses every link of the
    decentralized ring (nothing funnels through one process)."""
    ray = ray_start_regular
    from ray_tpu.util.collective import CollectiveActorMixin
    from ray_tpu.util import collective as col

    @ray.remote
    class Rank(CollectiveActorMixin):
        def go(self, value):
            from ray_tpu.util import collective as c

            arr = np.full(1000, float(value))
            total = c.allreduce(arr, group_name="ring4")
            gathered = c.allgather(np.array([float(value)]),
                                   group_name="ring4")
            chunk = c.reducescatter(np.arange(8.0), group_name="ring4")
            return total[0], [g[0] for g in gathered], chunk

    n = 4
    actors = [Rank.options(num_cpus=1).remote() for _ in range(n)]
    col.create_collective_group(actors, n, list(range(n)), backend="host",
                                group_name="ring4")
    out = ray.get([a.go.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=120)
    for rank, (total, gathered, chunk) in enumerate(out):
        assert total == 10.0                       # 1+2+3+4
        assert gathered == [1.0, 2.0, 3.0, 4.0]
        assert list(chunk) == [4 * v for v in
                               np.arange(8.0)[2 * rank:2 * rank + 2]]


def test_group_reuse_after_destroy(ray_start_regular):
    """ADVICE regression: back-to-back groups under the SAME name (two Tune
    trials both using 'train_dp') must not share rendezvous state."""
    ray = ray_start_regular
    from ray_tpu.util.collective import CollectiveActorMixin
    from ray_tpu.util import collective as col

    @ray.remote
    class Rank(CollectiveActorMixin):
        def go(self, value):
            from ray_tpu.util import collective as c

            out = c.allreduce(np.full(2, float(value)), group_name="reused")
            c.destroy_collective_group("reused")
            return out[0]

    for round_no in range(2):
        actors = [Rank.remote() for _ in range(2)]
        col.create_collective_group(actors, 2, [0, 1], backend="host",
                                    group_name="reused")
        out = ray.get([a.go.remote(round_no + i) for i, a in
                       enumerate(actors)], timeout=60)
        assert out[0] == out[1] == 2 * round_no + 1
        for a in actors:
            ray.kill(a)


def test_concurrent_ops_two_groups(ray_start_regular):
    """Two groups over overlapping member sets run interleaved ops without
    cross-talk (seq/tag isolation)."""
    ray = ray_start_regular
    from ray_tpu.util.collective import CollectiveActorMixin
    from ray_tpu.util import collective as col

    @ray.remote
    class Rank(CollectiveActorMixin):
        def both(self, value):
            from ray_tpu.util import collective as c

            outs = []
            for _ in range(5):     # interleave ops across the two groups
                a = c.allreduce(np.full(8, float(value)), group_name="gA")
                b = c.allreduce(np.full(8, float(value) * 10),
                                group_name="gB")
                outs.append((a[0], b[0]))
            return outs

    actors = [Rank.options(num_cpus=0).remote() for _ in range(3)]
    col.create_collective_group(actors, 3, [0, 1, 2], backend="host",
                                group_name="gA")
    col.create_collective_group(actors, 3, [0, 1, 2], backend="host",
                                group_name="gB")
    out = ray.get([a.both.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=120)
    for rows in out:
        for a, b in rows:
            assert a == 6.0      # 1+2+3
            assert b == 60.0


def test_member_failure_raises_not_hangs(ray_start_regular):
    """Kill a member mid-collective: survivors' op raises within the
    configured watchdog timeout instead of hanging (reference: NCCL abort
    on communicator error)."""
    import os as _os

    _os.environ["RAY_TPU_COLLECTIVE_OP_TIMEOUT_S"] = "5"
    try:
        ray = ray_start_regular
        from ray_tpu.util.collective import CollectiveActorMixin
        from ray_tpu.util import collective as col

        @ray.remote
        class Rank(CollectiveActorMixin):
            def go(self, value):
                from ray_tpu.util import collective as c

                return float(c.allreduce(np.full(2, float(value)),
                                         group_name="doomed")[0])

        actors = [Rank.options(num_cpus=0).remote() for _ in range(3)]
        col.create_collective_group(actors, 3, [0, 1, 2], backend="host",
                                    group_name="doomed")
        # warm up the group
        assert ray.get([a.go.remote(1) for a in actors], timeout=60) == \
            [3.0, 3.0, 3.0]
        ray.kill(actors[2])
        refs = [a.go.remote(1) for a in actors[:2]]
        with pytest.raises(Exception):
            ray.get(refs, timeout=60)
    finally:
        _os.environ.pop("RAY_TPU_COLLECTIVE_OP_TIMEOUT_S", None)


def test_host_large_tensor(ray_start_regular):
    """8 MB allreduce + allgather across 4 ranks (multi-chunk RPC frames)."""
    ray = ray_start_regular
    from ray_tpu.util.collective import CollectiveActorMixin
    from ray_tpu.util import collective as col

    @ray.remote
    class Rank(CollectiveActorMixin):
        def go(self, value):
            from ray_tpu.util import collective as c

            arr = np.full(1_000_000, float(value))          # 8 MB f64
            total = c.allreduce(arr, group_name="big")
            return float(total[0]), float(total[-1])

    actors = [Rank.options(num_cpus=0).remote() for _ in range(4)]
    col.create_collective_group(actors, 4, [0, 1, 2, 3], backend="host",
                                group_name="big")
    out = ray.get([a.go.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=180)
    assert out == [(10.0, 10.0)] * 4


def test_xla_device_residency_and_broadcast_src(ray_start_regular):
    """xla backend: jax-array inputs come back as jax arrays (no host
    round-trip), and broadcast works from a non-zero src rank (the old
    psum-of-zeros path is gone — this exercises the ppermute tree)."""
    ray = ray_start_regular
    from ray_tpu.util.collective import CollectiveActorMixin
    from ray_tpu.util import collective as col

    @ray.remote
    class Rank(CollectiveActorMixin):
        def go(self, value):
            import jax
            import jax.numpy as jnp

            from ray_tpu.util import collective as c

            x = jnp.full((4,), float(value))
            reduced = c.allreduce(x, group_name="xdev")
            is_jax = isinstance(reduced, jax.Array)
            b = c.broadcast(jnp.full((3,), float(value)), src_rank=1,
                            group_name="xdev")
            return is_jax, float(np.asarray(reduced)[0]), \
                float(np.asarray(b)[0])

    actors = [Rank.options(num_cpus=0).remote() for _ in range(2)]
    col.create_collective_group(actors, 2, [0, 1], backend="xla",
                                group_name="xdev")
    out = ray.get([a.go.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=180)
    for is_jax, reduced, bval in out:
        assert is_jax, "xla backend returned a host array for a jax input"
        assert reduced == 3.0
        assert bval == 2.0       # src_rank=1's value


def test_object_collectives(ray_start_regular):
    """allgather_object/broadcast_object over the host backend."""
    import ray_tpu
    from ray_tpu.util import collective as col

    @ray_tpu.remote
    class W(col.CollectiveActorMixin):
        def setup(self, world, rank):
            col.init_collective_group(world, rank, "host", "objgrp")
            return rank

        def gather(self, payload):
            return col.allgather_object(payload, "objgrp")

        def bcast(self, payload):
            return col.broadcast_object(payload, src_rank=0,
                                        group_name="objgrp")

    workers = [W.options(num_cpus=0).remote() for _ in range(3)]
    ray_tpu.get([w.setup.remote(3, i) for i, w in enumerate(workers)])
    payloads = [{"rank": i, "data": list(range(i + 1))} for i in range(3)]
    gathered = ray_tpu.get([w.gather.remote(p)
                            for w, p in zip(workers, payloads)])
    for g in gathered:
        assert g == payloads
    out = ray_tpu.get([w.bcast.remote(payloads[i] if i == 0 else None)
                       for i, w in enumerate(workers)])
    assert all(o == payloads[0] for o in out)


def test_xla_device_p2p_send_recv(ray_start_regular):
    """Device-resident p2p: endpoints exchange through a compiled
    2-device ppermute (NCCL-send/recv analog; on TPU this rides
    ICI/DCN, not the host mailbox plane)."""
    ray = ray_start_regular
    from ray_tpu.util import collective as col
    from ray_tpu.util.collective import CollectiveActorMixin

    @ray.remote
    class Rank(CollectiveActorMixin):
        def exchange(self, rank):
            import jax
            import jax.numpy as jnp

            from ray_tpu.util import collective as c

            if rank == 0:
                c.send_device(jnp.arange(6, dtype=jnp.float32) + 100.0,
                              dst_rank=1, group_name="p2pdev")
                return "sent"
            out = c.recv_device((6,), "float32", src_rank=0,
                                group_name="p2pdev")
            return bool(isinstance(out, jax.Array)), \
                [float(x) for x in out]

    actors = [Rank.options(num_cpus=0).remote() for _ in range(2)]
    col.create_collective_group(actors, 2, [0, 1], backend="xla",
                                group_name="p2pdev")
    sent, (is_jax, values) = ray.get(
        [a.exchange.remote(i) for i, a in enumerate(actors)], timeout=180)
    assert sent == "sent"
    assert is_jax, "recv_device returned a host array"
    assert values == [100.0, 101.0, 102.0, 103.0, 104.0, 105.0]


def test_xla_device_p2p_subset_of_larger_world(ray_start_regular):
    """Only the two endpoints enter the pair program — ranks 1 and 2 of
    a 4-rank world exchange while ranks 0 and 3 do unrelated work (the
    point-to-point property; a collective would hang them)."""
    ray = ray_start_regular
    from ray_tpu.util import collective as col
    from ray_tpu.util.collective import CollectiveActorMixin

    @ray.remote
    class Rank(CollectiveActorMixin):
        def run(self, rank):
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.util import collective as c

            if rank == 1:
                c.send_device(jnp.full((3,), 7.0), dst_rank=2,
                              group_name="p2pworld")
                return "sent"
            if rank == 2:
                out = c.recv_device((3,), "float32", src_rank=1,
                                    group_name="p2pworld")
                return [float(x) for x in np.asarray(out)]
            return "idle"

    actors = [Rank.options(num_cpus=0).remote() for _ in range(4)]
    col.create_collective_group(actors, 4, [0, 1, 2, 3], backend="xla",
                                group_name="p2pworld")
    out = ray.get([a.run.remote(i) for i, a in enumerate(actors)],
                  timeout=180)
    assert out[0] == "idle" and out[3] == "idle"
    assert out[1] == "sent"
    assert out[2] == [7.0, 7.0, 7.0]
