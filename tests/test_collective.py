"""Collective layer tests — the analog of the reference's
python/ray/util/collective/tests/single_node_cpu_tests/ (gloo backend):
N actors on one machine exercising each op."""
import numpy as np
import pytest


@pytest.fixture(params=["host", "xla"])
def collective_world(request, ray_start_regular):
    ray = ray_start_regular
    backend = request.param
    from ray_tpu.util.collective import CollectiveActorMixin

    @ray.remote
    class Rank(CollectiveActorMixin):
        def allreduce(self, value):
            from ray_tpu.util import collective as col

            arr = np.full(4, float(value))
            return col.allreduce(arr)

        def allgather(self, value):
            from ray_tpu.util import collective as col

            return col.allgather(np.array([float(value)]))

        def broadcast(self, value):
            from ray_tpu.util import collective as col

            return col.broadcast(np.array([float(value)]), src_rank=0)

        def reducescatter(self, value):
            from ray_tpu.util import collective as col

            return col.reducescatter(np.arange(4.0) + value, op="sum")

        def sendrecv(self, peer, value):
            from ray_tpu.util import collective as col

            rank = col.get_rank()
            if rank < peer:
                col.send(np.array([float(value)]), peer)
                return None
            return col.recv(peer if rank > peer else 0)

        def p2p(self, value):
            from ray_tpu.util import collective as col

            rank = col.get_rank()
            if rank == 0:
                col.send(np.array([float(value)]), 1)
                return None
            return col.recv(0)

        def barrier_then(self, value):
            from ray_tpu.util import collective as col

            col.barrier()
            return value

        def reduce_to0(self, value):
            from ray_tpu.util import collective as col

            return col.reduce(np.full(3, float(value)), dst_rank=0)

        def destroy(self):
            from ray_tpu.util import collective as col

            col.destroy_collective_group()

    world_size = 2
    actors = [Rank.remote() for _ in range(world_size)]
    from ray_tpu.util import collective as col

    col.create_collective_group(actors, world_size, list(range(world_size)),
                                backend=backend)
    yield ray, actors
    for a in actors:
        try:
            a.destroy.remote()
        except Exception:
            pass


def test_allreduce(collective_world):
    ray, actors = collective_world
    out = ray.get([a.allreduce.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=60)
    for arr in out:
        assert (arr == 3.0).all()     # 1 + 2


def test_allgather(collective_world):
    ray, actors = collective_world
    out = ray.get([a.allgather.remote(i * 10) for i, a in enumerate(actors)],
                  timeout=60)
    for gathered in out:
        assert [g[0] for g in gathered] == [0.0, 10.0]


def test_broadcast(collective_world):
    ray, actors = collective_world
    out = ray.get([a.broadcast.remote(i + 5) for i, a in enumerate(actors)],
                  timeout=60)
    for arr in out:
        assert arr[0] == 5.0          # rank 0's value


def test_reducescatter(collective_world):
    ray, actors = collective_world
    out = ray.get([a.reducescatter.remote(i) for i, a in enumerate(actors)],
                  timeout=60)
    # sum over ranks of arange(4)+rank = [1,3,5,7]; rank0 gets [1,3], rank1 [5,7]
    assert list(out[0]) == [1.0, 3.0]
    assert list(out[1]) == [5.0, 7.0]


def test_send_recv(collective_world):
    ray, actors = collective_world
    out = ray.get([a.p2p.remote(99) for a in actors], timeout=60)
    assert out[0] is None
    assert out[1][0] == 99.0


def test_barrier(collective_world):
    ray, actors = collective_world
    out = ray.get([a.barrier_then.remote(i) for i, a in enumerate(actors)],
                  timeout=60)
    assert out == [0, 1]


def test_reduce(collective_world):
    ray, actors = collective_world
    out = ray.get([a.reduce_to0.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=60)
    assert (out[0] == 3.0).all()      # dst rank holds the sum


def test_host_ring_four_ranks(ray_start_regular):
    """4-rank ring with a larger tensor: data crosses every link of the
    decentralized ring (nothing funnels through one process)."""
    ray = ray_start_regular
    from ray_tpu.util.collective import CollectiveActorMixin
    from ray_tpu.util import collective as col

    @ray.remote
    class Rank(CollectiveActorMixin):
        def go(self, value):
            from ray_tpu.util import collective as c

            arr = np.full(1000, float(value))
            total = c.allreduce(arr, group_name="ring4")
            gathered = c.allgather(np.array([float(value)]),
                                   group_name="ring4")
            chunk = c.reducescatter(np.arange(8.0), group_name="ring4")
            return total[0], [g[0] for g in gathered], chunk

    n = 4
    actors = [Rank.options(num_cpus=1).remote() for _ in range(n)]
    col.create_collective_group(actors, n, list(range(n)), backend="host",
                                group_name="ring4")
    out = ray.get([a.go.remote(i + 1) for i, a in enumerate(actors)],
                  timeout=120)
    for rank, (total, gathered, chunk) in enumerate(out):
        assert total == 10.0                       # 1+2+3+4
        assert gathered == [1.0, 2.0, 3.0, 4.0]
        assert list(chunk) == [4 * v for v in
                               np.arange(8.0)[2 * rank:2 * rank + 2]]


def test_group_reuse_after_destroy(ray_start_regular):
    """ADVICE regression: back-to-back groups under the SAME name (two Tune
    trials both using 'train_dp') must not share rendezvous state."""
    ray = ray_start_regular
    from ray_tpu.util.collective import CollectiveActorMixin
    from ray_tpu.util import collective as col

    @ray.remote
    class Rank(CollectiveActorMixin):
        def go(self, value):
            from ray_tpu.util import collective as c

            out = c.allreduce(np.full(2, float(value)), group_name="reused")
            c.destroy_collective_group("reused")
            return out[0]

    for round_no in range(2):
        actors = [Rank.remote() for _ in range(2)]
        col.create_collective_group(actors, 2, [0, 1], backend="host",
                                    group_name="reused")
        out = ray.get([a.go.remote(round_no + i) for i, a in
                       enumerate(actors)], timeout=60)
        assert out[0] == out[1] == 2 * round_no + 1
        for a in actors:
            ray.kill(a)
