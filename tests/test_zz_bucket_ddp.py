"""Async collective handles + bucketed DDP gradient sync (late-alphabet
on purpose: the gang tests here cost seconds each).

Covers the tentpole's two halves and their acceptance criteria:

- pure units: deterministic bucket planning + pack/unpack round trip,
  and the step-anatomy interval-union fix (a background bucket that
  completes inside another bucket's exposed wait window must not be
  double-counted);
- async handle semantics on a live 2-rank group: wait/poll/result,
  bitwise equality with the sync path, submission-order preservation
  across mixed sync/async call sites, out-of-order waits;
- the determinism contract: bucketed-on vs RAY_TPU_TRAIN_BUCKET_DDP=0
  produce rank-byte-identical synced grads AND final params per seed
  at world 2 (pairwise IEEE adds are commutative, so bucket boundaries
  cannot change results);
- composition: the int8 quantized wire (PR 8) applies per bucket
  unchanged (rank-identical, error inside the documented bound);
- chaos: a member killed with bucketed allreduces in flight surfaces
  as CollectiveGroupError from handle.wait() within the poison-latency
  bound (queued handles too, no serialized op timeouts), leaving zero
  stranded shm segments; a seeded dropped frame surfaces as a timeout,
  never a hang;
- cluster acceptance: a 2-worker gang on a REAL make_train_step loop
  (jitted grad step -> ddp.sync_gradients -> jitted apply) yields a
  summarize_steps() report with comm_hidden > 0 and
  overlap_fraction > 0, and both ranks end byte-identical.
"""
import os
import time

import numpy as np
import pytest

GROUP = "zzbd"


# ------------------------------------------------------------------- units


def test_bucket_plan_deterministic_and_size_targeted():
    from ray_tpu.parallel import sharding as sh

    tree = {
        "w1": np.zeros((100, 100), np.float32),     # 40 KB
        "b1": np.zeros(100, np.float32),            # 400 B
        "w2": np.zeros((50, 100), np.float32),      # 20 KB
        "ints": np.zeros(64, np.int64),             # distinct dtype
        "scalar": np.float32(1.0),
    }
    leaves, treedef = sh.flatten_tree(tree)
    plan = sh.plan_buckets(leaves, 24 * 1024)
    assert plan == sh.plan_buckets(leaves, 24 * 1024)   # deterministic
    # dtype purity + full coverage, order preserved within a bucket
    seen = []
    for bucket in plan:
        dtypes = {str(np.asarray(leaves[i]).dtype) for i in bucket}
        assert len(dtypes) == 1, dtypes
        assert bucket == sorted(bucket)
        seen += bucket
    assert sorted(seen) == list(range(len(leaves)))
    # size targeting: multi-leaf buckets stay under the target unless a
    # single leaf alone exceeds it (never split)
    for bucket in plan:
        nbytes = sum(int(np.asarray(leaves[i]).nbytes) for i in bucket)
        if len(bucket) > 1:
            assert nbytes <= 24 * 1024
    # the 40 KB leaf exceeds the target -> its own bucket
    big = [b for b in plan if any(
        np.asarray(leaves[i]).nbytes > 24 * 1024 for i in b)]
    assert all(len(b) == 1 for b in big) and big
    # pack/unpack round trip is the identity
    out = [None] * len(leaves)
    for bucket in plan:
        sh.unpack_bucket(sh.pack_bucket(leaves, bucket), leaves, bucket,
                         out)
    rt = sh.unflatten_tree(treedef, out)
    for k in tree:
        assert np.asarray(rt[k]).tobytes() == \
            np.asarray(tree[k]).tobytes(), k


def test_hidden_union_not_double_counted_for_concurrent_comm():
    """The satellite fix pin: two concurrent background buckets cover
    the same wall clock ONCE, and a background bucket that completes
    inside another bucket's exposed wait() window is hidden only where
    no one was blocked. Per-kind fields may overlap each other (they
    are attribution); overlap_fraction must use real coverage."""
    from ray_tpu.parallel import step_anatomy as sa

    step = {"step_id": 1, "rank": 0, "node": "n0", "pid": 1,
            "start": 0.0, "end": 1.0}
    acts = [
        # bucket A's allreduce, background on the issue thread
        {"step_id": 1, "rank": 0, "node": "n0", "pid": 1,
         "kind": "collective", "start": 0.0, "end": 0.5,
         "blocking": False},
        # bucket B overlaps A (it queued behind it; spans overlap once
        # submit+issue stamps both) and completes INSIDE the exposed
        # wait window below
        {"step_id": 1, "rank": 0, "node": "n0", "pid": 1,
         "kind": "collective", "start": 0.2, "end": 0.45,
         "blocking": False},
        # the caller blocked in handle.wait() for [0.4, 0.6]
        {"step_id": 1, "rank": 0, "node": "n0", "pid": 1,
         "kind": "collective", "start": 0.4, "end": 0.6,
         "blocking": True},
    ]
    br = sa.anatomize_rank_step(step, acts)
    # union of background = [0, 0.5]; minus exposed [0.4, 0.6] -> 0.4.
    # A per-record sum would claim 0.5 + 0.25 - overlap bugs.
    assert br["comm_hidden_s"] == pytest.approx(0.4)
    assert br["comm_exposed_s"] == pytest.approx(0.2)
    assert br["overlap_fraction"] == pytest.approx(0.4 / 0.6)
    # cross-kind double count: background comm + background data over
    # the same interval must not sum past the wall clock
    acts2 = [
        {"step_id": 1, "rank": 0, "node": "n0", "pid": 1,
         "kind": "collective", "start": 0.0, "end": 0.8,
         "blocking": False},
        {"step_id": 1, "rank": 0, "node": "n0", "pid": 1,
         "kind": "data_produce", "start": 0.0, "end": 0.8,
         "blocking": False},
    ]
    br2 = sa.anatomize_rank_step(step, acts2)
    # attribution fields overlap by design...
    assert br2["comm_hidden_s"] == pytest.approx(0.8)
    assert br2["data_hidden_s"] == pytest.approx(0.8)
    # ...but the fraction uses the union: hidden coverage is 0.8 of an
    # otherwise-free second, not 1.6
    assert br2["overlap_fraction"] == pytest.approx(1.0)


# --------------------------------------------------------------- live group


def _rank_cls(ray):
    @ray.remote
    class Rank:
        def configure(self, env):
            os.environ.update({k: str(v) for k, v in env.items()})
            return True

        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def async_vs_sync(self, rank, name):
            """Async results must be bitwise identical to sync results
            on the same inputs, seq order preserved across a mixed
            async/sync call site, waits in arbitrary order."""
            from ray_tpu.util import collective as col

            rng = np.random.RandomState(7 + rank)
            a = rng.standard_normal(4096).astype(np.float32)
            b = rng.standard_normal(333).astype(np.float64)
            c = np.arange(64, dtype=np.int64) * (rank + 1)
            h1 = col.allreduce_async(a, name)
            h2 = col.allreduce_async(b, name)
            done_before = h1.poll(), h2.poll()
            s = col.allreduce(c, name)          # sync: drains the queue
            h3 = col.reducescatter_async(a, name)
            # wait out of order: h2 then h1
            r2 = h2.result(60)
            r1 = h1.result(60)
            r3 = h3.result(60)
            assert h1.poll() and h2.poll() and h3.poll()
            return {"r1": r1, "r2": r2, "s": np.asarray(s), "r3": r3,
                    "done_before": done_before}

        def sync_oracle(self, rank, name):
            from ray_tpu.util import collective as col

            rng = np.random.RandomState(7 + rank)
            a = rng.standard_normal(4096).astype(np.float32)
            b = rng.standard_normal(333).astype(np.float64)
            return {"a": np.asarray(col.allreduce(a, name)),
                    "b": np.asarray(col.allreduce(b, name)),
                    "rs": np.asarray(col.reducescatter(a, name))}

        def train_numpy(self, rank, name, bucketed, steps=4):
            """Tiny numpy SGD loop: grads synced via ddp, params
            updated identically on every rank. Returns the final
            params' raw bytes — the on/off + cross-rank identity
            oracle."""
            os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = \
                "1" if bucketed else "0"
            from ray_tpu.train import ddp

            rng = np.random.RandomState(1234)      # same init everywhere
            params = {"w1": rng.standard_normal((96, 64))
                      .astype(np.float32),
                      "b1": rng.standard_normal(64).astype(np.float32),
                      "w2": rng.standard_normal((64, 11))
                      .astype(np.float32)}
            for step in range(steps):
                grng = np.random.RandomState(100 * step + rank)
                grads = {k: grng.standard_normal(v.shape)
                         .astype(np.float32) for k, v in params.items()}
                synced = ddp.sync_gradients(grads, name,
                                            bucket_bytes=8192)
                for k in params:
                    params[k] = params[k] - \
                        np.float32(0.01) * np.asarray(synced[k])
            return {k: v.tobytes() for k, v in params.items()}

        def bucket_metrics(self):
            from ray_tpu.util.metrics import registry_snapshot

            out = {}
            for fam in registry_snapshot():
                if fam["name"] in (
                        "ray_tpu_collective_async_inflight_tasks",
                        "ray_tpu_train_buckets_total"):
                    out[fam["name"]] = fam
            return out

        def quantized_bucketed(self, rank, name):
            """int8 wire per bucket: results rank-identical, error
            inside the documented bound vs a float64 oracle."""
            os.environ["RAY_TPU_COLLECTIVE_WIRE_DTYPE"] = "int8"
            os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = "1"
            try:
                from ray_tpu.train import ddp

                ins = [np.random.RandomState(500 + r)
                       .standard_normal(20000).astype(np.float32)
                       for r in range(2)]
                out = ddp.sync_gradients({"g": ins[rank]}, name,
                                         bucket_bytes=16384)
                got = np.asarray(out["g"])
                exact = ins[0].astype(np.float64) + \
                    ins[1].astype(np.float64)
                err = float(np.abs(got.astype(np.float64) - exact).max())
                bound = 2 * (1.0 / 254.0) * float(
                    sum(np.abs(x).max() for x in ins))
                return {"bytes": got.tobytes(), "err": err,
                        "bound": bound}
            finally:
                os.environ["RAY_TPU_COLLECTIVE_WIRE_DTYPE"] = "off"

        def launch_pending(self, rank, name, count=4):
            """Submit `count` async allreduces and park (rank 1 never
            calls, so they stay pending) — the chaos target."""
            from ray_tpu.util import collective as col

            self._handles = [
                col.allreduce_async(np.full(70000, float(rank + 1),
                                            np.float32), name)
                for _ in range(count)]
            return True

        def wait_pending(self, which, timeout):
            t0 = time.monotonic()
            try:
                self._handles[which].wait(timeout)
                return {"ok": True, "latency": time.monotonic() - t0}
            except BaseException as e:  # noqa: BLE001
                return {"ok": False, "latency": time.monotonic() - t0,
                        "type": type(e).__name__, "msg": str(e)}

        def chaos(self, seed, schedule):
            from ray_tpu._private import fault_injection as fi

            fi.install(seed, schedule)
            return True

        def segment_objects(self, name):
            from ray_tpu._private.worker_runtime import (col_oid_prefix,
                                                         current_worker)

            prefix = col_oid_prefix(name)
            return sum(1 for oid, _ in
                       current_worker().store.list_objects()
                       if oid.startswith(prefix))

        def destroy(self, name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(name)
            return True

    return Rank


def _world(ray, n, name, env=None):
    Rank = _rank_cls(ray)
    actors = [Rank.options(num_cpus=0).remote() for _ in range(n)]
    merged = {"RAY_TPU_TRAIN_BUCKET_DDP": "1"}
    merged.update(env or {})
    ray.get([a.configure.remote(merged) for a in actors])
    ray.get([a.join.remote(n, i, name) for i, a in enumerate(actors)],
            timeout=120)
    return actors


def test_async_handles_match_sync_bitwise(ray_start_regular):
    ray = ray_start_regular
    name = GROUP + "_async"
    actors = _world(ray, 2, name)
    try:
        got = ray.get([a.async_vs_sync.remote(i, name)
                       for i, a in enumerate(actors)], timeout=120)
        oracle = ray.get([a.sync_oracle.remote(i, name)
                          for i, a in enumerate(actors)], timeout=120)
        for rank in range(2):
            g, o = got[rank], oracle[rank]
            assert np.asarray(g["r1"]).tobytes() == o["a"].tobytes()
            assert np.asarray(g["r2"]).tobytes() == o["b"].tobytes()
            assert np.asarray(g["r3"]).tobytes() == o["rs"].tobytes()
            # the interleaved sync op saw both async ops' contributions
            # drained first and its own result correct
            assert np.array_equal(g["s"], np.arange(64) * 3)
        # metrics plane: the inflight gauge + bucket counter exist
        fams = ray.get(actors[0].bucket_metrics.remote())
        assert "ray_tpu_collective_async_inflight_tasks" in fams
    finally:
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)


def test_bucketed_on_off_final_params_identical(ray_start_regular):
    """Acceptance: bucketed-on vs bucketed-off produce rank-byte-
    identical final params per seed at world 2 (one pairwise IEEE add
    per element — commutative, so bucket boundaries can't change
    bits), and both ranks always agree with each other."""
    ray = ray_start_regular
    name = GROUP + "_id"
    actors = _world(ray, 2, name)
    try:
        on = ray.get([a.train_numpy.remote(i, name, True)
                      for i, a in enumerate(actors)], timeout=120)
        off = ray.get([a.train_numpy.remote(i, name, False)
                       for i, a in enumerate(actors)], timeout=120)
        for k in on[0]:
            assert on[0][k] == on[1][k], f"rank divergence (on) {k}"
            assert off[0][k] == off[1][k], f"rank divergence (off) {k}"
            assert on[0][k] == off[0][k], f"on/off divergence {k}"
        # the bucketed runs actually bucketed (several buckets per sync)
        fams = ray.get(actors[0].bucket_metrics.remote())
        total = sum(v["value"] for v in
                    fams["ray_tpu_train_buckets_total"]["values"])
        assert total >= 8, fams
    finally:
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)


def test_quantized_wire_applies_per_bucket(ray_start_regular):
    ray = ray_start_regular
    name = GROUP + "_q"
    # quantization is an inter-host wire feature; force the socket path
    # so the int8 codec actually runs (same choice as BENCH_r08)
    actors = _world(ray, 2, name, env={"RAY_TPU_COLLECTIVE_SHM": "0"})
    try:
        got = ray.get([a.quantized_bucketed.remote(i, name)
                       for i, a in enumerate(actors)], timeout=120)
        assert got[0]["bytes"] == got[1]["bytes"], "ranks diverged"
        assert 0 < got[0]["err"] <= got[0]["bound"], got[0]
    finally:
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)


@pytest.mark.chaos
def test_poison_fails_pending_handles_fast(ray_start_regular):
    """A member dies with bucketed allreduces IN FLIGHT: the surviving
    rank's pending handles — the one on the wire AND the queued ones —
    all surface CollectiveGroupError within the poison-latency bound
    (nowhere near one op timeout each), and group teardown leaves zero
    stranded shm segments."""
    ray = ray_start_regular
    from ray_tpu.exceptions import CollectiveGroupError  # noqa: F401

    name = GROUP + "_poison"
    actors = _world(ray, 2, name,
                    env={"RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "120"})
    ray.get(actors[0].launch_pending.remote(0, name, 4), timeout=30)
    time.sleep(0.5)          # let the issue thread put op #1 on the wire
    t0 = time.monotonic()
    ray.kill(actors[1], no_restart=True)
    outcomes = [ray.get(actors[0].wait_pending.remote(i, 90),
                        timeout=120) for i in range(4)]
    total = time.monotonic() - t0
    for out in outcomes:
        assert not out["ok"], out
        assert out["type"] == "CollectiveGroupError", out
    # all four handles failed in far less than ONE 120s op timeout —
    # the queued ones were failed in a batch, not issued serially
    assert total < 30, f"pending handles took {total:.1f}s to fail"
    assert ray.get(actors[0].destroy.remote(name), timeout=30)
    assert ray.get(actors[0].segment_objects.remote(name),
                   timeout=30) == 0
    ray.kill(actors[0], no_restart=True)


@pytest.mark.chaos
@pytest.mark.fault_injection
def test_dropped_frame_times_out_not_hangs(ray_start_regular):
    """A seeded dropped segment during an async bucketed allreduce
    surfaces as a timeout on the handle (the wire's failure detector of
    last resort), never a hang."""
    ray = ray_start_regular
    name = GROUP + "_drop"
    actors = _world(ray, 2, name,
                    env={"RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "6",
                         "RAY_TPU_COLLECTIVE_SHM": "0"})
    try:
        ray.get([a.chaos.remote(0, "drop:*.col_push_frame:#1")
                 for a in actors], timeout=30)
        ray.get([a.launch_pending.remote(i, name, 1)
                 for i, a in enumerate(actors)], timeout=30)
        t0 = time.monotonic()
        outs = ray.get([a.wait_pending.remote(0, 30) for a in actors],
                       timeout=90)
        elapsed = time.monotonic() - t0
        assert any(not o["ok"] for o in outs), outs
        for o in outs:
            if not o["ok"]:
                assert o["type"] == "TimeoutError", o
        assert elapsed < 45, f"drop took {elapsed:.1f}s to surface"
    finally:
        try:
            ray.get([a.destroy.remote(name) for a in actors],
                    timeout=30)
        except Exception:
            pass


# ------------------------------------------------------ cluster acceptance


def _bucketed_train_loop(config):
    import jax
    import jax.numpy as jnp
    import numpy as _np
    import optax

    from ray_tpu.air import session
    from ray_tpu.parallel.train_step import (
        make_train_state,
        make_train_step,
    )
    from ray_tpu.train import ddp

    rank = session.get_world_rank()

    def init_params(rng):
        k1, k2 = jax.random.split(rng)
        return {"w1": jax.random.normal(k1, (192, 256)) * 0.02,
                "w2": jax.random.normal(k2, (256, 8)) * 0.02}

    def loss_fn(params, batch):
        x, y = batch
        h = jnp.tanh(x @ params["w1"])
        logits = h @ params["w2"]
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, {"loss": loss}

    opt = optax.sgd(0.05)
    state = make_train_state(init_params, jax.random.PRNGKey(0), opt)
    step_fn = make_train_step(
        loss_fn, opt, donate=False,
        host_grad_sync=lambda g: ddp.sync_gradients(
            g, "zzbd_gang", average=True, bucket_bytes=64 * 1024))
    for step in range(6):
        srng = _np.random.RandomState(1000 * rank + step)
        batch = (jnp.asarray(srng.standard_normal((32, 192))
                             .astype(_np.float32)),
                 jnp.asarray(srng.randint(0, 8, 32)))
        state, metrics = step_fn(state, batch)
        session.report({"loss": float(metrics["loss"])})
    blob = b"".join(_np.asarray(v).tobytes()
                    for _, v in sorted(state.params.items()))
    import hashlib

    session.report({"digest": hashlib.sha256(blob).hexdigest()})


def test_overlap_proof_bucketed_train(ray_start_regular):
    """Acceptance: a 2-worker gang running a REAL make_train_step loop
    with host_grad_sync=ddp.sync_gradients shows background bucket comm
    genuinely hidden under the step (comm_hidden > 0 with
    overlap_fraction > 0 in the fused step-anatomy report), and both
    ranks' final params are byte-identical."""
    ray = ray_start_regular
    from ray_tpu._private import telemetry as _tm
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.experimental.state.api import summarize_steps
    from ray_tpu.train.backend_executor import BackendExecutor, JaxConfig

    if not _tm.ENABLED:
        pytest.skip("telemetry plane disabled")
    executor = BackendExecutor(
        JaxConfig(group_name="zzbd_gang"),
        ScalingConfig(num_workers=2,
                      resources_per_worker={"CPU": 1})).start()
    digests = {}
    try:
        executor.start_training(_bucketed_train_loop, {})
        deadline = time.time() + 180
        while True:
            rows = executor.next_results()
            for rank, r in enumerate(rows):
                if not r.get("done") and "digest" in r.get("metrics", {}):
                    digests[rank] = r["metrics"]["digest"]
            if all(r.get("done") for r in rows):
                assert not any(r.get("error") for r in rows), rows
                break
            assert time.time() < deadline, "train run wedged"
        summary = summarize_steps()
    finally:
        executor.shutdown()

    assert digests.get(0) and digests[0] == digests.get(1), digests
    complete = [s for s in summary["steps"]
                if s["complete"] and len(s["ranks"]) == 2]
    assert len(complete) >= 3, summary["steps"]
    hidden = sum(br["comm_hidden_s"] for s in complete
                 for br in s["ranks"].values())
    assert hidden > 0, \
        "no bucket comm was attributed as hidden under the step"
    fracs = [s["overlap_fraction"] for s in complete
             if s["overlap_fraction"] is not None]
    assert fracs and max(fracs) > 0
    # the waits the loop DID pay are exposed comm, not compute — the
    # honest-accounting half of the acceptance
    exposed = sum(br["comm_exposed_s"] for s in complete
                  for br in s["ranks"].values())
    assert exposed >= 0
