"""Workflow event providers — wait/trigger steps.

Reference tier: workflow event tests over event_listener.py +
http_event_provider.py: a workflow blocks on an external event, the
payload flows downstream, the provider's copy is acked AFTER the
payload is durably checkpointed, and resume does not re-wait.
"""
import json
import os
import tempfile
import threading
import time
import urllib.request

import pytest


def test_timer_listener_fires():
    from ray_tpu.workflow import TimerListener

    t0 = time.time()
    event = TimerListener(0.2).poll_for_event()
    assert time.time() - t0 >= 0.2
    assert event["fired_after_s"] == 0.2


def test_workflow_waits_for_file_event(ray_start_regular, tmp_path):
    """The workflow blocks on the event step; once the trigger file
    appears its payload flows into the downstream step, and the ack
    deletes the trigger."""
    import ray_tpu
    from ray_tpu import workflow
    from ray_tpu.workflow import FileEventListener, wait_for_event

    trigger = str(tmp_path / "trigger.json")
    storage = str(tmp_path / "wf")

    @ray_tpu.remote
    def combine(event, tag):
        return (event["value"], tag)

    dag = combine.bind(
        wait_for_event(FileEventListener, trigger), "done")

    result_box = {}

    def run():
        result_box["out"] = workflow.run(dag, workflow_id="evt1",
                                         storage_dir=storage)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(1.0)
    assert "out" not in result_box       # still waiting on the event
    with open(trigger, "w") as f:
        json.dump({"value": 41}, f)
    t.join(timeout=60)
    assert result_box.get("out") == (41, "done")
    deadline = time.time() + 10          # ack deletes the trigger file
    while os.path.exists(trigger) and time.time() < deadline:
        time.sleep(0.1)
    assert not os.path.exists(trigger)


def test_resume_does_not_rewait_checkpointed_event(ray_start_regular,
                                                   tmp_path):
    """After the event step persisted its payload, resume replays from
    storage — no second wait, same answer (the reference's
    event-checkpoint durability contract)."""
    import ray_tpu
    from ray_tpu import workflow
    from ray_tpu.workflow import FileEventListener, wait_for_event

    trigger = str(tmp_path / "t.json")
    storage = str(tmp_path / "wf")
    with open(trigger, "w") as f:
        json.dump({"value": 7}, f)

    @ray_tpu.remote
    def double(event):
        return event["value"] * 2

    dag = double.bind(wait_for_event(FileEventListener, trigger))
    assert workflow.run(dag, workflow_id="evt2",
                        storage_dir=storage) == 14
    # the trigger is gone (acked); resume must NOT wait for it again
    assert not os.path.exists(trigger)
    assert workflow.resume("evt2", storage_dir=storage) == 14


def test_http_event_provider_round_trip(ray_start_regular, tmp_path):
    """External systems POST to the provider; the workflow's HTTP
    listener picks the event up and acks it after checkpoint."""
    import ray_tpu
    from ray_tpu import workflow
    from ray_tpu.workflow import (HTTPEventListener, HTTPEventProvider,
                                  wait_for_event)

    provider = HTTPEventProvider()
    try:
        @ray_tpu.remote
        def greet(event):
            return f"hello {event['who']}"

        dag = greet.bind(wait_for_event(
            HTTPEventListener, provider.address, "approval"))

        box = {}
        t = threading.Thread(
            target=lambda: box.update(out=workflow.run(
                dag, workflow_id="evt3",
                storage_dir=str(tmp_path / "wf"))),
            daemon=True)
        t.start()
        time.sleep(1.0)
        assert "out" not in box
        req = urllib.request.Request(
            f"{provider.address}/event/approval",
            data=json.dumps({"who": "world"}).encode(), method="POST")
        urllib.request.urlopen(req, timeout=5).read()
        t.join(timeout=60)
        assert box.get("out") == "hello world"
        deadline = time.time() + 10      # acked → provider copy deleted
        while provider.pending_events() and time.time() < deadline:
            time.sleep(0.1)
        assert provider.pending_events() == []
    finally:
        provider.shutdown()
