"""Autoscaler tests over the local (process-spawning) provider.

Reference tier: tests/test_autoscaler.py + test_autoscaler_fake_multinode
(mock providers, demand-driven scale up, idle scale down).
"""
import time

import pytest


@pytest.fixture
def scaled_cluster():
    """Head-only cluster + autoscaler with a worker node type."""
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.raylet import Raylet, detect_resources
    from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler

    gcs = GcsServer().start()
    head = Raylet(gcs.addr,
                  resources=detect_resources(1, 0),
                  store_size=64 * 1024 * 1024)
    address = f"{gcs.addr[0]}:{gcs.addr[1]}"
    provider = LocalNodeProvider(address)
    autoscaler = StandardAutoscaler(
        address,
        {"max_workers": 2, "min_workers": 0, "idle_timeout_s": 1.0,
         "available_node_types": {
             "cpu_worker": {"resources": {"CPU": 2, "crunch": 2},
                            "max_workers": 2,
                            "object_store_memory": 64 * 1024 * 1024}}},
        provider)

    from ray_tpu._private.worker_runtime import CoreWorker, set_current_worker

    worker = CoreWorker(gcs.addr, head.addr, mode="driver")
    set_current_worker(worker)
    import ray_tpu

    yield ray_tpu, autoscaler, provider, address
    autoscaler.stop()
    provider.shutdown()
    worker.shutdown()
    set_current_worker(None)
    head.stop(kill_workers=True)
    gcs.stop()


def test_scale_up_on_demand_then_down_when_idle(scaled_cluster):
    ray_tpu, autoscaler, provider, _ = scaled_cluster

    @ray_tpu.remote(num_cpus=0, resources={"crunch": 1}, max_retries=0)
    def crunch(x):
        return x * 2

    # no node offers "crunch": tasks queue... but the head raylet rejects
    # infeasible shapes, so demand must come from a feasible-some-day shape.
    # Submit and let them queue as pending demand on the head? The head has
    # no "crunch" at all -> infeasible there. So instead we model the real
    # flow: demand arrives as a pending placement group (gang waiting for
    # capacity), which the GCS reports to the autoscaler directly.
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"crunch": 1}, {"crunch": 1}],
                         strategy="PACK")
    assert not pg.wait(1)          # pending: nothing can host it

    report = autoscaler.update()
    assert report["launched"], "autoscaler did not launch for PG demand"
    # the new node registers; PG becomes schedulable; tasks run INSIDE it
    # (the PG reserved the crunch units, so tasks ride its bundles)
    assert pg.wait(30)
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    in_pg = crunch.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg))
    out = ray_tpu.get([in_pg.remote(i) for i in range(4)], timeout=60)
    assert out == [0, 2, 4, 6]

    from ray_tpu.util.placement_group import remove_placement_group

    remove_placement_group(pg)
    # idle long enough -> scaled down (head survives; provider nodes gone)
    deadline = time.time() + 30
    while time.time() < deadline:
        report = autoscaler.update()
        if report["terminated"]:
            break
        time.sleep(0.5)
    assert report["terminated"], "idle node was not terminated"
    assert provider.non_terminated_nodes() == []


def test_max_workers_cap(scaled_cluster):
    ray_tpu, autoscaler, provider, _ = scaled_cluster
    from ray_tpu.util.placement_group import placement_group

    # demand for 5 nodes' worth of crunch, cap is 2
    pgs = [placement_group([{"crunch": 2}], strategy="PACK")
           for _ in range(5)]
    time.sleep(0.2)
    launched = []
    for _ in range(4):
        launched += autoscaler.update()["launched"]
    assert 1 <= len(launched) <= 2
    assert len(provider.non_terminated_nodes()) <= 2
    del pgs


class FakeSliceProvider:
    """In-memory provider recording exactly what the autoscaler asked
    for (reference: autoscaler/_private/fake_multi_node)."""

    def __init__(self):
        self.nodes = {}
        self.calls = []
        self._n = 0

    def non_terminated_nodes(self):
        return [{"provider_id": pid, "node_type": t, "node_id": None}
                for pid, t in self.nodes.items()]

    def create_node(self, node_type, node_config, count):
        self.calls.append(("create_node", node_type, count))
        out = []
        for _ in range(count):
            self._n += 1
            pid = f"fake-{self._n}"
            self.nodes[pid] = node_type
            out.append(pid)
        return out

    def create_slice(self, node_type, node_config, topology):
        self.calls.append(("create_slice", node_type, topology))
        hosts = int((node_config.get("tpu_slice") or {}).get("hosts", 1))
        out = []
        for _ in range(hosts):
            self._n += 1
            pid = f"fake-slice-{self._n}"
            self.nodes[pid] = node_type
            out.append(pid)
        return out

    def terminate_node(self, provider_id):
        self.nodes.pop(provider_id, None)


def test_strict_pack_pg_demand_launches_exact_node_set():
    """VERDICT #8 e2e: a queued STRICT_PACK PG whose combined shape only
    fits the TPU host type launches exactly ONE such node — not one per
    bundle, not a CPU node."""
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.raylet import Raylet, detect_resources
    from ray_tpu.autoscaler import StandardAutoscaler

    gcs = GcsServer().start()
    head = Raylet(gcs.addr, resources=detect_resources(1, 0),
                  store_size=64 * 1024 * 1024)
    try:
        import os

        # queue a STRICT_PACK PG needing {TPU: 4, CPU: 4} on one node
        pg_id = os.urandom(16)
        from ray_tpu._private.protocol import RpcClient

        c = RpcClient(gcs.addr)
        try:
            c.call("create_placement_group", pg_id=pg_id,
                   bundles=[{"CPU": 2, "TPU": 2}, {"CPU": 2, "TPU": 2}],
                   strategy="STRICT_PACK")
        finally:
            c.close()

        provider = FakeSliceProvider()
        autoscaler = StandardAutoscaler(
            f"{gcs.addr[0]}:{gcs.addr[1]}",
            {"max_workers": 8,
             "available_node_types": {
                 "cpu4": {"resources": {"CPU": 4}},
                 "tpu_host": {"resources": {"CPU": 8, "TPU": 4}},
             }},
            provider)
        result = autoscaler.update()
        autoscaler.stop()
        assert result["unfulfilled"] == []
        assert provider.calls == [("create_node", "tpu_host", 1)], \
            provider.calls
    finally:
        head.stop(kill_workers=True)
        gcs.stop()


def test_strict_spread_pg_launches_tpu_slice_as_unit():
    """A STRICT_SPREAD ring over 2x {TPU: 4} hosts maps onto ONE 2-host
    slice creation (the QR-style provider call), not two independent
    nodes."""
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.raylet import Raylet, detect_resources
    from ray_tpu.autoscaler import StandardAutoscaler

    gcs = GcsServer().start()
    head = Raylet(gcs.addr, resources=detect_resources(1, 0),
                  store_size=64 * 1024 * 1024)
    try:
        import os

        from ray_tpu._private.protocol import RpcClient

        c = RpcClient(gcs.addr)
        try:
            c.call("create_placement_group", pg_id=os.urandom(16),
                   bundles=[{"TPU": 4}, {"TPU": 4}],
                   strategy="STRICT_SPREAD")
        finally:
            c.close()

        provider = FakeSliceProvider()
        autoscaler = StandardAutoscaler(
            f"{gcs.addr[0]}:{gcs.addr[1]}",
            {"max_workers": 8,
             "available_node_types": {
                 "v5e_2x4": {"resources": {"CPU": 8, "TPU": 4},
                             "tpu_slice": {"topology": "2x4",
                                           "hosts": 2}},
             }},
            provider)
        result = autoscaler.update()
        autoscaler.stop()
        assert result["unfulfilled"] == []
        assert provider.calls == [("create_slice", "v5e_2x4", "2x4")], \
            provider.calls
        assert len(provider.nodes) == 2       # both member hosts exist
    finally:
        head.stop(kill_workers=True)
        gcs.stop()
