"""Distributed tracing tests (reference:
util/tracing/tracing_helper.py — spans injected through the TaskSpec so
one trace spans driver submit → worker execute → nested submissions)."""
import json

import pytest


def test_span_context_propagation_local():
    from ray_tpu.util import tracing

    tracing.clear()
    tracing.enable()
    try:
        with tracing.span("root", "INTERNAL") as root:
            with tracing.span("child", "INTERNAL") as child:
                assert child["trace_id"] == root["trace_id"]
        spans = tracing.local_spans()
        by_name = {s["name"]: s for s in spans}
        assert by_name["child"]["parentSpanId"] == root["span_id"]
        assert by_name["root"]["parentSpanId"] is None
        assert by_name["root"]["endTimeUnixNano"] >= \
            by_name["root"]["startTimeUnixNano"]
    finally:
        tracing.disable()
        tracing.clear()


def test_trace_spans_cross_process(ray_start_regular):
    """One trace covers the driver's submit spans and the workers'
    execute spans, including a nested task submitted FROM a worker."""
    import ray_tpu
    from ray_tpu.util import tracing

    tracing.clear()
    tracing.enable()
    try:
        @ray_tpu.remote
        def inner():
            return 1

        @ray_tpu.remote
        def outer():
            import ray_tpu as rt

            return rt.get(inner.remote(), timeout=60) + 1

        assert ray_tpu.get(outer.remote(), timeout=120) == 2
        spans = tracing.get_spans()
        traces = {}
        for s in spans:
            traces.setdefault(s["traceId"], []).append(s)
        # ONE trace contains submit+execute for outer AND inner
        big = max(traces.values(), key=len)
        names = sorted(s["name"] for s in big)
        assert any("submit task outer" in n for n in names), names
        assert any("execute task outer" in n for n in names), names
        assert any("submit task inner" in n for n in names), names
        assert any("execute task inner" in n for n in names), names
        by_name = {s["name"]: s for s in big}
        sub_out = by_name["submit task outer()"]
        exe_out = by_name["execute task outer()"]
        sub_in = by_name["submit task inner()"]
        exe_in = by_name["execute task inner()"]
        # parent chain: execute_outer -> submit_outer;
        # submit_inner happens INSIDE execute_outer (worker process);
        # execute_inner -> submit_inner
        assert exe_out["parentSpanId"] == sub_out["spanId"]
        assert sub_in["parentSpanId"] == exe_out["spanId"]
        assert exe_in["parentSpanId"] == sub_in["spanId"]
        # spans came from at least two processes (driver + worker)
        assert len({s["pid"] for s in big}) >= 2
    finally:
        tracing.disable()
        tracing.clear()


def test_actor_calls_traced(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import tracing

    tracing.clear()
    tracing.enable()
    try:
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.bump.remote(), timeout=60) == 1
        spans = tracing.get_spans()
        names = [s["name"] for s in spans]
        assert any(n.startswith("submit actor method bump")
                   for n in names), names
        assert any(n.startswith("execute actor method bump")
                   for n in names), names
    finally:
        tracing.disable()
        tracing.clear()


def test_otlp_export_shape(tmp_path):
    from ray_tpu.util import tracing

    tracing.clear()
    tracing.enable()
    try:
        with tracing.span("solo", "INTERNAL", attributes={"k": "v"}):
            pass
        path = tracing.export_otlp_json(tracing.local_spans(),
                                        str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        rs = doc["resourceSpans"][0]
        attrs = {a["key"]: a["value"] for a in rs["resource"]["attributes"]}
        assert attrs["service.name"]["stringValue"] == "ray_tpu"
        otlp_span = rs["scopeSpans"][0]["spans"][0]
        assert otlp_span["name"] == "solo"
        assert len(otlp_span["traceId"]) == 32    # 128-bit hex
        assert len(otlp_span["spanId"]) == 16     # 64-bit hex
        assert otlp_span["attributes"][0]["key"] == "k"
    finally:
        tracing.disable()
        tracing.clear()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
