"""ZeRO-style sharded data-parallel training (late-alphabet on purpose:
the gang tests here cost seconds each).

Covers the tentpole's three legs and their acceptance criteria:

- pure units: the shard map is the deterministic divmod split the
  collective backend's reducescatter uses (pinned equal), covers every
  bucket exactly, and the mode knob validates before any group state is
  touched;
- determinism contract at world 2: ZeroOptimizer (reducescatter grads →
  per-rank shard apply → async allgather params) ends byte-identical to
  legacy default-mode allreduce + the same elementwise optimizer applied
  over the full packed buckets — the pairwise exchange gives each shard
  the exact operand order the allreduce produces, and elementwise
  updates commute with slicing (world > 2 reassociates the reduce and
  only bounds, not bits, hold — documented in README);
- state accounting: the opt_state gauge carries the exact flatten-sum
  of this rank's materialized shard state, ~1/world of the replicated
  footprint (within per-bucket divmod rounding), and the per-rank
  budget raises where replicated state would fit sharded state;
- composition: the int8 quantized wire opts in per bucket on the
  reducescatter path (error inside the documented bound, nonzero — the
  codec actually ran);
- chaos: a member killed with sharded reducescatters in flight surfaces
  CollectiveGroupError from result() fast (not one op timeout each),
  leaving zero stranded shm segments;
- cluster acceptance: a 2-worker gang trains a model whose REPLICATED
  adam state exceeds the per-rank byte budget that the sharded state
  fits, via make_train_step(host_optimizer=ZeroOptimizer) — final
  params byte-identical across ranks, opt_state gauge == exact shard
  bytes <= budget < replicated bytes, and the fused step-anatomy report
  attributes MORE comm hidden than exposed (the allgathers ride under
  the next step's grad computation).
"""
import os
import time

import numpy as np
import pytest

GROUP = "zzzd"


# ------------------------------------------------------------------- units


def test_shard_bounds_pin_backend_split():
    """The shard map math IS the backend's reducescatter split: if one
    changes without the other, every rank applies its optimizer shard
    to someone else's gradient slice."""
    from ray_tpu.parallel import sharding as sh
    from ray_tpu.util.collective import host_backend as hb

    for total in (0, 1, 2, 7, 100, 101, 8191, 70000):
        for parts in (1, 2, 3, 4, 8):
            got = list(sh.shard_bounds(total, parts))
            assert got == list(hb._split_bounds(total, parts)), \
                (total, parts)
            # and np.array_split (the legacy sync reducescatter's
            # chunking) agrees on every boundary
            sizes = [hi - lo for lo, hi in got]
            assert sizes == [len(c) for c in
                             np.array_split(np.zeros(total), parts)], \
                (total, parts)
            # contiguous, rank-ordered, full coverage
            assert got[0][0] == 0 and got[-1][1] == total
            for (_, a), (b, _) in zip(got, got[1:]):
                assert a == b


def test_plan_shard_map_covers_plan():
    from ray_tpu.parallel import sharding as sh

    tree = {"w1": np.zeros((96, 64), np.float32),
            "b1": np.zeros(64, np.float32),
            "w2": np.zeros((64, 11), np.float32),
            "ints": np.zeros(33, np.int64)}
    leaves, _ = sh.flatten_tree(tree)
    plan = sh.plan_buckets(leaves, 8192)
    for world in (1, 2, 4):
        smap = sh.plan_shard_map(leaves, plan, world)
        assert smap == sh.plan_shard_map(leaves, plan, world)  # determ.
        assert len(smap) == len(plan)
        for b, indices in enumerate(plan):
            e = smap[b]
            assert e["indices"] == indices
            assert e["elems"] == sum(
                int(np.asarray(leaves[i]).size) for i in indices)
            assert e["dtype"] == np.asarray(leaves[indices[0]]).dtype
            assert e["bounds"] == sh.shard_bounds(e["elems"], world)


def test_mode_validation_raises_before_group_state():
    """Mode/wire misuse must fail loud at the call site — none of these
    need (or touch) a live collective group."""
    from ray_tpu.train import ddp

    with pytest.raises(ValueError, match="expected 'allreduce'"):
        ddp.sync_gradients_async({"g": np.zeros(4, np.float32)},
                                 "no_such_group", mode="zero3")
    with pytest.raises(ValueError, match="reducescatter"):
        ddp.sync_gradients_async({"g": np.zeros(4, np.float32)},
                                 "no_such_group", mode="allreduce",
                                 wire_dtype="int8")
    # the knob default resolves to the legacy mode: flipping the
    # default would silently change every caller's return type
    assert ddp._resolve_mode(None) == "allreduce"


# --------------------------------------------------------------- live group


def _rank_cls(ray):
    @ray.remote
    class Rank:
        def configure(self, env):
            os.environ.update({k: str(v) for k, v in env.items()})
            return True

        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def zero_vs_legacy(self, rank, name, steps=3):
            """ZeroOptimizer vs the legacy oracle: default-mode (pin:
            allreduce) sync_gradients + the SAME elementwise adam
            applied over the full packed buckets. Byte-identical at
            world 2. Also returns the state-accounting triple."""
            from ray_tpu.parallel import sharding as sh
            from ray_tpu.train import ddp
            from ray_tpu.util.metrics import registry_snapshot

            shapes = {"w1": (96, 64), "b1": (64,), "w2": (64, 11),
                      "b2": (11,)}

            def init_params():
                rng = np.random.RandomState(42)
                return {k: rng.standard_normal(s).astype(np.float32)
                        for k, s in sorted(shapes.items())}

            def grads_for(step):
                grng = np.random.RandomState(100 * step + rank)
                return {k: grng.standard_normal(s).astype(np.float32)
                        for k, s in sorted(shapes.items())}

            # --- sharded run
            params = init_params()
            zopt = ddp.ZeroOptimizer(ddp.zero_adam(0.01), name,
                                     bucket_bytes=8192)
            for step in range(steps):
                params = zopt.step(params, grads_for(step))
            zero_bytes = {k: np.asarray(v).tobytes()
                          for k, v in params.items()}

            # --- legacy oracle over the same plan
            params = init_params()
            leaves, treedef = sh.flatten_tree(params)
            plan = sh.plan_buckets(leaves, 8192)
            opt = ddp.zero_adam(0.01)
            full_state = [
                opt.init(sum(int(np.asarray(leaves[i]).size)
                             for i in b), np.dtype(np.float32))
                for b in plan]
            for step in range(steps):
                synced = ddp.sync_gradients(grads_for(step), name,
                                            bucket_bytes=8192)
                gleaves, _ = sh.flatten_tree(synced)
                pleaves, _ = sh.flatten_tree(params)
                out = [None] * len(pleaves)
                for b, indices in enumerate(plan):
                    pflat = sh.pack_bucket(pleaves, indices)
                    gflat = sh.pack_bucket(
                        [np.asarray(g) for g in gleaves], indices)
                    pflat = opt.apply(pflat, gflat, full_state[b],
                                      step + 1)
                    sh.unpack_bucket(pflat, pleaves, indices, out)
                params = sh.unflatten_tree(treedef, out)
            legacy_bytes = {k: np.asarray(v).tobytes()
                            for k, v in params.items()}

            gauge = None
            for fam in registry_snapshot():
                if fam["name"] == "ray_tpu_train_state_bytes":
                    for v in fam["values"]:
                        if v["tags"].get("kind") == "opt_state" and \
                                v["tags"].get("rank") == str(rank):
                            gauge = v["value"]
            return {"zero": zero_bytes, "legacy": legacy_bytes,
                    "state_bytes": zopt.state_bytes(),
                    "replicated": zopt.replicated_state_bytes(),
                    "n_buckets": len(zopt.shard_map),
                    "gauge": gauge}

        def int8_on_rs(self, rank, name):
            """Per-bucket int8 opt-in on the reducescatter path: this
            rank's shard vs the float64 exact sum's same slice."""
            from ray_tpu.parallel import sharding as sh
            from ray_tpu.train import ddp

            ins = [np.random.RandomState(700 + r)
                   .standard_normal(20000).astype(np.float32)
                   for r in range(2)]
            shards = ddp.sync_gradients({"g": ins[rank]}, name,
                                        mode="reducescatter",
                                        wire_dtype="int8",
                                        bucket_bytes=1 << 20)
            got = np.asarray(shards[0]).astype(np.float64)
            lo, hi = sh.shard_bounds(20000, 2)[rank]
            exact = (ins[0].astype(np.float64)
                     + ins[1].astype(np.float64))[lo:hi]
            err = float(np.abs(got - exact).max())
            bound = 2 * (1.0 / 254.0) * float(
                sum(np.abs(x).max() for x in ins))
            return {"bytes": np.asarray(shards[0]).tobytes(),
                    "err": err, "bound": bound, "lo": lo, "hi": hi}

        def kill_switch_same_shards(self, rank, name):
            """RAY_TPU_TRAIN_BUCKET_DDP=0 degrades the sharded mode to
            synchronous reducescatters over the UNCHANGED shard map —
            same shards, same bytes."""
            from ray_tpu.train import ddp

            x = np.random.RandomState(900 + rank) \
                .standard_normal(9000).astype(np.float32)
            os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = "1"
            on = ddp.sync_gradients({"g": x}, name,
                                    mode="reducescatter",
                                    bucket_bytes=16384)
            os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = "0"
            try:
                off = ddp.sync_gradients({"g": x}, name,
                                         mode="reducescatter",
                                         bucket_bytes=16384)
            finally:
                os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = "1"
            assert len(on) == len(off)
            return {"on": [np.asarray(s).tobytes() for s in on],
                    "off": [np.asarray(s).tobytes() for s in off]}

        def launch_shard_pending(self, rank, name):
            """Launch a sharded grad sync (4 one-leaf buckets) and park
            — rank 1 never calls, so the handles stay pending: the
            chaos target."""
            from ray_tpu.train import ddp

            grads = {f"w{i}": np.full(70000, float(rank + 1),
                                      np.float32) for i in range(4)}
            self._pending = ddp.sync_gradients_async(
                grads, name, mode="reducescatter", bucket_bytes=65536)
            return True

        def wait_shard_pending(self, timeout):
            t0 = time.monotonic()
            try:
                self._pending.result(timeout)
                return {"ok": True, "latency": time.monotonic() - t0}
            except BaseException as e:  # noqa: BLE001
                return {"ok": False, "latency": time.monotonic() - t0,
                        "type": type(e).__name__, "msg": str(e)}

        def segment_objects(self, name):
            from ray_tpu._private.worker_runtime import (col_oid_prefix,
                                                         current_worker)

            prefix = col_oid_prefix(name)
            return sum(1 for oid, _ in
                       current_worker().store.list_objects()
                       if oid.startswith(prefix))

        def destroy(self, name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(name)
            return True

    return Rank


def _world(ray, n, name, env=None):
    Rank = _rank_cls(ray)
    actors = [Rank.options(num_cpus=0).remote() for _ in range(n)]
    merged = {"RAY_TPU_TRAIN_BUCKET_DDP": "1"}
    merged.update(env or {})
    ray.get([a.configure.remote(merged) for a in actors])
    ray.get([a.join.remote(n, i, name) for i, a in enumerate(actors)],
            timeout=120)
    return actors


def test_zero_matches_legacy_bitwise_world2(ray_start_regular):
    """Determinism contract: sharded (rs + shard apply + allgather) ==
    legacy (allreduce + full apply), byte for byte, both ranks agree —
    plus the world-fold state accounting on a live group."""
    ray = ray_start_regular
    name = GROUP + "_id"
    actors = _world(ray, 2, name)
    try:
        got = ray.get([a.zero_vs_legacy.remote(i, name)
                       for i, a in enumerate(actors)], timeout=120)
        for k in got[0]["zero"]:
            assert got[0]["zero"][k] == got[1]["zero"][k], \
                f"rank divergence (zero) {k}"
            assert got[0]["legacy"][k] == got[1]["legacy"][k], \
                f"rank divergence (legacy) {k}"
            assert got[0]["zero"][k] == got[0]["legacy"][k], \
                f"zero/legacy divergence {k}"
        for rank, g in enumerate(got):
            # gauge carries the exact flatten-sum of the shard state
            assert g["gauge"] == pytest.approx(g["state_bytes"]), g
            # world-fold: 2 * shard ≈ replicated, off by at most one
            # element per bucket per slot (divmod rounding; adam = 2
            # float32 slots)
            slack = g["n_buckets"] * 4 * 2
            assert abs(2 * g["state_bytes"] - g["replicated"]) <= slack
            assert g["state_bytes"] < g["replicated"]
        # the two ranks' shards partition the state exactly
        assert got[0]["state_bytes"] + got[1]["state_bytes"] == \
            pytest.approx(got[0]["replicated"])
    finally:
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)


def test_kill_switch_keeps_shard_map(ray_start_regular):
    ray = ray_start_regular
    name = GROUP + "_ks"
    actors = _world(ray, 2, name)
    try:
        got = ray.get([a.kill_switch_same_shards.remote(i, name)
                       for i, a in enumerate(actors)], timeout=120)
        for rank in range(2):
            assert got[rank]["on"] == got[rank]["off"], \
                f"kill switch changed rank {rank}'s shards"
    finally:
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)


def test_int8_wire_opts_in_per_bucket_on_reducescatter(ray_start_regular):
    ray = ray_start_regular
    name = GROUP + "_q"
    # quantization is an inter-host wire feature; force the socket path
    # so the int8 codec actually runs (same choice as the bucket-DDP
    # quantized test and BENCH_r08)
    actors = _world(ray, 2, name, env={"RAY_TPU_COLLECTIVE_SHM": "0"})
    try:
        got = ray.get([a.int8_on_rs.remote(i, name)
                       for i, a in enumerate(actors)], timeout=120)
        # the two shards partition [0, 20000)
        assert got[0]["hi"] == got[1]["lo"]
        for g in got:
            # nonzero proves the codec engaged; the bound is the
            # documented two-sided quantization error
            assert 0 < g["err"] <= g["bound"], g
    finally:
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)


@pytest.mark.chaos
def test_poison_fails_pending_shard_sync_fast(ray_start_regular):
    """A member dies with sharded reducescatters IN FLIGHT: the
    survivor's PendingShardSync.result() surfaces CollectiveGroupError
    within the poison-latency bound (nowhere near one 120s op timeout
    per bucket), and teardown leaves zero stranded shm segments."""
    ray = ray_start_regular
    name = GROUP + "_poison"
    actors = _world(ray, 2, name,
                    env={"RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "120"})
    ray.get(actors[0].launch_shard_pending.remote(0, name), timeout=30)
    time.sleep(0.5)          # let the issue thread put op #1 on the wire
    t0 = time.monotonic()
    ray.kill(actors[1], no_restart=True)
    out = ray.get(actors[0].wait_shard_pending.remote(90), timeout=120)
    total = time.monotonic() - t0
    assert not out["ok"], out
    assert out["type"] == "CollectiveGroupError", out
    assert total < 30, f"pending shard sync took {total:.1f}s to fail"
    assert ray.get(actors[0].destroy.remote(name), timeout=30)
    assert ray.get(actors[0].segment_objects.remote(name),
                   timeout=30) == 0
    ray.kill(actors[0], no_restart=True)


def test_world1_budget_and_identity(ray_start_regular):
    """World-1 degeneracies + the budget contract: the sharded state IS
    the replicated state (nothing to fold), and a budget below it
    raises at materialization — not silently over-allocates."""
    ray_tpu = ray_start_regular  # noqa: F841 (needs the live runtime)
    from ray_tpu.train import ddp
    from ray_tpu.util import collective as col

    name = GROUP + "_w1"
    col.init_collective_group(1, 0, "host", name)
    try:
        params = {"w": np.ones(1000, np.float32)}
        grads = {"w": np.full(1000, 0.5, np.float32)}
        zopt = ddp.ZeroOptimizer(ddp.zero_adam(0.1), name,
                                 bucket_bytes=2048)
        out = zopt.step(params, grads)
        assert np.asarray(out["w"]).shape == (1000,)
        # world 1: the shard is the whole thing
        assert zopt.state_bytes() == zopt.replicated_state_bytes() \
            == 2 * 1000 * 4
        # budget: 7999 < the 8000 bytes adam needs for this rank
        tight = ddp.ZeroOptimizer(ddp.zero_adam(0.1), name,
                                  bucket_bytes=2048,
                                  state_budget_bytes=7999)
        with pytest.raises(RuntimeError, match="exceeds the per-rank "
                                               "budget"):
            tight.step(params, grads)
        # structure drift refuses to remap the shard state
        with pytest.raises(ValueError, match="structure changed"):
            zopt.step({"w": np.ones(999, np.float32)},
                      {"w": np.ones(999, np.float32)})
    finally:
        col.destroy_collective_group(name)


# ------------------------------------------------------ cluster acceptance


def _zero_train_loop(config):
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as _np
    import optax

    from ray_tpu.air import session
    from ray_tpu.parallel.train_step import (
        make_train_step,
        make_zero_train_state,
    )
    from ray_tpu.train import ddp

    rank = session.get_world_rank()
    layers, dim = 8, 512

    def init_params(rng):
        keys = jax.random.split(rng, layers + 1)
        params = {f"layer_{i:02d}": jax.random.normal(
            keys[i], (dim, dim)) * 0.05 for i in range(layers)}
        params["zz_head"] = jax.random.normal(keys[layers],
                                              (dim, 8)) * 0.05
        return params

    def loss_fn(params, batch):
        x, y = batch
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ params[f"layer_{i:02d}"])
        logits = h @ params["zz_head"]
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, {"loss": loss}

    # replicated adam over these params is ~16.8 MB/rank (2 float32
    # slots x ~8.4 MB of params) — OVER the 12 MB budget; the sharded
    # state (~8.4 MB at world 2) fits. 512 KB buckets put every 1 MB
    # layer in its own (oversized) bucket: a real multi-bucket pipeline
    # whose per-shard adam math is big enough to hide the next bucket's
    # reducescatter under it.
    zopt = ddp.ZeroOptimizer(ddp.zero_adam(0.01), "zzzd_gang",
                             bucket_bytes=512 * 1024,
                             state_budget_bytes=12_000_000,
                             average=True)
    state = make_zero_train_state(init_params, jax.random.PRNGKey(0))
    step_fn = make_train_step(loss_fn, None, donate=False,
                              host_optimizer=zopt)
    for step in range(8):
        srng = _np.random.RandomState(1000 * rank + step)
        # the data pipeline IS the overlap window the async param
        # gathers ride under (step anatomy attributes them hidden):
        # generate a pool and take the batch from it, like a real
        # host-side loader shard
        pool = srng.standard_normal((2048, dim)).astype(_np.float32)
        batch = (jnp.asarray(pool[:64]),
                 jnp.asarray(srng.randint(0, 8, 64)))
        state, metrics = step_fn(state, batch)
        session.report({"loss": float(metrics["loss"])})
    state = step_fn.finalize(state)

    from ray_tpu.util.metrics import registry_snapshot

    gauge = None
    for fam in registry_snapshot():
        if fam["name"] == "ray_tpu_train_state_bytes":
            for v in fam["values"]:
                if v["tags"].get("kind") == "opt_state" and \
                        v["tags"].get("rank") == str(rank):
                    gauge = v["value"]
    blob = b"".join(_np.asarray(v).tobytes()
                    for _, v in sorted(state.params.items()))
    session.report({"digest": hashlib.sha256(blob).hexdigest(),
                    "state_bytes": zopt.state_bytes(),
                    "replicated": zopt.replicated_state_bytes(),
                    "gauge": gauge})


def test_zero_train_overlap_and_budget_proof(ray_start_regular):
    """Acceptance: a 2-worker gang trains a model whose REPLICATED adam
    state exceeds the per-rank budget the SHARDED state fits, through
    make_train_step(host_optimizer=ZeroOptimizer) — ranks end
    byte-identical, the opt_state gauge carries the exact shard bytes,
    and step anatomy attributes more comm hidden than exposed (the
    param allgathers ride under the next step's grad computation)."""
    ray = ray_start_regular
    from ray_tpu._private import telemetry as _tm
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.experimental.state.api import summarize_steps
    from ray_tpu.train.backend_executor import BackendExecutor, JaxConfig

    if not _tm.ENABLED:
        pytest.skip("telemetry plane disabled")
    executor = BackendExecutor(
        JaxConfig(group_name="zzzd_gang"),
        ScalingConfig(num_workers=2,
                      resources_per_worker={"CPU": 1})).start()
    finals = {}
    try:
        executor.start_training(_zero_train_loop, {})
        deadline = time.time() + 240
        while True:
            rows = executor.next_results()
            for rank, r in enumerate(rows):
                m = r.get("metrics", {})
                if not r.get("done") and "digest" in m:
                    finals[rank] = m
            if all(r.get("done") for r in rows):
                assert not any(r.get("error") for r in rows), rows
                break
            assert time.time() < deadline, "train run wedged"
        summary = summarize_steps()
    finally:
        executor.shutdown()

    assert finals.get(0) and finals.get(1), finals
    assert finals[0]["digest"] == finals[1]["digest"], finals
    budget = 12_000_000
    for rank, m in finals.items():
        # the model this gang just trained does NOT fit replicated...
        assert m["replicated"] > budget, m
        # ...and the shard it actually held does, gauge-proven
        assert m["state_bytes"] <= budget, m
        assert m["gauge"] == pytest.approx(m["state_bytes"]), m
    # both shards together are the replicated footprint
    assert finals[0]["state_bytes"] + finals[1]["state_bytes"] == \
        pytest.approx(finals[0]["replicated"])

    complete = [s for s in summary["steps"]
                if s["complete"] and len(s["ranks"]) == 2]
    assert len(complete) >= 3, summary["steps"]
    if os.environ.get("ZZ_DEBUG"):
        for s in complete:
            h = sum(br["comm_hidden_s"] for br in s["ranks"].values())
            e = sum(br["comm_exposed_s"] for br in s["ranks"].values())
            print(f"step {s['step_id']}: hidden={h*1000:.1f}ms "
                  f"exposed={e*1000:.1f}ms")
    hidden = sum(br["comm_hidden_s"] for s in complete
                 for br in s["ranks"].values())
    exposed = sum(br["comm_exposed_s"] for s in complete
                  for br in s["ranks"].values())
    assert hidden > 0, \
        "no sharded comm was attributed as hidden under the step"
    # the acceptance bar: the pipeline hides MORE comm than it exposes
    assert hidden > exposed, (hidden, exposed)
