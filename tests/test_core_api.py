"""Core API tests: tasks, actors, objects — the analog of the reference's
python/ray/tests/test_basic.py / test_actor.py tier."""
import time

import numpy as np
import pytest


# ---------------------------------------------------------------- tasks

def test_task_basic(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def f(a, b=1):
        return a + b

    assert ray.get(f.remote(1)) == 2
    assert ray.get(f.remote(1, b=10)) == 11


def test_task_fanout(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(50)]
    assert ray.get(refs) == [i * i for i in range(50)]


def test_task_nested_submission(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def inner(x):
        return x + 1

    @ray.remote
    def outer(x):
        import ray_tpu

        return ray_tpu.get(inner.remote(x)) + 100

    assert ray.get(outer.remote(1)) == 102


def test_task_ref_args(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def add(a, b):
        return a + b

    r1 = add.remote(1, 2)
    r2 = add.remote(r1, 10)    # ref as arg resolves to its value
    assert ray.get(r2) == 13


def test_task_num_returns(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagation(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def boom():
        raise KeyError("nope")

    with pytest.raises(ray.exceptions.TaskError) as ei:
        ray.get(boom.remote())
    assert "KeyError" in str(ei.value)
    assert isinstance(ei.value.cause, KeyError)


def test_task_error_through_dependency(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def boom():
        raise ValueError("first")

    @ray.remote
    def use(x):
        return x

    # An errored dependency poisons downstream tasks too.
    with pytest.raises(ray.exceptions.TaskError):
        ray.get(use.remote(boom.remote()))


def test_large_object_through_store(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def make(n):
        return np.ones(n, dtype=np.float64)

    arr = ray.get(make.remote(500_000))   # ~4MB → shm store path
    assert arr.shape == (500_000,)
    assert arr[0] == 1.0


def test_put_get_roundtrip(ray_start_regular):
    ray = ray_start_regular
    for value in [1, "x", {"a": [1, 2]}, np.arange(10), None,
                  np.zeros((100, 100))]:
        out = ray.get(ray.put(value))
        if isinstance(value, np.ndarray):
            assert (out == value).all()
        else:
            assert out == value


def test_get_timeout(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def never():
        time.sleep(60)

    with pytest.raises(ray.exceptions.GetTimeoutError):
        ray.get(never.remote(), timeout=0.5)


def test_wait_semantics(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def slow(t):
        time.sleep(t)
        return t

    fast = slow.remote(0.05)
    slows = slow.remote(10)
    ready, pending = ray.wait([fast, slows], num_returns=1, timeout=5)
    assert ready == [fast]
    assert pending == [slows]
    ready2, _ = ray.wait([fast], num_returns=1)
    assert ready2 == [fast]


def test_task_resources_respected(ray_start_regular):
    ray = ray_start_regular
    # 4 CPUs in the fixture: 4 concurrent 2-CPU tasks must serialize 2-at-a-time
    import collections

    @ray.remote(num_cpus=2)
    def hold(i):
        time.sleep(0.3)
        return time.time()

    t0 = time.time()
    times = ray.get([hold.remote(i) for i in range(4)])
    elapsed = time.time() - t0
    assert elapsed >= 0.55, f"4x 2-CPU tasks on 4 CPUs finished in {elapsed}"


def test_options_override(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_cpus=1)
    def f():
        return 1

    assert ray.get(f.options(num_cpus=2).remote()) == 1


def test_infeasible_raises(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_cpus=64)
    def f():
        return 1

    with pytest.raises(Exception):
        ray.get(f.remote(), timeout=10)


# ---------------------------------------------------------------- actors

def test_actor_state_and_order(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Counter:
        def __init__(self):
            self.x = 0

        def incr(self):
            self.x += 1
            return self.x

    c = Counter.remote()
    results = ray.get([c.incr.remote() for _ in range(25)])
    assert results == list(range(1, 26))


def test_actor_constructor_args(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Box:
        def __init__(self, a, b=2):
            self.v = (a, b)

        def read(self):
            return self.v

    assert ray.get(Box.remote(1, b=5).read.remote()) == (1, 5)


def test_actor_error(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method error")

        def ok(self):
            return "fine"

    b = Bad.remote()
    with pytest.raises(ray.exceptions.TaskError):
        ray.get(b.fail.remote())
    # actor survives a method error
    assert ray.get(b.ok.remote()) == "fine"


def test_named_actor(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Registry:
        def ping(self):
            return "pong"

    Registry.options(name="reg").remote()
    h = ray.get_actor("reg")
    assert ray.get(h.ping.remote()) == "pong"

    with pytest.raises(ValueError):
        ray.get_actor("missing")


def test_named_actor_collision(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class A:
        def ping(self):
            return 1

    A.options(name="dup").remote()
    h = ray.get_actor("dup")
    ray.get(h.ping.remote())
    with pytest.raises(Exception):
        A.options(name="dup").remote()
        # registration error surfaces on next interaction
        h2 = ray.get_actor("dup")


def test_get_if_exists(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Singleton:
        def __init__(self):
            self.t = time.time()

        def created_at(self):
            return self.t

    a = Singleton.options(name="s", get_if_exists=True).remote()
    t1 = ray.get(a.created_at.remote())
    b = Singleton.options(name="s", get_if_exists=True).remote()
    t2 = ray.get(b.created_at.remote())
    assert t1 == t2


def test_actor_kill(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray.get(v.ping.remote()) == "pong"
    ray.kill(v)
    with pytest.raises((ray.exceptions.ActorDiedError,
                        ray.exceptions.ActorUnavailableError)):
        ray.get(v.ping.remote(), timeout=30)


def test_actor_restart(ray_start_regular):
    ray = ray_start_regular

    # max_task_retries stays 0: retrying `die` would kill the restarted
    # actor again (retries re-execute the method — reference semantics).
    @ray.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def die(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    assert ray.get(p.incr.remote()) == 1
    try:
        ray.get(p.die.remote(), timeout=10)
    except Exception:
        pass
    # restarted: state reset, calls served again
    deadline = time.time() + 30
    value = None
    while time.time() < deadline:
        try:
            value = ray.get(p.incr.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.2)
    assert value == 1, f"expected fresh state after restart, got {value}"


def test_async_actor(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class AsyncWorker:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 2

    w = AsyncWorker.remote()
    assert ray.get([w.work.remote(i) for i in range(5)]) == [0, 2, 4, 6, 8]


def test_actor_handle_passing(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Store:
        def __init__(self):
            self.v = {}

        def set(self, k, v):
            self.v[k] = v

        def get(self, k):
            return self.v.get(k)

    @ray.remote
    def writer(store, k, v):
        import ray_tpu

        ray_tpu.get(store.set.remote(k, v))
        return True

    s = Store.remote()
    ray.get(writer.remote(s, "a", 42))
    assert ray.get(s.get.remote("a")) == 42


def test_detached_semantics_runtime_context(ray_start_regular):
    ray = ray_start_regular
    ctx = ray.get_runtime_context()
    assert ctx.get_node_id()
    assert ctx.get_actor_id() is None

    @ray.remote
    class Introspect:
        def who(self):
            import ray_tpu

            return ray_tpu.get_runtime_context().get_actor_id()

    a = Introspect.remote()
    assert ray.get(a.who.remote()) is not None


# ------------------------------------------------------------- cluster-level

def test_cluster_resources(ray_start_regular):
    ray = ray_start_regular
    total = ray.cluster_resources()
    assert total["CPU"] == 4.0
    nodes = ray.nodes()
    assert len(nodes) == 1 and nodes[0]["Alive"]


def test_nested_ref_arg_not_promoted(ray_start_regular):
    """A ref-to-a-ref arg must deliver the INNER ObjectRef to the task
    (arg inlining must not promote it to a top-level auto-resolved arg)."""
    import ray_tpu
    from ray_tpu import ObjectRef

    inner = ray_tpu.put(41)

    @ray_tpu.remote
    def make_outer(lst):
        return lst[0]     # nested refs aren't auto-resolved: returns the
                          # ObjectRef itself

    outer = make_outer.remote([inner])

    @ray_tpu.remote
    def check(x):
        assert isinstance(x, ObjectRef), f"got {type(x).__name__}"
        return ray_tpu.get(x) + 1

    assert ray_tpu.get(check.remote(outer), timeout=30) == 42


def test_concurrency_groups(ray_start_regular):
    """Methods in a named concurrency group don't contend with the default
    group (reference: transport/concurrency_group_manager.h)."""
    import time as _time

    import ray_tpu

    @ray_tpu.remote(max_concurrency=1, concurrency_groups={"io": 2})
    class Mixed:
        def __init__(self):
            self.events = []

        def slow_default(self):
            self.events.append("default_start")
            _time.sleep(1.0)
            self.events.append("default_end")
            return "slow"

        @ray_tpu.method(concurrency_group="io")
        def fast_io(self):
            self.events.append("io")
            return "io"

        @ray_tpu.method(concurrency_group="io")
        def get_events(self):
            return list(self.events)

    a = Mixed.options(max_concurrency=8).remote()
    ray_tpu.get(a.get_events.remote(), timeout=60)   # actor is up
    slow = a.slow_default.remote()
    _time.sleep(0.2)              # slow task is now running
    t0 = _time.time()
    assert ray_tpu.get(a.fast_io.remote(), timeout=30) == "io"
    io_latency = _time.time() - t0
    assert io_latency < 0.8, (
        f"io-group call waited {io_latency:.2f}s behind the default group")
    assert ray_tpu.get(slow, timeout=30) == "slow"
    events = ray_tpu.get(a.get_events.remote(), timeout=30)
    assert events.index("io") < events.index("default_end")


def test_undeclared_concurrency_group_fails(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(concurrency_groups={"io": 2})
    class Typo:
        @ray_tpu.method(concurrency_group="oi")   # misspelled
        def call(self):
            return 1

    # caught at actor creation, before any call can run (advisor round 3:
    # a dispatch-time failure left the caller's seq unconsumed and wedged
    # every later call on that handle)
    with pytest.raises(Exception, match="concurrency group"):
        Typo.remote()


def test_dispatch_time_group_failure_does_not_wedge(ray_start_regular):
    """Defense-in-depth path: a group lookup failing at dispatch must
    consume the seq so later calls from the same handle still run."""
    import ray_tpu
    from ray_tpu._private import api as api_mod

    @ray_tpu.remote(concurrency_groups={"io": 2})
    class Typo:
        @ray_tpu.method(concurrency_group="oi")   # misspelled
        def bad(self):
            return 1

        def good(self):
            return 2

    # bypass creation-time validation to exercise the executor guard
    orig = api_mod._validate_concurrency_groups
    api_mod._validate_concurrency_groups = lambda cls, groups: None
    try:
        t = Typo.remote()
    finally:
        api_mod._validate_concurrency_groups = orig
    bad_ref = t.bad.remote()
    # the failed call errors, and the NEXT seq from this caller proceeds
    assert ray_tpu.get(t.good.remote(), timeout=30) == 2
    with pytest.raises(Exception, match="concurrency group"):
        ray_tpu.get(bad_ref, timeout=30)


def test_inline_exec_tasks(ray_start_regular):
    """inline_exec=True runs tasks on the worker's transport pump (no
    main-thread handoff). Semantics must match the default path: values,
    errors, and ref args all behave identically."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0, inline_exec=True)
    def double(x):
        return x * 2

    @ray_tpu.remote(num_cpus=0, inline_exec=True)
    def boom():
        raise ValueError("inline boom")

    assert ray_tpu.get([double.remote(i) for i in range(20)]) == \
        [i * 2 for i in range(20)]
    ref = ray_tpu.put(21)
    assert ray_tpu.get(double.remote(ref)) == 42
    import pytest as _pytest
    with _pytest.raises(ray_tpu.exceptions.TaskError, match="inline boom"):
        ray_tpu.get(boom.remote())
