"""Elastic gang fault tolerance (late-alphabet; sequenced after the
tier-1 timeout horizon by design).

Covers the gang-FT tentpole end to end: deterministic rank death via the
fault plane's `kill_actor` action (seeded + schedule-driven, reproducible
from the RAY_TPU_FAULT_SEED/RAY_TPU_FAULT_SCHEDULE pair), fast detection
(`TrainWorkerGroupError` with per-rank attribution instead of a hang),
collective group poisoning (surviving ranks raise a named
`CollectiveGroupError` well under the op timeout), incarnation-epoch
fencing (stale frames rejected at ingest, dead epochs' stranded shm
segments swept at rejoin), and `fit()`'s checkpoint-resume gang restart
loop under `FailureConfig.max_failures`.

The chaos-marked tests set the fault env BEFORE `ray_tpu.init` so every
spawned worker process inherits the schedule; rank scoping rides the
`rank<N>` process tags train workers add at construction.
"""
import os
import time

import numpy as np
import pytest

pytestmark = []

GROUP = "gang_ft_dp"
STEPS = 4


# ------------------------------------------------------------- pure units

def test_kill_actor_schedule_parsing():
    from ray_tpu._private.fault_injection import (ACTIONS, _REPLY_ACTIONS,
                                                  FaultInjector)

    assert "kill_actor" in ACTIONS
    assert "kill_actor" in _REPLY_ACTIONS
    inj = FaultInjector(7, "kill_actor:rank1.next_result:#2")
    [rule] = inj._reply_rules
    assert rule.action == "kill_actor"
    assert rule.role == "rank1"
    assert rule.method == "next_result"
    assert inj._send_rules == []
    with pytest.raises(ValueError):
        FaultInjector(0, "explode:*.foo:p1.0")


def test_tag_scope_matching():
    from ray_tpu._private import fault_injection as fi
    from ray_tpu._private.fault_injection import FaultInjector

    inj = FaultInjector(0, "kill_actor:rank3.next_result:#1")
    [rule] = inj._reply_rules
    # scope is neither this process's role nor a tag: no match
    assert not rule.matches_scope("worker", "next_result")
    # the gang-rank tag is what makes the rule land on one member
    assert rule.matches_scope("worker", "next_result",
                              frozenset({"rank3"}))
    assert not rule.matches_scope("worker", "other_method",
                                  frozenset({"rank3"}))
    fi.add_tag("zz_gang_ft_test_tag")
    assert "zz_gang_ft_test_tag" in fi.get_tags()


def test_gang_exceptions_pickle_roundtrip():
    import pickle

    from ray_tpu.exceptions import (CollectiveGroupError,
                                    TrainWorkerGroupError)

    e = pickle.loads(pickle.dumps(
        CollectiveGroupError("g", (2, 0), "rank 2 died")))
    assert e.group == "g" and e.dead_ranks == (0, 2)
    assert "rank 2 died" in str(e)

    class Unpicklable(Exception):
        def __reduce__(self):
            raise TypeError("nope")

    t = TrainWorkerGroupError({0: "boom", 1: Unpicklable("x")},
                              dead_ranks=(1,))
    t2 = pickle.loads(pickle.dumps(t))   # degrades rank 1's cause to str
    assert t2.dead_ranks == (1,)
    assert t2.errors[0] == "boom"
    assert "Unpicklable" in str(t2.errors[1])


def test_next_result_monotonic_deadline():
    """`waited_dead` used to accrue 0.1s per Empty regardless of how long
    the get actually blocked, so a loaded box drifted the dead-thread
    deadline arbitrarily late. The wait is now measured against a
    monotonic deadline: with each get() blocking 3.5x its nominal poll
    interval, the timeout still lands ~on time (the old counter would
    take ~3.5x the budget)."""
    import queue

    from ray_tpu.train.worker_group import TrainWorker

    class SlowEmptyQueue:
        def get(self, timeout=None):
            time.sleep(0.35)           # "under load": poll overruns
            raise queue.Empty

        def empty(self):
            return True

    w = TrainWorker(0, 1)
    w.session.results = SlowEmptyQueue()   # no train thread started
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        w.next_result(timeout=0.7)
    elapsed = time.monotonic() - t0
    assert elapsed < 1.8, f"deadline drifted: {elapsed:.2f}s for 0.7s"


# ---------------------------------------------------- per-rank attribution

def test_worker_group_execute_per_rank_attribution(ray_start_regular):
    """One failing rank must not poison the whole gang result with a
    generic error: execute resolves every rank's ref and surfaces a
    TrainWorkerGroupError mapping world rank -> that rank's exception."""
    from ray_tpu.exceptions import TrainWorkerGroupError
    from ray_tpu.train import WorkerGroup

    def setup(rank, world):
        if rank == 1:
            raise RuntimeError(f"rank {rank} exploded")
        return rank * 10

    wg = WorkerGroup(3, {"CPU": 1})
    try:
        with pytest.raises(TrainWorkerGroupError) as ei:
            wg.execute("run_setup", (setup, (), {}))
        err = ei.value
        assert set(err.errors) == {1}
        assert "rank 1 exploded" in str(err.errors[1])
        assert err.dead_ranks == ()        # raised, not died
        # healthy ranks answer normally once the culprit is gone
        assert wg.execute("run_setup",
                          ((lambda r, w: r), (), {})) == [0, 1, 2]
    finally:
        wg.shutdown()


def test_execute_abort_check_interrupts_blocked_call(ray_start_regular):
    """The death monitor's knowledge interrupts a BLOCKED gang call:
    `abort_check` is polled while refs are pending, so a death the
    transport never surfaces (e.g. a partition with no TCP reset) still
    fails the gang within the poll cadence — not the worker-side call's
    own multi-minute budget."""
    from ray_tpu.exceptions import TrainWorkerGroupError
    from ray_tpu.train import WorkerGroup

    wg = WorkerGroup(1, {"CPU": 1})
    try:
        # next_result blocks worker-side (~300s default: no train thread)
        t0 = time.monotonic()
        with pytest.raises(TrainWorkerGroupError) as ei:
            wg.execute("next_result", timeout=60.0,
                       abort_check=lambda: {0: "node lost"})
        assert time.monotonic() - t0 < 30
        assert 0 in ei.value.dead_ranks
        assert "node lost" in str(ei.value.errors[0])
    finally:
        wg.shutdown()


def test_fit_retries_then_reraises_on_exhaustion(ray_start_regular):
    """fit() honors FailureConfig.max_failures: a deterministic rank-1
    failure is retried (gang teardown + rebuild) exactly max_failures
    times, then the last TrainWorkerGroupError is re-raised with the
    culprit rank attributed. GANG_FAILED / train_gang_retry /
    GANG_RESTARTED cluster events trace each attempt."""
    from ray_tpu._private import events
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.exceptions import TrainWorkerGroupError
    from ray_tpu.train import JaxTrainer

    def bad_on_rank1(config):
        from ray_tpu.air import session

        if session.get_world_rank() == 1:
            raise RuntimeError("chip fell out")
        session.report({"ok": 1})

    def count(kind):
        return sum(1 for e in events.snapshot() if e["kind"] == kind)

    base_failed = count("GANG_FAILED")
    base_restarted = count("GANG_RESTARTED")
    trainer = JaxTrainer(
        bad_on_rank1,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            failure_config=FailureConfig(max_failures=1)),
    )
    with pytest.raises(TrainWorkerGroupError) as ei:
        trainer.fit()
    assert "chip fell out" in str(ei.value)
    assert 1 in ei.value.errors
    assert count("GANG_FAILED") - base_failed == 2      # both attempts
    assert count("GANG_RESTARTED") - base_restarted == 1


def test_fit_max_failures_zero_keeps_result_semantics(ray_start_regular):
    """max_failures=0 (the default) opts out of gang restarts entirely:
    a worker failure comes back as Result.error, exactly the pre-FT
    contract."""
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import JaxTrainer

    def bad_loop(config):
        raise RuntimeError("train exploded")

    result = JaxTrainer(
        bad_loop,
        scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.error is not None
    assert "train exploded" in str(result.error)


# --------------------------------------------------------------- chaos E2E

@pytest.fixture
def ray_chaos_env():
    """ray_start_regular, plus a seeded fault schedule exported BEFORE
    init so every spawned cluster process inherits the fault plane."""
    import ray_tpu

    started = []

    def _start(seed, schedule):
        os.environ["RAY_TPU_FAULT_SEED"] = str(seed)
        os.environ["RAY_TPU_FAULT_SCHEDULE"] = schedule
        ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
        started.append(True)
        return ray_tpu

    yield _start
    if started:
        ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_FAULT_SEED", None)
    os.environ.pop("RAY_TPU_FAULT_SCHEDULE", None)


def _resumable_loop(config):
    from ray_tpu.air import Checkpoint, session
    from ray_tpu.util import collective as col

    start, total = 0, 0.0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        state = ckpt.to_dict()
        start = int(state["step"]) + 1
        total = float(state["total"])
    rank = session.get_world_rank()
    for step in range(start, STEPS):
        contrib = np.full(2, float((step + 1) * (rank + 1)))
        s = col.allreduce(contrib, GROUP)
        total += float(s[0])
        session.report({"step": step, "total": total},
                       checkpoint=Checkpoint.from_dict(
                           {"step": step, "total": total}))


@pytest.mark.chaos
@pytest.mark.fault_injection
def test_rank_death_checkpoint_resume(ray_chaos_env, tmp_path):
    """The tentpole, end to end and fully deterministic: rank 1's worker
    process is killed (os._exit via the seeded `kill_actor` schedule)
    while serving its 4th next_result — i.e. mid-training, after three
    checkpointed steps. The death must surface fast as a gang failure
    (no hang), fit() must tear down + rebuild the gang exactly once, and
    the resumed attempt must continue FROM THE CHECKPOINT (not step 0)
    to the bit-correct final total."""
    from ray_tpu._private import events
    from ray_tpu._private import telemetry as tm
    from ray_tpu.air.config import (CheckpointConfig, FailureConfig,
                                    RunConfig, ScalingConfig)
    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.backend_executor import JaxConfig

    ray = ray_chaos_env(7, "kill_actor:rank1.next_result:#4")

    def count(kind):
        return sum(1 for e in events.snapshot() if e["kind"] == kind)

    def restarts_metric():
        m = tm._metrics.get("ray_tpu_train_gang_restarts_total")
        if m is None:
            return 0.0
        return sum(v["value"] for v in m.snapshot()["values"]
                   if v["tags"].get("group") == GROUP)

    base_failed = count("GANG_FAILED")
    base_restarted = count("GANG_RESTARTED")
    base_metric = restarts_metric()
    t0 = time.monotonic()
    result = JaxTrainer(
        _resumable_loop,
        backend_config=JaxConfig(group_name=GROUP),
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name="gang_ft", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2),
            checkpoint_config=CheckpointConfig(num_to_keep=2)),
    ).fit()
    elapsed = time.monotonic() - t0
    # detection + teardown + rebuild + resume — nowhere near the 300s
    # collective op timeout a hang would burn
    assert elapsed < 120, f"gang restart took {elapsed:.0f}s"
    assert result.error is None, result.error
    # oracle: step s contributes (s+1)*(1+2) summed over all STEPS
    oracle = 3.0 * STEPS * (STEPS + 1) / 2
    assert result.metrics["total"] == oracle
    assert result.metrics["step"] == STEPS - 1
    # resumed from the step-2 checkpoint: the final attempt replayed
    # only the remaining step(s), not the whole run
    assert len(result.metrics_history) < STEPS
    assert count("GANG_FAILED") - base_failed == 1
    assert count("GANG_RESTARTED") - base_restarted == 1
    assert restarts_metric() - base_metric == 1.0
    # num_to_keep survives the restart: the resumed attempt's pruning
    # window is re-seeded from disk, so the failed attempt's dirs still
    # count against the budget instead of being stranded forever
    dirs = [d for d in os.listdir(tmp_path / "gang_ft")
            if d.startswith("checkpoint_")]
    assert len(dirs) <= 2, dirs


def _rank_cls(ray):
    @ray.remote
    class Rank:
        def configure(self, env):
            os.environ.update({k: str(v) for k, v in env.items()})
            return True

        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def epoch(self, name):
            from ray_tpu.util.collective.collective import _manager

            return _manager.get(name).epoch

        def allreduce(self, arr, name):
            from ray_tpu.util import collective as col

            return col.allreduce(arr, name)

        def destroy(self, name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(name)
            return True

        def inject_stale_frame(self, name, old_epoch, payload):
            """A late frame from a dead incarnation arrives after the
            group was rebuilt under the same name."""
            from ray_tpu._private.worker_runtime import current_worker

            w = current_worker()
            # key shape: (group, epoch, phase, seq, *step, src)
            w.col_push_local((name, old_epoch, "rs", 1, 0, 1), payload)
            return sorted(str(k) for k in w._col_mailbox
                          if k[0] == name)

        def stale_counter(self):
            from ray_tpu._private import telemetry as tm

            m = tm._metrics.get("ray_tpu_collective_stale_epoch_total")
            if m is None:
                return 0.0
            return sum(v["value"] for v in m.snapshot()["values"])

        def plant_stranded_shm(self, name, old_epoch):
            from ray_tpu._private.worker_runtime import (col_epoch_tag,
                                                         col_oid_prefix,
                                                         current_worker)

            w = current_worker()
            oid = col_oid_prefix(name) + col_epoch_tag(old_epoch) \
                + (1).to_bytes(2, "big") + b"\x00\x00\x00\x01"
            w.store.put_ephemeral(oid, [b"x" * 70000])
            return oid

        def store_has(self, oid):
            from ray_tpu._private.worker_runtime import current_worker

            return any(o == oid for o, _ in
                       current_worker().store.list_objects())

    return Rank


@pytest.mark.chaos
def test_surviving_rank_poison_latency(ray_start_regular):
    """A member death poisons the group: the surviving rank's pending
    collective op raises a named CollectiveGroupError naming the dead
    rank — well under the (deliberately huge) op timeout, instead of
    hanging out the watchdog."""
    ray = ray_start_regular
    from ray_tpu.exceptions import CollectiveGroupError

    name = "gft_poison"
    Rank = _rank_cls(ray)
    actors = [Rank.options(num_cpus=0).remote() for _ in range(2)]
    ray.get([a.configure.remote(
        {"RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "120"}) for a in actors])
    ray.get([a.join.remote(2, i, name)
             for i, a in enumerate(actors)], timeout=60)
    # rank 0 blocks in the op (rank 1 never participates), then rank 1
    # dies out from under it
    ref = actors[0].allreduce.remote(np.ones(4), name)
    time.sleep(1.0)
    t0 = time.monotonic()
    ray.kill(actors[1], no_restart=True)
    with pytest.raises(CollectiveGroupError) as ei:
        ray.get(ref, timeout=90)
    latency = time.monotonic() - t0
    assert latency < 30, f"poison took {latency:.1f}s (op timeout 120s)"
    assert 1 in ei.value.dead_ranks
    assert name in str(ei.value)
    ray.kill(actors[0], no_restart=True)


@pytest.mark.chaos
def test_stale_epoch_rejection_and_shm_sweep(ray_start_regular):
    """Incarnation-epoch fencing: a rebuilt group under the same name
    mints a strictly larger epoch; a frame stamped with the dead
    incarnation's epoch is rejected at ingest (never parked where it
    could masquerade as live traffic), the dead epoch's stranded shm
    segments are swept at rejoin, and the rebuilt group's results stay
    correct."""
    ray = ray_start_regular
    name = "gft_epoch"
    Rank = _rank_cls(ray)
    actors = [Rank.options(num_cpus=0).remote() for _ in range(2)]
    ray.get([a.configure.remote(
        {"RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "15"}) for a in actors])
    try:
        ray.get([a.join.remote(2, i, name)
                 for i, a in enumerate(actors)], timeout=60)
        e1 = ray.get(actors[0].epoch.remote(name))
        out = ray.get([a.allreduce.remote(np.ones(4) * (i + 1), name)
                       for i, a in enumerate(actors)], timeout=60)
        assert np.allclose(out[0], 3.0)

        # incarnation 1 dies: destroy, plant a stranded shm segment
        # tagged with the dead epoch, rebuild under the same name
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)
        oid = ray.get(actors[0].plant_stranded_shm.remote(name, e1))
        assert ray.get(actors[0].store_has.remote(oid))

        ray.get([a.join.remote(2, i, name)
                 for i, a in enumerate(actors)], timeout=60)
        e2 = ray.get(actors[0].epoch.remote(name))
        assert e2 > e1
        # rejoin swept the dead incarnation's stranded segment
        assert not ray.get(actors[0].store_has.remote(oid))

        # stale-epoch ingest rejection: nothing parked, counter bumped
        keys = ray.get(actors[0].inject_stale_frame.remote(
            name, e1, np.zeros(4)))
        assert keys == []
        assert ray.get(actors[0].stale_counter.remote()) >= 1

        # the rebuilt group still computes bit-correct results
        out = ray.get([a.allreduce.remote(np.ones(4) * (i + 2), name)
                       for i, a in enumerate(actors)], timeout=60)
        assert np.allclose(out[0], 5.0)
    finally:
        try:
            ray.get([a.destroy.remote(name) for a in actors], timeout=30)
        except Exception:
            pass
        for a in actors:
            try:
                ray.kill(a)
            except Exception:
                pass
