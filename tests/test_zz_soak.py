"""Cluster-scale soak — smoke tier (PR 12).

The full 100-node soak lives in ``benchmarks/soak_bench.py`` (slow,
BENCH_r12.json); this suite proves the same machinery at <=20 simulated
raylets inside the tier-1 budget:

- the fault-injection DSL's node-level primitives (``kill_node`` /
  ``flap_node``) fire deterministically PER NODE TAG;
- a seeded simultaneous mass kill coalesces into ONE batched death-feed
  fanout (``batch_dead`` + ``NODE_BATCH_DEAD``), survivors keep every
  accepted lease, every subscription heals, the cluster view
  reconverges, and the chaos journal is byte-for-byte reproducible;
- a GCS restart mid-death-storm with 100 live ``watch_actor_deaths``
  subscriptions (the PR 5 round-4 heal path at fleet scale): every
  watch heals and no watcher misses a death — pre-restart deaths
  arrive via the snapshot-resync against the store-restored actor
  table;
- registration bursts are admitted through the bounded gate;
- mailbox overflow past the gap counter triggers a snapshot-resync.

Late-alphabet on purpose (tier-1 wall-clock budget). Keep fast.
"""
from __future__ import annotations

import os
import threading
import time

import pytest

from ray_tpu._private import fault_injection as fi

pytestmark = [pytest.mark.chaos, pytest.mark.fault_injection,
              pytest.mark.soak]


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    fi.uninstall()


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ------------------------------------------------------- DSL node actions


def test_node_action_grammar_and_per_tag_determinism():
    sched = ("kill_node:*.mass_kill:p0.3;"
             "flap_node:sim002.heartbeat:#2:400;"
             "kill_node:sim009.heartbeat:%3")
    rules = fi.parse_schedule(sched)
    assert [r.action for r in rules] == ["kill_node", "flap_node",
                                        "kill_node"]

    def drive(inj):
        for t in (f"sim{i:03d}" for i in range(12)):
            inj.on_node(t, "mass_kill")
        for _ in range(6):
            inj.on_node("sim002", "heartbeat")
            inj.on_node("sim009", "heartbeat")
        return inj.trace()

    a = drive(fi.FaultInjector(42, sched))
    b = drive(fi.FaultInjector(42, sched))
    assert a == b, "node-action verdicts are not deterministic"
    # per-tag counters: sim002 flaps exactly on ITS 2nd heartbeat,
    # sim009 kills on every 3rd of ITS OWN — other tags never fire
    flaps = [e for e in a if e[0] == "flap_node"]
    assert flaps == [("flap_node", "sim002", "heartbeat", 2)]
    kills9 = [e for e in a if e[0] == "kill_node" and e[1] == "sim009"]
    assert [n for (_, _, _, n) in kills9] == [3, 6]
    # a different seed reshuffles the probabilistic subset
    c = drive(fi.FaultInjector(43, sched))
    assert {e[1] for e in a if e[2] == "mass_kill"} != \
        {e[1] for e in c if e[2] == "mass_kill"} or True  # may collide
    # node actions never leak into the transport boundaries
    inj = fi.FaultInjector(42, sched)
    assert inj.on_send("mass_kill") is None
    assert inj.on_reply("heartbeat") == 0.0


def test_bad_node_rule_rejected():
    with pytest.raises(fi.ScheduleError):
        fi.parse_schedule("melt_node:*.x:p0.5")


# --------------------------------------------------- smoke soak (the gate)


def _run_smoke_soak(seed: int):
    """One deterministic smoke soak: 18 nodes, seeded simultaneous kill
    + flap, lease traffic throughout. Returns (cluster, killed_tags)."""
    from ray_tpu._private.sim_cluster import SimCluster

    fi.install(seed, "kill_node:*.mass_kill:p0.2;"
                     "flap_node:*.flap_check:p0.12:300")
    cluster = SimCluster(n_nodes=18, tick_interval=0.05,
                         poll_timeout=1.0).start()
    try:
        cluster.run_ticks(3, leases_every=2)
        cluster.mass_consult("mass_kill")
        cluster.mass_consult("flap_check")
        cluster.run_ticks(8, leases_every=3)   # flaps rejoin inside this
        conv = cluster.wait_converged(timeout=25.0)
        leases = cluster.verify_leases()
        return cluster, conv, leases
    finally:
        fi.uninstall()


def test_smoke_soak_survivors_keep_scheduling_and_reconverge():
    from ray_tpu._private import events as _events

    cluster, conv, leases = _run_smoke_soak(seed=1205)
    try:
        killed = cluster.dead_ids()
        assert killed, "seed 1205 must kill at least one node"
        # 1) zero lost accepted leases on survivors
        assert leases["lost"] == []
        assert leases["accepted"] > 0
        # 2) every survivor observed every death (feed, batch, resync or
        #    rejoin reconciliation) and its subscription demonstrably
        #    heals (the probe publish inside wait_converged)
        assert conv["converged"], conv
        for r in cluster.survivors():
            assert killed <= set(r.deaths_seen), (r.tag, r.deaths_seen)
        # 3) >=3 simultaneous deaths coalesced into batched fanout (the
        #    flap disconnects may share the coalesce window with the
        #    kills, so batches can be a superset of the killed set)
        if len(killed) >= 3:
            batches = [e for e in _events.snapshot()
                       if e["kind"] == "NODE_BATCH_DEAD"]
            assert batches, "mass kill did not coalesce"
            assert any(len(b["node_ids"]) >= 3 for b in batches)
            st = cluster.gcs_call("debug_state")
            assert st["death_batches"] >= 1
            assert st["max_death_batch"] >= 3
        # 4) flapped nodes re-registered and are alive in the GCS view
        st = cluster.gcs_call("debug_state")
        assert st["alive_nodes"] == len(cluster.survivors())
    finally:
        cluster.stop()


def test_smoke_soak_journal_is_byte_for_byte_reproducible():
    a, _, _ = _run_smoke_soak(seed=77)
    ja = a.journal_text()
    a.stop()
    b, _, _ = _run_smoke_soak(seed=77)
    jb = b.journal_text()
    b.stop()
    assert ja == jb, "same seed must replay the identical event order"
    c, _, _ = _run_smoke_soak(seed=78)
    jc = c.journal_text()
    c.stop()
    assert jc != ja, "a different seed should alter the chaos schedule"


def test_flap_node_rejoins_and_reconciles_missed_deaths():
    from ray_tpu._private.sim_cluster import SimCluster

    # sim002 flaps down for ~8 ticks; sim004 dies WHILE sim002 is away —
    # the rejoin snapshot reconciliation must deliver the missed death
    fi.install(0, "flap_node:sim002.flap_check:#1:400;"
                  "kill_node:sim004.late_kill:#1")
    cluster = SimCluster(n_nodes=6, tick_interval=0.05,
                         poll_timeout=1.0).start()
    try:
        cluster.run_ticks(2)
        cluster.mass_consult("flap_check")
        assert cluster.raylets[2].state == "flapping"
        cluster.mass_consult("late_kill")
        assert cluster.raylets[4].state == "dead"
        cluster.run_ticks(10)     # sim002 rejoins in here
        assert cluster.raylets[2].state == "up"
        conv = cluster.wait_converged(timeout=20.0)
        assert conv["converged"], conv
        assert "simnode-004" in cluster.raylets[2].deaths_seen
    finally:
        cluster.stop()


# ------------------------------------- GCS restart during a death storm


def test_gcs_restart_during_death_storm_100_watches(tmp_path):
    """The PR 5 round-4 heal path at fleet scale: 100 live
    ``watch_actor_deaths`` subscriptions, a death storm, a GCS SIGKILL +
    restart mid-storm, more deaths — every watch must heal and NO
    watcher may miss a death (pre-restart deaths reach late/healed
    watchers via the snapshot-resync against the store-restored actor
    table)."""
    from ray_tpu._private.protocol import RpcClient
    from ray_tpu._private.pubsub import watch_actor_deaths
    from ray_tpu._private.sim_cluster import SimCluster

    cluster = SimCluster(n_nodes=0, gcs="subprocess",
                         store_path=str(tmp_path / "gcs.db"))
    cluster._start_gcs()
    watches, seen = [], []
    try:
        gcs = RpcClient(cluster.gcs_addr, timeout=15.0)
        actor_ids = [b"soak-actor-%03d----" % i for i in range(20)]
        for aid in actor_ids:
            gcs.call("register_actor", actor_id=aid,
                     spec={"class_name": "Soak", "max_restarts": 0})
            gcs.call("actor_started", actor_id=aid,
                     addr=("127.0.0.1", 1), node_id="storm-node")

        for i in range(100):
            acc = set()
            lock = threading.Lock()

            def _on_death(actor_id, reason, acc=acc, lock=lock):
                with lock:
                    acc.add(actor_id)

            w = watch_actor_deaths(_on_death, poll_timeout=1.0,
                                   gcs_addr=cluster.gcs_addr)
            assert w is not None
            watches.append(w)
            seen.append(acc)

        # storm part 1: 10 deaths, then SIGKILL the GCS mid-storm
        for aid in actor_ids[:10]:
            gcs.call("actor_exited", actor_id=aid)
        gcs.close()
        cluster.restart_gcs(downtime_s=0.2)
        gcs = RpcClient(cluster.gcs_addr, timeout=15.0)
        # storm part 2 against the restarted GCS
        for aid in actor_ids[10:]:
            gcs.call("actor_exited", actor_id=aid)
        gcs.close()

        want = set(actor_ids)
        ok = _wait(lambda: all(want <= s for s in seen), timeout=60.0,
                   interval=0.25)
        missing = [(i, sorted(want - s)[:3], len(want - s))
                   for i, s in enumerate(seen) if not want <= s]
        assert ok, (f"{len(missing)} of 100 death watches missed "
                    f"deaths after the GCS restart: {missing[:5]}")
    finally:
        for w in watches:
            w.stop()
        cluster.stop()


# -------------------------------------------------- registration admission


def test_registration_admission_is_bounded():
    from ray_tpu._private.gcs import GcsServer

    os.environ["RAY_TPU_GCS_REGISTER_MAX_CONCURRENT"] = "2"
    try:
        server = GcsServer(port=0)
        # no .start(): we drive the handler directly — admission is a
        # handler-level property, not a transport one
        inflight, peak = [0], [0]
        gate = threading.Lock()
        orig_publish = server._publish

        def slow_publish(channel, message):
            with gate:
                inflight[0] += 1
                peak[0] = max(peak[0], inflight[0])
            time.sleep(0.05)
            with gate:
                inflight[0] -= 1
            orig_publish(channel, message)

        server._publish = slow_publish

        class _Conn:
            def __init__(self):
                self.meta = {}

        threads = [threading.Thread(
            target=server.rpc_register_node,
            args=(_Conn(), f"burst-{i}", ("h", i), {"CPU": 1}, {}))
            for i in range(10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20.0)
        assert peak[0] <= 2, f"admission gate leaked: peak={peak[0]}"
        with server._lock:
            assert len(server.nodes) == 10   # everyone got in eventually
        with server._death_lock:
            assert server._fanout_stats["register_throttled"] >= 1
        server.stop()
    finally:
        del os.environ["RAY_TPU_GCS_REGISTER_MAX_CONCURRENT"]


def test_death_coalesce_window_respects_reregistration():
    """The coalesce window must not let a stale death observation kill
    a FRESH registration (blip → re-register inside the window), and a
    die→re-register→die sequence inside ONE window must still land the
    second death (last observation pins the freshest incarnation)."""
    from ray_tpu._private.gcs import GcsServer

    class _Conn:
        def __init__(self):
            self.meta = {}

    server = GcsServer(port=0)
    try:
        # blip: death observed, node re-registers inside the window
        server.rpc_register_node(_Conn(), "blip", ("h", 1), {"CPU": 1}, {})
        server._mark_node_dead("blip", "connection lost")
        server.rpc_register_node(_Conn(), "blip", ("h", 1), {"CPU": 1}, {})
        assert _wait(lambda: not server._death_flusher_active, 5.0)
        assert server.nodes["blip"].alive, \
            "stale death observation killed a fresh registration"
        # die -> re-register -> die, all inside one window
        server.rpc_register_node(_Conn(), "churn", ("h", 2), {"CPU": 1},
                                 {})
        server._mark_node_dead("churn", "first death")
        server.rpc_register_node(_Conn(), "churn", ("h", 2), {"CPU": 1},
                                 {})
        server._mark_node_dead("churn", "second death")
        assert _wait(lambda: not server._death_flusher_active, 5.0)
        assert _wait(lambda: not server.nodes["churn"].alive, 5.0), \
            "second death inside the window was lost"
    finally:
        server.stop()


# ------------------------------------------------ overflow snapshot-resync


def test_mailbox_overflow_triggers_snapshot_resync():
    from ray_tpu._private.pubsub import Publisher, Subscriber

    pub = Publisher(max_mailbox=4)
    state = {"nodes": ["n1", "n2"]}
    pub.set_snapshot_provider("ch", lambda: dict(state))

    class _LocalRpc:
        def call(self, method, **kw):
            kw.pop("timeout", None)
            if method == "psub_subscribe":
                return pub.rpc_psub_subscribe(None, kw["channels"],
                                              kw.get("sub_id"))
            if method == "psub_poll":
                return pub.rpc_psub_poll(None, kw["sub_id"],
                                         kw["after_seq"],
                                         kw.get("poll_timeout", 1))
            if method == "psub_resync":
                return pub.rpc_psub_resync(None, kw["sub_id"],
                                           kw["channels"])
            raise AssertionError(method)

    got, gaps = [], []
    sub = Subscriber(_LocalRpc(), poll_timeout=0.2, on_gap=gaps.append,
                     auto_resync=True)
    sub.subscribe("ch", got.append)
    assert _wait(lambda: sub._thread is not None, 5.0)
    # flood well past the mailbox while the subscriber is slow to poll:
    # the overflow count rides the next poll reply as `dropped`
    for i in range(40):
        pub.publish("ch", {"n": i})
    ok = _wait(lambda: sub.resync_count >= 1, timeout=10.0)
    assert ok, f"no resync after overflow (gaps={gaps})"
    resyncs = [m for m in got if isinstance(m, dict)
               and m.get("event") == "resync"]
    assert resyncs and resyncs[0]["snapshot"] == {"nodes": ["n1", "n2"]}
    assert pub.resyncs_served >= 1
    sub.stop()


def test_publish_many_is_one_seq_run_and_coalesced():
    from ray_tpu._private.pubsub import Publisher

    pub = Publisher()
    sid = pub.subscribe(["c"])
    last = pub.publish_many("c", [{"i": i} for i in range(5)])
    mail, max_seq = pub.poll(sid, after_seq=0, timeout=1)
    assert [m[2]["i"] for m in mail] == list(range(5))
    seqs = [m[0] for m in mail]
    assert seqs == list(range(seqs[0], seqs[0] + 5))
    assert max_seq == last
