"""ICI-topology-aware placement group scheduling.

The TPU-native extension of gcs_placement_group_scheduler.h (SURVEY §2.4
gang row, §7 phase 3): TPU gang bundles land on a contiguous block of hosts
inside ONE slice so the gang's collectives ride ICI, not DCN.
"""
import time

import pytest


def _pg_nodes(ray_tpu, pg):
    worker = ray_tpu._private.api._require_worker()
    snap = worker.gcs.call("get_placement_group", pg_id=pg.id)
    return snap["State"], snap["BundleNodes"]


@pytest.fixture
def two_slice_cluster(ray_start_cluster):
    """Fake 2-slice topology: slice s0 has hosts 0..3, slice s1 hosts 0..1.
    Each host: 4 TPU chips, 2 CPUs."""
    cluster = ray_start_cluster
    cluster.remove_node(cluster.head_node)
    cluster.head_node = cluster.add_node(num_cpus=2)   # driver-only, no TPU
    nodes = {}
    for wid in range(4):
        nodes[("s0", wid)] = cluster.add_node(
            num_cpus=2, num_tpus=4,
            tpu_topology={"slice_id": "s0", "worker_id": wid, "chips": 4})
    for wid in range(2):
        nodes[("s1", wid)] = cluster.add_node(
            num_cpus=2, num_tpus=4,
            tpu_topology={"slice_id": "s1", "worker_id": wid, "chips": 4})
    cluster.connect()
    import ray_tpu

    yield cluster, ray_tpu, nodes


def test_strict_pack_lands_on_contiguous_slice_hosts(two_slice_cluster):
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"TPU": 4}] * 3, strategy="STRICT_PACK")
    assert pg.wait(10)
    state, bundle_nodes = _pg_nodes(ray_tpu, pg)
    assert state == "CREATED"
    # all three bundles on slice s0 (only slice with >= 3 hosts), and the
    # chosen hosts form a contiguous worker_id run
    by_node = {nodes[k].node_id: k for k in nodes}
    placed = [by_node[n] for n in bundle_nodes]
    slices = {s for s, _ in placed}
    assert slices == {"s0"}, f"gang split across slices: {placed}"
    wids = sorted(w for _, w in placed)
    assert wids == list(range(min(wids), min(wids) + 3)), \
        f"hosts not contiguous: {wids}"


def test_gang_avoids_gap_from_busy_host(two_slice_cluster):
    """With a mid-slice host occupied, a 2-bundle gang must use a
    contiguous pair, never straddle the gap."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    # occupy s0 host 1 entirely
    blocker = placement_group([{"TPU": 4}], strategy="STRICT_PACK")
    assert blocker.wait(10)
    _, blocker_nodes = _pg_nodes(ray_tpu, blocker)
    by_node = {nodes[k].node_id: k for k in nodes}
    # (the blocker itself goes to the smallest contiguous window; wherever
    # it landed, the next gang must still be contiguous)
    gang = placement_group([{"TPU": 4}] * 2, strategy="STRICT_PACK")
    assert gang.wait(10)
    _, gang_nodes = _pg_nodes(ray_tpu, gang)
    placed = [by_node[n] for n in gang_nodes]
    assert len({s for s, _ in placed}) == 1
    wids = sorted(w for _, w in placed)
    assert wids[1] - wids[0] == 1, f"non-adjacent hosts: {placed}"
    remove_placement_group(blocker)
    remove_placement_group(gang)


def test_two_gangs_get_disjoint_slices(two_slice_cluster):
    """Two 2-host gangs coexist without sharing chips."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import placement_group

    a = placement_group([{"TPU": 4}] * 2, strategy="STRICT_PACK")
    b = placement_group([{"TPU": 4}] * 2, strategy="STRICT_PACK")
    assert a.wait(10) and b.wait(10)
    _, a_nodes = _pg_nodes(ray_tpu, a)
    _, b_nodes = _pg_nodes(ray_tpu, b)
    assert not (set(a_nodes) & set(b_nodes))


def test_tune_trials_gang_scheduled(ray_start_regular):
    """Every Tune trial runs inside its own placement group (reference:
    tune/execution/placement_groups.py)."""
    ray_tpu = ray_start_regular
    from ray_tpu import tune
    from ray_tpu.air import session

    seen_pgs = []

    def trainable(config):
        session.report({"score": config["x"] * 2})

    # snapshot PGs while trials run via a scheduler hook: simplest is to
    # check the PG table right after fit (trial PGs are removed at stop,
    # so instead count distinct PG creations via the GCS list during run)
    worker = ray_tpu._private.api._require_worker()

    import threading

    stop = threading.Event()

    def watch():
        while not stop.is_set():
            for snap in worker.gcs.call("list_placement_groups"):
                if snap["Name"].startswith("trial-"):
                    seen_pgs.append(snap["Name"])
            time.sleep(0.01)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    results = tune.run(trainable, config={"x": tune.grid_search([1, 2, 3])})
    stop.set()
    t.join(timeout=5)
    assert len(results) == 3
    assert results.get_best_result("score").metrics["score"] == 6
    assert len(set(seen_pgs)) == 3, f"expected 3 trial PGs, saw {set(seen_pgs)}"
