"""ICI-topology-aware placement group scheduling.

The TPU-native extension of gcs_placement_group_scheduler.h (SURVEY §2.4
gang row, §7 phase 3): TPU gang bundles land on a contiguous block of hosts
inside ONE slice so the gang's collectives ride ICI, not DCN.
"""
import time

import pytest


def _pg_nodes(ray_tpu, pg):
    worker = ray_tpu._private.api._require_worker()
    snap = worker.gcs.call("get_placement_group", pg_id=pg.id)
    return snap["State"], snap["BundleNodes"]


@pytest.fixture
def two_slice_cluster(ray_start_cluster):
    """Fake 2-slice topology: slice s0 has hosts 0..3, slice s1 hosts 0..1.
    Each host: 4 TPU chips, 2 CPUs."""
    cluster = ray_start_cluster
    cluster.remove_node(cluster.head_node)
    cluster.head_node = cluster.add_node(num_cpus=2)   # driver-only, no TPU
    nodes = {}
    for wid in range(4):
        nodes[("s0", wid)] = cluster.add_node(
            num_cpus=2, num_tpus=4,
            tpu_topology={"slice_id": "s0", "worker_id": wid, "chips": 4})
    for wid in range(2):
        nodes[("s1", wid)] = cluster.add_node(
            num_cpus=2, num_tpus=4,
            tpu_topology={"slice_id": "s1", "worker_id": wid, "chips": 4})
    cluster.connect()
    import ray_tpu

    yield cluster, ray_tpu, nodes


def test_strict_pack_lands_on_contiguous_slice_hosts(two_slice_cluster):
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"TPU": 4}] * 3, strategy="STRICT_PACK")
    assert pg.wait(10)
    state, bundle_nodes = _pg_nodes(ray_tpu, pg)
    assert state == "CREATED"
    # all three bundles on slice s0 (only slice with >= 3 hosts), and the
    # chosen hosts form a contiguous worker_id run
    by_node = {nodes[k].node_id: k for k in nodes}
    placed = [by_node[n] for n in bundle_nodes]
    slices = {s for s, _ in placed}
    assert slices == {"s0"}, f"gang split across slices: {placed}"
    wids = sorted(w for _, w in placed)
    assert wids == list(range(min(wids), min(wids) + 3)), \
        f"hosts not contiguous: {wids}"


def test_gang_avoids_gap_from_busy_host(two_slice_cluster):
    """With a mid-slice host occupied, a 2-bundle gang must use a
    contiguous pair, never straddle the gap."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    # occupy s0 host 1 entirely
    blocker = placement_group([{"TPU": 4}], strategy="STRICT_PACK")
    assert blocker.wait(10)
    _, blocker_nodes = _pg_nodes(ray_tpu, blocker)
    by_node = {nodes[k].node_id: k for k in nodes}
    # (the blocker itself goes to the smallest contiguous window; wherever
    # it landed, the next gang must still be contiguous)
    gang = placement_group([{"TPU": 4}] * 2, strategy="STRICT_PACK")
    assert gang.wait(10)
    _, gang_nodes = _pg_nodes(ray_tpu, gang)
    placed = [by_node[n] for n in gang_nodes]
    assert len({s for s, _ in placed}) == 1
    wids = sorted(w for _, w in placed)
    assert wids[1] - wids[0] == 1, f"non-adjacent hosts: {placed}"
    remove_placement_group(blocker)
    remove_placement_group(gang)


def test_two_gangs_get_disjoint_slices(two_slice_cluster):
    """Two 2-host gangs coexist without sharing chips."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import placement_group

    a = placement_group([{"TPU": 4}] * 2, strategy="STRICT_PACK")
    b = placement_group([{"TPU": 4}] * 2, strategy="STRICT_PACK")
    assert a.wait(10) and b.wait(10)
    _, a_nodes = _pg_nodes(ray_tpu, a)
    _, b_nodes = _pg_nodes(ray_tpu, b)
    assert not (set(a_nodes) & set(b_nodes))


# ------------------------------------------- SPREAD_ACROSS_SLICES edges

def test_spread_across_slices_distinct_slices_contiguous(two_slice_cluster):
    """Each stage's sub-gang lands contiguous inside ONE slice; distinct
    stages land on distinct slices."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"TPU": 4}] * 4,
                         strategy="SPREAD_ACROSS_SLICES",
                         bundle_stages=[0, 0, 0, 1])
    assert pg.wait(10)
    state, bundle_nodes = _pg_nodes(ray_tpu, pg)
    assert state == "CREATED"
    by_node = {nodes[k].node_id: k for k in nodes}
    placed = [by_node[n] for n in bundle_nodes]
    s0_slices = {s for s, _ in placed[:3]}
    assert len(s0_slices) == 1, f"stage 0 split across slices: {placed}"
    assert placed[3][0] not in s0_slices, f"stages share a slice: {placed}"
    # stage 0 needs 3 hosts: only s0 has them, so stage 1 best-fits s1
    assert s0_slices == {"s0"} and placed[3][0] == "s1", placed
    wids = sorted(w for _, w in placed[:3])
    assert wids == list(range(min(wids), min(wids) + 3)), \
        f"stage 0 hosts not contiguous: {wids}"


def test_spread_across_slices_pending_whole_when_short(two_slice_cluster):
    """Fewer slices than stages: the gang stays PENDING with NO bundle
    placed (all-or-nothing), and becomes CREATED the moment a slice
    appears."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"TPU": 4}] * 3,
                         strategy="SPREAD_ACROSS_SLICES",
                         bundle_stages=[0, 1, 2])   # 3 stages, 2 slices
    assert not pg.wait(2)
    state, bundle_nodes = _pg_nodes(ray_tpu, pg)
    assert state == "PENDING"
    assert all(n is None for n in bundle_nodes), \
        f"partial placement of an unplaceable gang: {bundle_nodes}"
    cluster.add_node(num_cpus=2, num_tpus=4,
                     tpu_topology={"slice_id": "s2", "worker_id": 0,
                                   "chips": 4})
    assert pg.wait(15), "gang should place once a third slice registers"
    state, bundle_nodes = _pg_nodes(ray_tpu, pg)
    assert state == "CREATED" and all(bundle_nodes)


def test_spread_across_slices_default_stage_per_bundle(two_slice_cluster):
    """No stage labels: every bundle is its own stage — classic
    one-bundle-per-slice spread."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"TPU": 4}] * 2,
                         strategy="SPREAD_ACROSS_SLICES")
    assert pg.wait(10)
    _, bundle_nodes = _pg_nodes(ray_tpu, pg)
    by_node = {nodes[k].node_id: k for k in nodes}
    slices = [by_node[n][0] for n in bundle_nodes]
    assert len(set(slices)) == 2, f"bundles share a slice: {slices}"


def test_spread_across_slices_quota_blocked_whole(two_slice_cluster):
    """Multi-tenant interplay: an over-quota multi-slice gang stays
    PENDING all-or-nothing (no bundle placed, no slice reserved), and
    places whole the moment the quota is raised."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu.util import jobs
    from ray_tpu.util.placement_group import placement_group

    jobs.register_job("mpmd", quota={"TPU": 4.0})
    pg = placement_group([{"TPU": 4}] * 2,
                         strategy="SPREAD_ACROSS_SLICES",
                         bundle_stages=[0, 1], job="mpmd")
    assert not pg.wait(2)
    state, bundle_nodes = _pg_nodes(ray_tpu, pg)
    assert state == "PENDING"
    assert all(n is None for n in bundle_nodes), \
        f"quota-blocked gang partially placed: {bundle_nodes}"
    job = jobs.get_job("mpmd")
    assert job["QuotaRejections"] >= 1
    jobs.update_job("mpmd", quota={"TPU": 8.0})
    assert pg.wait(10), "raised quota should unblock the whole gang"
    state, bundle_nodes = _pg_nodes(ray_tpu, pg)
    assert state == "CREATED" and all(bundle_nodes)


def test_spread_slice_infeasible_high_pri_neither_preempts_nor_blocks(
        two_slice_cluster):
    """A high-priority SPREAD_ACROSS_SLICES gang with more STAGES than
    the cluster has SLICES is structurally infeasible even though its
    resource sums fit: it must not preempt checkpointed victims (the
    freed bundles cannot add a third slice) and must not raise the
    priority barrier that would starve lower-priority tenants."""
    cluster, ray_tpu, nodes = two_slice_cluster
    from ray_tpu._private import events
    from ray_tpu.util import jobs
    from ray_tpu.util.placement_group import placement_group

    jobs.register_job("low", priority=0)
    jobs.register_job("high", priority=10)
    victim = placement_group([{"TPU": 4}] * 2, strategy="STRICT_PACK",
                             job="low")
    assert victim.wait(10)
    base_warned = sum(1 for e in events.snapshot()
                      if e["kind"] == "PREEMPTION_WARNED")
    # 3 stages, 2 slices: resource totals fit, slices don't
    infeasible = placement_group([{"TPU": 4}] * 3,
                                 strategy="SPREAD_ACROSS_SLICES",
                                 bundle_stages=[0, 1, 2], job="high")
    assert not infeasible.wait(3)
    assert sum(1 for e in events.snapshot()
               if e["kind"] == "PREEMPTION_WARNED") == base_warned, \
        "slice-infeasible gang fired preemption warnings"
    # no priority barrier: a lower-priority gang still places
    low2 = placement_group([{"TPU": 4}], strategy="PACK", job="low")
    assert low2.wait(10), "infeasible high-pri gang starved the tenant"
    state, _ = _pg_nodes(ray_tpu, victim)
    assert state == "CREATED", "victim was torn down for nothing"


def test_spread_across_slices_validation(ray_start_regular):
    """bundle_stages must label every bundle; unknown strategies still
    raise at the call site."""
    import pytest as _pytest

    from ray_tpu.util.placement_group import placement_group

    with _pytest.raises(ValueError, match="bundle_stages"):
        placement_group([{"CPU": 1}] * 3, strategy="SPREAD_ACROSS_SLICES",
                        bundle_stages=[0, 1])
    with _pytest.raises(ValueError, match="strategy"):
        placement_group([{"CPU": 1}], strategy="SPREAD_SLICES")


def test_tune_trials_gang_scheduled(ray_start_regular):
    """Every Tune trial runs inside its own placement group (reference:
    tune/execution/placement_groups.py)."""
    ray_tpu = ray_start_regular
    from ray_tpu import tune
    from ray_tpu.air import session

    seen_pgs = []

    def trainable(config):
        session.report({"score": config["x"] * 2})

    # snapshot PGs while trials run via a scheduler hook: simplest is to
    # check the PG table right after fit (trial PGs are removed at stop,
    # so instead count distinct PG creations via the GCS list during run)
    worker = ray_tpu._private.api._require_worker()

    import threading

    stop = threading.Event()

    def watch():
        while not stop.is_set():
            for snap in worker.gcs.call("list_placement_groups"):
                if snap["Name"].startswith("trial-"):
                    seen_pgs.append(snap["Name"])
            time.sleep(0.01)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    results = tune.run(trainable, config={"x": tune.grid_search([1, 2, 3])})
    stop.set()
    t.join(timeout=5)
    assert len(results) == 3
    assert results.get_best_result("score").metrics["score"] == 6
    assert len(set(seen_pgs)) == 3, f"expected 3 trial PGs, saw {set(seen_pgs)}"
