"""Workflow tests: durable execution, kill-driver resume, continuations.

Reference tier: python/ray/workflow/tests/ (test_basic_workflows,
test_recovery). The kill test runs a workflow in a SEPARATE driver process,
SIGKILLs it mid-step, then resumes from the shared storage in this process
and checks the completed prefix did not re-execute.
"""
import os
import signal
import subprocess
import sys
import time

import pytest


@pytest.fixture
def wf_env(tmp_path, ray_start_regular):
    import ray_tpu

    yield ray_start_regular, str(tmp_path / "wf_storage"), str(tmp_path)


def test_linear_and_diamond_dag(wf_env):
    ray_tpu, storage, _ = wf_env
    from ray_tpu import workflow

    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    # diamond: d = (x+1) * (x+2) with shared source
    src = add.bind(1, 2)                       # 3
    left = add.bind(src, 1)                    # 4
    right = add.bind(src, 2)                   # 5
    out = mul.bind(left, right)                # 20
    result = workflow.run(out, workflow_id="diamond", storage_dir=storage)
    assert result == 20
    assert workflow.get_status("diamond", storage_dir=storage) == "SUCCEEDED"
    assert workflow.get_output("diamond", storage_dir=storage) == 20
    assert ("diamond", "SUCCEEDED") in workflow.list_all(storage_dir=storage)


def test_failure_then_resume_skips_done_steps(wf_env):
    ray_tpu, storage, scratch = wf_env
    from ray_tpu import workflow

    gate = os.path.join(scratch, "gate")
    counts = os.path.join(scratch, "counts")

    @ray_tpu.remote(max_retries=0)
    def tracked(x):
        with open(counts, "a") as f:
            f.write(f"tracked:{x}\n")
        return x * 10

    @ray_tpu.remote(max_retries=0)
    def gated(a, b):
        if not os.path.exists(gate):
            raise RuntimeError("gate closed")
        return a + b

    dag = gated.bind(tracked.bind(1), tracked.bind(2))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="gated", storage_dir=storage)
    assert workflow.get_status("gated", storage_dir=storage) == "FAILED"
    # both tracked steps persisted their results before the failure
    runs = open(counts).read().count("tracked")
    assert runs == 2
    open(gate, "w").close()
    result = workflow.resume("gated", storage_dir=storage)
    assert result == 30
    # resume did NOT re-execute the completed steps
    assert open(counts).read().count("tracked") == 2


def test_continuation_expands(wf_env):
    ray_tpu, storage, _ = wf_env
    from ray_tpu import workflow

    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def fan(x):
        from ray_tpu import workflow as wf

        # dynamic: decide the next stage at runtime
        return wf.continuation(double.bind(x + 1))

    result = workflow.run(fan.bind(10), workflow_id="cont",
                          storage_dir=storage)
    assert result == 22


def test_kill_driver_then_resume(tmp_path):
    """The done-criterion test from the round brief: SIGKILL the driver
    mid-workflow, resume, identical result."""
    storage = str(tmp_path / "wf")
    counts = str(tmp_path / "counts")
    block = str(tmp_path / "block")
    open(block, "w").close()

    driver = f"""
import os, sys
sys.path.insert(0, {os.getcwd()!r})
os.environ.setdefault("RAY_TPU_TESTING", "1")
import ray_tpu
from ray_tpu import workflow

@ray_tpu.remote(max_retries=0)
def step_a():
    with open({counts!r}, "a") as f:
        f.write("a\\n")
    return 5

@ray_tpu.remote(max_retries=0)
def step_b(x):
    # signal readiness, then block until killed
    import time
    with open({counts!r}, "a") as f:
        f.write("b-started\\n")
    while os.path.exists({block!r}):
        time.sleep(0.1)
    return x + 1

ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
workflow.run(step_b.bind(step_a.bind()), workflow_id="killed",
             storage_dir={storage!r})
"""
    proc = subprocess.Popen([sys.executable, "-c", driver],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)
    deadline = time.time() + 90
    while time.time() < deadline:
        if os.path.exists(counts) and \
                "b-started" in open(counts).read():
            break
        if proc.poll() is not None:
            raise AssertionError("driver exited early")
        time.sleep(0.2)
    else:
        proc.kill()
        raise AssertionError("driver never reached step_b")
    # SIGKILL the whole driver session (driver + its local cluster workers)
    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    proc.wait(timeout=30)

    os.unlink(block)   # unblock step_b for the resume
    import ray_tpu
    from ray_tpu import workflow

    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        result = workflow.resume("killed", storage_dir=storage)
        assert result == 6
        # step_a ran exactly once: its result was persisted pre-kill
        assert open(counts).read().count("a\n") == 1
        assert workflow.get_status("killed", storage_dir=storage) == \
            "SUCCEEDED"
    finally:
        ray_tpu.shutdown()
