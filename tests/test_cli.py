"""CLI + standalone node processes + state API.

Reference tier: `ray start/stop/status` smoke tests. The done-criterion
from the round brief: a two-process cluster stood up from the shell, tasks
run against it, state inspected, clean stop.
"""
import json
import os
import subprocess
import sys
import time

import pytest


def _cli(*args, timeout=90):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", *args],
        capture_output=True, text=True, timeout=timeout,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


@pytest.fixture
def shell_cluster():
    out = _cli("start", "--head", "--num-cpus", "2",
               "--object-store-memory", str(64 * 1024 * 1024))
    assert out.returncode == 0, out.stderr
    address = [line for line in out.stdout.splitlines()
               if line.startswith("GCS address:")][0].split(": ")[1]
    out2 = _cli("start", "--address", address, "--num-cpus", "2",
                "--resources", json.dumps({"side": 1}),
                "--object-store-memory", str(64 * 1024 * 1024))
    assert out2.returncode == 0, out2.stderr
    yield address
    _cli("stop")


def test_shell_cluster_end_to_end(shell_cluster):
    address = shell_cluster
    # status sees both nodes
    out = _cli("status", "--address", address)
    assert out.returncode == 0, out.stderr
    assert "Nodes: 2 alive" in out.stdout
    # run real tasks against the shell-started cluster from a driver
    import ray_tpu

    ray_tpu.init(address=address)
    try:
        @ray_tpu.remote(num_cpus=0, resources={"side": 0.5})
        def on_worker_node():
            return "remote-ok"

        @ray_tpu.remote
        def anywhere(x):
            return x * 2

        assert ray_tpu.get(on_worker_node.remote(), timeout=60) == "remote-ok"
        assert ray_tpu.get(anywhere.remote(21), timeout=60) == 42
        # state API over the live cluster
        from ray_tpu.experimental.state import api as state

        nodes = state.list_nodes()
        assert sum(1 for n in nodes if n["Alive"]) == 2
        workers = state.list_workers()
        assert len(workers) >= 1
    finally:
        ray_tpu.shutdown()
    # CLI list commands (standalone, via address)
    out = _cli("list", "nodes", "--address", address)
    assert out.returncode == 0 and json.loads(out.stdout)
    out = _cli("memory", "--address", address)
    assert out.returncode == 0
    assert "Object store" in out.stdout


def test_stop_kills_nodes(shell_cluster):
    address = shell_cluster
    out = _cli("stop")
    assert out.returncode == 0
    # GCS is gone: status against the dead address fails or shows nothing
    deadline = time.time() + 10
    dead = False
    while time.time() < deadline:
        out = _cli("status", "--address", address)
        if out.returncode != 0 or "0 alive" in out.stdout:
            dead = True
            break
        time.sleep(0.3)
    assert dead, "cluster still answering after stop"


def test_state_api_in_process(ray_start_regular):
    ray_tpu = ray_start_regular
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return 1

    a = Pinger.remote()
    assert ray_tpu.get(a.ping.remote()) == 1
    actors = state.list_actors()
    assert any(x["State"] == "ALIVE" for x in actors)
    assert state.list_nodes()
    ref = ray_tpu.put(list(range(100000)))   # force a store object
    objs = state.list_objects()
    del ref
    assert isinstance(objs, list)
    summary = state.cluster_status()
    assert "Nodes: 1 alive" in summary


def test_microbenchmark_smoke(ray_start_regular):
    from ray_tpu._private.ray_perf import main as perf_main

    results = perf_main(min_time=0.05)
    assert results["single client tasks sync"] > 0
    assert results["single client actor calls sync"] > 0
