"""Job submission + runtime env tests.

Reference tier: dashboard/modules/job/tests (submit/status/logs/stop) and
runtime_env working_dir tests.
"""
import sys
import time

import pytest


@pytest.fixture
def job_client(ray_start_regular):
    from ray_tpu.job_submission import JobSubmissionClient

    yield JobSubmissionClient()


def test_submit_and_logs(job_client, tmp_path):
    out = tmp_path / "out.txt"
    sid = job_client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('hello-job'); "
                   f"open({str(out)!r}, 'w').write('done')\"")
    status = job_client.wait_until_finish(sid, timeout=60)
    assert status == "SUCCEEDED"
    assert "hello-job" in job_client.get_job_logs(sid)
    assert out.read_text() == "done"


def test_env_vars_and_working_dir(job_client, tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mymod.py").write_text("VALUE = 'from-working-dir'\n")
    (pkg / "main.py").write_text(
        "import os, mymod\n"
        "print('mod:', mymod.VALUE)\n"
        "print('env:', os.environ['JOB_FLAVOR'])\n")
    sid = job_client.submit_job(
        entrypoint=f"{sys.executable} main.py",
        runtime_env={"working_dir": str(pkg),
                     "env_vars": {"JOB_FLAVOR": "tpu"}})
    assert job_client.wait_until_finish(sid, timeout=60) == "SUCCEEDED"
    logs = job_client.get_job_logs(sid)
    assert "mod: from-working-dir" in logs
    assert "env: tpu" in logs


def test_failed_job_status(job_client):
    sid = job_client.submit_job(
        entrypoint=f"{sys.executable} -c \"raise SystemExit(3)\"")
    assert job_client.wait_until_finish(sid, timeout=60) == "FAILED"
    assert "[job exited rc=3]" in job_client.get_job_logs(sid)


def test_stop_running_job(job_client):
    sid = job_client.submit_job(
        entrypoint=f"{sys.executable} -c \"import time; time.sleep(600)\"")
    deadline = time.time() + 30
    while time.time() < deadline:
        if job_client.get_job_status(sid) == "RUNNING":
            break
        time.sleep(0.1)
    assert job_client.get_job_status(sid) == "RUNNING"
    job_client.stop_job(sid)
    assert job_client.wait_until_finish(sid, timeout=30) == "STOPPED"


def test_list_jobs(job_client):
    sid = job_client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('x')\"",
        submission_id="listed-job")
    job_client.wait_until_finish(sid, timeout=60)
    jobs = job_client.list_jobs()
    assert any(j["submission_id"] == "listed-job"
               and j["status"] == "SUCCEEDED" for j in jobs)


def test_package_roundtrip(tmp_path):
    from ray_tpu._private.runtime_env import package_working_dir

    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.py").write_text("A = 1")
    (src / "sub" / "b.py").write_text("B = 2")
    (src / "__pycache__").mkdir()
    (src / "__pycache__" / "junk.pyc").write_text("x")
    key1, blob1 = package_working_dir(str(src))
    key2, blob2 = package_working_dir(str(src))
    assert key1 == key2 and blob1 == blob2   # deterministic
    import io
    import zipfile

    names = zipfile.ZipFile(io.BytesIO(blob1)).namelist()
    assert "a.py" in names and "sub/b.py" in names
    assert not any("__pycache__" in n for n in names)
