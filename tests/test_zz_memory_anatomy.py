"""Memory anatomy — provenance ledger, leak attribution, train-state
accounting (late-alphabet; the gang tests cost seconds each).

Covers the PR 18 tentpole end to end:

- category attribution oracle per call site: every put is stamped
  task_arg / task_return / collective_segment / serve_weights /
  data_staging (thread-local tag at the call site, oid-layout fallback
  for untagged collective/staging ids) and the category gauges balance
  to zero after delete;
- the leak sweep: referenced vs orphaned classification (pins, grace,
  dead owner pid, destroyed group, stale epoch, poisoned gang), one
  STORE_LEAK per orphan oid with full provenance;
- chaos acceptance: a seeded dropped shm notify strands a segment, the
  putter rank is killed, and the SURVIVOR's sweep emits exactly one
  STORE_LEAK naming the dead owner's group/rank/category;
- per-rank train-state gauges equal the deterministic flatten's byte
  sum EXACTLY on a live 2-rank gang (grads + bucket_inflight draining
  to zero), and params/opt_state from make_train_state;
- the kill switch (RAY_TPU_INTERNAL_TELEMETRY=0) disables every hook;
- the put/get hot path pays <5% instrumentation overhead (separated
  measurement — see test_zz_collective_telemetry's guard for why a
  direct A/B wall-clock ratio would drown in machine noise).
"""
import os
import time

import numpy as np
import pytest


def _fresh_ledger(monkeypatch):
    """An isolated Ledger so suite-global traffic (the driver runtime's
    own puts) can't bleed into category assertions."""
    from ray_tpu._private import memory_anatomy as ma

    led = ma.Ledger()
    monkeypatch.setattr(ma, "LEDGER", led)
    return ma, led


def _col_oid(group, epoch, rank, counter=1):
    from ray_tpu._private.worker_runtime import col_epoch_tag, col_oid_prefix

    return (col_oid_prefix(group) + col_epoch_tag(epoch)
            + int(rank).to_bytes(2, "big")
            + int(counter).to_bytes(4, "big"))


# ------------------------------------------------------ category oracle


def test_category_attribution_per_call_site(monkeypatch):
    """Unit oracle over the tagging plane: each call site's tag (or the
    oid-layout fallback) lands the put in the right category, and
    deletes return the gauges to zero."""
    ma, led = _fresh_ledger(monkeypatch)

    sites = [
        # (expected category, tag ctx or None, oid)
        ("task_arg", ma.default_tag("task_arg", owner="w1"),
         b"T" * 16),
        ("task_return", ma.default_tag("task_return", owner="t1"),
         b"R" * 16),
        ("serve_weights", ma.tagged("serve_weights", group="m:v1"),
         b"S" * 16),
        ("data_staging", ma.tagged("data_staging", owner="train"),
         b"dstrm" + b"\x00" * 11),
        ("collective_segment",
         ma.tagged("collective_segment", group="g", epoch=3, rank=1),
         _col_oid("g", 3, 1)),
        # untagged fallbacks classify from the oid layout alone
        ("collective_segment", None, _col_oid("h", 9, 0)),
        ("data_staging", None, b"dstrm" + b"\x01" * 11),
        ("other", None, b"\x00" * 16),
    ]
    for i, (cat, ctx, oid) in enumerate(sites):
        nbytes = 100 * (i + 1)
        if ctx is None:
            led.note_put(oid, nbytes)
        else:
            with ctx:
                led.note_put(oid, nbytes)
        rec = led._live[oid]
        assert rec.category == cat, (cat, rec.category)
        assert rec.nbytes == nbytes

    snap = led.snapshot()
    assert snap["categories"]["collective_segment"]["objects"] == 2
    assert snap["categories"]["data_staging"]["objects"] == 2
    assert snap["categories"]["task_arg"]["bytes"] == 100
    # untagged collective id: epoch + rank recovered from the oid itself
    rec = led._live[_col_oid("h", 9, 0)]
    assert rec.epoch == 9 and rec.rank == 0
    # tagged provenance beats the fallback
    rec = led._live[_col_oid("g", 3, 1)]
    assert rec.group == "g" and rec.epoch == 3 and rec.rank == 1

    for _, _, oid in sites:
        led.note_delete(oid)
    snap = led.snapshot()
    assert snap["live_objects"] == 0 and snap["live_bytes"] == 0


def test_default_tag_yields_to_outer_tag(monkeypatch):
    """The worker's task_arg/task_return default tagging must not
    clobber a caller-provided category (e.g. a checkpoint writer that
    puts through a task argument path)."""
    ma, led = _fresh_ledger(monkeypatch)
    with ma.tagged("checkpoint", owner="ckpt-7"):
        with ma.default_tag("task_arg", owner="w"):
            led.note_put(b"C" * 16, 64)
    rec = led._live[b"C" * 16]
    assert rec.category == "checkpoint"
    assert rec.owner == "ckpt-7"
    # and with no outer tag the default applies
    with ma.default_tag("task_arg", owner="w"):
        led.note_put(b"D" * 16, 64)
    assert led._live[b"D" * 16].category == "task_arg"


def test_store_client_call_sites_attribute(ray_start_regular,
                                           monkeypatch):
    """E2E attribution through the real call sites: a driver-side
    ``put`` lands in task_arg (raw args ride the task spec and never
    hit the store); the executor's oversized return lands in
    task_return IN ITS OWN process ledger, visible via the
    summarize_memory fan-out; serve shared weights land in
    serve_weights."""
    ray = ray_start_regular
    from ray_tpu._private import memory_anatomy as ma
    from ray_tpu.experimental.state.api import summarize_memory

    base = ma.LEDGER.snapshot()

    @ray.remote
    def echo(x):
        return np.asarray(x) * 2

    arg = np.arange(50_000, dtype=np.float64)   # > inline threshold
    ref = ray.put(arg)
    out_ref = echo.remote(ref)
    out = ray.get(out_ref, timeout=60)
    assert np.array_equal(out, arg * 2)

    snap = ma.LEDGER.snapshot()

    def grew(cat):
        b0 = (base["categories"].get(cat) or {}).get("bytes", 0)
        return (snap["categories"].get(cat) or {}).get("bytes", 0) > b0 \
            or any(r["op"].startswith("put") and r["category"] == cat
                   for r in snap["ring"])

    assert grew("task_arg")
    # the 400KB return was stored by the EXECUTOR process under
    # task_return; the cluster rollup reaches that process's ledger
    # (out_ref stays referenced so ref-zero can't free it first)
    roll = summarize_memory()
    assert (roll["categories"].get("task_return")
            or {}).get("bytes", 0) > 0, roll["categories"]
    del out_ref

    # serve weights: the driver-side shared_weights publish is tagged
    from ray_tpu.serve._private.weights import (
        release_shared_weights,
        shared_weights,
    )

    key = "zzma:model:v1"
    w = shared_weights(key, lambda: {"w": np.ones(30_000, np.float32)})
    assert np.asarray(w["w"]).shape == (30_000,)
    snap2 = ma.LEDGER.snapshot()
    assert any(r["category"] == "serve_weights"
               for r in snap2["ring"]), "serve_weights put not tagged"
    release_shared_weights(key, delete=True)


# ------------------------------------------------------------ leak sweep


class _FakeStore:
    def __init__(self, objs):
        self.objs = dict(objs)

    def list_objects(self):
        return list(self.objs.items())


def test_sweep_classifies_referenced_vs_orphaned(monkeypatch):
    ma, led = _fresh_ledger(monkeypatch)
    live_oid = b"L" * 16
    pinned_oid = b"P" * 16
    dead_oid = b"X" * 16
    led.note_put(live_oid, 10)
    led.note_put(pinned_oid, 20)
    led.note_pin(pinned_oid)
    # a record whose creator pid is dead (pid 2**22+9999 can't exist
    # under default pid_max)
    led.note_put(dead_oid, 30, pid=(1 << 22) + 9999)
    store = _FakeStore({live_oid: 10, pinned_oid: 20, dead_oid: 30})
    orphans = led.sweep(store, grace_s=0.0)
    reasons = {r["oid"]: r["reason"] for r in orphans}
    assert reasons == {dead_oid.hex(): "owner_dead"}
    # grace spares a just-created object even with a dead owner
    fresh = b"F" * 16
    led.note_put(fresh, 5, pid=(1 << 22) + 9998)
    store.objs[fresh] = 5
    assert all(r["oid"] != fresh.hex()
               for r in led.sweep(store, grace_s=60.0))
    # deletion by ANOTHER process (object gone from the store) prunes
    # the record and clears the leak latch
    del store.objs[dead_oid]
    led.sweep(store, grace_s=0.0)
    assert dead_oid not in led._live
    assert dead_oid not in led._leaked


def test_sweep_group_destroyed_epoch_stale_and_poisoned(monkeypatch):
    ma, led = _fresh_ledger(monkeypatch)
    ok = _col_oid("alive", 4, 0)
    stale = _col_oid("alive", 3, 1)
    gone = _col_oid("deadgrp", 1, 0)
    foreign = _col_oid("poisoned", 2, 1, counter=7)
    with ma.tagged("collective_segment", group="alive", epoch=4, rank=0):
        led.note_put(ok, 100)
    with ma.tagged("collective_segment", group="alive", epoch=3, rank=1):
        led.note_put(stale, 100)
    with ma.tagged("collective_segment", group="deadgrp", epoch=1,
                   rank=0):
        led.note_put(gone, 100)
    store = _FakeStore({ok: 100, stale: 100, gone: 100, foreign: 100})
    orphans = led.sweep(store, known_groups={"alive": 4},
                        poisoned={"poisoned": (1,)}, grace_s=0.0)
    by_oid = {r["oid"]: r for r in orphans}
    assert ok.hex() not in by_oid
    assert by_oid[stale.hex()]["reason"] == "epoch_stale"
    assert by_oid[gone.hex()]["reason"] == "group_destroyed"
    # the foreign segment (put by a process this ledger never saw) of a
    # poisoned gang classifies owner_dead, named by group + dead rank
    row = by_oid[foreign.hex()]
    assert row["reason"] == "owner_dead"
    assert row["group"] == "poisoned"
    assert row["rank"] == 1 and row["dead_ranks"] == [1]
    # STORE_LEAK is once-per-oid: a second sweep emits no new events
    from ray_tpu._private import events

    before = sum(1 for e in events.snapshot()
                 if e.get("kind") == "STORE_LEAK")
    led.sweep(store, known_groups={"alive": 4},
              poisoned={"poisoned": (1,)}, grace_s=0.0)
    after = sum(1 for e in events.snapshot()
                if e.get("kind") == "STORE_LEAK")
    assert after == before


def test_store_leak_event_payload_names_creator(monkeypatch):
    """The event payload carries the CREATOR's identity under owner_*
    (pid/node are envelope keys stamped with the SWEEPER's identity)."""
    ma, led = _fresh_ledger(monkeypatch)
    from ray_tpu._private import events

    oid = b"E" * 16
    with ma.tagged("serve_weights", group="m:v2"):
        led.note_put(oid, 77, pid=(1 << 22) + 9997)
    led.sweep(_FakeStore({oid: 77}), grace_s=0.0)
    leaks = [e for e in events.snapshot()
             if e.get("kind") == "STORE_LEAK"
             and e.get("oid") == oid.hex()]
    assert len(leaks) == 1
    e = leaks[0]
    assert e["category"] == "serve_weights"
    assert e["group"] == "m:v2"
    assert e["reason"] == "owner_dead"
    assert e["owner_pid"] == (1 << 22) + 9997
    assert e["pid"] == os.getpid()      # envelope: the sweeper


# ------------------------------------------------------- dropped frees


def test_dropped_free_counter_stages(monkeypatch):
    ma, led = _fresh_ledger(monkeypatch)
    led.note_free_dropped("owner_push")
    led.note_free_dropped("gcs_fanout", count=2)
    led.note_free_dropped("raylet_delete")
    snap = led.snapshot()
    assert snap["dropped_frees"] == {"owner_push": 1, "gcs_fanout": 2,
                                     "raylet_delete": 1}


def test_gcs_free_fanout_resend_is_config_gated(monkeypatch):
    """The GCS's free fan-out retries a failed push exactly once when
    store_free_resend=1 and counts what still never landed."""
    import threading

    from ray_tpu._private import gcs as gcs_mod

    class _Conn:
        def __init__(self, node_id, fail=False):
            self.meta = {"node_id": node_id}
            self.fail = fail
            self.pushed = []

        def push(self, method, **kw):
            if self.fail:
                raise OSError("wire down")
            self.pushed.append((method, kw))

    class _Server:
        def __init__(self, conns):
            self._conns = conns

        def connections(self):
            return list(self._conns)

    class _GCS:
        _retry_free_fanout = gcs_mod.GcsServer._retry_free_fanout

        def __init__(self, conns):
            self._lock = threading.Lock()
            self._server = _Server(conns)

    recovered = _Conn("n1")             # came back between hops
    down = _Conn("n2", fail=True)       # never comes back
    g = _GCS([recovered, down])
    monkeypatch.setenv("RAY_TPU_STORE_FREE_RESEND", "1")
    g._retry_free_fanout([("n1", [b"a" * 16]), ("n2", [b"b" * 16])])
    assert [m for m, _ in recovered.pushed] == ["free_objects"]
    monkeypatch.setenv("RAY_TPU_STORE_FREE_RESEND", "0")
    recovered2 = _Conn("n1")
    g2 = _GCS([recovered2])
    g2._retry_free_fanout([("n1", [b"c" * 16])])
    assert recovered2.pushed == []      # gate off: no resend


# ------------------------------------------------------- kill switch


def test_kill_switch_disables_every_hook(monkeypatch):
    from ray_tpu._private import memory_anatomy as ma
    from ray_tpu._private import telemetry as tm
    from ray_tpu._private.store_client import StoreClient

    led = ma.Ledger()
    monkeypatch.setattr(ma, "LEDGER", led)
    monkeypatch.setattr(tm, "ENABLED", False)
    name = f"/raystore_zzma_ks_{os.getpid()}"
    c = StoreClient(name, create=True, size=4 * 1024 * 1024, n_slots=64)
    try:
        oid = b"K" * 16
        assert c.put(oid, b"x" * 1000)
        buf = c.get(oid)
        buf.release()
        c.delete(oid)
        assert led._live == {} and led._ring == []
        snap = ma.local_snapshot()
        assert snap["enabled"] is False
        # the periodic sweep refuses to start under the switch
        assert ma.start_periodic_sweep(None) is False
    finally:
        c.close()


# --------------------------------------------------- train-state gauges


def _rank_cls(ray):
    @ray.remote
    class Rank:
        def configure(self, env):
            os.environ.update({k: str(v) for k, v in env.items()})
            return True

        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def sync(self, rank, name, bucket_bytes=8192):
            from ray_tpu.train import ddp

            rng = np.random.RandomState(42 + rank)
            grads = {"w1": rng.standard_normal((64, 48))
                     .astype(np.float32),
                     "b1": rng.standard_normal(48).astype(np.float32),
                     "w2": rng.standard_normal((48, 7))
                     .astype(np.float64)}
            out = ddp.sync_gradients(grads, name,
                                     bucket_bytes=bucket_bytes)
            from ray_tpu.parallel import sharding as sh

            leaves, _ = sh.flatten_tree(grads)
            return {"flat_bytes": int(sum(
                int(np.asarray(x).nbytes) for x in leaves)),
                "out_sum": float(sum(np.asarray(v).sum()
                                     for v in out.values()))}

        def train_state_rows(self):
            from ray_tpu._private import memory_anatomy as ma

            snap = ma.LEDGER.snapshot()
            return {"train_state": snap["train_state"],
                    "inflight": dict(ma.LEDGER._inflight)}

        def destroy(self, name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(name)
            return True

    return Rank


def test_train_state_gauge_exact_on_2rank_gang(ray_start_regular):
    """`ray_tpu_train_state_bytes{kind=grads,rank}` equals the
    deterministic flatten's byte sum EXACTLY on a live 2-rank gang, and
    bucket_inflight drains back to zero once every bucket is
    harvested."""
    ray = ray_start_regular
    name = "zzma_ts"
    Rank = _rank_cls(ray)
    actors = [Rank.options(num_cpus=0).remote() for _ in range(2)]
    ray.get([a.configure.remote({"RAY_TPU_TRAIN_BUCKET_DDP": "1"})
             for a in actors])
    ray.get([a.join.remote(2, i, name) for i, a in enumerate(actors)],
            timeout=120)
    try:
        outs = ray.get([a.sync.remote(r, name)
                        for r, a in enumerate(actors)], timeout=120)
        expect = outs[0]["flat_bytes"]
        assert expect == outs[1]["flat_bytes"]
        rows = ray.get([a.train_state_rows.remote() for a in actors],
                       timeout=30)
        for rank, row in enumerate(rows):
            assert row["train_state"].get(f"grads:{rank}") == expect, \
                (rank, row)
            # every launched bucket was harvested at result(): nothing
            # left on the wire
            assert row["inflight"].get(str(rank), 0) == 0, row
    finally:
        try:
            ray.get([a.destroy.remote(name) for a in actors],
                    timeout=30)
        except Exception:
            pass
        for a in actors:
            ray.kill(a)


def test_make_train_state_reports_params_and_opt_bytes(monkeypatch):
    """params/opt_state gauges equal the flatten byte sum of the
    actual initialized state."""
    import jax

    from ray_tpu._private import memory_anatomy as ma
    from ray_tpu.parallel import sharding as sh
    from ray_tpu.parallel.train_step import (
        default_optimizer,
        make_train_state,
    )

    led = ma.Ledger()
    monkeypatch.setattr(ma, "LEDGER", led)

    def init_params(rng):
        import jax.numpy as jnp

        return {"w": jnp.zeros((32, 16), jnp.float32),
                "b": jnp.zeros((16,), jnp.float32)}

    state = make_train_state(init_params, jax.random.PRNGKey(0),
                             default_optimizer())
    p_leaves, _ = sh.flatten_tree(state.params)
    o_leaves, _ = sh.flatten_tree(state.opt_state)
    p_bytes = sum(int(x.nbytes) for x in p_leaves)
    o_bytes = sum(int(x.nbytes) for x in o_leaves)
    ts = led.snapshot()["train_state"]
    assert ts.get("params:0") == p_bytes, ts
    assert ts.get("opt_state:0") == o_bytes, ts


# ------------------------------------------------------ chaos acceptance


@pytest.mark.chaos
def test_killed_member_stranded_segment_names_dead_owner(
        ray_start_regular):
    """Acceptance (PR 18): seeded chaos drops rank 0's shm push notify
    (stranding its already-stored segment with no consumer ref), then
    rank 0 is KILLED. The death poisons the gang on the survivor, whose
    sweep must classify the stranded segment — which it never saw put —
    as orphaned, emitting exactly one STORE_LEAK naming the dead
    owner's group, rank, and category; summarize_memory() surfaces the
    same row cluster-wide."""
    ray = ray_start_regular
    name = "zzma_leak"

    @ray.remote
    class M:
        def configure(self, env):
            os.environ.update({k: str(v) for k, v in env.items()})
            return True

        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def allreduce(self, arr, name):
            from ray_tpu.util import collective as col

            return col.allreduce(arr, name)

        def chaos(self, seed, schedule):
            from ray_tpu._private import fault_injection as fi

            fi.install(seed, schedule)
            return True

        def poisoned(self, name):
            from ray_tpu._private.worker_runtime import current_worker

            return current_worker()._col_poison.get(name)

        def sweep_and_report(self, name):
            from ray_tpu._private import events
            from ray_tpu._private import memory_anatomy as ma

            ma.sweep_local()
            snap = ma.LEDGER.snapshot()
            leaks = [e for e in events.snapshot()
                     if e.get("kind") == "STORE_LEAK"
                     and e.get("group") == name]
            return {"orphans": [r for r in snap["orphans"]
                                if r.get("group") == name],
                    "leaks": leaks}

    actors = [M.options(num_cpus=0).remote() for _ in range(2)]
    ray.get([a.configure.remote({
        "RAY_TPU_COLLECTIVE_SEGMENT_BYTES": 128 * 1024,
        "RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "3",
        "RAY_TPU_MEMORY_SWEEP_GRACE_S": "0.2",
    }) for a in actors])
    ray.get([a.join.remote(2, i, name) for i, a in enumerate(actors)],
            timeout=120)
    # 100KB: over the shm-transport gate, but ONE 128KB segment — the
    # "exactly one STORE_LEAK" oracle needs a single stranded put
    ins = [np.random.RandomState(r).standard_normal(12_500)
           for r in range(2)]
    ray.get([a.allreduce.remote(ins[r], name)
             for r, a in enumerate(actors)], timeout=60)   # warm: works
    # rank 0 drops its NEXT outgoing shm notify: its stored segment
    # strands (rank 1 never learns the oid), rank 1's op times out
    ray.get(actors[0].chaos.remote(0, "drop:*.col_push_shm:#1"))
    refs = [a.allreduce.remote(ins[r], name)
            for r, a in enumerate(actors)]
    with pytest.raises(Exception):
        ray.get(refs, timeout=60)
    # kill the putter: the stranded segment's owner (and its ledger
    # record) die with it
    ray.kill(actors[0], no_restart=True)
    deadline = time.time() + 30
    while ray.get(actors[1].poisoned.remote(name), timeout=30) is None:
        assert time.time() < deadline, "gang never poisoned"
        time.sleep(0.25)
    time.sleep(0.5)     # clear the sweep grace window
    report = ray.get(actors[1].sweep_and_report.remote(name),
                     timeout=60)
    assert len(report["orphans"]) == 1, report
    row = report["orphans"][0]
    assert row["category"] == "collective_segment"
    assert row["group"] == name
    assert row["rank"] == 0             # the dead putter, from the oid
    assert row["reason"] == "owner_dead"
    assert 0 in (row.get("dead_ranks") or [])
    # exactly ONE STORE_LEAK for this group, even after a re-sweep
    ray.get(actors[1].sweep_and_report.remote(name), timeout=60)
    report2 = ray.get(actors[1].sweep_and_report.remote(name),
                      timeout=60)
    assert len(report2["leaks"]) == 1, report2["leaks"]
    leak = report2["leaks"][0]
    assert leak["group"] == name and leak["reason"] == "owner_dead"
    # the cluster rollup surfaces the same orphan with its provenance
    from ray_tpu.experimental.state.api import summarize_memory

    rollup = summarize_memory()
    hits = [r for r in rollup["orphans"] if r.get("group") == name]
    assert len(hits) == 1 and hits[0]["reason"] == "owner_dead"
    ray.kill(actors[1], no_restart=True)


# ------------------------------------------------------ overhead guard


def test_overhead_guard_store_put_get_under_5pct(monkeypatch):
    """CI satellite: the ledger hooks on the store put/get hot path stay
    <5% of the op. Separated measurement (the collective guard's
    pattern): a realistic 4MB put+read+delete cycle is bandwidth-bound
    and ±10% noisy round to round, so an on-vs-off wall-clock diff over
    it can never resolve a µs-scale hook — instead (a) resolve the
    ABSOLUTE per-cycle hook cost on a tiny-payload cycle, where the op
    is ~20µs and the diff is measurable, then (b) compare that absolute
    cost against the real data-plane op (consumers copy the bytes out;
    the memcpys ARE the hot path). min-of-rounds of medians throughout
    so scheduler noise can't fake an overhead."""
    import statistics

    from ray_tpu._private import memory_anatomy as ma
    from ray_tpu._private import telemetry as tm
    from ray_tpu._private.store_client import StoreClient

    led = ma.Ledger()
    monkeypatch.setattr(ma, "LEDGER", led)
    name = f"/raystore_zzma_ovh_{os.getpid()}"
    c = StoreClient(name, create=True, size=64 * 1024 * 1024,
                    n_slots=64)

    def cycle(payload, n):
        samples = []
        for i in range(n):
            oid = i.to_bytes(16, "little")
            t0 = time.perf_counter()
            c.put(oid, payload)
            buf = c.get(oid)
            buf.to_bytes()
            buf.release()
            c.delete(oid)
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples)

    tiny = b"x" * 64
    big = os.urandom(4 * 1024 * 1024)
    try:
        cycle(big, 5)    # warm slots / page cache
        on_rounds, off_rounds = [], []
        for _ in range(5):
            monkeypatch.setattr(tm, "ENABLED", False)
            off_rounds.append(cycle(tiny, 60))
            monkeypatch.setattr(tm, "ENABLED", True)
            on_rounds.append(cycle(tiny, 60))
        # absolute instrumentation cost per put+get+delete cycle
        overhead = max(0.0, min(on_rounds) - min(off_rounds))
        monkeypatch.setattr(tm, "ENABLED", False)
        op_cost = min(cycle(big, 25) for _ in range(3))
        assert overhead < 0.05 * op_cost, (
            f"ledger hooks add {overhead * 1e6:.1f}µs/op — "
            f"{overhead / op_cost * 100:.1f}% of a "
            f"{op_cost * 1e6:.1f}µs 4MB put+read+delete cycle "
            f"(budget: 5%)")
    finally:
        c.close()


# ------------------------------------------------------------ surfaces


def test_summarize_memory_shape_and_fanout(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.experimental.state.api import summarize_memory

    @ray.remote
    def touch(x):
        return x

    ray.get(touch.remote(np.arange(20_000)), timeout=60)
    out = summarize_memory()
    for key in ("categories", "live_bytes", "live_objects", "orphans",
                "orphan_bytes", "dropped_frees", "train_state",
                "top_owners", "per_process"):
        assert key in out, key
    assert out["per_process"], "fan-out returned no ledgers"
    assert all("ring" not in p for p in out["per_process"])
    pids = {(p.get("node"), p.get("pid")) for p in out["per_process"]}
    assert len(pids) == len(out["per_process"]), "dedup failed"


def test_flight_recorder_dump_contains_memory_jsonl(ray_start_regular,
                                                    tmp_path,
                                                    monkeypatch):
    import json

    ray = ray_start_regular
    from ray_tpu._private import flight_recorder as fr
    from ray_tpu._private import memory_anatomy as ma

    from ray_tpu._private.worker_runtime import current_worker

    led = ma.Ledger()
    monkeypatch.setattr(ma, "LEDGER", led)
    # a REAL store object (the dump's snapshot sweeps the ledger
    # against the store; a fabricated record would be pruned)
    store = current_worker().store
    with ma.tagged("checkpoint", owner="ck"):
        store.put(b"Z" * 16, b"x" * 512)
    path = fr.dump("zzma_test", out_dir=str(tmp_path))
    store.delete(b"Z" * 16)
    assert path is not None
    mem = os.path.join(path, "memory.jsonl")
    assert os.path.exists(mem), os.listdir(path)
    rows = [json.loads(line) for line in open(mem)]
    summaries = [r for r in rows if r["table"] == "memory_summary"]
    assert summaries, rows[:3]
    mine = [r for r in summaries if r.get("pid") == os.getpid()]
    assert mine and mine[0]["categories"].get("checkpoint")
    ring = [r for r in rows if r["table"] == "memory_ring"
            and r.get("pid") == os.getpid()]
    assert any(r["op"] == "put" and r["category"] == "checkpoint"
               for r in ring)
